"""H2D bandwidth, optimizer cost, take width sensitivity."""
import time
import numpy as np
import jax
import jax.numpy as jnp

rng = np.random.default_rng(0)

# --- H2D bandwidth ---------------------------------------------------------
for mb in (16, 64, 160):
    a = rng.integers(0, 2**31, size=(mb * 1024 * 1024 // 4,), dtype=np.int32)
    d = jax.device_put(a); jax.block_until_ready(d)  # warm path
    t0 = time.perf_counter()
    d = jax.device_put(a)
    jax.block_until_ready(d)
    # force real completion: read one element back
    _ = int(d[0])
    dt = time.perf_counter() - t0
    print(f"H2D {mb:4d} MB: {dt:6.2f} s  -> {mb/dt:7.1f} MB/s")

# --- optimizer full-table cost --------------------------------------------
from paddlebox_tpu.ps import optimizer as sparse_opt
from paddlebox_tpu.ps import embedding
from paddlebox_tpu.config import EmbeddingTableConfig, SparseSGDConfig

N_ROWS, MF = 2_000_000, 8
cfg = EmbeddingTableConfig(embedding_dim=MF,
                           sgd=SparseSGDConfig(mf_create_thresholds=0.0))
host = {}
ws = embedding.build_working_set(
    {"show": rng.random(N_ROWS).astype(np.float32),
     "click": rng.random(N_ROWS).astype(np.float32),
     "embed_w": rng.random(N_ROWS).astype(np.float32),
     "embedx": rng.random((N_ROWS, MF)).astype(np.float32),
     }, MF) if hasattr(embedding, "build_working_set") else None
print("ws keys:", None if ws is None else list(ws.keys()))

acc = {
    "g_show": jnp.asarray(rng.random(N_ROWS, dtype=np.float32)),
    "g_click": jnp.asarray(rng.random(N_ROWS, dtype=np.float32)),
    "g_embed": jnp.asarray(rng.random(N_ROWS, dtype=np.float32)),
    "g_embedx": jnp.asarray(rng.random((N_ROWS, MF), dtype=np.float32)),
    "slot": jnp.zeros((N_ROWS,), jnp.int32),
}
K = 20

@jax.jit
def opt_loop(ws_in, acc_in):
    def it(i, w):
        w2 = sparse_opt.apply_push(w, acc_in, cfg.sgd)
        return w2
    w = jax.lax.fori_loop(0, K, it, ws_in)
    return w["show"].sum()

@jax.jit
def floor_loop(ws_in):
    def it(i, c):
        return c + ws_in["show"][0]
    return jax.lax.fori_loop(0, K, it, jnp.float32(0))

float(floor_loop(ws))
t0 = time.perf_counter(); float(floor_loop(ws)); fl = time.perf_counter() - t0
float(opt_loop(ws, acc))
t0 = time.perf_counter(); float(opt_loop(ws, acc)); dt = time.perf_counter() - t0
print(f"apply_push per-op: {(dt-fl)/K*1e3:.2f} ms")

# --- take width sensitivity ------------------------------------------------
P = 1_277_952
perm = jnp.asarray(rng.permutation(P).astype(np.int32))
for w_, dt_ in ((12, jnp.float32), (24, jnp.float32), (6, jnp.float32),
                (12, jnp.bfloat16)):
    v = jnp.asarray(rng.random((P, w_), dtype=np.float32)).astype(dt_)

    @jax.jit
    def tk(v_, p_):
        def it(i, c):
            return c + jnp.take(v_ + c.astype(v_.dtype), p_, axis=0
                                ).sum().astype(jnp.float32)
        return jax.lax.fori_loop(0, K, it, jnp.float32(0))
    float(tk(v, perm))
    t0 = time.perf_counter(); float(tk(v, perm)); dt = time.perf_counter() - t0
    print(f"take [P,{w_}] {dt_.__name__}: {(dt-fl)/K*1e3:.2f} ms")
