"""Benchmark: Criteo-shaped sparse-CTR training throughput on one chip.

Prints JSON lines on stdout; the LAST line is the result the driver
records.  The headline value is END-TO-END examples/s — the full
train_pass loop over the pass-resident device feed (≙ the reference's
TrainFiles loop consuming SlotPaddleBoxDataFeed's whole-pass GPU pack,
boxps_worker.cc:1278 + data_feed.cu:1210-1318).  Pass packing/translation/
upload happens at pass-build time, exactly where the reference does it
(feed pass, not train), and is reported separately as `pass_pack_s`.
`device_step` (steady re-fed device step) is reported alongside;
`basis` names which quantity the headline value is.

Diagnosable by construction (≙ the per-phase timer discipline of
TrainFilesWithProfiler, boxps_worker.cc:1358):
 * every phase prints a timestamped checkpoint to STDERR, so a captured
   tail locates any hang exactly;
 * a SMOKE geometry (B=1024, 2 batches, 100k keys) runs the whole path
   first and emits its own JSON line before the full config is attempted;
 * partial numbers (smoke/device_step/e2e) are recorded the moment they
   are measured; the watchdog emits the best value seen so far plus the
   name of the wedged phase — never a bare 0.0;
 * each phase has its own budget; a wedged phase fails fast;
 * `step_ms` breaks the device step into pull/dense/push phases for the
   SELECTED sparse step path (BENCH_SPARSE_PATH, default ragged) and
   profiles the padded-dense fast path side by side: `sparse_share` =
   sparse / (sparse + dense) device time, `ragged_speedup` = fast-path
   sparse time / selected-path sparse time.

Geometry (full): 26 sparse slots with variable lengths 1..3 (capacity 3),
13 dense features, mf_dim=8, 2M-key working set, B=16384.

Supervisor architecture (hang-proof backend init): the driver-invoked
process is a thin SUPERVISOR that runs the actual bench in a child
process.  A hung `jax.devices()` (tunnel wedge — exactly what burned
round 4) cannot be interrupted in-process, but the child is killable:
the supervisor gives each attempt a bounded backend-init window, kills
and respawns on a wedge, and keeps retrying until the total budget is
nearly exhausted — backend-init effectively owns the WHOLE budget,
because no later phase exists until a backend does.  The child's own
thread watchdog still handles post-backend phase hangs.  The supervisor
always prints the final stdout line (best result seen across attempts).

Wedge postmortems (utils/doctor.py): when a phase budget expires the
child writes a full postmortem bundle (all-thread stacks + flight ring +
stat snapshot) BEFORE emitting its error line; the bundle path rides the
error line and the supervisor's attempt_log — a wedged round ships
stacks, not a mystery.  SIGUSR1 on the child dumps one live.

Compare mode: ``bench.py --compare OLD.json NEW.json [--threshold=0.05]``
diffs two BENCH result files (throughput, feed_gap_ratio, obs_stats
movers) and exits nonzero on regression beyond the threshold — the
recorded CPU-basis bench delta the ROADMAP asks every perf PR to carry.

Env knobs: BENCH_BATCH_SIZE, BENCH_BATCHES, BENCH_KEYS, BENCH_TIMEOUT_S,
BENCH_PACK_THREADS, BENCH_SKIP_SMOKE=1, BENCH_SMOKE_ONLY=1,
BENCH_LEGACY_FEED=1 (per-batch host pack path), BENCH_STEP_PROFILE=0,
BENCH_BACKEND_ATTEMPT_S (per-attempt backend-init window, default 150),
BENCH_NO_SUPERVISE=1 (single-process debug mode),
BENCH_COMPARE_THRESHOLD (default regression threshold for --compare),
BENCH_CACHE=0 (skip the device-cache on/off compare),
BENCH_CACHE_PASSES/_KEYS/_DRAWS/_ROWS (cache-compare geometry),
BENCH_HEAT=0 (skip the heat-telemetry on/off overhead phase),
BENCH_HEAT_PASSES/_CYCLES/_KEYS/_DRAWS (heat-phase geometry),
BENCH_SERVING=0 (skip the serving-tier QPS/p99 phase),
BENCH_SERVING_KEYS/_BATCHES/_BATCH (serving-phase geometry),
BENCH_SERVING_FLEET=0 (skip the sharded-fleet + heat-routing sub-phases),
BENCH_SERVING_FLEET_SHARDS/_ROUNDS/_BATCH/_REPS (fleet geometry),
BENCH_SERVING_FLIP=0 (skip the streamed-delta-flip-under-load sub-phase),
BENCH_SERVING_FLIP_GENS (save_pass generations streamed during traffic),
BENCH_SERVING_HOT (replicated hot-key set size for the heat-routing leg),
BENCH_CLUSTER=0 (skip the sharded-PS N=1 vs N=4 phase),
BENCH_CLUSTER_KEYS/_ROUNDS/_BATCH/_SHARDS/_REPS (cluster-phase geometry),
BENCH_MT=0 (skip the trainer-fleet N=1 vs N=4 phase),
BENCH_MT_FILES/_ROWS/_TRAINERS/_SHARDS (multi-trainer geometry),
BENCH_MT_CHAOS=0 (skip the multi-trainer kill/restart MTTR rep),
BENCH_TIMELINE_S (telemetry-timeline sampler cadence, default 1.0;
0 disables — the run's `timeline` summary then stays empty).
"""

import json
import math
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

METRIC = "criteo_deepfm_train_examples_per_sec_per_chip"
T0 = time.time()
TOTAL_BUDGET = int(os.environ.get("BENCH_TIMEOUT_S", 1500))
_LOCK = threading.Lock()
_STATE = {
    "phase": "start",
    "deadline": T0 + TOTAL_BUDGET,
    "partial": {},     # numbers recorded as soon as they are measured
    "done": False,
}


def trace(msg: str) -> None:
    print(f"[bench +{time.time() - T0:7.1f}s] {msg}", file=sys.stderr,
          flush=True)


def set_phase(name: str, budget_s: float) -> None:
    """Enter a phase: stderr checkpoint + its own watchdog budget (capped
    by the global deadline, minus a grace window to emit before the driver
    kills us)."""
    hard = T0 + TOTAL_BUDGET - 20
    with _LOCK:
        _STATE["phase"] = name
        _STATE["deadline"] = min(time.time() + budget_s, hard)
    trace(f"phase={name} budget={budget_s:.0f}s")
    try:  # phase boundaries belong in the flight ring: a postmortem's
        from paddlebox_tpu.utils import flight  # event tail then shows
        flight.record("bench_phase", phase=name, budget_s=budget_s)
    except Exception:  # how far the run got before wedging
        pass


def record(**kw) -> None:
    with _LOCK:
        _STATE["partial"].update(kw)


def _best() -> float:
    p = _STATE["partial"]
    for k in ("e2e", "device_step", "smoke_e2e", "smoke_device_step"):
        v = p.get(k)
        if v:
            return float(v)
    return 0.0


def _san(o):
    """json-strict: non-finite floats become null (driver must always be
    able to parse the line)."""
    if isinstance(o, float) and not math.isfinite(o):
        return None
    if isinstance(o, dict):
        return {k: _san(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_san(v) for v in o]
    return o


def emit(value: float, final: bool = False, **extra) -> None:
    if final:
        # retire the watchdog BEFORE printing, or it can race a late
        # phase-budget expiry and append an error line after the result
        with _LOCK:
            _STATE["done"] = True
    line = {"metric": METRIC, "value": round(float(value), 1),
            "unit": "examples/s",
            "vs_baseline": round(float(value) / 1_000_000.0, 4)}
    if final:
        line["final"] = True    # the supervisor keys clean-run detection
        # on this: a mid-run smoke line must never pass for the result
    line.update(extra)
    print(json.dumps(_san(line)), flush=True)


def _watchdog() -> None:
    """Thread watchdog (survives the main thread being wedged inside an
    XLA compile, where SIGALRM handlers never run): on phase-budget expiry
    emit the best partial value + the wedged phase name, then hard-exit."""
    while True:
        # pboxlint: disable-next=PB501 -- fixed poll cadence, not a retry
        time.sleep(2)
        with _LOCK:
            if _STATE["done"]:
                return
            expired = time.time() > _STATE["deadline"]
            phase = _STATE["phase"]
            partial = dict(_STATE["partial"])
        if expired:
            # postmortem FIRST, error line second: the bundle (all-thread
            # stacks + flight tail + stat snapshot) is the whole point of
            # a wedge report, and os._exit below forecloses any later shot
            pm = None
            try:
                from paddlebox_tpu.utils import doctor
                pm = doctor.write_postmortem(
                    reason=f"watchdog: phase '{phase}' exceeded its budget")
                trace(f"watchdog: postmortem {pm}")
            except Exception as e:  # never let diagnostics block the emit
                trace(f"watchdog: postmortem failed: {e!r}")
            emit(_best(),
                 error=f"watchdog: phase '{phase}' exceeded its budget",
                 last_phase=phase, partial=partial, postmortem=pm,
                 elapsed_s=round(time.time() - T0, 1))
            os._exit(0)


def _obs_snapshot():
    """End-of-run observability snapshot (wire bytes, stall seconds,
    inflight hwm, latency-histogram percentiles) embedded in the result
    line — the perf trajectory carries CAUSES, not just numbers."""
    try:
        from paddlebox_tpu.utils.monitor import stat_snapshot
        obs = {}
        for prefix in ("ps.", "data.", "trainer.", "feed."):
            obs.update(stat_snapshot(prefix))
        return {k: round(v, 6) if isinstance(v, float) else v
                for k, v in sorted(obs.items())}
    except Exception:  # diagnostics must never sink the result line
        return {}


def _bench_slo_rules():
    """The production rule set minus throughput_stall: the bench's
    step-profile and cache-compare phases run for minutes without a
    single device step BY DESIGN, so the stall rule would breach on
    every healthy run and poison the --compare gate."""
    from paddlebox_tpu.utils import timeline
    return [r for r in timeline.default_rules()
            if r.name != "throughput_stall"]


def _start_timeline(restart=False):
    """Run the telemetry timeline sampler (utils/timeline.py): 1 s
    cadence by default, BENCH_TIMELINE_S=0 disables.  Its summary lands
    in the result line and --compare gates on new SLO breaches.
    restart=True tears the ring down first — each bench geometry is a
    fresh job, and the previous config's samples must not sit inside
    the new watchdog's evaluation window."""
    try:
        interval = float(os.environ.get("BENCH_TIMELINE_S", 1.0))
        if interval <= 0:
            return
        from paddlebox_tpu.utils import timeline
        if restart:
            timeline.stop()
        timeline.start(interval_s=interval, cap=4096,
                       rules=_bench_slo_rules())
    except Exception:  # diagnostics must never sink the run
        pass


def _quality_observe(metrics):
    """Feed one pass result to the training-quality monitors so the
    timeline carries an AUC trajectory (fleet.train_passes does this in
    production; the bench drives the trainer directly)."""
    try:
        from paddlebox_tpu.metrics import quality
        quality.observe_pass(metrics)
    except Exception:
        pass


def _timeline_summary():
    """The timeline's view of the run for the BENCH JSON: throughput-
    over-time stability (per-interval step-dispatch rates), the AUC
    trajectory, and the SLO breach count."""
    try:
        from paddlebox_tpu.metrics import quality
        from paddlebox_tpu.utils import flight, timeline
        s = timeline.sampler()
        if s is None:
            return {}
        rates = [r for _, r in
                 s.ring.series("trainer.step_dispatch_s.count")["rates"]
                 if r > 0]
        thr = {}
        if rates:
            mean = sum(rates) / len(rates)
            var = sum((r - mean) ** 2 for r in rates) / len(rates)
            thr = {"steps_per_s_mean": round(mean, 3),
                   "steps_per_s_cv":
                       round(var ** 0.5 / mean, 4) if mean else 0.0,
                   "active_intervals": len(rates)}
        breaches = flight.events(kind="slo_breach")
        return {"samples": len(s.ring), "interval_s": s.interval_s,
                "throughput": thr,
                "auc_trajectory": [round(a, 4) for a in quality.aucs()],
                "slo_breaches": len(breaches),
                "breached_rules": sorted({b.get("rule") for b in breaches}),
                "slo_states": s.watchdog.states()}
    except Exception:  # diagnostics must never sink the result line
        return {}


def _init_devices(retries: int = 3, delay: float = 5.0):
    if os.environ.get("BENCH_TEST_HANG_INIT") == "1":
        # harness-test hook: simulate the round-4 tunnel wedge (a hang,
        # not an exception — only an outside kill can clear it)
        time.sleep(10 ** 6)
    once = os.environ.get("BENCH_TEST_HANG_INIT_ONCE")
    if once and os.path.exists(once):
        os.unlink(once)    # next attempt (fresh child) proceeds — models
        time.sleep(10 ** 6)  # a transient tunnel wedge
    if os.environ.get("BENCH_TEST_HANG_UNLESS_CPU") == "1" \
            and os.environ.get("BENCH_FORCE_CPU") != "1":
        # harness-test hook: models a persistently wedged accelerator
        # platform (BENCH_r05's 'axon' tunnel) that only the supervisor's
        # cpu fallback can get past
        time.sleep(10 ** 6)
    import jax
    last = None
    for attempt in range(retries):
        try:
            return jax.devices()
        except Exception as e:  # backend init is flaky under the tunnel
            last = e
            trace(f"backend init attempt {attempt + 1} failed: {e!r}")
            if attempt + 1 < retries:
                time.sleep(delay)
    raise RuntimeError(
        f"jax backend init failed after {retries} attempts: {last!r}")


def _make_blocks(rng, n_records, sparse_names, n_keys, dense_dim, cap,
                 chunk=65536):
    """Synthetic pass data as SlotRecordBlocks (variable-length slots)."""
    from paddlebox_tpu.data.slot_record import SlotRecordBlock
    blocks = []
    done = 0
    while done < n_records:
        n = min(chunk, n_records - done)
        blk = SlotRecordBlock(n=n)
        for name in sparse_names:
            lens = rng.integers(1, cap + 1, size=n)
            offsets = np.zeros((n + 1,), np.int64)
            np.cumsum(lens, out=offsets[1:])
            values = rng.integers(
                1, n_keys, size=int(offsets[-1])).astype(np.uint64)
            blk.uint64_slots[name] = (values, offsets)
        blk.float_slots["label"] = (
            rng.integers(0, 2, size=n).astype(np.float32),
            np.arange(n + 1, dtype=np.int64))
        blk.float_slots["dense0"] = (
            rng.normal(0, 1, size=n * dense_dim).astype(np.float32),
            np.arange(n + 1, dtype=np.int64) * dense_dim)
        blocks.append(blk)
        done += n
    return blocks


def _profile_step_phases(trainer, feed, k=8):
    """Per-phase device-time breakdown of the packed step (≙ the per-op
    timer discipline of TrainFilesWithProfiler, boxps_worker.cc:1358-1407).
    Each phase runs k chained iterations inside one jit (a scalar carry
    defeats CSE and amortizes RPC latency), synced by a scalar readback;
    the no-op floor is subtracted.

    Profiles the SELECTED step path's pull/dense/push phases AND the
    padded-dense fast path's pull/push side by side, so every record
    carries the comparison the ragged path exists to win:
    `sparse_share` = sparse / (sparse + dense), `ragged_speedup` =
    fast sparse time / selected sparse time."""
    import jax
    import jax.numpy as jnp
    from paddlebox_tpu.ps import fast_path, mxu_path, ragged_path
    from paddlebox_tpu.data.pass_feed import plan_tuple

    path = trainer._resolve_path()
    ws = trainer.engine.ws
    n_rows = ws["show"].shape[0]
    n, s, l, b = feed.data["indices"].shape
    interpret = jax.default_backend() == "cpu"
    bt = jax.tree.map(lambda a: a[0], feed.data)
    half = trainer._pooled_dense_half()
    slot_ids = jnp.asarray(trainer.slot_ids)
    sgd_cfg = trainer.engine.config.sgd
    ins_cvm = jnp.stack([jnp.ones_like(bt["labels"]), bt["labels"]], axis=1)

    def timed(body):
        @jax.jit
        def run():
            def it(i, c):
                return body(c)
            return jax.lax.fori_loop(0, k, it, jnp.float32(0))
        float(run())  # compile + first run
        t0 = time.perf_counter()
        float(run())
        return time.perf_counter() - t0

    def timed_ws(body):
        # push phases MUTATE ws: time them the way the trainer's jitted
        # step runs them — ws donated and carried through the loop, so
        # each update is in-place rather than paying a full-[N] working-
        # set copy per iteration (a scalar-carry closure over ws would
        # charge that copy to every path and flatten the comparison)
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def run(w):
            return jax.lax.fori_loop(0, k, lambda i, w: body(w), w)
        jax.block_until_ready(run(jax.tree.map(jnp.copy, ws)))  # compile
        w0 = jax.tree.map(jnp.copy, ws)
        jax.block_until_ready(w0)
        t0 = time.perf_counter()
        jax.block_until_ready(run(w0))
        return time.perf_counter() - t0

    floor = timed(lambda c: c + ws["show"][0])
    floor_w = timed_ws(lambda w: w)

    def vary(c):  # cheap data-dependence injection, defeats loop CSE
        return {**ws, "show": ws["show"] + c}

    # -- fast path (padded-dense baseline): always profiled ---------------
    fast_pooled0 = jax.jit(lambda w: fast_path.pull_pool_cvm(
        w, bt["indices"], bt["lengths"], trainer.use_cvm))(ws)
    t_fast_pull = timed(lambda c: c + fast_path.pull_pool_cvm(
        vary(c), bt["indices"], bt["lengths"], trainer.use_cvm).sum())
    t_fast_push = timed_ws(lambda w: fast_path.push_and_update(
        w, bt["indices"], bt["lengths"], fast_pooled0, ins_cvm,
        slot_ids, sgd_cfg))

    # -- selected path -----------------------------------------------------
    out = {"path": path}
    if path == "fast":
        pooled0 = fast_pooled0
        t_pull, t_push = t_fast_pull, t_fast_push
    elif path == "ragged":
        plan = plan_tuple(jax.tree.map(lambda a: a[0], feed.plans))
        pooled0 = jax.jit(lambda w: ragged_path.pull_pool_cvm(
            w, plan, (s, l, b), trainer.use_cvm))(ws)
        t_pull = timed(lambda c: c + ragged_path.pull_pool_cvm(
            vary(c), plan, (s, l, b), trainer.use_cvm).sum())
        t_push = timed_ws(lambda w: ragged_path.push_and_update(
            w, plan, pooled0, ins_cvm, (s, l, b), sgd_cfg))
    else:  # mxu
        dims = mxu_path.make_dims(s * l * b, n_rows)
        plan = plan_tuple(jax.tree.map(lambda a: a[0], feed.plans))
        cross = getattr(trainer, "_mxu_crossing", ("take", "take"))
        out["crossing"] = f"{cross[0]}/{cross[1]}"
        pooled0 = jax.jit(lambda w: mxu_path.pull_pool_cvm(
            w, plan, dims, (s, l, b), trainer.use_cvm,
            interpret=interpret))(ws)
        t_pull = timed(lambda c: c + mxu_path.pull_pool_cvm(
            vary(c), plan, dims, (s, l, b), trainer.use_cvm,
            interpret=interpret, crossing=cross[0]).sum())
        t_push = timed_ws(lambda w: mxu_path.push_and_update(
            w, plan, dims, bt["indices"], pooled0, ins_cvm,
            slot_ids, sgd_cfg, interpret=interpret, crossing=cross[1]))

    def dense_body(c):
        res = half(trainer.params, trainer.opt_state, trainer.auc_state,
                   pooled0 + c, bt["dense"], bt["labels"], bt["valid"])
        return c + res[3]  # loss
    t_dense = timed(dense_body)

    def ms(t, f=None):
        return round(max(0.0, (t - (floor if f is None else f)) / k * 1e3),
                     2)

    out.update(pull_pool=ms(t_pull), dense_fwd_bwd=ms(t_dense),
               push_optimizer=ms(t_push, floor_w),
               fast_pull_pool=ms(t_fast_pull),
               fast_push_optimizer=ms(t_fast_push, floor_w))
    sparse = out["pull_pool"] + out["push_optimizer"]
    total = sparse + out["dense_fwd_bwd"]
    out["sparse_share"] = round(sparse / total, 4) if total > 0 else 0.0
    fast_sparse = out["fast_pull_pool"] + out["fast_push_optimizer"]
    out["ragged_speedup"] = (round(fast_sparse / sparse, 2)
                             if sparse > 0 else 0.0)
    return out


def _pass_cycle(tag, dataset, engine, trainer, n_passes):
    """Same-run pipeline on/off comparison over WHOLE pass cycles.

    The e2e phase measures the train loop on a prebuilt feed; this phase
    measures full cycles (key feed -> dedup -> table pull -> pack ->
    upload -> train -> write-back) over the same in-memory blocks, twice:
    first with the pipeline OFF (pack_threads=1, serial pass loop), then
    ON (pack WorkPool at min(4, cpu) + PassPrefetcher double buffer).
    Same process, same compiled step — the ratio isolates exactly what
    the pipelined feed engine buys."""
    from paddlebox_tpu import flags
    from paddlebox_tpu.data.prefetch import PassPrefetcher
    from paddlebox_tpu.utils import intervals

    n_examples = dataset.instance_num()
    prev_threads = flags.get_flags("pass_pack_threads")

    def feed_keys():
        for blk in dataset.get_blocks():
            engine.add_keys(blk.all_keys())
        return dataset

    def cycle(mode):
        def heartbeat(p):
            def hb(n):   # refresh phase budget: forward progress ≠ hang
                set_phase(f"{tag}:pass-cycle:{mode}"
                          f"[pass {p + 1}/{n_passes} batch {n}]", 300)
            return hb

        m0 = time.monotonic()
        t0 = time.perf_counter()
        if mode == "serial":
            for p in range(n_passes):
                set_phase(f"{tag}:pass-cycle:serial"
                          f"[pass {p + 1}/{n_passes}]", 900)
                engine.begin_feed_pass()
                feed_keys()
                engine.end_feed_pass()
                engine.begin_pass()
                feed = trainer.build_pass_feed(dataset)
                _quality_observe(
                    trainer.train_pass(feed, progress=heartbeat(p)))
                engine.end_pass()
        else:
            pf = PassPrefetcher(engine, trainer)
            try:
                for _ in range(n_passes):
                    pf.submit(feed_keys)
                for p in range(n_passes):
                    set_phase(f"{tag}:pass-cycle:pipelined"
                              f"[pass {p + 1}/{n_passes}]", 900)
                    feed = pf.next_pass()
                    _quality_observe(
                        trainer.train_pass(feed, progress=heartbeat(p)))
                    pf.end_pass()
            finally:
                pf.close()
        dt = time.perf_counter() - t0
        rep = intervals.report(since=m0)
        return {"wall_s": round(dt, 1),
                "ex_s": round(n_passes * n_examples / dt, 1),
                "feed_gap_ratio": round(rep.get("feed_gap_ratio", 0.0), 2),
                "device_busy_frac":
                    round(rep.get("device_busy_frac", 0.0), 4),
                "hidden_s": {k: round(rep.get(f"{k}_hidden_s", 0.0), 3)
                             for k in ("pull", "pack", "upload")}}

    try:
        # the pass opened for device-step/e2e is still live: write it
        # back so both variants start from the same table state
        if engine.ws is not None:
            engine.end_pass()
        flags.set_flags({"pass_pack_threads": 1})
        serial = dict(cycle("serial"), pack_threads=1, prefetch=False)
        pipe_threads = min(4, os.cpu_count() or 1)
        flags.set_flags({"pass_pack_threads": pipe_threads})
        pipelined = dict(cycle("pipelined"),
                         pack_threads=pipe_threads, prefetch=True)
    finally:
        flags.set_flags({"pass_pack_threads": prev_threads})
    speedup = pipelined["ex_s"] / max(serial["ex_s"], 1e-9)
    return {"serial": serial, "pipelined": pipelined, "passes": n_passes,
            "speedup": round(speedup, 2),
            "feed_gap_improved":
                pipelined["feed_gap_ratio"] < serial["feed_gap_ratio"]}


def _recovery_drill(tag, dataset, engine, trainer):
    """Kill + resume in-process, clocking MTTR: time from simulated
    trainer death to the first post-resume train step.  Checkpoints the
    live table + dense state to a scratch generation root
    (io/checkpoint.py), drops the engine's feed state on the floor (the
    abrupt-death analogue), restores from the generation chain, and
    re-drives one pass — the first completed batch stops the clock.

    MTTR is a wall-clock-class metric (one kill → one restore interval,
    scheduler-noise-dominated), so the drill runs THREE kill/resume
    cycles from the same saved generation and reports the median with
    the per-cycle ``runs`` alongside: --compare only gates a delta that
    reproduces across a median-of-3 record on both sides."""
    import shutil as _shutil
    import tempfile as _tempfile
    from paddlebox_tpu.io.checkpoint import TrainCheckpoint

    if engine.ws is not None:       # close any live pass first
        engine.end_pass()
    root = _tempfile.mkdtemp(prefix="pbox-bench-ckpt-")
    try:
        ck = TrainCheckpoint(root)
        t0 = time.perf_counter()
        gen = ck.save(engine, trainer)
        save_s = time.perf_counter() - t0

        runs, restores = [], []
        for cyc in range(3):
            t_kill = time.perf_counter()
            engine.reset_feed_state()   # the crashed run's in-flight state
            ck.resume(engine, trainer)
            restores.append(time.perf_counter() - t_kill)

            first = [None]

            def progress(n):
                if first[0] is None:
                    first[0] = time.perf_counter()
                set_phase(f"{tag}:recovery-drill[run {cyc} batch {n}]", 300)

            engine.begin_feed_pass()
            for blk in dataset.get_blocks():
                engine.add_keys(blk.all_keys())
            engine.end_feed_pass()
            engine.begin_pass()
            feed = trainer.build_pass_feed(dataset)
            trainer.train_pass(feed, progress=progress)
            engine.end_pass()
            t_first = first[0] or time.perf_counter()
            runs.append(round(t_first - t_kill, 3))
        return {"mttr_s": sorted(runs)[1],
                "runs": sorted(runs),
                "save_s": round(save_s, 3),
                "restore_s": round(sorted(restores)[1], 3),
                "generation": int(gen)}
    finally:
        _shutil.rmtree(root, ignore_errors=True)


def _cache_compare(tag):
    """Same-process device-cache on/off comparison over a zipf-skewed key
    stream (the production shape: a small hot set dominates every pass).

    Two fresh engines — the cache flag is read at engine construction —
    drive the same pass-cycle key feed (begin_feed_pass -> add_keys ->
    end_feed_pass -> begin_pass -> end_pass) over IDENTICAL key blocks.
    No trainer: the cache lives entirely on the pull/fold-back path, so
    engine-level cycles isolate exactly what the HBM tier buys — wire
    rows that never leave the host table.  Steady-state numbers exclude
    the all-miss cold first pass (stat deltas from pass 2 on)."""
    from paddlebox_tpu import flags
    from paddlebox_tpu.config import EmbeddingTableConfig, SparseSGDConfig
    from paddlebox_tpu.ps.pass_manager import BoxPSEngine
    from paddlebox_tpu.utils.monitor import stat_snapshot

    n_passes = int(os.environ.get("BENCH_CACHE_PASSES", 6))
    n_keys = int(os.environ.get("BENCH_CACHE_KEYS", 100_000))
    draws = int(os.environ.get("BENCH_CACHE_DRAWS", 262_144))
    cap = int(os.environ.get("BENCH_CACHE_ROWS", 65_536))

    rng = np.random.default_rng(7)
    blocks = [np.minimum(rng.zipf(1.3, size=draws), n_keys)
              .astype(np.uint64) for _ in range(n_passes)]

    def cycle(on):
        def delta(key):
            return (stat_snapshot("ps.").get(key, 0.0)
                    - warm.get(key, 0.0))

        flags.set_flags({"ps_device_cache": bool(on),
                         "ps_device_cache_rows": cap})
        engine = BoxPSEngine(EmbeddingTableConfig(
            embedding_dim=8, shard_num=8,
            sgd=SparseSGDConfig(mf_create_thresholds=0.0)))
        warm = {}
        t0 = time.perf_counter()
        for p in range(n_passes):
            set_phase(f"{tag}:cache-compare:{'on' if on else 'off'}"
                      f"[pass {p + 1}/{n_passes}]", 300)
            engine.begin_feed_pass()
            engine.add_keys(blocks[p])
            engine.end_feed_pass()
            engine.begin_pass()
            engine.end_pass()
            if p == 0:      # steady-state basis: skip the cold pass
                warm = stat_snapshot("ps.")
        wall = time.perf_counter() - t0
        out = {"wall_s": round(wall, 1),
               "wire_rows": int(delta("ps.engine.build_pull_rows"))}
        if on:
            hits, misses = delta("ps.cache.hits"), delta("ps.cache.misses")
            out.update(
                hits=int(hits), misses=int(misses),
                hit_rate=round(hits / max(hits + misses, 1.0), 4),
                wire_bytes_saved=int(delta("ps.cache.bytes_saved")),
                evictions=int(delta("ps.cache.evictions")))
        return out

    prev = {k: flags.get_flags(k)
            for k in ("ps_device_cache", "ps_device_cache_rows")}
    try:
        off = cycle(False)
        on = cycle(True)
    finally:
        flags.set_flags(prev)
    reduction = off["wire_rows"] / max(on["wire_rows"], 1)
    return {"off": off, "on": on, "passes": n_passes,
            "cache_rows": cap, "zipf_a": 1.3,
            "hit_rate": on["hit_rate"],
            "wire_bytes_saved": on["wire_bytes_saved"],
            "wire_reduction": round(reduction, 2)}


def _heat_bench(tag):
    """Key-space heat telemetry on/off overhead + gauge snapshot over the
    real sharded wire path (ISSUE 19).

    Two fresh 2-shard PS fleets drive IDENTICAL zipf-skewed engine pass
    cycles through a RemoteTableAdapter — remote, because the shard-load
    attribution tap lives in the client's sharded fan, and a local table
    would leave ``heat.shard_imbalance`` vacuously zero.  The device row
    cache is on in BOTH cycles so the hot-coverage tap has admissions to
    observe and the off/on walls stay like-for-like.  Cycles run
    interleaved off/on (BENCH_HEAT_CYCLES pairs) and the walls are the
    per-mode medians — a single 0.3s engine-only cycle is
    noise-dominated and scheduler drift would otherwise masquerade as
    tap cost.  tap_ns_per_key is the headline (absolute sketch cost per
    ingested key, budget 250 ns); overhead_pct is relative to this
    engine-only cycle (~230 ns/key of useful work) and so reads ~10x
    worse than what a real train pass with dense compute would pay."""
    from paddlebox_tpu import flags
    from paddlebox_tpu.config import EmbeddingTableConfig, SparseSGDConfig
    from paddlebox_tpu.launch import PSFleet
    from paddlebox_tpu.ps import heat
    from paddlebox_tpu.ps.pass_manager import BoxPSEngine
    from paddlebox_tpu.ps.service import PSClient, RemoteTableAdapter
    from paddlebox_tpu.utils.monitor import stat_snapshot

    n_passes = int(os.environ.get("BENCH_HEAT_PASSES", 6))
    n_cycles = int(os.environ.get("BENCH_HEAT_CYCLES", 3))
    n_keys = int(os.environ.get("BENCH_HEAT_KEYS", 100_000))
    draws = int(os.environ.get("BENCH_HEAT_DRAWS", 262_144))

    rng = np.random.default_rng(11)
    blocks = [np.minimum(rng.zipf(1.3, size=draws), n_keys)
              .astype(np.uint64) for _ in range(n_passes)]
    tcfg = EmbeddingTableConfig(
        embedding_dim=8, shard_num=8,
        sgd=SparseSGDConfig(mf_create_thresholds=0.0))

    def cycle(on):
        flags.set_flags({"obs_heat": bool(on),
                         "ps_device_cache": True})
        heat.disable()                  # fresh sketches per cycle
        flt = PSFleet(2, config=tcfg, seed=0)
        try:
            client = PSClient(flt.addrs, deadline=60)
            engine = BoxPSEngine(tcfg)
            engine.table = RemoteTableAdapter(client)
            t0 = None
            for p in range(n_passes):
                set_phase(f"{tag}:heat:{'on' if on else 'off'}"
                          f"[pass {p + 1}/{n_passes}]", 300)
                engine.begin_feed_pass()
                engine.add_keys(blocks[p])
                engine.end_feed_pass()
                engine.begin_pass()
                engine.end_pass()
                if p == 0:
                    # steady-state wall: pass 1 pays fleet spin-up, first
                    # connects and row-width learning — whichever cycle
                    # runs first would absorb process-wide warmup and
                    # poison the off/on delta
                    t0 = time.perf_counter()
            return time.perf_counter() - t0
        finally:
            flt.stop()

    prev = {k: flags.get_flags(k) for k in ("obs_heat", "ps_device_cache")}
    try:
        cycle(False)    # discarded: process-wide jit + wire-path warmup
        off_walls, on_walls = [], []
        for _ in range(max(1, n_cycles)):   # interleaved: drift hits both
            off_walls.append(cycle(False))
            on_walls.append(cycle(True))
        off_wall = sorted(off_walls)[len(off_walls) // 2]
        on_wall = sorted(on_walls)[len(on_walls) // 2]
        gauges = stat_snapshot("heat.")
        hm = heat.ACTIVE
        sketch_bytes = hm.nbytes() if hm is not None else 0
    finally:
        heat.disable()
        flags.set_flags(prev)
    overhead = (on_wall - off_wall) / max(off_wall, 1e-9)
    # absolute tap cost per ingested key — the workload-independent
    # number.  overhead_pct divides by whatever the off-cycle happens to
    # cost: this engine-only cycle moves a key end-to-end in ~230 ns, so
    # ~60 ns/key of sketch taps reads as ~25% here but is <1% of a real
    # train pass with dense compute behind the same pulls.
    tap_ns = (on_wall - off_wall) \
        / max(1, (n_passes - 1) * draws) * 1e9
    return {"off_wall_s": round(off_wall, 2),
            "on_wall_s": round(on_wall, 2),
            "overhead_pct": round(100.0 * overhead, 2),
            "tap_ns_per_key": round(tap_ns, 1),
            "topk_share": round(gauges.get("heat.topk_share", 0.0), 4),
            "shard_imbalance":
                round(gauges.get("heat.shard_imbalance", 0.0), 4),
            "cache_hot_coverage":
                round(gauges.get("heat.cache_hot_coverage", 0.0), 4),
            "working_set_rows":
                round(gauges.get("heat.working_set_rows", 0.0), 1),
            "sketch_bytes": int(sketch_bytes),
            "passes": n_passes, "zipf_a": 1.3}


def _serving_bench(tag):
    """Serving-tier phase: batched-pull QPS + p99 against a live
    ServingReplica over the real wire path (PSClient pipelining, frozen
    tables, per-tenant admission) on a zipf-skewed key stream — the
    inference-side complement of the training headline.  Builds a small
    trained-shaped table, save_xbox's it (rows seeded above the base
    threshold so the dump is non-empty), serves it from a fresh replica,
    and drives the router exactly like an inference frontend would."""
    import shutil as _shutil
    import tempfile as _tempfile

    from paddlebox_tpu.config import EmbeddingTableConfig
    from paddlebox_tpu.io.checkpoint import save_xbox
    from paddlebox_tpu.ps.host_table import ShardedHostTable
    from paddlebox_tpu.ps.serving import ServingReplica, ServingRouter
    from paddlebox_tpu.utils.monitor import stat_snapshot

    n_keys = int(os.environ.get("BENCH_SERVING_KEYS", 50_000))
    n_batches = int(os.environ.get("BENCH_SERVING_BATCHES", 200))
    batch = int(os.environ.get("BENCH_SERVING_BATCH", 2048))
    mf_dim = 8

    cfg = EmbeddingTableConfig(embedding_dim=mf_dim, shard_num=8)
    table = ShardedHostTable(cfg, seed=0)
    rng = np.random.default_rng(11)
    keys = (rng.choice(2 ** 40, n_keys, replace=False)
            .astype(np.uint64))
    rows = table.bulk_pull(keys)
    # score = 0.1*(show-click) + 1.0*click must clear base_threshold
    # (1.5) or save_xbox filters the row and the dump comes out empty
    rows["show"] = rows["show"] + 20.0
    rows["click"] = rows["click"] + 5.0
    rows["mf_size"][:] = mf_dim
    rows["mf"][:] = rng.standard_normal(rows["mf"].shape) \
        .astype(np.float32)
    table.bulk_write(keys, rows)

    class _Eng:
        pass
    eng = _Eng()
    eng.table, eng.config = table, cfg

    root = _tempfile.mkdtemp(prefix="bench_serving_")
    rep = router = None
    try:
        dump = os.path.join(root, "xbox_base")
        save_xbox(eng, dump, base=True)
        t0 = time.perf_counter()
        rep = ServingReplica(config=cfg, xbox_path=dump, port=0)
        load_s = time.perf_counter() - t0
        router = ServingRouter([rep.addr])

        # zipf over the RESIDENT keys (hot-set skew, all hits) plus a
        # tail of misses — the production mix a frontend actually sends
        draws = np.minimum(rng.zipf(1.3, size=(n_batches, batch)),
                           n_keys) - 1
        batches = [keys[d] for d in draws]
        warm = stat_snapshot("serving.")

        def delta(key):
            return (stat_snapshot("serving.").get(key, 0.0)
                    - warm.get(key, 0.0))

        router.pull_sparse(batches[0])          # connect + compile warm
        # QPS is a wall-clock-class metric: three full sweeps, report the
        # median plus the per-run list — --compare only gates a delta
        # that reproduces across a median-of-3 record on both sides
        walls = []
        for run in range(3):
            t0 = time.perf_counter()
            for i, b in enumerate(batches):
                if i % 50 == 0:
                    set_phase(f"{tag}:serving[run {run} "
                              f"{i}/{n_batches}]", 300)
                router.pull_sparse(b)
            walls.append(time.perf_counter() - t0)
        runs = sorted(round(n_batches / max(w, 1e-9), 1) for w in walls)
        wall = sorted(walls)[1]

        snap = stat_snapshot("serving.")
        p99_s = float(snap.get("serving.default.latency_s.p99", 0.0))
        p50_s = float(snap.get("serving.default.latency_s.p50", 0.0))
        queries = delta("serving.default.qps") or float(3 * n_batches)
        shed = delta("serving.default.shed")
        out = {"qps": runs[1], "runs": runs,
               "keys_per_s": round(n_batches * batch / max(wall, 1e-9)),
               "p50_ms": round(p50_s * 1000, 3),
               "p99_ms": round(p99_s * 1000, 3),
               "shed_rate": round(shed / max(queries, 1.0), 4),
               "batch": batch, "batches": n_batches,
               "resident_keys": n_keys, "zipf_a": 1.3,
               "load_s": round(load_s, 3)}
        if os.environ.get("BENCH_SERVING_FLEET", "1") == "1":
            out["fleet"] = _serving_fleet_bench(tag, cfg, dump, keys, rng)
            out["heat_routing"] = _serving_heat_bench(tag, cfg, dump,
                                                      keys, batches)
        if os.environ.get("BENCH_SERVING_FLIP", "1") == "1":
            out["flip"] = _serving_flip_bench(tag)
        return out
    finally:
        if router is not None:
            router.close()
        if rep is not None:
            rep.shutdown()
        _shutil.rmtree(root, ignore_errors=True)


def _serving_fleet_bench(tag, cfg, dump, keys, rng):
    """Sharded-fleet sub-phase: the SAME xbox dump served by a 4-shard
    ServerMap-partitioned fleet (hot set replicated, the full tentpole
    shape) vs one full-table replica, over identical zipf blocks.

    Fleet throughput is the BOTTLENECK-SHARD basis: serving requests are
    independent — there is no cross-request barrier, so steady-state QPS
    is total rounds over the most-loaded shard's TOTAL busy seconds (a
    round's verbs queue behind earlier rounds on the same shard, they do
    not wait for sibling shards).  This differs deliberately from the
    cluster bench's per-round critical path, which models
    barrier-synchronized training fan-outs.  Each verb's service time is
    measured uncontended (min over reps): every replica shares this
    interpreter, so concurrent wall clock would measure GIL contention,
    not serving capacity — the live sharded-router fan is reported
    separately as fan_wall_s.

    Routing mirrors the router exactly: cold keys go to their ServerMap
    owner, the replicated hot bundle goes to ONE group per round,
    rotating round-robin — the balanced-load limit that p2c-over-EWMAs
    converges to when groups are symmetric (the router's actual p2c
    draws are load-feedback-driven and unreproducible across runs;
    rotation is the deterministic stand-in with the same long-run
    per-shard totals)."""
    from paddlebox_tpu.ps import cluster as ps_cluster
    from paddlebox_tpu.ps.serving import ServingReplica, ServingRouter

    n_shards = int(os.environ.get("BENCH_SERVING_FLEET_SHARDS", 4))
    n_rounds = int(os.environ.get("BENCH_SERVING_FLEET_ROUNDS", 30))
    # batch sized like a full mini-batch lookup (1k ads x ~100 slots):
    # big enough that the ~0.7 ms per-verb fixed cost is noise and the
    # response-assembly memory behavior — which is where a full-table
    # replica actually loses to a sharded fleet — shows through
    batch = int(os.environ.get("BENCH_SERVING_FLEET_BATCH", 131072))
    reps = max(1, int(os.environ.get("BENCH_SERVING_FLEET_REPS", 2)))
    n_hot = int(os.environ.get("BENCH_SERVING_HOT", 64))
    n_keys = len(keys)
    hot = np.sort(keys[:n_hot])     # zipf rank order: keys[0] hottest
    blocks = [keys[np.minimum(rng.zipf(1.3, size=batch), n_keys) - 1]
              for _ in range(n_rounds)]

    def split(b):
        """(cold per-shard partitions, hot bundle) of one block."""
        pos = np.minimum(np.searchsorted(hot, b), len(hot) - 1)
        hit = hot[pos] == b
        cold = b[~hit]
        return ([cold[ps_cluster.owned_mask(cold, s, n_shards)]
                 for s in range(n_shards)], b[hit])

    parts = [split(b) for b in blocks]

    solo, fleet, routers = None, [], []
    try:
        solo = ServingReplica(config=cfg, xbox_path=dump, port=0)
        r1 = ServingRouter([solo.addr])
        routers.append(r1)
        fleet = [ServingReplica(config=cfg, xbox_path=dump, shard=s,
                                n_shards=n_shards, hot_keys=hot)
                 for s in range(n_shards)]
        per = [ServingRouter([rep.addr]) for rep in fleet]
        routers.extend(per)
        rfan = ServingRouter(shard_groups=[[rep.addr] for rep in fleet],
                             hot_keys=hot, seed=17)
        routers.append(rfan)

        r1.pull_sparse(blocks[0])               # connect warm, all paths
        rfan.pull_sparse(blocks[0])
        for rt, p in zip(per, parts[0][0]):
            if len(p):
                rt.pull_sparse(p)

        def t_pull(rt, b):
            t0 = time.perf_counter()
            rt.pull_sparse(b)
            return time.perf_counter() - t0

        solo_wall = 0.0
        busy = [0.0] * n_shards
        for i, (b, (cold, hotb)) in enumerate(zip(blocks, parts)):
            if i % 5 == 0:
                set_phase(f"{tag}:serving[fleet {i}/{n_rounds}]", 300)
            solo_wall += min(t_pull(r1, b) for _ in range(reps))
            for s in range(n_shards):
                if len(cold[s]):
                    busy[s] += min(t_pull(per[s], cold[s])
                                   for _ in range(reps))
            if len(hotb):
                g = i % n_shards
                busy[g] += min(t_pull(per[g], hotb) for _ in range(reps))
        bottleneck = max(busy)
        t0 = time.perf_counter()
        for b in blocks:                        # live fan: GIL-contended
            rfan.pull_sparse(b)
        fan_wall = time.perf_counter() - t0
        return {"n_shards": n_shards, "rounds": n_rounds, "batch": batch,
                "hot_keys": n_hot,
                "solo_wall_s": round(solo_wall, 3),
                "bottleneck_busy_s": round(bottleneck, 3),
                "busy_s": [round(x, 3) for x in busy],
                "fan_wall_s": round(fan_wall, 3),
                "solo_qps": round(n_rounds / max(solo_wall, 1e-9), 1),
                "qps": round(n_rounds / max(bottleneck, 1e-9), 1),
                "speedup": round(solo_wall / max(bottleneck, 1e-9), 2)}
    finally:
        for rt in routers:
            rt.close()
        for rep in ([solo] if solo is not None else []) + fleet:
            rep.shutdown()


def _serving_heat_bench(tag, cfg, dump, keys, batches):
    """Heat-replication on/off shard-imbalance comparison over the SAME
    zipf stream the solo phase drove.  The off leg is exact owner
    accounting — heat-off routing is deterministic ServerMap placement,
    so per-shard loads follow from owned_mask with no serving needed.
    The on leg drives a REAL hot-replicated fleet through the sharded
    router from four concurrent threads — p2c balances on LIVE
    outstanding-load feedback, so sequential driving would degenerate it
    to an EWMA tie-break — and the cold part is accounted to its owners
    (still deterministic) while the hot part lands wherever p2c actually
    sent it (the router's own observe_shard taps).  Both legs publish
    through a fresh HeatMap load sketch; the gate is
    imbalance_on < imbalance_off."""
    from paddlebox_tpu.ps import cluster as ps_cluster
    from paddlebox_tpu.ps import heat
    from paddlebox_tpu.ps.serving import ServingReplica, ServingRouter
    from paddlebox_tpu.utils.monitor import stat_get, stat_snapshot

    n_shards = int(os.environ.get("BENCH_SERVING_FLEET_SHARDS", 4))
    n_hot = int(os.environ.get("BENCH_SERVING_HOT", 64))
    hot = np.sort(keys[:n_hot])     # zipf rank order: keys[0] hottest

    def owner_counts(b, counts):
        for s in range(n_shards):
            counts[s] += int(ps_cluster.owned_mask(b, s, n_shards).sum())

    fleet, router = [], None
    heat.disable()
    hm = heat.enable()
    try:
        counts = np.zeros(n_shards)
        for b in batches:               # off leg: everything to its owner
            owner_counts(b, counts)
        for s in range(n_shards):
            hm.observe_shard(s, counts[s])
        imb_off = float(stat_snapshot("heat.")
                        .get("heat.shard_imbalance", 0.0))

        heat.disable()                  # fresh load sketch for the on leg
        hm = heat.enable()
        fleet = [ServingReplica(config=cfg, xbox_path=dump, shard=s,
                                n_shards=n_shards, hot_keys=hot)
                 for s in range(n_shards)]
        router = ServingRouter(shard_groups=[[r.addr] for r in fleet],
                               hot_keys=hot, seed=17)
        routed0 = stat_get("serving.router.hot_routed")
        set_phase(f"{tag}:serving[heat 0/{len(batches)}]", 300)
        errs = []

        def drive(lane):
            try:
                for b in batches[lane::4]:  # hot part: real p2c routing
                    router.pull_sparse(b)
            except Exception as e:          # noqa: BLE001 — surfaced below
                errs.append(repr(e))

        lanes = [threading.Thread(target=drive, args=(ln,))
                 for ln in range(4)]
        for t in lanes:
            t.start()
        for t in lanes:
            t.join(timeout=120)
        if errs:
            raise RuntimeError(f"heat-routing leg failed: {errs[:2]}")
        counts = np.zeros(n_shards)
        hot_n = total = 0
        for b in batches:
            pos = np.searchsorted(hot, b)
            pos = np.minimum(pos, len(hot) - 1)
            cold = b[hot[pos] != b]
            hot_n += len(b) - len(cold)
            total += len(b)
            owner_counts(cold, counts)
        for s in range(n_shards):
            if counts[s]:
                hm.observe_shard(s, counts[s])
        imb_on = float(stat_snapshot("heat.")
                       .get("heat.shard_imbalance", 0.0))
        return {"hot_keys": n_hot,
                "hot_share": round(hot_n / max(total, 1), 4),
                "hot_routed": int(stat_get("serving.router.hot_routed")
                                  - routed0),
                "imbalance_off": round(imb_off, 4),
                "imbalance_on": round(imb_on, 4),
                "imbalance_ratio": round(imb_on / max(imb_off, 1e-9), 4)}
    finally:
        heat.disable()
        if router is not None:
            router.close()
        for rep in fleet:
            rep.shutdown()


def _serving_flip_bench(tag):
    """Streamed-freshness sub-phase: a 4-shard fleet fed by watch_ckpt
    takes save_pass delta generations (base_every=2, so the stream
    crosses a compaction re-base) while router traffic runs — the
    acceptance numbers are ZERO failed requests across every flip and
    the observed serving.staleness_s histogram (commit-to-swap lag)."""
    import shutil as _shutil
    import tempfile as _tempfile

    from paddlebox_tpu.config import EmbeddingTableConfig, SparseSGDConfig
    from paddlebox_tpu.io.checkpoint import TrainCheckpoint
    from paddlebox_tpu.ps.pass_manager import BoxPSEngine
    from paddlebox_tpu.ps.serving import ServingReplica, ServingRouter
    from paddlebox_tpu.utils.monitor import stat_snapshot

    n_shards = 4
    n_gens = int(os.environ.get("BENCH_SERVING_FLIP_GENS", 4))

    class _Dense:
        def __init__(self):
            self.params = {"w": np.zeros(3, np.float32)}
            self.opt_state = {"m": np.zeros((2, 2), np.float32)}

    def grow(ck, eng, tr, p):
        pk = np.unique(np.random.default_rng(p).integers(
            1, 4000, size=600).astype(np.uint64))
        eng.begin_feed_pass()
        eng.add_keys(pk)
        eng.end_feed_pass()
        eng.begin_pass()
        eng.ws["show"] = eng.ws["show"] + float(p + 1)
        eng.end_pass()
        ck.save_pass(eng, tr)

    cfg = EmbeddingTableConfig(
        embedding_dim=4, shard_num=4,
        sgd=SparseSGDConfig(mf_create_thresholds=0.0))
    root = _tempfile.mkdtemp(prefix="bench_serving_flip_")
    fleet, router = [], None
    stop = threading.Event()
    threads = []
    warm = stat_snapshot("serving.")
    try:
        eng = BoxPSEngine(cfg, seed=0)
        eng.set_date("20260807")
        tr = _Dense()
        ck = TrainCheckpoint(root, keep=4, base_every=2)
        ck.save(eng, tr)
        grow(ck, eng, tr, 0)
        fleet = [ServingReplica(config=cfg, ckpt_root=root, shard=s,
                                n_shards=n_shards)
                 for s in range(n_shards)]
        for rep in fleet:
            rep.watch_ckpt(poll_s=0.1)
        router = ServingRouter(shard_groups=[[r.addr] for r in fleet])
        q = np.unique(np.random.default_rng(99).integers(
            1, 4200, size=800).astype(np.uint64))
        errors, pulls = [], [0]

        def traffic():
            while not stop.is_set():
                try:
                    rows = router.pull_sparse(q)
                    if len(rows["embed_w"]) != len(q):
                        errors.append("short read")
                    pulls[0] += 1
                except Exception as e:      # the count IS the metric
                    errors.append(repr(e))

        threads = [threading.Thread(target=traffic) for _ in range(2)]
        for t in threads:
            t.start()
        t0 = time.perf_counter()
        for p in range(1, 1 + n_gens):
            set_phase(f"{tag}:serving[flip {p}/{n_gens}]", 300)
            grow(ck, eng, tr, p)
            time.sleep(0.3)     # every watcher sees THIS head → deltas
        head = ck.head()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not all(
                rep._gen.generation == head for rep in fleet):
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        snap = stat_snapshot("serving.")

        def delta(k):
            return snap.get(k, 0.0) - warm.get(k, 0.0)

        return {"failed_requests": len(errors),
                "pulls_during_flips": int(pulls[0]),
                "flips": int(delta("serving.delta_flip")),
                "converged": bool(all(rep._gen.generation == head
                                      for rep in fleet)),
                "head_generation": int(head),
                "staleness_p50_s": round(float(
                    snap.get("serving.staleness_s.p50", 0.0)), 3),
                "staleness_p99_s": round(float(
                    snap.get("serving.staleness_s.p99", 0.0)), 3),
                "wall_s": round(time.perf_counter() - t0, 3),
                "errors": errors[:3]}
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
        if router is not None:
            router.close()
        for rep in fleet:
            rep.shutdown(drain_timeout=2.0)
        _shutil.rmtree(root, ignore_errors=True)


def _cluster_bench(tag):
    """Sharded-PS phase: aggregate pull+push wire throughput of ONE
    sharded client against N=1 vs N=4 live PS server PROCESSES (real
    sockets, one interpreter per shard — the production fleet shape;
    in-process servers would serialize all table work on this
    interpreter's lock and measure nothing) over IDENTICAL zipf key
    blocks — the ROADMAP item 1 scale-out claim on the CPU basis.

    Fleet throughput is defined by the CRITICAL PATH: with shards on
    independent hosts/cores, a fanned-out verb completes when the
    slowest shard finishes its partition, so aggregate wire throughput
    is total keys / Σ_rounds max_shard(service time), with each shard's
    service time measured uncontended (this bench host may have fewer
    cores than shards — concurrent wall clock there measures core
    contention, not wire capacity, and is reported separately as
    n4.wall_s alongside slowest_shard_stall_s from the live fan-out).
    wire_speedup = t(N=1) / t(N=4 critical path).

    Both sides of that ratio are min-of-k per-round times (k =
    BENCH_CLUSTER_REPS): service time is a property of the work, so any
    slower repeat is interference (this process keeps the timeline
    sampler + obs stack running through every phase), and the per-round
    max-over-shards estimator would otherwise amplify a single stolen
    timeslice into the whole round's cost."""

    import subprocess

    from paddlebox_tpu.ps.cluster import make_server_map
    from paddlebox_tpu.ps.service import PSClient
    from paddlebox_tpu.utils.monitor import stat_snapshot

    n_keys = int(os.environ.get("BENCH_CLUSTER_KEYS", 400_000))
    n_rounds = int(os.environ.get("BENCH_CLUSTER_ROUNDS", 12))
    batch = int(os.environ.get("BENCH_CLUSTER_BATCH", 600_000))
    n_wide = int(os.environ.get("BENCH_CLUSTER_SHARDS", 4))
    n_reps = max(1, int(os.environ.get("BENCH_CLUSTER_REPS", 2)))
    mf_dim = 8

    # identical blocks for both fleet sizes: zipf-ranked draws into one
    # fixed key universe (the production skew both configs must serve)
    rng = np.random.default_rng(23)
    universe = rng.choice(2 ** 40, n_keys, replace=False).astype(np.uint64)
    blocks = [np.unique(universe[
        np.minimum(rng.zipf(1.3, size=batch), n_keys) - 1])
        for _ in range(n_rounds)]

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    def spawn(n):
        """n shard processes; returns (procs, addrs) once all announce."""
        procs = [subprocess.Popen(
            [sys.executable, "-m", "paddlebox_tpu.ps.server_main",
             "--port", "0", "--mf_dim", str(mf_dim), "--seed", "5"],
            cwd=repo, env=env, stdout=subprocess.PIPE, text=True)
            for _ in range(n)]
        addrs = []
        for p in procs:
            line = p.stdout.readline().strip()
            host, _, port = line.rpartition(" ")[2].rpartition(":")
            addrs.append((host, int(port)))
        return procs, addrs

    def reap(procs):
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def verb_round(client, b):
        """One pull+push of block b; → seconds (pull/push are state-
        idempotent after warm, so repeats time the same work)."""
        t0 = time.perf_counter()
        rows = client.pull_sparse(b, create=True)
        client.push_sparse(b, rows)
        return time.perf_counter() - t0

    def timed_rounds(client, label, reps=1):
        """Pull+push every block through one client; each round is the
        min over `reps` repeats; → (wall, keys)."""
        keys_done = 0
        wall = 0.0
        for i, b in enumerate(blocks):
            if i % 4 == 0:
                set_phase(f"{tag}:cluster[{label} {i}/{n_rounds}]", 300)
            wall += min(verb_round(client, b) for _ in range(reps))
            keys_done += 2 * len(b)
        return wall, keys_done

    def drive_one():
        procs, addrs = spawn(1)
        client = None
        try:
            client = PSClient(addrs)
            for b in blocks:                       # warm: resident + conn
                client.pull_sparse(b, create=True)
            wall, keys_done = timed_rounds(client, "n=1", reps=n_reps)
            return {"wall_s": round(wall, 3),
                    "keys_s": round(keys_done / max(wall, 1e-9)),
                    "keys": int(keys_done)}
        finally:
            if client is not None:
                client.close()
            reap(procs)

    def drive_wide():
        procs, addrs = spawn(n_wide)
        smap = make_server_map(addrs)
        fan = None
        per_shard = []
        try:
            fan = PSClient(addrs)
            for b in blocks:                       # warm all shards
                fan.pull_sparse(b, create=True)
            # live concurrent fan-out: exercises _pipeline_sharded +
            # the shared inflight budget, lands slowest_shard_stall_s
            wall, keys_done = timed_rounds(fan, f"n={n_wide}")
            # critical path: each shard serves its partition with the
            # core to itself; a round costs what its slowest shard costs
            per_shard = [PSClient((h, p)) for h, p in addrs]
            parts = [smap.partition(b) for b in blocks]
            critical = 0.0
            for i, (b, pos) in enumerate(zip(blocks, parts)):
                if i % 4 == 0:
                    set_phase(f"{tag}:cluster[crit {i}/{n_rounds}]", 300)
                critical += max(
                    min(verb_round(cl, b[pos[s]]) for _ in range(n_reps))
                    for s, cl in enumerate(per_shard))
            return {"wall_s": round(wall, 3),
                    "keys_s": round(keys_done / max(wall, 1e-9)),
                    "keys": int(keys_done),
                    "critical_path_s": round(critical, 3),
                    "agg_keys_s": round(keys_done / max(critical, 1e-9))}
        finally:
            if fan is not None:
                fan.close()
            for cl in per_shard:
                cl.close()
            reap(procs)

    one = drive_one()
    wide = drive_wide()
    snap = stat_snapshot("ps.cluster.")
    stall = float(snap.get("ps.cluster.slowest_shard_stall_s.max", 0.0))
    return {"n1": one, "n4": wide, "n_shards": n_wide,
            "rounds": n_rounds, "zipf_a": 1.3,
            "ex_s": wide["agg_keys_s"],
            "wire_speedup": round(
                one["wall_s"] / max(wide["critical_path_s"], 1e-9), 2),
            "slowest_shard_stall_s": round(stall, 4)}


def _reshard_bench(tag):
    """Elastic-membership phase: grow a live N=2 PS fleet to N=4 by the
    ps/reshard.py key-range handoff while zipf read+write traffic keeps
    flowing against the NON-moving key range, and measure what the
    migration actually costs the fleet:

      cutover_stall_ms    — freeze-to-commit window (the only interval
                            where moving-range writes block)
      moved_rows_per_s    — snapshot + delta shipping rate
      nonmoving_qps_drop  — fractional traffic-rate drop during the
                            migration vs the pre-migration baseline;
                            the graceful-degradation claim is that
                            non-moving shards keep serving, so this
                            should stay near 0

    Real server processes (same reasons as _cluster_bench), old members
    started epoch-0 legacy (the production bootstrap shape: a fleet that
    never resharded), new members started PENDING (``--shard -1`` with
    the old membership — they answer typed redirects until the cutover
    admits them).  The traffic client discovers the cutover organically
    through wrong_epoch redirects — the same path production clients
    take — so the qps trace also covers the refresh-and-re-drive cost."""

    import subprocess
    import tempfile

    from paddlebox_tpu.ps import cluster as ps_cluster
    from paddlebox_tpu.ps.reshard import reshard
    from paddlebox_tpu.ps.service import PSClient
    from paddlebox_tpu.utils.monitor import stat_snapshot

    n_keys = int(os.environ.get("BENCH_RESHARD_KEYS", 200_000))
    n_old = int(os.environ.get("BENCH_RESHARD_OLD", 2))
    n_new = int(os.environ.get("BENCH_RESHARD_NEW", 4))
    batch = int(os.environ.get("BENCH_RESHARD_BATCH", 50_000))
    warm_s = float(os.environ.get("BENCH_RESHARD_WARM_S", 2.0))
    mf_dim = 8

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    def spawn(n, extra=()):
        procs = [subprocess.Popen(
            [sys.executable, "-m", "paddlebox_tpu.ps.server_main",
             "--port", "0", "--mf_dim", str(mf_dim), "--seed", "5",
             *extra],
            cwd=repo, env=env, stdout=subprocess.PIPE, text=True)
            for _ in range(n)]
        addrs = []
        for p in procs:
            line = p.stdout.readline().strip()
            host, _, port = line.rpartition(" ")[2].rpartition(":")
            addrs.append((host, int(port)))
        return procs, addrs

    def reap(procs):
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    set_phase(f"{tag}:reshard[spawn]", 120)
    old_procs, old_addrs = spawn(n_old)
    new_procs = []
    client = None
    stop = threading.Event()
    samples = []                        # (t_done, keys) per traffic round
    errors = []
    try:
        client = PSClient(old_addrs, retries=None, deadline=120)
        rng = np.random.default_rng(29)
        universe = rng.choice(2 ** 40, n_keys,
                              replace=False).astype(np.uint64)
        set_phase(f"{tag}:reshard[seed]", 300)
        client.pull_sparse(universe, create=True)   # materialize rows

        new_procs, grown = spawn(
            n_new - n_old,
            extra=("--membership", ps_cluster.format_addrs(old_addrs),
                   "--epoch", "0", "--shard", "-1"))
        union = list(old_addrs) + grown
        old_map = client.server_map
        target = ps_cluster.make_server_map(union)   # partition preview
        moving = (target.shard_of_keys(universe)
                  != old_map.shard_of_keys(universe))
        stay = universe[~moving]
        blocks = [np.unique(stay[
            np.minimum(rng.zipf(1.3, size=batch), len(stay)) - 1])
            for _ in range(8)]

        def traffic():
            cl = PSClient(old_addrs, retries=None, retry_sleep=0.02,
                          backoff_cap=0.25, deadline=60)
            try:
                i = 0
                while not stop.is_set():
                    b = blocks[i % len(blocks)]
                    rows = cl.pull_sparse(b)
                    cl.push_sparse(b, rows)
                    samples.append((time.perf_counter(), 2 * len(b)))
                    i += 1
            except Exception as e:      # noqa: BLE001 — reported below
                errors.append(e)
            finally:
                cl.close()

        t_start = time.perf_counter()
        pump = threading.Thread(target=traffic, name="reshard-traffic",
                                daemon=True)
        pump.start()
        time.sleep(warm_s)              # pre-migration qps baseline

        set_phase(f"{tag}:reshard[migrate {n_old}->{n_new}]", 300)
        workdir = tempfile.mkdtemp(prefix="bench-reshard-")
        t0 = time.perf_counter()
        reshard(client, union, workdir, rounds=2, timeout=120)
        t1 = time.perf_counter()
        time.sleep(min(warm_s, 1.0))    # post-cutover redirect recovery
        stop.set()
        pump.join(timeout=60)
        if errors:
            raise errors[0]

        def rate(lo, hi):
            keys = sum(k for t, k in samples if lo <= t < hi)
            return keys / max(hi - lo, 1e-9)

        qps_before = rate(t_start + 0.25, t0)
        qps_during = rate(t0, t1)
        drop = max(0.0, 1.0 - qps_during / max(qps_before, 1e-9))
        snap = stat_snapshot("ps.reshard.")
        moved = float(snap.get("ps.reshard.rows_moved", 0.0))
        stall = float(snap.get("ps.reshard.cutover_stall_ms.max", 0.0))
        return {"cutover_stall_ms": round(stall, 2),
                "moved_rows_per_s": round(moved / max(t1 - t0, 1e-9)),
                "nonmoving_qps_drop": round(drop, 4),
                "moved_rows": int(moved),
                "migrate_s": round(t1 - t0, 3),
                "qps_before": round(qps_before),
                "qps_during": round(qps_during),
                "epoch": int(client.server_map.epoch),
                "n_old": n_old, "n_new": n_new, "keys": n_keys}
    finally:
        stop.set()
        if client is not None:
            client.close()
        reap(old_procs + new_procs)


def _multi_trainer_bench(tag):
    """Trainer-fleet phase: N=1 vs N=4 REAL subprocess trainers (one OS
    process per rank — trainer/fleet_main.py — against an M=2 subprocess
    PS cluster) over IDENTICAL zipf-keyed day files, the ISSUE-17
    data-parallel scale-out claim.

    Scaling is defined on the CRITICAL-PATH basis, same discipline as
    _cluster_bench: on a host with fewer cores than ranks, concurrent
    wall clock measures core timesharing, not fleet capacity.  Each rank
    reports its own process CPU seconds for the measured lap (fleet_main
    --warm runs the schedule once un-timed first, so jit compile and PS
    row creation are excluded), a blocked rank burns no CPU, and the
    fleet finishes when its busiest rank does:

        scaling = cpu_s(N=1) / max_rank(cpu_s(N=4))

    The chaos rep re-runs at N=2 with a seeded mid-allreduce kill of
    rank 1; its supervisor restart lands restart_mttr_s (observed death
    to the replacement incarnation entering run())."""

    import subprocess
    import tempfile

    n_files = int(os.environ.get("BENCH_MT_FILES", 8))
    rows = int(os.environ.get("BENCH_MT_ROWS", 1500))
    n_wide = int(os.environ.get("BENCH_MT_TRAINERS", 4))
    m_shards = int(os.environ.get("BENCH_MT_SHARDS", 2))
    chaos = os.environ.get("BENCH_MT_CHAOS", "1") == "1"
    mf_dim, n_slots, dense_dim, vocab = 4, 3, 2, 600
    zipf_a = 1.3

    tmp = tempfile.mkdtemp(prefix="bench-mt-")
    rng = np.random.default_rng(29)
    files = []
    for i in range(n_files):
        path = os.path.join(tmp, f"day0-f{i}.txt")
        with open(path, "w") as f:
            for _ in range(rows):
                parts = [
                    f"1 {int(rng.random() < 0.5)}",
                    "2 " + " ".join(f"{d:.4f}"
                                    for d in rng.normal(0, 1, dense_dim))]
                for s in range(n_slots):
                    kk = np.minimum(
                        rng.zipf(zipf_a, size=int(rng.integers(1, 3))),
                        vocab)
                    parts.append(f"{len(kk)} " + " ".join(
                        str(s * 1000 + int(k)) for k in kk))
                f.write(" ".join(parts) + "\n")
        files.append(path)
    days = [["20260701", [files[:n_files // 2], files[n_files // 2:]]]]
    examples = n_files * rows            # each file trained once per lap
    spec_path = os.path.join(tmp, "spec.json")
    with open(spec_path, "w") as f:
        json.dump({"days": days, "n_slots": n_slots, "mf_dim": mf_dim,
                   "dense_dim": dense_dim}, f)

    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    def spawn_ps(n):
        procs = [subprocess.Popen(
            [sys.executable, "-m", "paddlebox_tpu.ps.server_main",
             "--port", "0", "--mf_dim", str(mf_dim), "--seed", "5"],
            cwd=repo, env=env, stdout=subprocess.PIPE, text=True)
            for _ in range(n)]
        addrs = []
        for p in procs:
            line = p.stdout.readline().strip()
            host, _, port = line.rpartition(" ")[2].rpartition(":")
            addrs.append((host, int(port)))
        return procs, addrs

    def reap(procs):
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    # fixed trainer ports BELOW the ephemeral range: a supervisor-
    # restarted rank re-binds its OWN address, which must not be
    # squattable as some outbound connection's local port
    port_base = [27100]

    def free_ports(n):
        import socket as _socket
        out = []
        while len(out) < n:
            port_base[0] += 1
            try:
                s = _socket.socket()
                s.bind(("127.0.0.1", port_base[0]))
                s.close()
                out.append(port_base[0])
            except OSError:
                pass
        return out

    def run_fleet(world, label, fault_site=None, fault_rank=None):
        set_phase(f"{tag}:multi_trainer[{label}]", 900)
        ps_procs, ps_addrs = spawn_ps(m_shards)
        try:
            ps_csv = ",".join(f"{h}:{p}" for h, p in ps_addrs)
            tr_csv = ",".join(f"127.0.0.1:{p}" for p in free_ports(world))
            procs = []
            for r in range(world):
                cmd = [sys.executable, "-m",
                       "paddlebox_tpu.trainer.fleet_main",
                       "--rank", str(r), "--world", str(world),
                       "--ps", ps_csv,
                       "--workdir", os.path.join(tmp, f"wd-{label}"),
                       "--spec", spec_path, "--virtual_shards", "4",
                       "--table_seed", "5", "--warm"]
                if world > 1:
                    cmd += ["--trainer_addrs", tr_csv]
                if fault_site is not None and r == fault_rank:
                    cmd += ["--fault_site", fault_site]
                procs.append(subprocess.Popen(
                    cmd, cwd=repo, env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.DEVNULL, text=True))
            reports = {}
            for r, p in enumerate(procs):
                out, _ = p.communicate(timeout=900)
                lines = [ln for ln in out.splitlines()
                         if ln.startswith("FLEETMAIN ")]
                if p.returncode != 0 or not lines:
                    raise RuntimeError(
                        f"trainer rank {r} ({label}) failed "
                        f"(rc={p.returncode})")
                reports[r] = json.loads(lines[-1][len("FLEETMAIN "):])
            return reports
        finally:
            reap(ps_procs)

    def delta(rep, key):
        return (float(rep["stats"].get(key, 0.0))
                - float(rep["stats_warm"].get(key, 0.0)))

    one = run_fleet(1, "n=1")
    wide = run_fleet(n_wide, f"n={n_wide}")

    busy1 = float(one[0]["cpu_s"])
    critical = max(float(r["cpu_s"]) for r in wide.values())
    tx = sum(delta(r, "trainer.fleet.shuffle_tx_bytes")
             for r in wide.values())
    shuffle_s = max(delta(r, "trainer.fleet.shuffle_s.sum")
                    for r in wide.values())
    p99 = max(float(r["stats"].get("trainer.fleet.barrier_wait_s.p99",
                                   0.0)) for r in wide.values())
    out = {"n1": {"cpu_s": round(busy1, 3),
                  "wall_s": one[0]["wall_s"],
                  "ex_s": round(examples / max(busy1, 1e-9))},
           "n4": {"critical_cpu_s": round(critical, 3),
                  "wall_s": max(r["wall_s"] for r in wide.values()),
                  "ex_s": round(examples / max(critical, 1e-9))},
           "n_trainers": n_wide, "ps_shards": m_shards,
           "examples": int(examples), "zipf_a": zipf_a,
           "scaling": round(busy1 / max(critical, 1e-9), 2),
           "shuffle_mb_s": round(tx / 1e6 / max(shuffle_s, 1e-9), 2),
           "barrier_wait_p99": round(p99, 4)}
    if chaos:
        ch = run_fleet(2, "chaos", fault_site="fleet_allreduce",
                       fault_rank=1)
        out["restart_mttr_s"] = round(float(
            ch[1]["stats"].get("trainer.fleet.restart_mttr_s.max", 0.0)),
            3)
        out["chaos_restarts"] = int(ch[1]["restarts"])
    return out


def run_config(tag, batch_size, n_batches, n_keys, pack_threads):
    """One full bench at a given geometry.  Returns the results dict;
    records partials into _STATE as they are measured."""
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.config import (DataFeedConfig, EmbeddingTableConfig,
                                      SlotConfig, SparseSGDConfig)
    from paddlebox_tpu.data.dataset import SlotDataset
    from paddlebox_tpu.models.deepfm import DeepFM
    from paddlebox_tpu.ps.pass_manager import BoxPSEngine
    from paddlebox_tpu.trainer.trainer import SparseTrainer

    N_SLOTS, DENSE_DIM, MF_DIM, CAP = 26, 13, 8, 3
    STEPS_WARM = 5

    try:      # each geometry is a fresh model: restart the AUC trajectory
        from paddlebox_tpu.metrics import quality
        quality.reset()
    except Exception:
        pass
    # ... and a fresh timeline ring: the smoke config's gauges must not
    # read as drops/collapses inside this config's watchdog window
    _start_timeline(restart=True)

    set_phase(f"{tag}:data-build", 240)
    rng = np.random.default_rng(0)
    dataset = SlotDataset(DataFeedConfig(slots=tuple(
        [SlotConfig("label", dtype="float", is_dense=True, dim=1),
         SlotConfig("dense0", dtype="float", is_dense=True, dim=DENSE_DIM)]
        + [SlotConfig(f"s{i}", slot_id=100 + i, capacity=CAP)
           for i in range(N_SLOTS)])))
    dataset._blocks = _make_blocks(
        rng, n_batches * batch_size, [f"s{i}" for i in range(N_SLOTS)],
        n_keys, DENSE_DIM, CAP)

    set_phase(f"{tag}:pass-build", 420)
    engine = BoxPSEngine(EmbeddingTableConfig(
        embedding_dim=MF_DIM, shard_num=8,
        sgd=SparseSGDConfig(mf_create_thresholds=0.0)))
    engine.begin_feed_pass()
    for blk in dataset.get_blocks():
        engine.add_keys(blk.all_keys())
    engine.end_feed_pass()
    engine.begin_pass()
    # steady-state assumption: all mf created, full-width embeddings train
    engine.ws["mf_size"] = jnp.full_like(engine.ws["mf_size"], MF_DIM)
    trace(f"{tag}: working set rows={engine.num_keys}")

    model = DeepFM(num_slots=N_SLOTS, emb_width=3 + MF_DIM,
                   dense_dim=DENSE_DIM, hidden=(400, 400, 400))
    # amp: bf16 dense compute with f32 master weights (the fleet amp
    # meta-optimizer ≙) — MXU-native precision for the MLP
    amp = os.environ.get("BENCH_AMP", "1") == "1"
    legacy = os.environ.get("BENCH_LEGACY_FEED") == "1"
    # sparse step path: ragged (CSR [U]-domain kernels, ROADMAP item 1) is
    # the default for the pass-resident feed; the legacy streaming feed
    # can't carry a CSR plan, so it stays on the auto (mxu) resolution
    sparse_path = os.environ.get("BENCH_SPARSE_PATH",
                                 "auto" if legacy else "ragged")
    trainer = SparseTrainer(engine, model, dataset.feed_config,
                            batch_size=batch_size, auc_table_size=100_000,
                            amp=amp, sparse_path=sparse_path)
    resolved = trainer._resolve_path()
    assert resolved == ("mxu" if sparse_path == "auto" else sparse_path), \
        resolved
    record(**{f"{tag}_sparse_path": resolved})

    # pass-resident feed: pack + translate + upload + plans at pass-build
    # time (≙ SlotPaddleBoxDataFeed feed-time GPU pack + DedupKeysAndFillIdx,
    # data_feed.cu:1210-1318 / box_wrapper_impl.h:129)
    feed = None
    pack_s = 0.0
    trim_frac = 1.0
    if not legacy:
        t0 = time.perf_counter()
        feed = trainer.build_pass_feed(dataset)
        jax.block_until_ready(next(iter(feed.plans.values()))
                              if feed.plans else feed.data["indices"])
        pack_s = time.perf_counter() - t0
        if feed.plans is not None and "rows2d" in feed.plans:
            # kept fraction of the sorted domain after padding-trim
            # (sorted_spmm.trimmed_dims) — the kernel/push-crossing work
            # scales with this; plan_dims holds the untrimmed geometry
            # (mxu plans only; ragged CSR plans have no trimmed domain)
            trim_frac = (feed.plans["rows2d"].shape[1]
                         / feed.plan_dims.n_chunks)
        record(**{f"{tag}_pass_pack_s": round(pack_s, 1),
                  f"{tag}_trim_frac": round(trim_frac, 3)})
        trace(f"{tag}: pass feed built in {pack_s:.1f}s "
              f"({feed.device_bytes() / 1e6:.0f} MB device-resident, "
              f"trim_frac={trim_frac:.3f})")

    set_phase(f"{tag}:compile", 600)
    ws, params = engine.ws, trainer.params
    opt_state, auc_state = trainer.opt_state, trainer.auc_state
    tc = time.perf_counter()
    if legacy:
        trainer._build_step()
        first = dataset.get_blocks()[0].slice(0, batch_size)
        batch = trainer.packer.pack(first, key_mapper=engine.mapper)
        dev = trainer._put_batch(batch)

        def one_step(w, p, o, a):
            return trainer._step_fn(w, p, o, a, *dev)
    else:
        trainer._build_packed_step(feed)
        i0 = np.int32(0)
        plans = feed.plans if feed.plans is not None else {}

        def one_step(w, p, o, a):
            return trainer._packed_step_fn(w, p, o, a, i0, feed.data, plans)

    ws, params, opt_state, auc_state, loss, _p = one_step(
        ws, params, opt_state, auc_state)
    jax.block_until_ready(loss)
    compile_s = time.perf_counter() - tc
    record(**{f"{tag}_compile_s": round(compile_s, 1)})
    trace(f"{tag}: step compiled+first-run in {compile_s:.1f}s")

    # -- device_step: steady-state jitted step, one re-fed batch -----------
    set_phase(f"{tag}:device-step", 300)
    for _ in range(STEPS_WARM):
        ws, params, opt_state, auc_state, loss, _p = one_step(
            ws, params, opt_state, auc_state)
    jax.block_until_ready(loss)
    trace(f"{tag}: warm done")
    t0 = time.perf_counter()
    for _ in range(n_batches):
        ws, params, opt_state, auc_state, loss, _p = one_step(
            ws, params, opt_state, auc_state)
    jax.block_until_ready(loss)
    device_eps = batch_size * n_batches / (time.perf_counter() - t0)
    record(**{("device_step" if tag == "full" else f"{tag}_device_step"):
              round(device_eps, 1)})
    trace(f"{tag}: device_step={device_eps:,.0f} ex/s")
    engine.ws = ws
    trainer.params = params
    trainer.opt_state = opt_state
    # the warmup steps above accumulated the same batch into auc_state;
    # start the measured pass clean so the reported AUC is honest
    trainer.reset_metrics()

    # -- end_to_end: the real train_pass loop ------------------------------
    set_phase(f"{tag}:e2e", 600)
    n_examples = dataset.instance_num()

    def heartbeat(n):
        # refresh the phase budget too: forward progress is not a hang
        set_phase(f"{tag}:e2e[batch {n}/{n_batches}]", 120)

    t0 = time.perf_counter()
    m0 = time.monotonic()
    if legacy:
        stats = trainer.train_pass(dataset, prefetch=8,
                                   pack_threads=pack_threads,
                                   progress=heartbeat)
    else:
        stats = trainer.train_pass(feed, progress=heartbeat)
    dt = time.perf_counter() - t0
    _quality_observe(stats)
    e2e_eps = n_examples / dt
    record(**{("e2e" if tag == "full" else f"{tag}_e2e"): round(e2e_eps, 1)})
    trace(f"{tag}: e2e={e2e_eps:,.0f} ex/s over {dt:.1f}s")

    # interval-level feed-gap attribution over the e2e window (report()
    # clips to [m0, now], so earlier phases' intervals don't leak in)
    feed_rep = {}
    try:
        from paddlebox_tpu.utils import intervals
        feed_rep = intervals.report(since=m0)
        trace(f"{tag}: device_busy_frac={feed_rep['device_busy_frac']:.3f} "
              f"feed_gap_ratio={feed_rep['feed_gap_ratio']:.2f}")
    except Exception as e:  # attribution is diagnostic, never fatal
        trace(f"{tag}: interval report failed: {type(e).__name__}: {e}")
    record(**{f"{tag}_device_busy_frac":
              round(feed_rep.get("device_busy_frac", 0.0), 4)})

    step_ms = {}
    if tag == "full" and not legacy \
            and os.environ.get("BENCH_STEP_PROFILE", "1") == "1":
        set_phase(f"{tag}:step-profile", 600)  # two paths profiled
        try:
            step_ms = _profile_step_phases(trainer, feed)
            trace(f"{tag}: step phases {step_ms}")
        except Exception as e:  # profile is diagnostic, never fatal
            trace(f"{tag}: step profile failed: {type(e).__name__}: {e}")

    pass_cycle = {}
    if tag == "full" and not legacy \
            and os.environ.get("BENCH_PASS_CYCLE", "1") == "1":
        set_phase(f"{tag}:pass-cycle", 900)
        try:
            pass_cycle = _pass_cycle(
                tag, dataset, engine, trainer,
                int(os.environ.get("BENCH_E2E_PASSES", 2)))
            record(pass_cycle_speedup=pass_cycle["speedup"],
                   pass_cycle_serial_eps=pass_cycle["serial"]["ex_s"],
                   pass_cycle_pipelined_eps=pass_cycle["pipelined"]["ex_s"])
            trace(f"{tag}: pass-cycle serial={pass_cycle['serial']['ex_s']:,.0f}"
                  f" ex/s (gap {pass_cycle['serial']['feed_gap_ratio']:.2f})"
                  f" pipelined={pass_cycle['pipelined']['ex_s']:,.0f} ex/s"
                  f" (gap {pass_cycle['pipelined']['feed_gap_ratio']:.2f})"
                  f" speedup={pass_cycle['speedup']:.2f}x")
            if not pass_cycle["feed_gap_improved"]:
                trace(f"{tag}: WARNING pass-cycle feed_gap_ratio did not "
                      "improve with the pipeline on")
        except Exception as e:  # comparison is diagnostic, never fatal
            trace(f"{tag}: pass-cycle failed: {type(e).__name__}: {e}")

    recovery = {}
    if tag == "full" and not legacy \
            and os.environ.get("BENCH_RECOVERY", "1") == "1":
        set_phase(f"{tag}:recovery-drill", 600)
        try:
            recovery = _recovery_drill(tag, dataset, engine, trainer)
            record(mttr_s=recovery["mttr_s"])
            trace(f"{tag}: recovery drill mttr_s={recovery['mttr_s']:.3f} "
                  f"(ckpt save {recovery['save_s']:.3f}s restore "
                  f"{recovery['restore_s']:.3f}s gen {recovery['generation']})")
        except Exception as e:  # drill is diagnostic, never fatal
            trace(f"{tag}: recovery drill failed: {type(e).__name__}: {e}")

    cache_cmp = {}
    if tag == "full" and not legacy \
            and os.environ.get("BENCH_CACHE", "1") == "1":
        set_phase(f"{tag}:cache-compare", 600)
        try:
            cache_cmp = _cache_compare(tag)
            record(cache_hit_rate=cache_cmp["hit_rate"],
                   cache_wire_reduction=cache_cmp["wire_reduction"])
            trace(f"{tag}: cache-compare hit_rate="
                  f"{cache_cmp['hit_rate']:.3f} wire_rows "
                  f"{cache_cmp['off']['wire_rows']:,} -> "
                  f"{cache_cmp['on']['wire_rows']:,} "
                  f"({cache_cmp['wire_reduction']:.2f}x reduction, "
                  f"{cache_cmp['wire_bytes_saved'] / 1e6:.1f} MB saved)")
            if cache_cmp["wire_reduction"] < 2.0:
                trace(f"{tag}: WARNING cache wire-row reduction below the "
                      "2x acceptance floor on the zipf workload")
        except Exception as e:  # comparison is diagnostic, never fatal
            trace(f"{tag}: cache-compare failed: {type(e).__name__}: {e}")

    heat_cmp = {}
    if tag == "full" and not legacy \
            and os.environ.get("BENCH_HEAT", "1") == "1":
        set_phase(f"{tag}:heat", 600)
        try:
            heat_cmp = _heat_bench(tag)
            record(heat_tap_ns_per_key=heat_cmp["tap_ns_per_key"],
                   heat_shard_imbalance=heat_cmp["shard_imbalance"])
            trace(f"{tag}: heat tap={heat_cmp['tap_ns_per_key']:.0f}ns/key "
                  f"(wall {heat_cmp['overhead_pct']:+.1f}% of the "
                  f"engine-only cycle) "
                  f"topk_share={heat_cmp['topk_share']:.3f} "
                  f"shard_imbalance={heat_cmp['shard_imbalance']:.2f} "
                  f"ws_rows={heat_cmp['working_set_rows']:,.0f} "
                  f"hot_coverage={heat_cmp['cache_hot_coverage']:.3f} "
                  f"({heat_cmp['sketch_bytes'] / 1e3:.0f} KB sketches)")
            if heat_cmp["tap_ns_per_key"] > 250.0:
                trace(f"{tag}: WARNING heat tap cost above the "
                      "250 ns/key budget")
        except Exception as e:  # phase is diagnostic, never fatal
            trace(f"{tag}: heat bench failed: {type(e).__name__}: {e}")

    serving = {}
    if tag == "full" and not legacy \
            and os.environ.get("BENCH_SERVING", "1") == "1":
        set_phase(f"{tag}:serving", 600)
        try:
            serving = _serving_bench(tag)
            record(serving_qps=serving["qps"],
                   serving_p99_ms=serving["p99_ms"])
            trace(f"{tag}: serving qps={serving['qps']:.1f} "
                  f"(median of {serving['runs']}; "
                  f"{serving['keys_per_s']:,} keys/s) "
                  f"p99={serving['p99_ms']:.2f}ms "
                  f"shed_rate={serving['shed_rate']:.4f}")
            flt = serving.get("fleet") or {}
            if flt:
                record(serving_fleet_speedup=flt["speedup"])
                trace(f"{tag}: serving fleet n{flt['n_shards']}="
                      f"{flt['qps']:.1f} qps (critical-path basis) vs "
                      f"solo {flt['solo_qps']:.1f} "
                      f"speedup={flt['speedup']:.2f}x "
                      f"fan_wall={flt['fan_wall_s']:.2f}s")
                if flt["speedup"] < 3.0:
                    trace(f"{tag}: WARNING serving fleet speedup below "
                          "the 3x acceptance floor at N=4")
            flip = serving.get("flip") or {}
            if flip:
                record(serving_staleness_p99_s=flip["staleness_p99_s"])
                trace(f"{tag}: serving flip head="
                      f"{flip['head_generation']} "
                      f"flips={flip['flips']} "
                      f"failed={flip['failed_requests']} "
                      f"pulls={flip['pulls_during_flips']} "
                      f"staleness_p99={flip['staleness_p99_s']:.2f}s")
                if flip["failed_requests"]:
                    trace(f"{tag}: WARNING requests failed during the "
                          "streamed delta flip")
            hr = serving.get("heat_routing") or {}
            if hr:
                trace(f"{tag}: serving heat routing shard_imbalance "
                      f"{hr['imbalance_off']:.2f} -> "
                      f"{hr['imbalance_on']:.2f} "
                      f"(ratio {hr['imbalance_ratio']:.2f}, "
                      f"hot_share {hr['hot_share']:.2f})")
                if hr["imbalance_ratio"] >= 1.0:
                    trace(f"{tag}: WARNING hot-key replication did not "
                          "cut shard imbalance")
        except Exception as e:  # phase is diagnostic, never fatal
            trace(f"{tag}: serving bench failed: {type(e).__name__}: {e}")

    cluster = {}
    if tag == "full" and not legacy \
            and os.environ.get("BENCH_CLUSTER", "1") == "1":
        set_phase(f"{tag}:cluster", 600)
        try:
            cluster = _cluster_bench(tag)
            record(cluster_wire_speedup=cluster["wire_speedup"],
                   cluster_ex_s=cluster["ex_s"])
            trace(f"{tag}: cluster n1={cluster['n1']['keys_s']:,} keys/s "
                  f"n{cluster['n_shards']}={cluster['n4']['agg_keys_s']:,} "
                  f"keys/s (critical-path basis) "
                  f"wire_speedup={cluster['wire_speedup']:.2f}x "
                  f"stall={cluster['slowest_shard_stall_s']:.4f}s")
            if cluster["wire_speedup"] < 2.0:
                trace(f"{tag}: WARNING cluster wire speedup below the 2x "
                      "acceptance floor at N=4")
        except Exception as e:  # phase is diagnostic, never fatal
            trace(f"{tag}: cluster bench failed: {type(e).__name__}: {e}")

    reshard = {}
    if tag == "full" and not legacy \
            and os.environ.get("BENCH_RESHARD", "1") == "1":
        set_phase(f"{tag}:reshard", 600)
        try:
            reshard = _reshard_bench(tag)
            record(reshard_stall_ms=reshard["cutover_stall_ms"],
                   reshard_qps_drop=reshard["nonmoving_qps_drop"])
            trace(f"{tag}: reshard {reshard['n_old']}->{reshard['n_new']} "
                  f"moved {reshard['moved_rows']:,} rows "
                  f"({reshard['moved_rows_per_s']:,}/s) "
                  f"cutover_stall={reshard['cutover_stall_ms']:.1f}ms "
                  f"nonmoving_qps_drop={reshard['nonmoving_qps_drop']:.3f}")
            if reshard["nonmoving_qps_drop"] > 0.5:
                trace(f"{tag}: WARNING non-moving traffic lost more than "
                      "half its rate during the live handoff")
        except Exception as e:  # phase is diagnostic, never fatal
            trace(f"{tag}: reshard bench failed: {type(e).__name__}: {e}")

    multi_trainer = {}
    if tag == "full" and not legacy \
            and os.environ.get("BENCH_MT", "1") == "1":
        set_phase(f"{tag}:multi_trainer", 900)
        try:
            multi_trainer = _multi_trainer_bench(tag)
            record(mt_scaling=multi_trainer["scaling"],
                   mt_ex_s=multi_trainer["n4"]["ex_s"])
            trace(f"{tag}: multi_trainer n1={multi_trainer['n1']['ex_s']:,}"
                  f" ex/s n{multi_trainer['n_trainers']}="
                  f"{multi_trainer['n4']['ex_s']:,} ex/s (critical-path "
                  f"cpu basis) scaling={multi_trainer['scaling']:.2f}x "
                  f"shuffle={multi_trainer['shuffle_mb_s']:.1f}MB/s "
                  f"barrier_p99={multi_trainer['barrier_wait_p99']:.3f}s "
                  f"mttr={multi_trainer.get('restart_mttr_s', 0.0):.2f}s")
            if multi_trainer["scaling"] < 2.0:
                trace(f"{tag}: WARNING multi_trainer scaling below the "
                      "2x acceptance floor at N=4")
        except Exception as e:  # phase is diagnostic, never fatal
            trace(f"{tag}: multi_trainer bench failed: "
                  f"{type(e).__name__}: {e}")

    return {"e2e": e2e_eps, "device_step": device_eps,
            "pass_cycle": pass_cycle, "recovery": recovery,
            "cache": cache_cmp, "heat": heat_cmp, "serving": serving,
            "cluster": cluster,
            "reshard": reshard, "multi_trainer": multi_trainer,
            "batches": int(stats["batches"]), "examples": int(n_examples),
            "auc": round(float(stats.get("auc", float("nan"))), 4),
            "compile_s": round(compile_s, 1), "pass_pack_s": round(pack_s, 1),
            "amp": amp, "step_ms": step_ms, "trim_frac": round(trim_frac, 3),
            "device_busy_frac": round(feed_rep.get("device_busy_frac", 0.0), 4),
            "feed_gap_ratio": round(feed_rep.get("feed_gap_ratio", 0.0), 2),
            "feed_intervals": {k: round(v, 3)
                               for k, v in sorted(feed_rep.items())},
            "timers": trainer.timers.report()}


def run() -> None:
    B = int(os.environ.get("BENCH_BATCH_SIZE", 16384))
    N_BATCHES = int(os.environ.get("BENCH_BATCHES", 30))
    N_KEYS = int(os.environ.get("BENCH_KEYS", 2_000_000))
    PACK_THREADS = int(os.environ.get(
        "BENCH_PACK_THREADS", min(8, os.cpu_count() or 1)))

    # backend-init gets its OWN short budget (just under the supervisor's
    # attempt window, so the child watchdog fires first and reports
    # last_phase="backend-init" cleanly instead of dying to an outside
    # SIGKILL with no output).  BENCH_r05 burned all 1466s on 10 wedged
    # attempts precisely because init owned the whole budget; now a
    # wedged init ends the attempt in ~2min and the supervisor's CPU
    # fallback gets its turn while real budget remains
    attempt_s = float(os.environ.get("BENCH_BACKEND_ATTEMPT_S", 150))
    set_phase("backend-init", max(attempt_s - 10, 20))
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # local validation: the image's sitecustomize pins the 'axon' TPU
        # platform even when JAX_PLATFORMS=cpu; override via jax.config
        # before backend init (same workaround as tests/conftest.py)
        import jax
        jax.config.update("jax_platforms", "cpu")
    devices = _init_devices()
    backend = devices[0].platform
    trace(f"backend up: {backend} x{len(devices)}")
    # partial evidence the instant the backend answers — if everything
    # later wedges, the recorded round still proves the chip was reachable
    record(backend=backend, n_devices=len(devices))
    emit(0.0, stage="backend-up", backend=backend, n_devices=len(devices))
    _start_timeline()
    fail = os.environ.get("BENCH_TEST_FAIL_AFTER_INIT")
    if fail:    # harness-test hook: deterministic post-backend failure
        raise RuntimeError(fail)
    if os.environ.get("BENCH_TEST_WEDGE_PHASE") == "1":
        # harness-test hook: a post-backend wedge with a recognizably
        # named stuck thread — exercises watchdog → postmortem → error
        # line end to end (the postmortem must name phase and thread)
        def _wedge_sleep():     # python frame so the postmortem shows it
            time.sleep(10 ** 6)
        threading.Thread(target=_wedge_sleep,
                         name="wedge-sleeper", daemon=True).start()
        set_phase("wedge-sim",
                  float(os.environ.get("BENCH_TEST_WEDGE_BUDGET_S", 3)))
        time.sleep(10 ** 6)

    if os.environ.get("BENCH_SKIP_SMOKE") != "1":
        smoke = run_config(
            "smoke",
            int(os.environ.get("BENCH_SMOKE_BATCH", 1024)),
            int(os.environ.get("BENCH_SMOKE_BATCHES", 2)),
            int(os.environ.get("BENCH_SMOKE_KEYS", 100_000)), 1)
        smoke_only = os.environ.get("BENCH_SMOKE_ONLY") == "1"
        emit(smoke["e2e"], final=smoke_only, basis="end_to_end",
             stage="smoke", device_step=round(smoke["device_step"], 1),
             backend=backend, batches=smoke["batches"],
             compile_s=smoke["compile_s"],
             **({"obs_stats": _obs_snapshot(),
                 "timeline": _timeline_summary()} if smoke_only else {}))
        if smoke_only:
            return
        if os.environ.get("BENCH_TEST_DIE_AFTER_SMOKE") == "1":
            # harness-test hook: segfault-style death (no except clause,
            # no watchdog emit) between the smoke and full runs
            os._exit(9)

    full = run_config("full", B, N_BATCHES, N_KEYS, PACK_THREADS)
    emit(full["e2e"], final=True, basis="end_to_end", stage="full",
         end_to_end=round(full["e2e"], 1),
         device_step=round(full["device_step"], 1),
         batches=full["batches"], examples=full["examples"],
         auc=full["auc"], backend=backend, pack_threads=PACK_THREADS,
         compile_s=full["compile_s"], pass_pack_s=full["pass_pack_s"],
         amp=full["amp"], step_ms=full["step_ms"],
         trim_frac=full["trim_frac"],
         device_busy_frac=full["device_busy_frac"],
         feed_gap_ratio=full["feed_gap_ratio"],
         pass_cycle=full["pass_cycle"], recovery=full["recovery"],
         cache=full["cache"], heat=full["heat"], serving=full["serving"],
         cluster=full["cluster"], reshard=full["reshard"],
         multi_trainer=full["multi_trainer"],
         feed_intervals=full["feed_intervals"], timers=full["timers"],
         timeline=_timeline_summary(), obs_stats=_obs_snapshot())


def child_main() -> None:
    threading.Thread(target=_watchdog, daemon=True).start()
    try:
        from paddlebox_tpu.utils import doctor
        doctor.install()   # kill -USR1 <child> dumps a live postmortem
    except Exception:
        pass
    try:
        run()
    except Exception as e:
        trace(f"FAILED in phase {_STATE['phase']}: {type(e).__name__}: {e}")
        emit(_best(), final=True, error=f"{type(e).__name__}: {e}",
             last_phase=_STATE["phase"], partial=dict(_STATE["partial"]),
             timeline=_timeline_summary(), obs_stats=_obs_snapshot())
        # exit 0: the driver must always find a parseable JSON line
    finally:
        with _LOCK:
            _STATE["done"] = True
    sys.exit(0)


# ---------------------------------------------------------------------------
# Supervisor: killable, retryable backend init (see module docstring).
# ---------------------------------------------------------------------------

def _spawn_child(budget_s: float, force_cpu: bool = False):
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env["BENCH_TIMEOUT_S"] = str(max(int(budget_s), 30))
    if force_cpu:
        # backend fallback after a wedged accelerator attempt: a real CPU
        # throughput number beats burning the rest of the budget on
        # repeated jax.devices() hangs (BENCH_r05: 10 wedged attempts,
        # final value 0.0).  BENCH_FORCE_CPU routes through jax.config in
        # the child — the env var alone loses to the image's
        # sitecustomize platform pin.
        env["BENCH_FORCE_CPU"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=env, start_new_session=True)


def _kill_child(proc) -> None:
    # the whole session: the axon plugin may fork helpers that hold the
    # tunnel socket; a surviving helper would wedge the NEXT attempt too
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    try:
        proc.kill()
    except Exception:
        pass
    try:
        proc.wait(timeout=10)
    except Exception:
        pass


def _parse_result_line(line: str):
    try:
        obj = json.loads(line)
    except ValueError:
        return None
    return obj if isinstance(obj, dict) and "metric" in obj else None


def _rank(line) -> tuple:
    """Result-line preference: a clean TERMINAL result (the final emit —
    stage=full, or stage=smoke under BENCH_SMOKE_ONLY) beats everything;
    a mid-run smoke line may carry a HIGHER value at its toy geometry and
    must never shadow the real number.  Otherwise any informative line
    (an error name or a nonzero partial) by value; the bare backend-up
    marker only beats having nothing at all."""
    clean = not line.get("error")
    terminal = line.get("final") or line.get("stage") == "full"
    val = float(line.get("value") or 0)
    informative = bool(line.get("error")) or val > 0
    return (2 if (clean and terminal) else (1 if informative else 0), val)


def _better(a, b):
    """Pick the preferred of two result lines; tie → the later (b) wins,
    it has fresher metadata."""
    if a is None:
        return b
    if b is None:
        return a
    return a if _rank(a) > _rank(b) else b


def supervise() -> None:
    """Run bench children until one finishes cleanly or the budget is spent.
    A child that does not report a live backend within its attempt window
    is killed and respawned (hung jax.devices() is killable only from
    outside).  Always prints the final stdout line."""
    hard_deadline = T0 + TOTAL_BUDGET - 15       # grace to emit + flush
    attempt_window = float(os.environ.get("BENCH_BACKEND_ATTEMPT_S", 150))
    best = None
    attempts = 0
    last_err = ""
    attempt_log = []     # per-attempt {platform, last_phase, error} —
    # recorded into the final BENCH JSON so a failed round says exactly
    # which phase each attempt died in and on which platform (BENCH_r05's
    # ten wedged attempts were invisible in the 0.0 result line)
    force_cpu = os.environ.get("BENCH_FORCE_CPU") == "1"
    fast_failures = 0        # consecutive child exits within seconds —
    # a systematic error (bad import, broken env), not a tunnel wedge;
    # retrying can't help and would spin the whole budget away
    prev_sig = None
    repeat_failures = 0      # same post-backend failure twice in a row —
    # deterministic, not transient; stop burning budget on it

    while time.time() < hard_deadline - 30 and attempts < 20 \
            and fast_failures < 3 and repeat_failures < 2:
        attempts += 1
        t_attempt = time.time()
        remaining = hard_deadline - time.time()
        proc = _spawn_child(remaining, force_cpu=force_cpu)
        trace(f"supervisor: attempt {attempts} started (pid {proc.pid}, "
              f"{remaining:.0f}s remaining)")
        backend_up = threading.Event()
        out_lines = []

        def pump_stderr(p=proc):
            for ln in p.stderr:
                sys.stderr.write(ln)
                sys.stderr.flush()
                if "backend up:" in ln:
                    backend_up.set()

        def pump_stdout(p=proc):
            for ln in p.stdout:
                if ln.strip():
                    out_lines.append(ln.strip())
                    sys.stderr.write(f"[child stdout] {ln}")
                    sys.stderr.flush()

        te = threading.Thread(target=pump_stderr, daemon=True)
        to = threading.Thread(target=pump_stdout, daemon=True)
        te.start()
        to.start()

        # window for the backend to come up; a wedge here is killable
        init_deadline = min(time.time() + attempt_window, hard_deadline)
        while time.time() < init_deadline and proc.poll() is None \
                and not backend_up.is_set():
            time.sleep(1)

        platform = "cpu" if force_cpu else "default"
        if not backend_up.is_set() and proc.poll() is None:
            trace(f"supervisor: attempt {attempts} backend wedged "
                  f"after {attempt_window:.0f}s — killing")
            last_err = "backend-init wedged (jax.devices() hang)"
            attempt_log.append({"attempt": attempts, "platform": platform,
                                "last_phase": "backend-init",
                                "error": last_err})
            _kill_child(proc)
            if not force_cpu:
                # one wedged accelerator attempt is enough evidence: fall
                # back to the CPU backend so the round reports a REAL
                # throughput number instead of spending every remaining
                # attempt on the same hang
                force_cpu = True
                trace("supervisor: falling back to JAX_PLATFORMS=cpu for "
                      "subsequent attempts")
            continue

        # backend is up (or the child already exited): let it run to the
        # hard deadline; its own watchdog handles phase hangs
        killed = False
        while proc.poll() is None and time.time() < hard_deadline:
            time.sleep(1)
        if proc.poll() is None:
            trace("supervisor: hard deadline — killing child")
            last_err = "hard deadline during bench"
            _kill_child(proc)
            killed = True
        te.join(timeout=5)
        to.join(timeout=5)

        attempt_best = None
        for ln in out_lines:
            attempt_best = _better(attempt_best, _parse_result_line(ln))
        best = _better(best, attempt_best)
        attempt_log.append({
            "attempt": attempts, "platform": platform,
            "last_phase": (attempt_best or {}).get("last_phase")
            or ("done" if attempt_best is not None
                and _rank(attempt_best)[0] == 2
                else (attempt_best or {}).get("stage", "no-output")),
            "error": (attempt_best or {}).get("error")
            or (f"rc={proc.returncode}" if proc.returncode else None),
            # child watchdog wrote a stack bundle before dying — carry its
            # path so a wedged attempt is debuggable from the result JSON
            "postmortem": (attempt_best or {}).get("postmortem")})
        if attempt_best is not None and _rank(attempt_best)[0] == 2 \
                and float(attempt_best.get("value") or 0) > 0:
            break                     # clean TERMINAL result — done
        if not force_cpu \
                and (attempt_best or {}).get("last_phase") == "backend-init":
            # the CHILD's own backend-init watchdog fired (its budget is
            # shorter than the supervisor window) — same wedge evidence
            # as a supervisor kill, same response: go CPU
            force_cpu = True
            trace("supervisor: falling back to JAX_PLATFORMS=cpu for "
                  "subsequent attempts")
        if attempt_best is not None and attempt_best.get("error"):
            last_err = str(attempt_best["error"])
        elif not killed and proc.returncode:
            last_err = (f"child died rc={proc.returncode} "
                        "without reporting (segfault/OOM?)")
        if best is not None and float(best.get("value") or 0) > 0:
            # got a number, but not a clean terminal result; retry only
            # if a full re-run plausibly fits
            if hard_deadline - time.time() < 420:
                break
        if time.time() - t_attempt < 15 and not backend_up.is_set():
            fast_failures += 1
        else:
            fast_failures = 0
        if backend_up.is_set() and not killed:
            # the child failed on its own after a live backend — if the
            # exact same failure repeats, it is deterministic
            sig = (str(attempt_best.get("error"))
                   if attempt_best and attempt_best.get("error")
                   else f"rc={proc.returncode}")
            repeat_failures = repeat_failures + 1 if sig == prev_sig else 1
            prev_sig = sig
        trace(f"supervisor: attempt {attempts} ended without a clean "
              f"result ({hard_deadline - time.time():.0f}s remaining)")
        time.sleep(2)

    if best is None:
        best = {"metric": METRIC, "value": 0.0, "unit": "examples/s",
                "vs_baseline": 0.0}
    if not best.get("error") and _rank(best)[0] != 2:
        # never a bare 0.0 — and never a mid-run smoke line passing for a
        # clean result: anything short of a clean terminal line carries
        # the supervisor's failure context
        best["error"] = last_err or "no clean terminal result"
    best["supervisor_attempts"] = attempts
    best["attempt_log"] = attempt_log
    if force_cpu and os.environ.get("BENCH_FORCE_CPU") != "1":
        best["platform_fallback"] = "cpu"   # wedge-triggered, not requested
    best["elapsed_s"] = round(time.time() - T0, 1)
    print(json.dumps(_san(best)), flush=True)
    sys.exit(0)


# ---------------------------------------------------------------------------
# Compare mode: diff two recorded BENCH result files.
# ---------------------------------------------------------------------------

def _load_result(path):
    """Load a BENCH result: either a raw result line (has "metric") or the
    driver's wrapper file whose "parsed" key holds the result line."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict) and "metric" in obj:
        return obj
    if isinstance(obj, dict) and isinstance(obj.get("parsed"), dict):
        return obj["parsed"]
    raise ValueError(f"{path}: not a BENCH result file "
                     "(no 'metric' or 'parsed' key)")


def _reproduced_drop(runs_old, runs_new, old_val, threshold, sign=-1):
    """Median-of-3 discipline for wall-clock-class metrics (serving.qps,
    recovery.mttr_s): the delta gates only when BOTH records carry the
    per-run list (len >= 3, i.e. the phase ran its median-of-3 loop) and
    the regression direction reproduces on at least 2 of the new runs
    against the old median.  sign=-1 gates drops, sign=+1 gates growth."""
    if not (isinstance(runs_old, list) and len(runs_old) >= 3
            and isinstance(runs_new, list) and len(runs_new) >= 3):
        return False
    hits = sum(1 for r in runs_new
               if isinstance(r, (int, float))
               and sign * (float(r) - old_val) / old_val > threshold)
    return hits >= 2


def compare(old_path: str, new_path: str, threshold=None) -> int:
    """Diff two BENCH result files; 0 = within threshold, 1 = regression.

    Regressions: headline value drops by more than the threshold fraction,
    feed_gap_ratio grows by more than it, or the run picked up NEW SLO
    breaches (timeline.slo_breaches above the old run's count).  obs_stats
    movers beyond the threshold are reported (informational — counters
    legitimately move)."""
    if threshold is None:
        threshold = float(os.environ.get("BENCH_COMPARE_THRESHOLD", 0.05))
    old, new = _load_result(old_path), _load_result(new_path)

    def num(d, k):
        v = d.get(k)
        return float(v) if isinstance(v, (int, float)) \
            and math.isfinite(float(v)) else None

    out = {"old": old_path, "new": new_path, "threshold": threshold}
    regressions = []
    vo, vn = num(old, "value"), num(new, "value")
    if vo and vn is not None:           # lower throughput = regression
        frac = (vn - vo) / vo
        out["value"] = {"old": vo, "new": vn, "delta_frac": round(frac, 4)}
        if frac < -threshold:
            regressions.append(
                f"value {vo:.1f} -> {vn:.1f} ({frac:+.1%})")
    go, gn = num(old, "feed_gap_ratio"), num(new, "feed_gap_ratio")
    if go and gn is not None:           # higher feed gap = regression
        gfrac = (gn - go) / go
        out["feed_gap_ratio"] = {"old": go, "new": gn,
                                 "delta_frac": round(gfrac, 4)}
        # the ratio's denominator is device-busy seconds: when both runs
        # saw an essentially idle device (CPU basis: ~4 ms busy across a
        # ~50 s pass) a 1 ms timing wobble swings the ratio by double
        # digits, so the gate only arms on a non-degenerate measurement
        dbo = num(old, "device_busy_frac")
        dbn = num(new, "device_busy_frac")
        degenerate = (dbo is not None and dbn is not None
                      and max(dbo, dbn) < 0.01)
        if degenerate:
            out["feed_gap_ratio"]["degenerate"] = True
        elif gfrac > threshold:
            regressions.append(
                f"feed_gap_ratio {go:.2f} -> {gn:.2f} ({gfrac:+.1%})")
    po = num(old.get("step_ms") or {}, "sparse_share")
    pn = num(new.get("step_ms") or {}, "sparse_share")
    if po and pn is not None:           # sparse share creeping back up =
        pfrac = (pn - po) / po          # the padded-dense regression class
        out["sparse_share"] = {"old": po, "new": pn,
                               "delta_frac": round(pfrac, 4)}
        if pfrac > threshold:
            regressions.append(
                f"step_ms.sparse_share {po:.3f} -> {pn:.3f} ({pfrac:+.1%})")
    so = num(old.get("pass_cycle") or {}, "speedup")
    sn = num(new.get("pass_cycle") or {}, "speedup")
    if so and sn is not None:           # lower pipeline speedup = regression
        sfrac = (sn - so) / so
        out["pass_cycle_speedup"] = {"old": so, "new": sn,
                                     "delta_frac": round(sfrac, 4)}
        if sfrac < -threshold:
            regressions.append(
                f"pass_cycle.speedup {so:.2f} -> {sn:.2f} ({sfrac:+.1%})")
    co, cn = old.get("cache") or {}, new.get("cache") or {}
    ho, hn = num(co, "hit_rate"), num(cn, "hit_rate")
    if ho and hn is not None:           # lower cache hit rate = regression
        hfrac = (hn - ho) / ho
        out["cache_hit_rate"] = {"old": ho, "new": hn,
                                 "delta_frac": round(hfrac, 4)}
        if hfrac < -threshold:
            regressions.append(
                f"cache.hit_rate {ho:.3f} -> {hn:.3f} ({hfrac:+.1%})")
    wo, wn = num(co, "wire_reduction"), num(cn, "wire_reduction")
    if wo and wn is not None:           # less wire saved = regression
        wfrac = (wn - wo) / wo
        out["cache_wire_reduction"] = {"old": wo, "new": wn,
                                       "delta_frac": round(wfrac, 4)}
        if wfrac < -threshold:
            regressions.append(
                f"cache.wire_reduction {wo:.2f}x -> {wn:.2f}x "
                f"({wfrac:+.1%})")
    hto, htn = old.get("heat") or {}, new.get("heat") or {}
    ovo, ovn = num(hto, "tap_ns_per_key"), num(htn, "tap_ns_per_key")
    if ovn is not None:                 # heat taps must stay cheap
        # absolute per-key cost, not a wall percentage: the engine-only
        # cycle's denominator is ~230 ns/key, so percent-of-wall is
        # workload-relative noise, while ns/key is what a real train
        # pass actually pays per pulled key.  Gate: 250 ns/key floor or
        # +100 ns/key over the old run, whichever is larger.
        out["heat_tap_ns_per_key"] = {"old": ovo, "new": ovn}
        if ovn > max(250.0, (ovo or 0.0) + 100.0):
            regressions.append(
                f"heat.tap_ns_per_key "
                f"{ovo if ovo is not None else 0:.0f} -> {ovn:.0f}")
    pco, pcn = num(hto, "overhead_pct"), num(htn, "overhead_pct")
    if pcn is not None:                 # relative backstop for the same
        # signal: the engine-only cycle pays ~10-30% for ~20-60 ns/key
        # of taps, and single-run medians still wobble ±10 points — only
        # a catastrophic tap regression clears this band
        out["heat_overhead_pct"] = {"old": pco, "new": pcn}
        if pcn > max(50.0, (pco or 0.0) + 25.0):
            regressions.append(
                f"heat.overhead_pct "
                f"{pco if pco is not None else 0:.1f} -> {pcn:.1f}")
    sio, sin_ = num(hto, "shard_imbalance"), num(htn, "shard_imbalance")
    if sin_ is not None:                # key placement newly skewing
        # growth gate with an absolute floor: the workload is fixed, so
        # a jump means the partition (or a hot-key storm) changed — a
        # None baseline means the old record predates the phase
        out["heat_shard_imbalance"] = {"old": sio, "new": sin_}
        if sio and (sin_ - sio) / sio > threshold and (sin_ - sio) > 0.25:
            regressions.append(
                f"heat.shard_imbalance {sio:.2f} -> {sin_:.2f}")
    svo, svn = old.get("serving") or {}, new.get("serving") or {}
    qo, qn = num(svo, "qps"), num(svn, "qps")
    if qo and qn is not None:           # lower serving QPS = regression
        qfrac = (qn - qo) / qo
        out["serving_qps"] = {"old": qo, "new": qn,
                              "delta_frac": round(qfrac, 4)}
        if qfrac < -threshold:
            # wall-clock-class metric: one sweep on a contended CPU host
            # swings past any sane threshold on scheduler noise alone, so
            # the delta only GATES when both records are medians-of-3 AND
            # the drop reproduces (>= 2 of the new runs individually
            # clear the threshold vs the old median); otherwise it is
            # report-only drift
            if _reproduced_drop(svo.get("runs"), svn.get("runs"),
                                qo, threshold):
                regressions.append(
                    f"serving.qps {qo:.1f} -> {qn:.1f} ({qfrac:+.1%})")
            else:
                out["serving_qps"]["report_only_drift"] = True
    po, pn = num(svo, "p99_ms"), num(svn, "p99_ms")
    if po and pn is not None:           # higher serving p99 = regression
        pfrac = (pn - po) / po
        out["serving_p99_ms"] = {"old": po, "new": pn,
                                 "delta_frac": round(pfrac, 4)}
        # one 200-batch sample of a sub-ms p99 on a contended CPU host
        # swings ±20% run to run (r09 1.05 / r10 0.90 / r11 1.07) — gate
        # only when the growth clears an absolute floor too
        if pfrac > threshold and (pn - po) > 0.25:
            regressions.append(
                f"serving.p99_ms {po:.2f} -> {pn:.2f} ({pfrac:+.1%})")
    sho, shn = num(svo, "shed_rate") or 0.0, num(svn, "shed_rate")
    if shn is not None:                 # new sustained shed = regression
        out["serving_shed_rate"] = {"old": sho, "new": shn}
        if shn > sho + 0.01:
            regressions.append(
                f"serving.shed_rate {sho:.4f} -> {shn:.4f}")
    flo, fln = svo.get("fleet") or {}, svn.get("fleet") or {}
    fso, fsn = num(flo, "speedup"), num(fln, "speedup")
    if fsn is not None:                 # sharded fleet must beat solo
        # absolute acceptance floor (critical-path basis, so the number
        # is service-time arithmetic, not scheduler luck) plus the usual
        # relative gate against the old record
        out["serving_fleet_speedup"] = {"old": fso, "new": fsn}
        if fsn < 3.0:
            regressions.append(
                f"serving.fleet.speedup {fsn:.2f}x below the 3x "
                f"acceptance floor at N="
                f"{int(num(fln, 'n_shards') or 4)}")
        elif fso and (fsn - fso) / fso < -threshold:
            regressions.append(
                f"serving.fleet.speedup {fso:.2f}x -> {fsn:.2f}x")
    fpo, fpn = svo.get("flip") or {}, svn.get("flip") or {}
    ffn = num(fpn, "failed_requests")
    if ffn is not None:                 # ANY failed request during a
        out["serving_flip_failed"] = {  # streamed flip = regression
            "old": num(fpo, "failed_requests"), "new": ffn,
            "errors": fpn.get("errors", [])}
        if ffn > 0:
            regressions.append(
                f"serving.flip.failed_requests {int(ffn)} "
                f"(errors: {fpn.get('errors', [])})")
        if fpn.get("converged") is False:
            regressions.append(
                "serving.flip fleet never converged to the manifest head")
    spo, spn = num(fpo, "staleness_p99_s"), num(fpn, "staleness_p99_s")
    if spn is not None:                 # freshness lag is the product:
        # p99 commit-to-swap staleness is gated on half-again growth
        # over the old record with a 1 s absolute deadband (one poll
        # cadence + patch build), plus a 10 s hard ceiling — past that
        # the delta stream is not "delta-fresh" regardless of baseline
        out["serving_staleness_p99_s"] = {"old": spo, "new": spn}
        if spn > 10.0:
            regressions.append(
                f"serving.flip.staleness_p99_s {spn:.2f} above the 10 s "
                f"freshness ceiling")
        elif spo and spn > 1.5 * spo and (spn - spo) > 1.0:
            regressions.append(
                f"serving.flip.staleness_p99_s {spo:.2f} -> {spn:.2f}")
    hro, hrn = svo.get("heat_routing") or {}, svn.get("heat_routing") or {}
    rto, rtn = num(hro, "imbalance_ratio"), num(hrn, "imbalance_ratio")
    if rtn is not None:                 # hot-key replication must CUT
        # shard imbalance vs owner-only routing: ratio >= 1 means the
        # p2c hot path stopped paying for its replicated rows
        out["serving_heat_imbalance_ratio"] = {"old": rto, "new": rtn}
        if rtn >= 1.0:
            regressions.append(
                f"serving.heat_routing.imbalance_ratio {rtn:.2f} — "
                f"hot-key replication no longer cuts shard imbalance "
                f"(off {num(hrn, 'imbalance_off')} -> "
                f"on {num(hrn, 'imbalance_on')})")
    clo = num(old.get("cluster") or {}, "wire_speedup")
    cln = num(new.get("cluster") or {}, "wire_speedup")
    if clo and cln is not None:         # lower fan-out speedup = regression
        clfrac = (cln - clo) / clo
        out["cluster_wire_speedup"] = {"old": clo, "new": cln,
                                       "delta_frac": round(clfrac, 4)}
        if clfrac < -threshold:
            regressions.append(
                f"cluster.wire_speedup {clo:.2f}x -> {cln:.2f}x "
                f"({clfrac:+.1%})")
    reo, ren = old.get("reshard") or {}, new.get("reshard") or {}
    rmo, rmn = num(reo, "moved_rows_per_s"), num(ren, "moved_rows_per_s")
    if rmo and rmn is not None:         # slower row shipping = regression
        rmfrac = (rmn - rmo) / rmo
        out["reshard_moved_rows_per_s"] = {"old": rmo, "new": rmn,
                                           "delta_frac": round(rmfrac, 4)}
        if rmfrac < -threshold:
            regressions.append(
                f"reshard.moved_rows_per_s {rmo:.0f} -> {rmn:.0f} "
                f"({rmfrac:+.1%})")
    rso, rsn = num(reo, "cutover_stall_ms"), num(ren, "cutover_stall_ms")
    if rso and rsn is not None:         # longer freeze window = regression
        # the stall is one freeze→commit interval measured once, so CPU
        # scheduling noise dominates small deltas — gate only on a
        # half-again growth, never on the plain threshold
        rsfrac = (rsn - rso) / rso
        out["reshard_cutover_stall_ms"] = {"old": rso, "new": rsn,
                                           "delta_frac": round(rsfrac, 4)}
        if rsfrac > max(threshold, 0.5):
            regressions.append(
                f"reshard.cutover_stall_ms {rso:.1f} -> {rsn:.1f} "
                f"({rsfrac:+.1%})")
    rdo = num(reo, "nonmoving_qps_drop")
    rdn = num(ren, "nonmoving_qps_drop")
    if rdn is not None:                 # non-moving traffic newly stalling
        # a drop gate needs a same-basis baseline: the first round that
        # records the reshard phase only reports (rdo None — the old
        # record predates the phase, NOT a zero-drop measurement)
        out["reshard_nonmoving_qps_drop"] = {"old": rdo, "new": rdn}
        if rdo is not None and rdn > rdo + 0.10:
            regressions.append(
                f"reshard.nonmoving_qps_drop {rdo:.3f} -> {rdn:.3f}")
    mto, mtn = old.get("multi_trainer") or {}, \
        new.get("multi_trainer") or {}
    sco, scn = num(mto, "scaling"), num(mtn, "scaling")
    if sco and scn is not None:         # worse fleet scaling = regression
        scfrac = (scn - sco) / sco
        out["multi_trainer_scaling"] = {"old": sco, "new": scn,
                                        "delta_frac": round(scfrac, 4)}
        if scfrac < -threshold:
            regressions.append(
                f"multi_trainer.scaling {sco:.2f}x -> {scn:.2f}x "
                f"({scfrac:+.1%})")
    tmo = num(mto, "restart_mttr_s")
    tmn = num(mtn, "restart_mttr_s")
    if tmn is not None:                 # slower trainer restart = regression
        # one kill -> one restart interval per run, backoff-quantised, so
        # gate only on half-again growth; a None baseline means the old
        # record predates the phase, NOT a zero-MTTR measurement
        out["multi_trainer_restart_mttr_s"] = {"old": tmo, "new": tmn}
        if tmo and (tmn - tmo) / tmo > max(threshold, 0.5):
            regressions.append(
                f"multi_trainer.restart_mttr_s {tmo:.2f} -> {tmn:.2f}")
    rco, rcn = old.get("recovery") or {}, new.get("recovery") or {}
    mo, mn = num(rco, "mttr_s"), num(rcn, "mttr_s")
    if mo and mn is not None:           # slower recovery = regression
        mfrac = (mn - mo) / mo
        out["mttr_s"] = {"old": mo, "new": mn,
                         "delta_frac": round(mfrac, 4)}
        if mfrac > threshold:
            # wall-clock-class: same median-of-3 discipline as
            # serving.qps — gate only a reproduced growth, report drift
            # otherwise
            if _reproduced_drop(rco.get("runs"), rcn.get("runs"),
                                mo, threshold, sign=1):
                regressions.append(
                    f"recovery.mttr_s {mo:.3f} -> {mn:.3f} ({mfrac:+.1%})")
            else:
                out["mttr_s"]["report_only_drift"] = True
    bo = num(old.get("timeline") or {}, "slo_breaches") or 0.0
    bn = num(new.get("timeline") or {}, "slo_breaches")
    if bn is not None:                  # new SLO breaches = regression
        out["slo_breaches"] = {
            "old": int(bo), "new": int(bn),
            "new_rules": (new.get("timeline") or {}).get("breached_rules",
                                                         [])}
        if bn > bo:
            regressions.append(
                f"slo_breaches {int(bo)} -> {int(bn)} "
                f"({(new.get('timeline') or {}).get('breached_rules', [])})")
    oo = old.get("obs_stats") or {}
    on = new.get("obs_stats") or {}
    movers = []
    for k in set(oo) & set(on):
        a, b = oo[k], on[k]
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                and (a or b):
            rel = abs(b - a) / max(abs(a), abs(b))
            if rel > threshold:
                movers.append((rel, k, a, b))
    movers.sort(reverse=True)
    out["obs_deltas"] = {k: {"old": a, "new": b}
                         for _, k, a, b in movers[:20]}
    out["regressions"] = regressions
    out["ok"] = not regressions
    print(json.dumps(_san(out), indent=1), flush=True)
    return 1 if regressions else 0


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--compare":
        thr = None
        paths = []
        for a in sys.argv[2:]:
            if a.startswith("--threshold="):
                thr = float(a.split("=", 1)[1])
            else:
                paths.append(a)
        if len(paths) != 2:
            print("usage: bench.py --compare OLD.json NEW.json "
                  "[--threshold=0.05]", file=sys.stderr)
            sys.exit(2)
        sys.exit(compare(paths[0], paths[1], threshold=thr))
    if os.environ.get("BENCH_CHILD") == "1" \
            or os.environ.get("BENCH_NO_SUPERVISE") == "1":
        child_main()
    else:
        supervise()


if __name__ == "__main__":
    main()
