"""Benchmark: Criteo-shaped sparse-CTR training throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "examples/s", "vs_baseline": N}
vs_baseline is against the north-star 1M examples/sec/chip (BASELINE.md).

Measures the steady-state full training step (embedding pull gather →
fused_seqpool_cvm → DeepFM fwd/bwd → scatter push + sparse adagrad → dense
adam → AUC accumulation) with Criteo geometry: 26 sparse slots × 1 feasign,
13 dense features, mf_dim=8, on-device pass working set.
"""

import json
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.config import (DataFeedConfig, EmbeddingTableConfig,
                                      SlotConfig, SparseSGDConfig)
    from paddlebox_tpu.data.batch_pack import PackedBatch
    from paddlebox_tpu.models.deepfm import DeepFM
    from paddlebox_tpu.ps.pass_manager import BoxPSEngine
    from paddlebox_tpu.trainer.trainer import SparseTrainer

    N_SLOTS, DENSE_DIM, MF_DIM, CAP = 26, 13, 8, 1
    B = 16384
    N_KEYS = 2_000_000
    STEPS_WARM, STEPS = 5, 30

    slots = [SlotConfig("label", dtype="float", is_dense=True, dim=1),
             SlotConfig("dense0", dtype="float", is_dense=True,
                        dim=DENSE_DIM)]
    slots += [SlotConfig(f"s{i}", slot_id=100 + i, capacity=CAP)
              for i in range(N_SLOTS)]
    cfg = DataFeedConfig(slots=tuple(slots))

    engine = BoxPSEngine(EmbeddingTableConfig(
        embedding_dim=MF_DIM, shard_num=8,
        sgd=SparseSGDConfig(mf_create_thresholds=0.0)))
    engine.begin_feed_pass()
    engine.add_keys(np.arange(1, N_KEYS + 1, dtype=np.uint64))
    engine.end_feed_pass()
    engine.begin_pass()
    # mark all mf created so the bench trains full-width embeddings
    engine.ws["mf_size"] = jnp.full_like(engine.ws["mf_size"], MF_DIM)

    model = DeepFM(num_slots=N_SLOTS, emb_width=3 + MF_DIM,
                   dense_dim=DENSE_DIM, hidden=(400, 400, 400))
    trainer = SparseTrainer(engine, model, cfg, batch_size=B,
                            auc_table_size=100_000)
    trainer._build_step()

    rng = np.random.default_rng(0)
    batch = PackedBatch(
        indices=rng.integers(1, N_KEYS, (N_SLOTS, B, CAP)).astype(np.int32),
        lengths=np.ones((N_SLOTS, B), np.int32),
        dense=rng.normal(0, 1, (B, DENSE_DIM)).astype(np.float32),
        labels=rng.integers(0, 2, (B,)).astype(np.float32),
        valid=np.ones((B,), bool), num_real=B)
    dev = trainer._put_batch(batch)

    ws, params = engine.ws, trainer.params
    opt_state, auc_state = trainer.opt_state, trainer.auc_state
    for _ in range(STEPS_WARM):
        ws, params, opt_state, auc_state, loss, _p = trainer._step_fn(
            ws, params, opt_state, auc_state, *dev)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(STEPS):
        ws, params, opt_state, auc_state, loss, _p = trainer._step_fn(
            ws, params, opt_state, auc_state, *dev)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    eps = B * STEPS / dt
    print(json.dumps({
        "metric": "criteo_deepfm_train_examples_per_sec_per_chip",
        "value": round(eps, 1),
        "unit": "examples/s",
        "vs_baseline": round(eps / 1_000_000.0, 4),
    }))


if __name__ == "__main__":
    main()
