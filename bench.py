"""Benchmark: Criteo-shaped sparse-CTR training throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "examples/s", "vs_baseline": N, ...}
vs_baseline is against the north-star 1M examples/sec/chip (BASELINE.md).
The headline value is END-TO-END examples/s — the full train_pass loop
(host batch packing + key translation + H2D + jitted train step, the loop
≙ BoxPSWorker::TrainFiles boxps_worker.cc:1278), streaming fresh batches
through the packer thread pool + bounded channel.  `device_step` (steady
re-fed device step, the round-1 quantity) is reported alongside.

Geometry: 26 sparse slots with variable lengths 1..3 (capacity 3), 13
dense features, mf_dim=8, 2M-key working set, B=16384.

Hardened per VERDICT.md: backend init retries, a watchdog that emits a
parseable JSON error line instead of hanging the chip, and JSON error
output on any failure (exit code 0 so the driver can always parse).

Env knobs: BENCH_BATCH_SIZE, BENCH_BATCHES, BENCH_KEYS, BENCH_TIMEOUT_S,
BENCH_PACK_THREADS.
"""

import json
import os
import sys
import time

import numpy as np

METRIC = "criteo_deepfm_train_examples_per_sec_per_chip"


def _emit(value: float, **extra) -> None:
    line = {"metric": METRIC, "value": round(float(value), 1),
            "unit": "examples/s",
            "vs_baseline": round(float(value) / 1_000_000.0, 4)}
    line.update(extra)
    print(json.dumps(line))
    sys.stdout.flush()


def _arm_watchdog(seconds: int) -> None:
    """Never leave the driver with a silent hang holding the chip: on
    timeout, print the JSON error line and hard-exit."""
    import signal

    def fire(signum, frame):
        _emit(0.0, error=f"bench watchdog fired after {seconds}s")
        os._exit(0)

    try:
        signal.signal(signal.SIGALRM, fire)
        signal.alarm(seconds)
    except (ValueError, AttributeError):
        pass  # non-main thread / platform without SIGALRM


def _init_devices(retries: int = 3, delay: float = 5.0):
    import jax
    last = None
    for attempt in range(retries):
        try:
            return jax.devices()
        except Exception as e:  # backend init is flaky under the tunnel
            last = e
            if attempt + 1 < retries:
                time.sleep(delay)
    raise RuntimeError(
        f"jax backend init failed after {retries} attempts: {last!r}")


def _make_blocks(rng, n_records, sparse_names, n_keys, dense_dim, cap,
                 chunk=65536):
    """Synthetic pass data as SlotRecordBlocks (variable-length slots)."""
    from paddlebox_tpu.data.slot_record import SlotRecordBlock
    blocks = []
    done = 0
    while done < n_records:
        n = min(chunk, n_records - done)
        blk = SlotRecordBlock(n=n)
        for name in sparse_names:
            lens = rng.integers(1, cap + 1, size=n)
            offsets = np.zeros((n + 1,), np.int64)
            np.cumsum(lens, out=offsets[1:])
            values = rng.integers(
                1, n_keys, size=int(offsets[-1])).astype(np.uint64)
            blk.uint64_slots[name] = (values, offsets)
        blk.float_slots["label"] = (
            rng.integers(0, 2, size=n).astype(np.float32),
            np.arange(n + 1, dtype=np.int64))
        blk.float_slots["dense0"] = (
            rng.normal(0, 1, size=n * dense_dim).astype(np.float32),
            np.arange(n + 1, dtype=np.int64) * dense_dim)
        blocks.append(blk)
        done += n
    return blocks


def run() -> None:
    import jax
    import jax.numpy as jnp

    from paddlebox_tpu.config import (DataFeedConfig, EmbeddingTableConfig,
                                      SlotConfig, SparseSGDConfig)
    from paddlebox_tpu.data.dataset import SlotDataset
    from paddlebox_tpu.models.deepfm import DeepFM
    from paddlebox_tpu.ps.pass_manager import BoxPSEngine
    from paddlebox_tpu.trainer.trainer import SparseTrainer

    N_SLOTS, DENSE_DIM, MF_DIM, CAP = 26, 13, 8, 3
    B = int(os.environ.get("BENCH_BATCH_SIZE", 16384))
    N_BATCHES = int(os.environ.get("BENCH_BATCHES", 30))
    N_KEYS = int(os.environ.get("BENCH_KEYS", 2_000_000))
    PACK_THREADS = int(os.environ.get(
        "BENCH_PACK_THREADS", min(8, os.cpu_count() or 1)))
    STEPS_WARM = 5

    devices = _init_devices()
    backend = devices[0].platform

    sparse_names = [f"s{i}" for i in range(N_SLOTS)]
    slots = [SlotConfig("label", dtype="float", is_dense=True, dim=1),
             SlotConfig("dense0", dtype="float", is_dense=True,
                        dim=DENSE_DIM)]
    slots += [SlotConfig(name, slot_id=100 + i, capacity=CAP)
              for i, name in enumerate(sparse_names)]
    cfg = DataFeedConfig(slots=tuple(slots))

    # -- synthetic pass data + the real feed-pass lifecycle ----------------
    rng = np.random.default_rng(0)
    dataset = SlotDataset(cfg)
    dataset._blocks = _make_blocks(rng, N_BATCHES * B, sparse_names,
                                   N_KEYS, DENSE_DIM, CAP)

    engine = BoxPSEngine(EmbeddingTableConfig(
        embedding_dim=MF_DIM, shard_num=8,
        sgd=SparseSGDConfig(mf_create_thresholds=0.0)))
    engine.begin_feed_pass()
    for blk in dataset.get_blocks():
        engine.add_keys(blk.all_keys())
    engine.end_feed_pass()
    engine.begin_pass()
    # steady-state assumption: all mf created, full-width embeddings train
    engine.ws["mf_size"] = jnp.full_like(engine.ws["mf_size"], MF_DIM)

    model = DeepFM(num_slots=N_SLOTS, emb_width=3 + MF_DIM,
                   dense_dim=DENSE_DIM, hidden=(400, 400, 400))
    trainer = SparseTrainer(engine, model, cfg, batch_size=B,
                            auc_table_size=100_000)
    trainer._build_step()

    # -- device_step: steady-state jitted step, one re-fed batch -----------
    first = dataset.get_blocks()[0].slice(0, B)
    batch = trainer.packer.pack(first, key_mapper=engine.mapper)
    dev = trainer._put_batch(batch)
    ws, params = engine.ws, trainer.params
    opt_state, auc_state = trainer.opt_state, trainer.auc_state
    for _ in range(STEPS_WARM):
        ws, params, opt_state, auc_state, loss, _p = trainer._step_fn(
            ws, params, opt_state, auc_state, *dev)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(N_BATCHES):
        ws, params, opt_state, auc_state, loss, _p = trainer._step_fn(
            ws, params, opt_state, auc_state, *dev)
    jax.block_until_ready(loss)
    device_eps = B * N_BATCHES / (time.perf_counter() - t0)
    engine.ws = ws
    trainer.params = params
    trainer.opt_state = opt_state
    trainer.auc_state = auc_state

    # -- end_to_end: the real train_pass loop over fresh batches -----------
    t0 = time.perf_counter()
    stats = trainer.train_pass(dataset, prefetch=8,
                               pack_threads=PACK_THREADS)
    dt = time.perf_counter() - t0
    n_examples = dataset.instance_num()
    e2e_eps = n_examples / dt

    _emit(e2e_eps,
          end_to_end=round(e2e_eps, 1),
          device_step=round(device_eps, 1),
          batches=int(stats["batches"]),
          examples=int(n_examples),
          auc=round(float(stats.get("auc", float("nan"))), 4),
          backend=backend,
          pack_threads=PACK_THREADS,
          timers=trainer.timers.report())


def main() -> None:
    _arm_watchdog(int(os.environ.get("BENCH_TIMEOUT_S", 1500)))
    try:
        run()
    except Exception as e:
        _emit(0.0, error=f"{type(e).__name__}: {e}")
        # exit 0: the driver must always find a parseable JSON line
        sys.exit(0)


if __name__ == "__main__":
    main()
