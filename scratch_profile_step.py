"""Profile the mxu step phases at driver geometry on the real chip."""
import time
import numpy as np
import jax
import jax.numpy as jnp

S, L, B = 26, 3, 16384
N_ROWS = 2_000_000
MF = 8
P = S * L * B

rng = np.random.default_rng(0)
idx_np = rng.integers(1, N_ROWS, size=(S, L, B)).astype(np.int32)

from paddlebox_tpu.ps import mxu_path
from paddlebox_tpu.ops import sorted_spmm as sp

dims = mxu_path.make_dims(P, N_ROWS)
print("dims:", dims)

idx = jnp.asarray(idx_np)

def timeit(name, fn, *args, n=20, **kw):
    fn_j = jax.jit(fn, **kw)
    out = fn_j(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn_j(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:34s} {dt*1e3:8.2f} ms")
    return out, dt

# 1. plan build (sort + worklist)
plan, t_plan = timeit("build_plan", lambda i: mxu_path.build_plan(i, dims), idx)
rows2d, perm, inv_perm, ch, tl, fg, fs = plan

# 2. pull table build
ws = {
    "show": jnp.asarray(rng.random(N_ROWS, dtype=np.float32)),
    "click": jnp.asarray(rng.random(N_ROWS, dtype=np.float32)),
    "embed_w": jnp.asarray(rng.random(N_ROWS, dtype=np.float32)),
    "mf": jnp.asarray(rng.random((N_ROWS, MF), dtype=np.float32)),
    "mf_size": jnp.full((N_ROWS,), MF, jnp.int32),
}
tab, t_tab = timeit("pull_table build", lambda w: mxu_path._pull_table(w, dims), ws)

# 3. gather kernel
g, t_g = timeit("gather_sorted kernel",
                lambda t, r: sp.gather_sorted(t, r, ch, tl, fg, dims), tab, rows2d)

# 4. inv_perm take (sorted -> canonical) [p, 12]
v, t_take = timeit("take(inv_perm) [p,12]",
                   lambda g_, ip: jnp.take(g_.T[:dims.p], ip, axis=0), g, inv_perm)

# 4b. the whole pull_pool_cvm fused
pooled, t_pull = timeit("pull_pool_cvm (fused)",
                        lambda w, r, ip: mxu_path.pull_pool_cvm(
                            w, (r, perm, ip, ch, tl, fg, fs), dims, (S, L, B), True),
                        ws, rows2d, inv_perm)

# 5. payload build + perm take + scatter
payload = jnp.asarray(rng.random((dims.p, MF + 5), dtype=np.float32))
srt, t_ptake = timeit("take(perm) [p,13]",
                      lambda p_, pm: jnp.take(p_, pm, axis=0), payload, perm)
srt_pad = jnp.concatenate([srt, jnp.zeros((dims.p_pad - dims.p, MF + 5), jnp.float32)])
delta, t_s = timeit("scatter_add_sorted kernel",
                    lambda s_, r: sp.scatter_add_sorted(s_.T, r, ch, tl, fs, dims),
                    srt_pad, rows2d)

# 6. optimizer full-table
from paddlebox_tpu.ps import optimizer as sparse_opt
from paddlebox_tpu.config import SparseSGDConfig
cfg = SparseSGDConfig(mf_create_thresholds=0.0)
ws2 = dict(ws)
ws2["g2sum"] = jnp.zeros((N_ROWS,), jnp.float32)
ws2["mf_g2sum"] = jnp.zeros((N_ROWS,), jnp.float32)
acc = {
    "g_show": jnp.asarray(rng.random(N_ROWS, dtype=np.float32)),
    "g_click": jnp.asarray(rng.random(N_ROWS, dtype=np.float32)),
    "g_embed": jnp.asarray(rng.random(N_ROWS, dtype=np.float32)),
    "g_embedx": jnp.asarray(rng.random((N_ROWS, MF), dtype=np.float32)),
    "slot": jnp.zeros((N_ROWS,), jnp.int32),
}
try:
    opt_out, t_opt = timeit("apply_push optimizer",
                            lambda w, a: sparse_opt.apply_push(w, a, cfg), ws2, acc)
except Exception as e:
    print("optimizer profile failed:", e)

# 7. dense half: DeepFM fwd/bwd
from paddlebox_tpu.models.deepfm import DeepFM
import optax
model = DeepFM(num_slots=S, emb_width=3 + MF, dense_dim=13, hidden=(400, 400, 400))
params = model.init(jax.random.PRNGKey(0))
dense = jnp.asarray(rng.random((B, 13), dtype=np.float32))
labels = jnp.asarray(rng.integers(0, 2, B).astype(np.float32))

def dense_fwd_bwd(p, pooled_in):
    def loss_fn(p_, x):
        logits = model.apply(p_, x.reshape(B, -1), dense)
        return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))
    return jax.value_and_grad(loss_fn, argnums=(0, 1))(p, pooled_in)

_, t_dense = timeit("dense fwd/bwd (DeepFM 400x3)", dense_fwd_bwd, params, pooled)

print()
tot = t_plan + t_tab + t_g + t_take + t_ptake + t_s + t_dense
print(f"sum of pieces (no opt): {tot*1e3:.1f} ms -> {B/tot:,.0f} ex/s")
