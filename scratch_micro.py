"""Microbench: crossing strategies + kernel tile geometry on the real chip."""
import time
import numpy as np
import jax
import jax.numpy as jnp

P = 1_277_952          # padded occurrences at driver geometry
N_ROWS = 2_000_000
W = 12

rng = np.random.default_rng(0)
perm_np = rng.permutation(P).astype(np.int32)
vals_np = rng.random((P, W), dtype=np.float32)

perm = jnp.asarray(perm_np)
vals = jnp.asarray(vals_np)


def timeit(name, fn, *args, n=20):
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn_j(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:44s} {dt*1e3:8.2f} ms")
    return dt


# --- crossing strategies ---------------------------------------------------
timeit("take rows [P,12] f32", lambda v, p: jnp.take(v, p, axis=0), vals, perm)
timeit("take rows [P,12] bf16",
       lambda v, p: jnp.take(v.astype(jnp.bfloat16), p, axis=0), vals, perm)
timeit("take rows [P,4] f32",
       lambda v, p: jnp.take(v[:, :4], p, axis=0), vals, perm)
timeit("take rows [P,1] f32",
       lambda v, p: jnp.take(v[:, 0], p, axis=0), vals, perm)
timeit("take rows [P//4, 48] f32 (4x fewer, 4x wider)",
       lambda v, p: jnp.take(v.reshape(P // 4, 4 * W), p[: P // 4] // 4, axis=0),
       vals, perm)
# sort-as-permute: sort by key=inv_perm carrying the 12 floats
timeit("lax.sort key+12xf32 payload",
       lambda p, v: jax.lax.sort((p,) + tuple(v[:, i] for i in range(W)),
                                 num_keys=1), perm, vals)
timeit("lax.sort key+payload-as-2d? key + 3 f32",
       lambda p, v: jax.lax.sort((p, v[:, 0], v[:, 1], v[:, 2]), num_keys=1),
       perm, vals)
timeit("lax.sort key only", lambda p: jax.lax.sort(p), perm)
# permutation as argsort application via take of wide rows reshaped - n/a

# --- kernel geometry -------------------------------------------------------
from paddlebox_tpu.ops import sorted_spmm as sp

idx_np = np.sort(rng.integers(1, N_ROWS, size=P).astype(np.int32))
for chunk, tile in [(512, 2048), (1024, 4096), (2048, 4096), (1024, 8192),
                    (2048, 8192)]:
    dims = sp.spmm_dims(P, N_ROWS, chunk=chunk, tile=tile)
    rows = jnp.asarray(idx_np)
    plan = jax.jit(lambda r: sp.build_plan(r, dims))(rows)
    rows2d, perm2, inv2, ch, tl, fg, fs = plan
    tab = jnp.asarray(rng.random((W, dims.n_kernel), dtype=np.float32))
    try:
        t = timeit(f"gather kernel c={chunk} t={tile} n_work={dims.n_work}",
                   lambda t_, r: sp.gather_sorted(t_, r, ch, tl, fg, dims),
                   tab, rows2d)
    except Exception as e:
        print(f"gather c={chunk} t={tile} FAILED: {type(e).__name__}: {e}")
    pay = jnp.asarray(rng.random((W + 1, dims.p_pad), dtype=np.float32))
    try:
        t = timeit(f"scatter kernel c={chunk} t={tile}",
                   lambda p_, r: sp.scatter_add_sorted(p_, r, ch, tl, fs, dims),
                   pay, rows2d)
    except Exception as e:
        print(f"scatter c={chunk} t={tile} FAILED: {type(e).__name__}: {e}")
