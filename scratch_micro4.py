"""Device-time microbench immune to RPC latency: K dependent iterations
inside one jit, scalar out; per-op = (t - floor) / K."""
import time
import numpy as np
import jax
import jax.numpy as jnp
from functools import partial

P = 1_277_952
W = 12
N_ROWS = 2_000_000
K = 20
rng = np.random.default_rng(0)
perm_np = rng.permutation(P).astype(np.int32)
perm = jnp.asarray(perm_np)
vals = jnp.asarray(rng.random((P, W), dtype=np.float32))
table = jnp.asarray(rng.random((N_ROWS, W), dtype=np.float32))
idx_flat = jnp.asarray(rng.integers(1, N_ROWS, size=P).astype(np.int32))

FLOOR = None

def timeit(name, body, *args, k=K, n=6):
    """body(carry_scalar, *args) -> scalar; iterated k times."""
    @jax.jit
    def run(*a):
        def it(i, c):
            return body(c, *a)
        return jax.lax.fori_loop(0, k, it, jnp.float32(0))
    float(run(*args))
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        float(run(*args))
        ts.append(time.perf_counter() - t0)
    med = np.median(ts)
    global FLOOR
    if FLOOR is None:
        FLOOR = med
        print(f"{name:52s} total={med*1e3:8.1f} ms (floor)")
    else:
        per = (med - FLOOR) / k
        print(f"{name:52s} per-op={per*1e3:8.2f} ms")


timeit("floor (add only)", lambda c, v: c + v[0, 0], vals)
timeit("take perm [P,12]",
       lambda c, v, p: c + jnp.take(v + c, p, axis=0).sum(), vals, perm)
timeit("take table [2M,12] by [P]",
       lambda c, t, i: c + jnp.take(t + c, i, axis=0).sum(), table, idx_flat)
timeit("take table [2M,12] by [P] no-table-dep",
       lambda c, t, i: c + jnp.take(t, jnp.minimum(i + c.astype(jnp.int32), N_ROWS - 1), axis=0).sum(),
       table, idx_flat)
timeit("sum [P,12]", lambda c, v: c + (v + c).sum(), vals)
timeit("transpose [12,P]->[P,12]",
       lambda c, g: c + (g + c).T.sum(), vals.T + 0.0)
timeit("sort key+12 payload",
       lambda c, p, v: c + sum(x.sum() for x in jax.lax.sort(
           (p,) + tuple((v + c)[:, i] for i in range(W)), num_keys=1)[1:]),
       perm, vals)
timeit("sort key+iota (plan sort)",
       lambda c, i: c + jax.lax.sort(
           (jnp.minimum(i + c.astype(jnp.int32), N_ROWS - 1),
            jnp.arange(P, dtype=jnp.int32)), num_keys=1)[1].sum().astype(jnp.float32),
       idx_flat)

from paddlebox_tpu.ops import sorted_spmm as sp
dims = sp.spmm_dims(P, N_ROWS)
plan = jax.jit(lambda r: sp.build_plan(r, dims))(idx_flat)
rows2d, perm2, inv2, ch, tl, fg, fs = plan
tab_fm = jnp.asarray(rng.random((W, dims.n_kernel), dtype=np.float32))
timeit("gather kernel c512 t2048",
       lambda c, t, r: c + sp.gather_sorted(t + c, r, ch, tl, fg, dims).sum(),
       tab_fm, rows2d)
pay = jnp.asarray(rng.random((W + 1, dims.p_pad), dtype=np.float32))
timeit("scatter kernel c512 t2048",
       lambda c, p_, r: c + sp.scatter_add_sorted(p_ + c, r, ch, tl, fs,
                                                  dims).sum(),
       pay, rows2d)

dims2 = sp.spmm_dims(P, N_ROWS, chunk=1024, tile=4096)
plan2 = jax.jit(lambda r: sp.build_plan(r, dims2))(idx_flat)
rows2d2, _, _, ch2, tl2, fg2, fs2 = plan2
tab2 = jnp.asarray(rng.random((W, dims2.n_kernel), dtype=np.float32))
timeit("gather kernel c1024 t4096",
       lambda c, t, r: c + sp.gather_sorted(t + c, r, ch2, tl2, fg2,
                                            dims2).sum(), tab2, rows2d2)
pay2 = jnp.asarray(rng.random((W + 1, dims2.p_pad), dtype=np.float32))
timeit("scatter kernel c1024 t4096",
       lambda c, p_, r: c + sp.scatter_add_sorted(p_ + c, r, ch2, tl2, fs2,
                                                  dims2).sum(), pay2, rows2d2)
