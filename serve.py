"""Standalone serving-replica entrypoint — the third leg of the BoxPS
day loop (train → save_xbox → **serve**), runnable on a box that never
trains.

Loads an xbox dump (or the current one named by an xbox swap manifest)
into a read-only :class:`~paddlebox_tpu.ps.serving.ServingReplica`,
optionally watches the manifest and hot-swaps when the trainer publishes
the next day, and blocks until interrupted.  Observability comes up
in-process: ``--obs_port`` serves /statz + /timelinez, the telemetry
timeline samples ``serving.<tenant>.*`` on a cadence, and the SLO
watchdog evaluates the serving rule set (per-tenant p99 budget +
sustained-shed) alongside the defaults.

Usage:
    python serve.py --xbox /dumps/xbox_base_20260805            # pinned
    python serve.py --manifest /dumps --watch_s 2 \
        --tenants ads,feed --max_inflight 128 --obs_port 9200   # fleet
    python serve.py --ckpt /ckpt --shard 2 --n_shards 4         # sharded

Multiple replicas: run this once per port (each loads the dump
independently and answers bit-identically) and point a
``ServingRouter([(host, port), ...])`` at the set — or use
``python -m paddlebox_tpu.launch --serve N ...`` to supervise an
in-process fleet with restart-in-place.

Sharded fleets: give every process the SAME ``--n_shards`` and a
distinct ``--shard``, then point a ``ServingRouter(shard_groups=[
[(h, p), ...], ...])`` (group k = shard k's replicas) at the set.
``--ckpt`` streams pass-delta freshness from a TrainCheckpoint root
instead of day-granularity xbox manifests: each published ``save_pass``
generation is hot-patched into the live planes copy-on-write.
"""

from __future__ import annotations

import argparse
import sys
import time


def parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--xbox", default="",
                     help="xbox dump path to serve (pinned generation)")
    src.add_argument("--manifest", default="",
                     help="directory holding XBOX_MANIFEST.json; serves "
                          "the manifest's current dump")
    src.add_argument("--ckpt", default="",
                     help="TrainCheckpoint root to stream: loads the "
                          "manifest head's base+delta chain and hot-"
                          "patches each new save_pass generation "
                          "(pass-granularity freshness)")
    ap.add_argument("--watch_s", type=float, default=0.0,
                    help="poll the manifest every N seconds and hot-swap "
                         "on a generation advance (0 = never; swap verb "
                         "only).  Requires --manifest")
    ap.add_argument("--day", default="", help="day label for --xbox mode")
    ap.add_argument("--generation", type=int, default=1,
                    help="starting generation number for --xbox mode")
    ap.add_argument("--tenants", default="default",
                    help="comma-separated tenant namespaces "
                         "(FLAGS_serve_tenants)")
    ap.add_argument("--max_inflight", type=int, default=None,
                    help="per-tenant admission cap "
                         "(FLAGS_serve_max_inflight; 0 = unbounded)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="listen port (0 = ephemeral, printed on start)")
    ap.add_argument("--mf_dim", type=int, default=8,
                    help="table embedding_dim — must match the trainer "
                         "that wrote the dump")
    ap.add_argument("--seed", type=int, default=0,
                    help="default-row seed — must match the trainer for "
                         "bit-identical miss rows")
    ap.add_argument("--shard", type=int, default=0,
                    help="ServerMap shard this replica owns (with "
                         "--n_shards > 1 it keeps only its key range "
                         "plus the replicated hot set)")
    ap.add_argument("--n_shards", type=int, default=1,
                    help="total ServerMap shards in the fleet — must "
                         "match every other replica AND the router")
    ap.add_argument("--hot_keys", type=int, default=None,
                    help="top-K heat-sketch keys replicated into every "
                         "shard (0 = off) (FLAGS_serving_hot_keys)")
    ap.add_argument("--patch_poll_s", type=float, default=None,
                    help="--ckpt manifest poll cadence "
                         "(FLAGS_serving_patch_poll_s)")
    ap.add_argument("--obs_port", type=int, default=0,
                    help="/statz + /timelinez exporter port (0 = off)")
    ap.add_argument("--timeline_s", type=float, default=1.0,
                    help="timeline sample cadence feeding the SLO "
                         "watchdog (0 = off)")
    return ap.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    from paddlebox_tpu import flags
    from paddlebox_tpu.config import EmbeddingTableConfig
    from paddlebox_tpu.io.checkpoint import read_xbox_manifest
    from paddlebox_tpu.ps.serving import ServingReplica
    from paddlebox_tpu.utils import obs_server, timeline

    tenants = [t.strip() for t in args.tenants.split(",") if t.strip()]
    fl = {"serve_tenants": ",".join(tenants) or "default"}
    if args.max_inflight is not None:
        fl["serve_max_inflight"] = args.max_inflight
    if args.obs_port:
        fl["obs_port"] = args.obs_port
    if args.hot_keys is not None:
        fl["serving_hot_keys"] = args.hot_keys
    if args.patch_poll_s is not None:
        fl["serving_patch_poll_s"] = args.patch_poll_s
    flags.set_flags(fl)

    path, day, gen = args.xbox, args.day, args.generation
    if args.manifest:
        man = read_xbox_manifest(args.manifest)
        if man is None:
            print(f"serve: no {args.manifest}/XBOX_MANIFEST.json yet — "
                  f"waiting for the trainer to publish one",
                  file=sys.stderr)
            while man is None:
                time.sleep(max(args.watch_s, 0.5))
                man = read_xbox_manifest(args.manifest)
        path, day, gen = (man["path"], str(man.get("day", "")),
                          int(man["generation"]))

    config = EmbeddingTableConfig(embedding_dim=args.mf_dim)
    rep = ServingReplica(config=config, xbox_path=path, tenants=tenants,
                         max_inflight=args.max_inflight, host=args.host,
                         port=args.port, day=day, generation=gen,
                         seed=args.seed, shard=args.shard,
                         n_shards=args.n_shards,
                         ckpt_root=args.ckpt or None)
    if args.ckpt:
        rep.watch_ckpt()
    elif args.manifest and args.watch_s > 0:
        rep.watch_manifest(args.manifest, args.watch_s)

    obs_server.maybe_start_from_flags()
    sampler = None
    if args.timeline_s > 0:
        rules = timeline.default_rules() + timeline.serving_rules(tenants)
        sampler = timeline.start(interval_s=args.timeline_s, rules=rules)

    src = f"ckpt={args.ckpt}" if args.ckpt else f"dump={path}"
    print(f"serve: replica {rep.addr[0]}:{rep.addr[1]} "
          f"shard={args.shard}/{max(1, args.n_shards)} "
          f"generation={rep._gen.generation} "
          f"tenants={','.join(tenants)} {src}",
          file=sys.stderr, flush=True)
    try:
        while not rep._dead:
            time.sleep(1.0)
        print("serve: replica died", file=sys.stderr)
        return 1
    except KeyboardInterrupt:
        return 0
    finally:
        if sampler is not None:
            timeline.stop()
        rep.shutdown()


if __name__ == "__main__":
    sys.exit(main())
