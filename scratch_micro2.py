"""Careful re-measurement: per-iteration block, correctness check."""
import time
import numpy as np
import jax
import jax.numpy as jnp

P = 1_277_952
W = 12
rng = np.random.default_rng(0)
perm_np = rng.permutation(P).astype(np.int32)
vals_np = rng.random((P, W), dtype=np.float32)
perm = jnp.asarray(perm_np)
vals = jnp.asarray(vals_np)


def timeit(name, fn, *args, n=10):
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn_j(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    print(f"{name:44s} med={np.median(ts)*1e3:8.2f} ms  min={min(ts)*1e3:.2f}")
    return out


o = timeit("take rows [P,12] f32", lambda v, p: jnp.take(v, p, axis=0),
           vals, perm)
# correctness
exp = vals_np[perm_np[:100]]
got = np.asarray(o[:100])
print("take correct:", np.allclose(exp, got))

timeit("transpose [12,P] -> [P,12]",
       lambda v: v.T.reshape(P, W) + 0.0, vals.T + 0.0)
timeit("take + transpose chained",
       lambda g, p: jnp.take(g.T, p, axis=0), vals.T + 0.0, perm)
o2 = timeit("sort key + 12 payload cols",
            lambda p, v: jax.lax.sort((p,) + tuple(v[:, i] for i in range(W)),
                                      num_keys=1), perm, vals)
# verify sort-permute semantics: sorting (inv_perm, vals) by key gives vals[perm]
inv_np = np.empty_like(perm_np)
inv_np[perm_np] = np.arange(P, dtype=np.int32)
inv = jnp.asarray(inv_np)
o3 = jax.jit(lambda k, v: jax.lax.sort((k,) + tuple(v[:, i] for i in range(W)),
                                       num_keys=1))(inv, vals)
got3 = np.stack([np.asarray(c[:100]) for c in o3[1:]], axis=1)
print("sort-permute correct:", np.allclose(vals_np[perm_np[:100]], got3))

# device->host roundtrip sanity: how long does materializing take?
t0 = time.perf_counter(); _ = np.asarray(o[:10]); print("d2h 10 rows:", time.perf_counter()-t0)
