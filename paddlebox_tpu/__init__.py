"""paddlebox_tpu — a TPU-native sparse-CTR training framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of PaddleBox
(Baidu's GPU-box sparse parameter-server trainer embedded in the PaddlePaddle
fork at mark914/PaddleBox): trillion-parameter embedding tables streamed
through a tiered parameter server (TPU HBM working set -> host DRAM -> SSD),
pass/day-scoped datasets with inter-host shuffle, fused CTR kernels, streaming
AUC metrics, and DP/TP/PP/sharding/MoE/CP parallelism over a jax device mesh.

Structural parity map: see SURVEY.md at the repo root.  Reference citations in
docstrings point into /root/reference (mark914/PaddleBox).
"""

from paddlebox_tpu.version import __version__  # noqa: F401
from paddlebox_tpu import flags  # noqa: F401

set_flags = flags.set_flags
get_flags = flags.get_flags
