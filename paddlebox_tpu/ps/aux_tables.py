"""Auxiliary tables: replica cache + string-keyed input table.

≙ GpuReplicaCache (box_wrapper.h:63-122 + PullCacheValue box_wrapper.cu:1210)
— a small dense table fully replicated in every device's HBM, pulled by row
index; and InputTable (box_wrapper.h:124-197, ops lookup_input,
InputTableDataFeed data_feed.h:2224) — a host-side string→index dictionary
assigning stable ids used as replica-cache rows.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax

from paddlebox_tpu.utils import lockdep
import jax.numpy as jnp


class ReplicaCache:
    """Host-accumulated dense rows, replicated to device; gather by index.

    Row 0 is reserved as the zero/miss row (same convention as the sparse
    working set)."""

    def __init__(self, dim: int):
        self.dim = dim
        self._rows: List[np.ndarray] = [np.zeros((dim,), np.float32)]
        self._device: Optional[jnp.ndarray] = None
        self._lock = lockdep.lock("ps.aux_tables.ReplicaCache._lock")

    def add_item(self, vec: np.ndarray) -> int:
        with self._lock:
            self._rows.append(np.asarray(vec, np.float32).reshape(self.dim))
            self._device = None
            return len(self._rows) - 1

    def add_items(self, mat: np.ndarray) -> np.ndarray:
        with self._lock:
            start = len(self._rows)
            for r in np.asarray(mat, np.float32).reshape(-1, self.dim):
                self._rows.append(r)
            self._device = None
            return np.arange(start, len(self._rows))

    def to_device(self, sharding=None) -> jnp.ndarray:
        """Replicate to HBM (≙ h2d copy in InitializeGPUAndLoadModel)."""
        with self._lock:
            if self._device is None:
                host = np.stack(self._rows)
                self._device = (jax.device_put(host, sharding)
                                if sharding is not None else
                                jnp.asarray(host))
            return self._device

    @staticmethod
    def pull(table: jnp.ndarray, indices: jnp.ndarray) -> jnp.ndarray:
        """jit-able gather (≙ PullCacheValue kernel)."""
        return table[indices]

    def __len__(self):
        return len(self._rows)


class InputTable:
    """String → stable index (≙ InputTable box_wrapper.h:124; the index is
    then used against a ReplicaCache or dense var)."""

    def __init__(self):
        self._map: Dict[str, int] = {}
        self._lock = lockdep.lock("ps.aux_tables.InputTable._lock")

    def get_or_insert(self, key: str) -> int:
        with self._lock:
            idx = self._map.get(key)
            if idx is None:
                idx = len(self._map) + 1  # 0 = miss
                self._map[key] = idx
            return idx

    def get_or_insert_many(self, keys: Sequence[str]) -> np.ndarray:
        """Batched resolve — one lock round-trip per call, not per token
        (the parser hot loop resolves a whole slot occurrence list)."""
        with self._lock:
            out = np.empty((len(keys),), np.uint64)
            m = self._map
            for i, k in enumerate(keys):
                idx = m.get(k)
                if idx is None:
                    idx = len(m) + 1
                    m[k] = idx
                out[i] = idx
            return out

    def lookup(self, keys: Sequence[str]) -> np.ndarray:
        with self._lock:
            return np.array([self._map.get(k, 0) for k in keys], np.int32)

    def __len__(self):
        return len(self._map)

    def save(self, path: str) -> None:
        # dump must snapshot the map atomically vs concurrent resolve();
        # write-tmp + os.replace so a crash mid-dump never leaves a torn
        # file at the committed name (PB502 discipline)
        tmp = path + ".tmp"
        # pboxlint: disable-next=PB104 -- save is a rare cold verb
        with self._lock, open(tmp, "w") as f:
            for k, v in self._map.items():
                f.write(f"{k}\t{v}\n")
        os.replace(tmp, path)

    def load(self, path: str) -> None:
        # load swaps the whole map; readers must not see a half-built one
        # pboxlint: disable-next=PB104 -- the map swap is the locked op
        with self._lock, open(path) as f:
            self._map = {}
            for line in f:
                k, v = line.rstrip("\n").split("\t")
                self._map[k] = int(v)
