"""Pass lifecycle engine — the BoxWrapper/BoxHelper equivalent.

≙ BoxWrapper (box_wrapper.h:377) + BoxHelper (box_wrapper.h:1043) + the
open-source PSGPUWrapper pass machinery (ps_gpu_wrapper.cc:114-1007):

  set_date            ≙ BoxHelper::SetDate (box_wrapper.h:1048)
  begin_feed_pass     ≙ BeginFeedPass (box_wrapper.cc:129) — opens a key
                        collection agent for the loading pass
  add_keys            ≙ PSAgent::AddKey via MergeInsKeys (data_set.cc:2293)
  end_feed_pass       ≙ EndFeedPass (box_wrapper.cc:152) — dedups the pass
                        keys (≙ PreBuildTask ps_gpu_wrapper.cc:114), pulls
                        rows from the host table (≙ BuildPull :337) and
                        builds the device working set (≙ BuildGPUTask :684)
  begin_pass/end_pass ≙ box_wrapper.cc:171,186 — end_pass flushes the
                        working set back to the DRAM tier
                        (≙ EndPass dump_pool_to_cpu ps_gpu_wrapper.cc:983)
  save_base/save_delta≙ SaveBase/SaveDelta (box_wrapper.cc:1286)
  load                ≙ InitializeGPUAndLoadModel (box_wrapper.h:624)
  shrink              ≙ ShrinkTable (box_wrapper.h:638)
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from paddlebox_tpu import flags
from paddlebox_tpu.config import EmbeddingTableConfig
from paddlebox_tpu.metrics import quality
from paddlebox_tpu.parallel.topology import HybridTopology
from paddlebox_tpu.ps import embedding, faults
from paddlebox_tpu.ps import heat
from paddlebox_tpu.ps.device_cache import CachePlan, DeviceRowCache
from paddlebox_tpu.ps.host_table import ShardedHostTable
from paddlebox_tpu.utils import flight, intervals, lockdep, trace
from paddlebox_tpu.utils.monitor import stat_add, stat_set, stat_snapshot
from paddlebox_tpu.utils.timer import TimerRegistry

flags.define_flag(
    "obs_pass_report", False,
    "print a PrintSyncTimer-style per-pass wall-time table (pull/train/"
    "write seconds, wire bytes, inflight hwm, injected faults) at every "
    "end_pass (≙ PrintSyncTimer box_wrapper.h:795)")


class BoxPSEngine:
    def __init__(self, config: Optional[EmbeddingTableConfig] = None,
                 topology: Optional[HybridTopology] = None, seed: int = 0,
                 mode: str = "train", device_rank: int = 0,
                 device_world: int = 1):
        if mode not in ("train", "serving"):
            raise ValueError(f"mode must be 'train' or 'serving', "
                             f"got {mode!r}")
        self.config = config or EmbeddingTableConfig()
        self.topology = topology
        heat.maybe_enable_from_flags()
        # declared intent, not enforcement: io/checkpoint.py uses it to
        # warn when a serving-only loader (load_xbox) feeds a training
        # engine — the xbox dump cannot round-trip mf_size exactly
        self.mode = mode
        self.table = ShardedHostTable(self.config, seed=seed)
        self.timers = TimerRegistry()
        self.day_id: Optional[str] = None
        self.pass_id = 0
        self.phase = 1  # join/update flip (≙ FlipPhase box_wrapper.h:805)

        self._agent_lock = lockdep.lock("ps.pass_manager.BoxPSEngine._agent_lock")
        self._agent_keys: List[np.ndarray] = []
        self._feeding = False

        self.mapper: Optional[embedding.PassKeyMapper] = None
        self.ws: Optional[Dict[str, jnp.ndarray]] = None
        self.num_keys = 0

        # pass-pipelined preload (≙ PreLoadIntoMemory + pre-build thread,
        # box_wrapper.h:1141 / ps_gpu_wrapper.cc:907-955): the next pass's
        # working set builds in the background while the current one trains
        self._build_thread: Optional[threading.Thread] = None
        self._next: Optional[tuple] = None  # (mapper, n, host_rows, plan)
        self._last_written: Optional[np.ndarray] = None

        # HBM tier: device-resident hot-row cache (ps/device_cache.py).
        # No longer single-topology-gated: under a sharded PS cluster the
        # cache keys admission by the fleet's ServerMap (attached lazily
        # at the first feed pass — a remote table is wired to the engine
        # AFTER __init__), and per-engine (device_rank, device_world)
        # partitions the cached slice so aggregate cache capacity scales
        # with the mesh instead of every engine caching the same head rows.
        self.device_rank = int(device_rank)
        self.device_world = max(1, int(device_world))
        self.cache: Optional[DeviceRowCache] = None
        self._cache_smap_attached = False
        if mode == "train" and flags.get_flags("ps_device_cache"):
            cap = int(flags.get_flags("ps_device_cache_rows"))
            if cap > 0:
                sgd = self.config.sgd
                self.cache = DeviceRowCache(
                    cap, nonclk_coeff=sgd.nonclk_coeff,
                    clk_coeff=sgd.clk_coeff)
        self._feed_cache_snap = None     # index snapshot for the open feed
        self._cache_fresh_keys = None    # adoption-fresh rows (skip refresh)
        # build_working_set staging-buffer pool (ps.engine.ws_buffer_reuse):
        # adoption/upload is main-thread-only, so one pool per engine
        self._ws_buffers: Dict[str, np.ndarray] = {}

    # -- date / phase --------------------------------------------------------
    def set_date(self, date: str, *, table_decay: bool = True) -> None:
        """Advance the engine's day.  ``table_decay=False`` keeps the
        local day bookkeeping (quality rollover, cache invalidation) but
        skips the ``table.end_day()`` decay — the trainer fleet's mode,
        where exactly ONE rank (the elected leader) drives the decay
        through the 2-phase lifecycle verb and every engine merely
        adopts the new date; N engines each decaying the shared remote
        table would compound the decay N times."""
        if self.day_id is not None and date != self.day_id:
            flight.record("day_end", day=self.day_id, next_day=date)
            if table_decay:
                with self.timers("end_day"):
                    self.table.end_day()
            # day-scale concept-drift rollover (quality.psi.day)
            quality.end_day(self.day_id)
            # coherence point: end_day decayed show/click table-wide —
            # every cached row is stale now (the prefetcher's day-boundary
            # drain guarantees no feed snapshot is in flight here)
            if self.cache is not None:
                self.cache.invalidate("end_day")
            if heat.ACTIVE is not None:
                # heat is per-process telemetry: every engine fades its
                # own sketches at its own day boundary (no N-fold
                # compounding concern — nothing here is shared state)
                heat.ACTIVE.decay_day()
        self.day_id = date

    def flip_phase(self) -> None:
        self.phase = 1 - self.phase

    # -- feed pass -----------------------------------------------------------
    def begin_feed_pass(self) -> None:
        assert not self._feeding, "previous feed pass not closed"
        with self._agent_lock:
            self._agent_keys = []
        # per-pass observability baseline: the end_pass report prints
        # DELTAS against these (wire bytes, faults, timer seconds of this
        # pass only).  Held PENDING until begin_pass promotes it — under
        # pass prefetch, pass N+1's begin_feed_pass runs while pass N is
        # still training, and must not clobber N's open window.
        self._feed_obs0 = {
            # ckpt.* rides along so the per-pass report can show this
            # pass's checkpoint cost next to its wire/train phases
            "stats0": {**stat_snapshot("ps."), **stat_snapshot("ckpt.")},
            "timers0": {n: (s, c) for n, s, c in self.timers.rows()},
            # feed-gap window anchor: end_pass computes the pass's
            # device_busy_frac / feed_gap_ratio over [here, write-back]
            "m0": time.monotonic(),
        }
        flight.record("pass_feed_begin", pass_id=self.pass_id + 1,
                      day=self.day_id)
        # lazy cluster attach: a RemoteTableAdapter over a sharded fleet
        # is wired to the engine after __init__, so adopt its ServerMap
        # for cache admission at the first feed that sees one
        if self.cache is not None and not self._cache_smap_attached:
            smap = getattr(self.table, "server_map", None)
            if smap is not None:
                self.cache.attach_server_map(
                    smap, device_rank=self.device_rank,
                    device_world=self.device_world)
                # elastic fleet: when a fence redirect adopts a newer
                # map, invalidate exactly the moved key range (stale
                # cached rows now belong to a different shard)
                client = getattr(self.table, "client", None)
                if client is not None \
                        and hasattr(client, "on_map_change"):
                    cache = self.cache
                    client.on_map_change(
                        lambda m: cache.update_server_map(
                            m, reason="map_refresh"))
                # pboxlint: disable-next=PB102 -- single-coordinator lifecycle flag
                self._cache_smap_attached = True
        # publish the cache index snapshot for THIS feed (prefetcher-safe:
        # the build thread intersects against this frozen view; authoritative
        # hit resolution re-checks the live index at adoption)
        self._feed_cache_snap = (self.cache.snapshot()
                                 if self.cache is not None else None)
        # the pass lifecycle is driven by one coordinator thread;
        # _agent_lock only guards the add_keys sink
        # pboxlint: disable-next=PB102 -- single-coordinator lifecycle flag
        self._feeding = True

    def add_keys(self, keys: np.ndarray) -> None:
        """Thread-safe feasign sink for dataset reader threads."""
        if len(keys):
            with self._agent_lock:
                self._agent_keys.append(np.asarray(keys, np.uint64))

    def _dedup_agent_keys(self) -> np.ndarray:
        with self.timers("dedup_keys"):
            with self._agent_lock:
                parts = self._agent_keys
                self._agent_keys = []
            allk = np.concatenate(parts) if parts else \
                np.empty((0,), np.uint64)
            uniq = np.unique(allk)
            return uniq[uniq != 0]  # key 0 = reserved zero row

    def _build_host(self, uniq: np.ndarray) -> tuple:
        # the pass-build bulk pull is one of the two big wire transfers
        # per pass (with the end-pass delta push) — surface its wall time
        # in the monitor so the pipelined PS wire path's effect shows up
        # beside the ps.wire.* byte counters (ps/service.py)
        snap = self._feed_cache_snap
        with self.timers("build_pull"), \
                trace.span("ps.engine.build_pull", keys=len(uniq)):
            t0 = time.monotonic()
            plan = None
            if snap is not None and len(snap.keys) and len(uniq):
                # HBM tier: pull only cache MISSES over the wire; the
                # snapshot-hit rows are filled from the device cache at
                # adoption (begin_pass, main thread)
                hit_mask = snap.lookup(uniq)
                miss = uniq[~hit_mask]
                if len(miss):
                    pulled = self.table.bulk_pull(miss)
                    miss_pos = np.flatnonzero(~hit_mask)
                    host_rows = {}
                    for f, v in pulled.items():
                        full = np.zeros((len(uniq),) + v.shape[1:], v.dtype)
                        full[miss_pos] = v
                        host_rows[f] = full
                else:
                    host_rows = self.cache.host_templates(len(uniq))
                plan = CachePlan(uniq[hit_mask], np.flatnonzero(hit_mask),
                                 snap, len(miss),
                                 miss if len(miss) else None)
                pulled_n = len(miss)
            else:
                host_rows = self.table.bulk_pull(uniq)
                pulled_n = len(uniq)
                if self.cache is not None:
                    stat_add("ps.cache.misses", float(len(uniq)))
                    if heat.ACTIVE is not None:
                        heat.ACTIVE.observe_cache(0, len(uniq))
            t1 = time.monotonic()
            intervals.record("pull", t0, t1)
            stat_add("ps.engine.build_pull_s", t1 - t0)
            stat_add("ps.engine.build_pull_rows", float(pulled_n))
        return embedding.PassKeyMapper(uniq), len(uniq), host_rows, plan

    def _upload(self, host_rows) -> Dict[str, jnp.ndarray]:
        # The ws built here is the one contract every step path consumes
        # — fast's padded [S,L,B] gathers, mxu's sorted chunks, and
        # ragged's CSR [U]-row gather/scatter all index the same [N]-row
        # SoA (row 0 reserved zero), so path selection never changes what
        # begin_pass/end_pass upload or write back.
        #
        # ctr_double accessor: the host keeps f64 show/click; the device
        # trains in f32, so end_pass writes back host + (device delta) in
        # f64 — counters stay exact past f32's 2^24 integer range
        # (≙ DownpourCtrDoubleAccessor, ctr_double_accessor.h)
        if host_rows["show"].dtype == np.float64:
            self._pulled_stats = {f: host_rows[f].copy()
                                  for f in ("show", "click")}
        else:
            self._pulled_stats = None
        with self.timers("build_device"):
            t0 = time.monotonic()
            sharding = (self.topology.table_sharding()
                        if self.topology is not None else None)
            ws = embedding.build_working_set(
                host_rows, self.config.embedding_dim, sharding=sharding,
                buffers=self._ws_buffers)
            intervals.record("upload", t0, time.monotonic())
            if self._pulled_stats is not None:
                # exact per-pass counter accumulators (small magnitudes
                # stay exact in f32); merged into the f64 host stats at
                # end_pass
                ws["show_acc"] = jnp.zeros_like(ws["show"])
                ws["click_acc"] = jnp.zeros_like(ws["click"])
            return ws

    def _adopt(self, mapper, n: int, host_rows,
               plan: Optional[CachePlan]) -> Dict[str, jnp.ndarray]:
        """Main-thread working-set assembly: resolve the feed's cache plan
        against the live index, wire-pull any hit that was evicted since
        the snapshot, reconcile the f64 pulled-stats / delta-mode
        write-back base, upload the miss plane and gather the hit plane
        device-side."""
        if plan is None or self.cache is None:
            return self._upload(host_rows)
        with self.timers("cache_gather"):
            valid, slots = self.cache.resolve(plan.keys, plan.snap)
            n_valid = int(valid.sum())
            inv_keys = plan.keys[~valid]
            if len(inv_keys):
                # evicted (or invalidated) between snapshot and adoption —
                # an ordinary wire miss, just discovered late
                fresh = self.table.bulk_pull(inv_keys)
                inv_pos = plan.pos[~valid]
                for f, v in fresh.items():
                    if f in host_rows:
                        host_rows[f][inv_pos] = v
                stat_add("ps.engine.build_pull_rows", float(len(inv_keys)))
                stat_add("ps.cache.gather_fallback_rows",
                         float(len(inv_keys)))
            hit_pos = plan.pos[valid]
            hit_slots = np.asarray(slots[valid], np.int32)
            delta_seed = (getattr(self.table, "delta_mode", False)
                          and hasattr(self.table, "seed_snapshot"))
            if n_valid:
                if delta_seed:
                    # the write-back base for hit rows is the cache's host
                    # mirror (exactly what we last wrote back for them)
                    for f, v in self.cache.read_mirror(hit_slots).items():
                        if f in host_rows:
                            host_rows[f][hit_pos] = v
                elif host_rows["show"].dtype == np.float64:
                    # ctr_double: the f64 stats base comes from the mirror
                    for f, v in self.cache.read_mirror(
                            hit_slots, fields=("show", "click")).items():
                        host_rows[f][hit_pos] = v
            if delta_seed:
                # delta-mode remotes snapshot what they pull — only the
                # misses here.  Install the full assembled key set as the
                # write-back base, dropping the partial pull snapshots.
                consumed = [k for k in (plan.pulled_keys, inv_keys)
                            if k is not None and len(k)]
                self.table.seed_snapshot(mapper.sorted_keys, host_rows,
                                         consumed=consumed)
            ws = self._upload(host_rows)
            if n_valid:
                ws = self.cache.scatter_into(
                    ws, mapper(plan.keys[valid]), hit_slots)
            # rows assembled from post-write-back state at adoption time —
            # the stale-row refresh must not re-pull them
            self._cache_fresh_keys = np.union1d(
                plan.keys[valid], inv_keys) if len(inv_keys) \
                else plan.keys[valid]
            n_miss = plan.n_miss + len(inv_keys)
            stat_add("ps.cache.hits", float(n_valid))
            stat_add("ps.cache.misses", float(n_miss))
            stat_set("ps.cache.hit_rate",
                     n_valid / max(n_valid + n_miss, 1))
            if heat.ACTIVE is not None:
                # hot-coverage: share of this pass's pulled rows the
                # device cache served resident
                heat.ACTIVE.observe_cache(n_valid, n_miss)
            stat_add("ps.cache.bytes_saved",
                     float(n_valid * self.cache.row_bytes))
        return ws

    def _build(self, uniq: np.ndarray) -> tuple:
        mapper, n, host_rows, plan = self._build_host(uniq)
        return mapper, n, self._adopt(mapper, n, host_rows, plan)

    def end_feed_pass(self, async_build: bool = False) -> None:
        """Dedup pass keys, pull host rows, build the device working set.

        async_build=True builds in a background thread for the NEXT pass
        while the current one is still training (≙ EndFeedPass handing the
        agent to the feedpass thread pool, box_wrapper.cc:152 +
        start_build_thread ps_gpu_wrapper.cc:907); adopt the result with
        begin_pass, which also refreshes rows the in-flight pass updates at
        its end_pass (the reference accepts that staleness — we do not).
        """
        assert self._feeding
        # pboxlint: disable-next=PB102 -- lifecycle flag, coordinator-only
        self._feeding = False
        uniq = self._dedup_agent_keys()
        flight.record("pass_feed_end", pass_id=self.pass_id + 1,
                      keys=len(uniq), asynchronous=async_build)
        if not async_build:
            assert self._build_thread is None and self._next is None, \
                "a preloaded pass is pending adoption (begin_pass) — " \
                "mixing it with a synchronous feed pass would discard data"
            self.mapper, self.num_keys, self.ws = self._build(uniq)
            return
        assert self._build_thread is None, "previous async build not adopted"

        # host-only work in the thread (dedup'd table pull — the slow DRAM/
        # SSD part); the device upload happens in begin_pass on the MAIN
        # thread: concurrent device dispatch from two python threads can
        # deadlock single-stream runtimes
        def run():
            try:
                self._next = self._build_host(uniq)
            except BaseException as e:  # re-raised in begin_pass, not lost
                self._build_error = e

        self._build_error = None
        # the handoff is coordinator-only: begin_pass joins before clearing
        # pboxlint: disable-next=PB102 -- coordinator-only thread handoff
        self._build_thread = threading.Thread(target=run, daemon=True)
        self._build_thread.start()

    def wait_feed_pass_done(self) -> None:
        """≙ BoxHelper::WaitFeedPassDone (box_wrapper.h:1156).  Raises if
        the background build failed — whichever of this or begin_pass runs
        first surfaces the error; a stale previous working set must never
        silently train in place of the failed pass."""
        if self._build_thread is not None:
            self._build_thread.join()
            self._build_thread = None
        err = getattr(self, "_build_error", None)
        if err is not None:
            self._build_error = None
            raise RuntimeError(
                "async working-set build failed (end_feed_pass "
                "background thread)") from err

    def peek_next_mapper(self) -> Optional[embedding.PassKeyMapper]:
        """The key mapper the NEXT begin_pass will adopt — available as
        soon as the async host build finishes (this waits on it), WITHOUT
        adopting the working set.  The pass prefetcher packs pass N+1's
        feed against this on a background thread while pass N still
        trains; key translation reads only the sorted key array, which
        begin_pass's stale-row refresh never mutates (it rewrites working-
        set VALUES), so the pre-adoption pack is bit-identical to packing
        after adoption."""
        self.wait_feed_pass_done()
        if self._next is not None:
            return self._next[0]
        return self.mapper

    # -- train pass ----------------------------------------------------------
    def begin_pass(self) -> None:
        with trace.span("ps.engine.begin_pass", pass_id=self.pass_id + 1):
            if self._build_thread is not None or self._next is not None:
                self.wait_feed_pass_done()  # raises if async build failed
                assert self._next is not None
                self.mapper, self.num_keys, host_rows, plan = self._next
                self.ws = self._adopt(self.mapper, self.num_keys,
                                      host_rows, plan)
                self._next = None
                self._refresh_stale_rows()
                self._cache_fresh_keys = None
            assert self.ws is not None, \
                "end_feed_pass must run before begin_pass"
            # promote the pending feed-time baseline: THIS pass's report
            # window (prefetch keeps N+1's pending window separate while
            # N's promoted one is still open)
            obs0 = getattr(self, "_feed_obs0", None)
            if obs0 is not None:
                self._pass_obs0 = obs0
                self._feed_obs0 = None
            self.pass_id += 1
            flight.record("pass_begin", pass_id=self.pass_id,
                          keys=self.num_keys)

    def _refresh_stale_rows(self) -> None:
        """An async-built working set pulled host rows while the previous
        pass was still training; rows that pass wrote at its end_pass are
        stale here.  Re-pull the intersection and overwrite."""
        if self._last_written is None or self.mapper is None \
                or self.num_keys == 0:
            return
        stale = np.intersect1d(self._last_written, self.mapper.sorted_keys,
                               assume_unique=True)
        fresh_keys = self._cache_fresh_keys
        if fresh_keys is not None and len(fresh_keys):
            # cache hits (and adoption-time fallback pulls) were assembled
            # AFTER the previous pass's write-back + fold-back — already
            # fresh, and re-pulling them would hand back the wire bytes
            # the cache just saved
            stale = np.setdiff1d(stale, fresh_keys, assume_unique=True)
        if not len(stale):
            return
        with self.timers("refresh_stale"):
            # remote tables: this pull retries through the exactly-once
            # protocol (service.py) — a dropped connection here no longer
            # aborts the pass adoption
            stat_add("ps.engine.stale_refresh_rows", float(len(stale)))
            fresh = self.table.bulk_pull(stale)
            if getattr(self, "_pulled_stats", None) is not None:
                pos = np.searchsorted(self.mapper.sorted_keys, stale)
                for f in ("show", "click"):
                    if f in fresh:
                        self._pulled_stats[f][pos] = fresh[f]
            if hasattr(self.table, "patch_snapshot"):
                # delta-mode remote tables: the refreshed values must also
                # replace the write-back base for these rows (service.py
                # RemoteTableAdapter.patch_snapshot)
                self.table.patch_snapshot(self.mapper.sorted_keys, stale,
                                          fresh)
            rows = jnp.asarray(self.mapper(stale))
            for f in self.ws:
                if f in fresh:
                    self.ws[f] = self.ws[f].at[rows].set(
                        jnp.asarray(fresh[f], self.ws[f].dtype))

    def end_pass(self, need_save_delta: bool = False,
                 delta_path: str = "") -> None:
        """Write the trained working set back to the DRAM tier.

        Pass-level recovery contract: if the write-back raises (remote PS
        unreachable past the client's retry deadline), the engine state —
        ``ws``, ``mapper``, ``_pulled_stats`` — is left intact and a
        delta-mode RemoteTableAdapter restores its pull snapshot + pins
        the chunk rid-group, so calling ``end_pass`` again replays the
        SAME write-back exactly-once (already-applied chunks dedup
        server-side)."""
        assert self.ws is not None and self.mapper is not None
        if faults.ACTIVE is not None:
            # chaos SIGKILL-schedule site: a seeded kill here simulates the
            # trainer dying with a trained-but-unwritten pass — auto-resume
            # must re-drive the pass from the last checkpoint
            faults.on_lifecycle("end_pass")
        if embedding.is_quantized(self.ws):
            raise RuntimeError(
                "serving-frozen working set cannot write back (its embedx "
                "is an int16 grid, not the f32 store) — a frozen pass ends "
                "by discarding the device copy (engine.ws = None) or "
                "rebuilding the pass")
        with self.timers("dump_to_cpu"), \
                trace.span("ps.engine.end_pass_write",
                           pass_id=self.pass_id, keys=self.num_keys):
            soa = embedding.dump_working_set(self.ws, self.num_keys)
            soa["unseen_days"] = np.zeros((self.num_keys,), np.float32)
            if getattr(self, "_pulled_stats", None) is not None:
                # f64 base + the exact per-pass delta accumulators — the
                # absolute device copy may have rounded (f32 at 2^24+),
                # the small-magnitude delta did not
                for f in ("show", "click"):
                    soa[f] = self._pulled_stats[f] + \
                        soa[f + "_acc"].astype(np.float64)
                    del soa[f + "_acc"]
            try:
                t0 = time.monotonic()
                self.table.bulk_write(self.mapper.sorted_keys, soa)
                t1 = time.monotonic()
                intervals.record("write", t0, t1)
                stat_add("ps.engine.end_pass_write_s", t1 - t0)
            except Exception:
                # keep _pulled_stats/ws/mapper: a re-driven end_pass must
                # rebuild the IDENTICAL soa (clearing the stats first used
                # to make the retry write absolute f32 values — divergent)
                stat_add("ps.engine.end_pass_write_failure")
                raise
            self._pulled_stats = None
            if self.cache is not None:
                # fold-back: the ONLY cache row mutation (PB503) — after
                # the table write succeeded, so a failed write-back replays
                # end_pass with the cache untouched (exactly-once), and a
                # checkpoint commit never sees cache-only state
                with self.timers("cache_fold"):
                    fold, casts = soa, None
                    pop = getattr(self.table, "pop_write_effect", None)
                    eff = pop() if pop is not None else None
                    if eff is not None:
                        # delta-mode remote: the server materialized
                        # base+delta, which can differ from the written
                        # soa in the last ulp — the cache must hold the
                        # SERVER's bits or a later hit diverges from the
                        # wire pull it replaces
                        fold = eff
                        casts = {f: eff[f] for f in eff
                                 if f != "unseen_days"}
                    elif soa["show"].dtype == np.float64:
                        # hit rows must replay the same f64→f32 cast a
                        # wire pull of the written row would
                        casts = {f: soa[f].astype(np.float32)
                                 for f in ("show", "click")}
                    self.cache.update_after_pass(
                        self.mapper.sorted_keys, fold, self.ws,
                        pass_id=self.pass_id, host_casts=casts)
        self.ws = None
        self._last_written = np.asarray(self.mapper.sorted_keys)
        # feed-gap attribution over THIS pass's window (begin_feed_pass →
        # write-back done), overlap-aware: surfaces in /statz, the
        # per-pass report, and the BENCH result JSON (ROADMAP item 2)
        obs0 = getattr(self, "_pass_obs0", None) or {}
        m0 = obs0.get("m0")
        if m0 is not None:
            rep = intervals.report(since=m0)
            self._pass_feed_report = rep
            stat_set("feed.device_busy_frac", rep["device_busy_frac"])
            stat_set("feed.feed_gap_ratio", rep["feed_gap_ratio"])
            # per-stage prefetch-hidden seconds: host feed work that ran
            # UNDER device busy — the pipelined engine's win in /statz
            for k in ("pull", "pack", "upload", "write"):
                # pboxlint: disable-next=PB204 -- closed kind set (intervals.KINDS)
                stat_set(f"feed.{k}_hidden_s", rep.get(f"{k}_hidden_s", 0.0))
        flight.record("pass_end", pass_id=self.pass_id,
                      keys=self.num_keys)
        if flags.get_flags("obs_pass_report"):
            print(self.pass_report(), flush=True)
        if need_save_delta and delta_path:
            self.save_delta(delta_path)

    def reset_feed_state(self) -> None:
        """Drop every in-flight feed/pass artifact so a checkpoint restore
        starts from a clean pass boundary (io/checkpoint.py resume, and
        fleet.train_passes' auto-resume loop after a simulated trainer
        death).  Joins a live async build first — its thread touches
        ``_next``/``_build_error`` and must not race the reset — then
        clears the working set, mapper, agent sink and the stale-row
        cursor (the restored table already reflects the last durable
        pass; replaying a stale ``_last_written`` would re-pull rows the
        rollback discarded)."""
        t = self._build_thread
        if t is not None:
            t.join(timeout=30)
        # crash-recovery teardown: the only writer thread joined above
        # pboxlint: disable-next=PB102 -- no concurrent builder remains
        self._build_thread = None
        self._build_error = None
        self._next = None
        with self._agent_lock:
            self._agent_keys = []
        # pboxlint: disable-next=PB102 -- single-coordinator lifecycle flag
        self._feeding = False
        self._feed_obs0 = None
        self._pass_obs0 = None
        self.ws = None
        self.mapper = None
        self.num_keys = 0
        self._pulled_stats = None
        self._last_written = None
        self._feed_cache_snap = None
        self._cache_fresh_keys = None
        if self.cache is not None:
            # coherence point: a checkpoint restore / crash teardown may
            # roll the table back past rows the cache folded in — rebuild
            # cold (covers io/checkpoint.resume, PassPrefetcher.abort and
            # fleet.train_passes' auto-resume loop)
            self.cache.invalidate("reset")

    def freeze_for_serving(self, scale: float = 1.0 / 32767.0) -> None:
        """Re-encode the live working set's embedx as int16 for pull-only
        serving (≙ loading a quant-feature table + EmbedxQuantOp dequant,
        box_wrapper.cu:37 / pull_embedx_scale box_wrapper.h:655): embedx
        pulls read half the bytes, the table holds half the HBM.  Training
        on a frozen set raises — re-run the pass lifecycle to train."""
        assert self.ws is not None, "no live working set to freeze"
        qb = self.config.quant_bits or 16
        self.ws = embedding.quantize_working_set(self.ws, qb, scale)
        if self.cache is not None:
            # a frozen pass never writes back — don't let its rows serve
            # as a later pass's write base
            self.cache.invalidate("freeze")

    # -- persistence ---------------------------------------------------------
    def _save(self, path: str, mode: str) -> int:
        rows = self.table.save(path, mode=mode)
        flight.record("checkpoint_save", mode=mode, path=path, rows=rows)
        return rows

    def save_base(self, path: str) -> int:
        return self._save(path, "base")

    def save_delta(self, path: str) -> int:
        return self._save(path, "delta")

    def save_checkpoint(self, path: str) -> int:
        return self._save(path, "all")

    def load(self, path: str) -> int:
        rows = self.table.load(path)
        flight.record("checkpoint_load", path=path, rows=rows)
        if self.cache is not None:
            self.cache.invalidate("load")
        return rows

    def shrink(self) -> int:
        removed = self.table.shrink()
        if self.cache is not None:
            # shrink evicted dead table rows — cached copies of them must
            # not resurrect through a later fold-back's write base
            self.cache.invalidate("shrink")
        return removed

    # -- convenience ---------------------------------------------------------
    def attach_dataset(self, dataset) -> None:
        """Register this engine as the dataset's feasign consumer
        (≙ PadBoxSlotDataset holding the BoxWrapper agent)."""
        dataset.register_key_consumer(self.add_keys)

    def print_sync_timers(self) -> str:
        return self.timers.report()

    def pass_report(self) -> str:
        """PrintSyncTimer-style per-pass wall-time table (≙ PrintSyncTimer
        box_wrapper.h:795): the phase seconds of THIS pass (deltas since
        begin_feed_pass), plus the pass's wire bytes, pipeline pressure
        and injected-fault counts — the at-a-glance answer to "was this
        pass pull-bound, train-bound or write-bound?".  Printed at every
        end_pass under ``FLAGS_obs_pass_report``."""
        obs0 = getattr(self, "_pass_obs0", None) or {}
        stats0 = obs0.get("stats0") or {}
        timers0 = obs0.get("timers0") or {}
        cur = {**stat_snapshot("ps."), **stat_snapshot("ckpt.")}

        def delta(key: str) -> float:
            return cur.get(key, 0.0) - stats0.get(key, 0.0)

        lines = [f"---- PrintSyncTimer pass {self.pass_id} "
                 f"day {self.day_id or '-'} ----",
                 f"  {'phase':<20} {'seconds':>10} {'count':>7}"]
        for name, secs, count in self.timers.rows():
            s0, c0 = timers0.get(name, (0.0, 0))
            if count - c0 == 0 and secs - s0 < 1e-9:
                continue            # phase did not run this pass
            lines.append(f"  {name:<20} {secs - s0:>10.3f} "
                         f"{count - c0:>7d}")
        tx = {k[len("ps.wire."):-len(".tx_bytes")]: delta(k)
              for k in cur if k.startswith("ps.wire.")
              and k.endswith(".tx_bytes") and delta(k) > 0}
        if tx:
            per_verb = " ".join(f"{v}={int(b)}" for v, b in sorted(tx.items()))
            lines.append(f"  wire tx_bytes: total={int(sum(tx.values()))} "
                         f"({per_verb})")
        lines.append(
            f"  inflight_hwm={int(cur.get('ps.client.inflight_hwm', 0))} "
            f"pipeline_stall={delta('ps.client.pipeline_stall_s'):.3f}s "
            f"retries={int(delta('ps.client.retry'))} "
            f"dedup_hits={int(delta('ps.server.dedup_hit'))}")
        ch, cm = delta("ps.cache.hits"), delta("ps.cache.misses")
        if ch or cm:
            # HBM-tier effectiveness for THIS pass: wire rows the device
            # cache kept off the network, vs rows still pulled
            lines.append(
                f"  cache: hits={int(ch)} misses={int(cm)} "
                f"hit_rate={ch / max(ch + cm, 1.0):.2f} "
                f"resident={int(cur.get('ps.cache.resident_rows', 0))} "
                f"evictions={int(delta('ps.cache.evictions'))} "
                f"bytes_saved={int(delta('ps.cache.bytes_saved'))}")
        pool_tasks = delta("ps.pool.table.tasks")
        if pool_tasks:
            # shard-pool pressure for THIS pass: busy seconds across
            # workers, plus the process-lifetime queue/active high-water
            # marks — the at-a-glance answer to "is the table apply
            # pool-parallel or queueing on a hot shard?"
            lines.append(
                f"  pool table: tasks={int(pool_tasks)} "
                f"busy={delta('ps.pool.table.busy_s'):.3f}s "
                f"threads={int(cur.get('ps.pool.table.threads', 1))} "
                f"queue_hwm={int(cur.get('ps.pool.table.queue_depth_hwm', 0))} "
                f"active_hwm={int(cur.get('ps.pool.table.active_hwm', 0))} "
                f"util_p95={cur.get('ps.pool.table.utilization.p95', 0.0):.2f}")
        faults_n = sum(delta(k) for k in cur if k.startswith("ps.fault."))
        if faults_n:
            lines.append(f"  injected_faults={int(faults_n)}")
        if delta("ckpt.save_s.count") > 0 or delta("ckpt.restore_s.count"):
            # this pass paid checkpoint cost (generation-chained save at
            # the pass boundary, or a crash-recovery restore mid-window)
            lines.append(
                f"  ckpt: saves={int(delta('ckpt.save_s.count'))} "
                f"save_s={delta('ckpt.save_s.sum'):.3f} "
                f"delta_rows={int(delta('ckpt.delta_rows'))} "
                f"restores={int(delta('ckpt.restore_s.count'))} "
                f"restore_s={delta('ckpt.restore_s.sum'):.3f} "
                f"generation={int(cur.get('ckpt.generation', -1))}")
        q = stat_snapshot("quality.")
        if q.get("quality.passes"):
            # training-quality trajectory (metrics/quality.py): the
            # latest pass's AUC next to its windowed value and the drift
            # monitors the SLO watchdog reads
            lines.append(
                f"  quality: auc={q.get('quality.auc', 0.0):.4f} "
                f"auc_window={q.get('quality.auc_window', 0.0):.4f} "
                f"auc_drop={q.get('quality.auc_drop', 0.0):.4f} "
                f"calib_drift={q.get('quality.calibration_drift', 0.0):.4f} "
                f"psi={q.get('quality.psi.prediction', 0.0):.4f}")
        rep = getattr(self, "_pass_feed_report", None)
        if rep:
            # interval-accounted utilization (utils/intervals.py): how
            # much of the pass wall the device actually had work, and
            # how much host feed time hid behind it
            lines.append(
                f"  feed gap: wall={rep['wall_s']:.3f}s "
                f"device_busy={rep['device_busy_s']:.3f}s "
                f"device_busy_frac={rep['device_busy_frac']:.2f} "
                f"feed_gap_ratio={rep['feed_gap_ratio']:.2f}")
            lines.append(
                f"  host busy: pull={rep['pull_busy_s']:.3f}s "
                f"pack={rep['pack_busy_s']:.3f}s "
                f"upload={rep['upload_busy_s']:.3f}s "
                f"write={rep['write_busy_s']:.3f}s "
                f"overlapped_with_device={rep['overlap_s']:.3f}s")
            hidden = {k: rep.get(f"{k}_hidden_s", 0.0)
                      for k in ("pull", "pack", "upload", "write")}
            if any(v > 1e-9 for v in hidden.values()):
                # per-stage feed work hidden behind device busy — the
                # prefetch pipeline's visible effect (data/prefetch.py)
                lines.append(
                    "  prefetch hidden: " + " ".join(
                        f"{k}={v:.3f}s" for k, v in hidden.items()))
        return "\n".join(lines)
