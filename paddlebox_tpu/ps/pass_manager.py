"""Pass lifecycle engine — the BoxWrapper/BoxHelper equivalent.

≙ BoxWrapper (box_wrapper.h:377) + BoxHelper (box_wrapper.h:1043) + the
open-source PSGPUWrapper pass machinery (ps_gpu_wrapper.cc:114-1007):

  set_date            ≙ BoxHelper::SetDate (box_wrapper.h:1048)
  begin_feed_pass     ≙ BeginFeedPass (box_wrapper.cc:129) — opens a key
                        collection agent for the loading pass
  add_keys            ≙ PSAgent::AddKey via MergeInsKeys (data_set.cc:2293)
  end_feed_pass       ≙ EndFeedPass (box_wrapper.cc:152) — dedups the pass
                        keys (≙ PreBuildTask ps_gpu_wrapper.cc:114), pulls
                        rows from the host table (≙ BuildPull :337) and
                        builds the device working set (≙ BuildGPUTask :684)
  begin_pass/end_pass ≙ box_wrapper.cc:171,186 — end_pass flushes the
                        working set back to the DRAM tier
                        (≙ EndPass dump_pool_to_cpu ps_gpu_wrapper.cc:983)
  save_base/save_delta≙ SaveBase/SaveDelta (box_wrapper.cc:1286)
  load                ≙ InitializeGPUAndLoadModel (box_wrapper.h:624)
  shrink              ≙ ShrinkTable (box_wrapper.h:638)
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from paddlebox_tpu.config import EmbeddingTableConfig
from paddlebox_tpu.parallel.topology import HybridTopology
from paddlebox_tpu.ps import embedding
from paddlebox_tpu.ps.host_table import ShardedHostTable
from paddlebox_tpu.utils.timer import TimerRegistry


class BoxPSEngine:
    def __init__(self, config: Optional[EmbeddingTableConfig] = None,
                 topology: Optional[HybridTopology] = None, seed: int = 0):
        self.config = config or EmbeddingTableConfig()
        self.topology = topology
        self.table = ShardedHostTable(self.config, seed=seed)
        self.timers = TimerRegistry()
        self.day_id: Optional[str] = None
        self.pass_id = 0
        self.phase = 1  # join/update flip (≙ FlipPhase box_wrapper.h:805)

        self._agent_lock = threading.Lock()
        self._agent_keys: List[np.ndarray] = []
        self._feeding = False

        self.mapper: Optional[embedding.PassKeyMapper] = None
        self.ws: Optional[Dict[str, jnp.ndarray]] = None
        self.num_keys = 0

    # -- date / phase --------------------------------------------------------
    def set_date(self, date: str) -> None:
        if self.day_id is not None and date != self.day_id:
            with self.timers("end_day"):
                self.table.end_day()
        self.day_id = date

    def flip_phase(self) -> None:
        self.phase = 1 - self.phase

    # -- feed pass -----------------------------------------------------------
    def begin_feed_pass(self) -> None:
        assert not self._feeding, "previous feed pass not closed"
        with self._agent_lock:
            self._agent_keys = []
        self._feeding = True

    def add_keys(self, keys: np.ndarray) -> None:
        """Thread-safe feasign sink for dataset reader threads."""
        if len(keys):
            with self._agent_lock:
                self._agent_keys.append(np.asarray(keys, np.uint64))

    def end_feed_pass(self) -> None:
        """Dedup pass keys, pull host rows, build the device working set."""
        assert self._feeding
        self._feeding = False
        with self.timers("dedup_keys"):
            with self._agent_lock:
                parts = self._agent_keys
                self._agent_keys = []
            allk = np.concatenate(parts) if parts else \
                np.empty((0,), np.uint64)
            uniq = np.unique(allk)
            uniq = uniq[uniq != 0]  # key 0 = reserved zero row
        self.mapper = embedding.PassKeyMapper(uniq)
        self.num_keys = len(uniq)
        with self.timers("build_pull"):
            host_rows = self.table.bulk_pull(uniq)
        with self.timers("build_device"):
            sharding = (self.topology.table_sharding()
                        if self.topology is not None else None)
            self.ws = embedding.build_working_set(
                host_rows, self.config.embedding_dim, sharding=sharding)

    # -- train pass ----------------------------------------------------------
    def begin_pass(self) -> None:
        assert self.ws is not None, "end_feed_pass must run before begin_pass"
        self.pass_id += 1

    def end_pass(self, need_save_delta: bool = False,
                 delta_path: str = "") -> None:
        """Write the trained working set back to the DRAM tier."""
        assert self.ws is not None and self.mapper is not None
        with self.timers("dump_to_cpu"):
            soa = embedding.dump_working_set(self.ws, self.num_keys)
            soa["unseen_days"] = np.zeros((self.num_keys,), np.float32)
            self.table.bulk_write(self.mapper.sorted_keys, soa)
        self.ws = None
        if need_save_delta and delta_path:
            self.save_delta(delta_path)

    # -- persistence ---------------------------------------------------------
    def save_base(self, path: str) -> int:
        return self.table.save(path, mode="base")

    def save_delta(self, path: str) -> int:
        return self.table.save(path, mode="delta")

    def save_checkpoint(self, path: str) -> int:
        return self.table.save(path, mode="all")

    def load(self, path: str) -> int:
        return self.table.load(path)

    def shrink(self) -> int:
        return self.table.shrink()

    # -- convenience ---------------------------------------------------------
    def attach_dataset(self, dataset) -> None:
        """Register this engine as the dataset's feasign consumer
        (≙ PadBoxSlotDataset holding the BoxWrapper agent)."""
        dataset.register_key_consumer(self.add_keys)

    def print_sync_timers(self) -> str:
        return self.timers.report()
