"""TPU-tiling-aware fast path for the sparse pull/pool/push pipeline.

Why this exists: TPU tiles the last two dims of every array to (8, 128)
(f32).  The straightforward layout — embeddings [S, B, L, E] with L≈1, E≈11
— pads 1→8 sublanes and 11→128 lanes, a ~90x HBM-traffic blowup on every
elementwise op, and the whole-table optimizer pays 16x on [N, D] state.
Measured on v5e this made the fused step ~20x slower than the math requires.

Fast-path rules implemented here:
* index tensors are [S, L, B] — batch minor, so every scalar intermediate
  ([S, L, B], [S, B]) tiles perfectly;
* per-feature scalars stay [N] 1-D (no padding);
* the only E-minor tensors are the unavoidable mf gathers, touched O(1)
  times each;
* NO full-table [N, D] elementwise pass in the optimizer: merged grads are
  scattered once, gathered back per occurrence, updated row-wise in the
  batch domain, and scatter-.set back (duplicate occurrences write
  identical values, so the .set is deterministic).

Semantics are bit-for-bit the v1 path (embedding.py + optimizer.py — itself
matching optimizer.cuh.h:31-130); tests/test_fast_path.py asserts equality.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import SparseSGDConfig


def step_prelude(idx: jnp.ndarray, lengths: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                            jnp.ndarray]:
    """Shared per-step mask/flatten prelude: (m, safe_idx, flat, occ).

    pull_pool_cvm and push_and_update both need the length mask (and push
    its flattened forms); computing it once per step and passing it to
    both halves saves a [S, L, B] broadcast-compare + where + reshape per
    step.  Pure function of the batch planes — training-state-free.
    """
    S, L, B = idx.shape
    m = (jnp.arange(L)[None, :, None] < lengths[:, None, :]).astype(
        jnp.float32)                                       # [S, L, B]
    safe_idx = jnp.where(m > 0, idx, 0)
    return m, safe_idx, safe_idx.reshape(-1), m.reshape(-1)


def pull_pool_cvm(ws: Dict[str, jnp.ndarray], idx: jnp.ndarray,
                  lengths: jnp.ndarray, use_cvm: bool = True,
                  prelude: Optional[Tuple] = None) -> jnp.ndarray:
    """Fused pull + seqpool + CVM.

    idx: [S, L, B] pass rows (0 = padding); lengths: [S, B].
    → pooled [B, S, E] with E = 3 + D (cols: cvm'show, cvm'click, w, mf...).
    prelude: optional step_prelude(idx, lengths) result shared with
    push_and_update; computed here when absent (back-compat callers).
    """
    S, L, B = idx.shape
    m = (prelude[0] if prelude is not None
         else step_prelude(idx, lengths)[0]).astype(
        ws["show"].dtype)                                  # [S, L, B]
    show = jnp.sum(ws["show"][idx] * m, axis=1)            # [S, B]
    click = jnp.sum(ws["click"][idx] * m, axis=1)
    w = jnp.sum(ws["embed_w"][idx] * m, axis=1)
    created = (ws["mf_size"][idx] > 0).astype(m.dtype) * m
    from paddlebox_tpu.ps.embedding import mf_values
    mf_rows = mf_values(ws, ws["mf"][idx])  # dequant if serving-frozen
    mf = jnp.einsum("slbd,slb->sbd", mf_rows, created)     # [S, B, D]
    if use_cvm:
        show_t = jnp.log(show + 1.0)
        click_t = jnp.log(click + 1.0) - show_t
    else:
        show_t, click_t = show, click
    head = jnp.stack([show_t, click_t, w], axis=-1)        # [S, B, 3]
    pooled = jnp.concatenate([head, mf], axis=-1)          # [S, B, E]
    return jnp.transpose(pooled, (1, 0, 2))                # [B, S, E]


def push_and_update(ws: Dict[str, jnp.ndarray], idx: jnp.ndarray,
                    lengths: jnp.ndarray, d_pooled: jnp.ndarray,
                    ins_cvm: jnp.ndarray, slot_ids: jnp.ndarray,
                    cfg: SparseSGDConfig,
                    prelude: Optional[Tuple] = None) -> Dict[str, jnp.ndarray]:
    """Merged push + sparse adagrad, batch-domain for the mf table.

    idx [S, L, B]; d_pooled [B, S, E] (model grads wrt pull_pool_cvm output
    — cols 0,1 ignored, replaced by ins_cvm per the reference push
    semantics); ins_cvm [B, 2]; slot_ids [S]; prelude: optional shared
    step_prelude(idx, lengths) result (padding occurrences scatter into
    reserved row 0 via safe_idx).
    """
    S, L, B = idx.shape
    n = ws["show"].shape[0]
    D = ws["mf"].shape[1]
    m, safe_idx, flat, occ = (prelude if prelude is not None
                              else step_prelude(idx, lengths))

    # -- merged per-row accumulators ([N] scalars; [N, D] once for mf) ----
    g_show = jnp.zeros((n,), jnp.float32).at[flat].add(
        occ * jnp.broadcast_to(ins_cvm[None, None, :, 0], (S, L, B)
                               ).reshape(-1))
    g_click = jnp.zeros((n,), jnp.float32).at[flat].add(
        occ * jnp.broadcast_to(ins_cvm[None, None, :, 1], (S, L, B)
                               ).reshape(-1))
    d_w = jnp.transpose(d_pooled[:, :, 2], (1, 0))         # [S, B]
    g_embed = jnp.zeros((n,), jnp.float32).at[flat].add(
        occ * jnp.broadcast_to(d_w[:, None, :], (S, L, B)).reshape(-1))
    d_mf = jnp.transpose(d_pooled[:, :, 3:], (1, 0, 2))    # [S, B, D]
    d_mf_occ = jnp.broadcast_to(d_mf[:, None], (S, L, B, D)) \
        * m[..., None]
    g_mf = jnp.zeros((n, D), jnp.float32).at[flat].add(
        d_mf_occ.reshape(-1, D))
    slot_occ = jnp.broadcast_to(
        slot_ids[:, None, None].astype(jnp.int32), (S, L, B)).reshape(-1)
    slot_acc = jnp.zeros((n,), jnp.int32).at[flat].max(
        jnp.where(occ > 0, slot_occ, 0))

    # -- scalar state: full-table [N] ops (8MB/pass — cheap) --------------
    # PB301 suppressions below: these 1-D [N] scalar sweeps are this
    # path's documented contract (module docstring — "per-feature scalars
    # stay [N] 1-D"); the [U]-domain alternative is ps/ragged_path.py.
    from paddlebox_tpu.ps.optimizer import push_touched
    touched = push_touched(ws, {"g_show": g_show})
    # pboxlint: disable-next=PB301 -- documented-cheap [N] scalar pass
    show = jnp.where(touched, ws["show"] + g_show, ws["show"])
    # pboxlint: disable-next=PB301 -- documented-cheap [N] scalar pass
    click = jnp.where(touched, ws["click"] + g_click, ws["click"])
    # pboxlint: disable-next=PB301 -- documented-cheap [N] scalar pass
    delta = jnp.where(
        touched,
        ws["delta_score"] + cfg.nonclk_coeff * (g_show - g_click)
        + cfg.clk_coeff * g_click,
        ws["delta_score"])
    # pboxlint: disable-next=PB301 -- documented-cheap [N] scalar pass
    slot = jnp.where(touched, slot_acc, ws["slot"])
    lr_embed = jnp.where(slot == cfg.nodeid_slot, cfg.learning_rate,
                         cfg.feature_learning_rate)
    safe_scale = jnp.where(g_show > 0, g_show, 1.0)
    # pboxlint: disable-next=PB301 -- documented-cheap [N] scalar pass
    ratio = lr_embed * jnp.sqrt(cfg.initial_g2sum /
                                (cfg.initial_g2sum + ws["embed_g2sum"]))
    sg = g_embed / safe_scale
    # pboxlint: disable-next=PB301 -- documented-cheap [N] scalar pass
    embed_w = jnp.where(
        touched,
        jnp.clip(ws["embed_w"] + sg * ratio, cfg.min_bound, cfg.max_bound),
        ws["embed_w"])
    # pboxlint: disable-next=PB301 -- documented-cheap [N] scalar pass
    embed_g2sum = jnp.where(touched, ws["embed_g2sum"] + sg * sg,
                            ws["embed_g2sum"])
    score = cfg.nonclk_coeff * (show - click) + cfg.clk_coeff * click
    # pboxlint: disable-next=PB301 -- documented-cheap [N] scalar pass
    create = touched & (ws["mf_size"] == 0) & \
        (score >= cfg.mf_create_thresholds)
    # dynamic per-slot dims (≙ CtrDymfAccessor): created rows record their
    # slot's true width, resolved from the MERGED row slot (same chain the
    # optimizer rules use — keeps multi-slot keys deterministic)
    from paddlebox_tpu.ps.optimizer import _dym_dims
    dims_row = _dym_dims(cfg, slot, D)
    # pboxlint: disable-next=PB301 -- documented-cheap [N] scalar pass
    mf_size = jnp.where(create,
                        dims_row if dims_row is not None else D,
                        ws["mf_size"])

    # -- mf: batch-domain row updates (no [N, D] full pass) ---------------
    # gather merged values back per occurrence; every occurrence of a row
    # computes the identical new row, so scatter-.set is deterministic.
    r_gshow = g_show[flat]                                 # [P]
    r_g2 = ws["mf_g2sum"][flat]
    r_trainable = (ws["mf_size"][flat] > 0) & (r_gshow > 0) & (flat != 0)
    r_scale = jnp.where(r_gshow > 0, r_gshow, 1.0)
    r_ratio = cfg.mf_learning_rate * jnp.sqrt(
        cfg.mf_initial_g2sum / (cfg.mf_initial_g2sum + r_g2))
    r_g = g_mf[flat] / r_scale[:, None]                    # [P, D]
    r_mf = ws["mf"][flat]
    new_mf = jnp.clip(r_mf + r_g * r_ratio[:, None],
                      cfg.mf_min_bound, cfg.mf_max_bound)
    # mean-square divisor is the ROW's true dim (merged slot, gathered per
    # occurrence like the other row state — every occurrence of a row then
    # computes the identical update, preserving the .set determinism)
    if dims_row is not None:
        new_g2 = r_g2 + jnp.sum(r_g * r_g, axis=1) \
            / dims_row[flat].astype(jnp.float32)
    else:
        new_g2 = r_g2 + jnp.sum(r_g * r_g, axis=1) / D
    write_idx = jnp.where(r_trainable, flat, 0)
    mf = ws["mf"].at[write_idx].set(
        jnp.where(r_trainable[:, None], new_mf, ws["mf"][0][None, :]))
    mf = mf.at[0].set(0.0)  # keep the reserved row zero
    mf_g2sum = ws["mf_g2sum"].at[write_idx].set(
        jnp.where(r_trainable, new_g2, ws["mf_g2sum"][0]))
    mf_g2sum = mf_g2sum.at[0].set(ws["mf_g2sum"][0])

    out = {"show": show, "click": click, "delta_score": delta, "slot": slot,
           "embed_w": embed_w, "embed_g2sum": embed_g2sum,
           "mf_size": mf_size, "mf_g2sum": mf_g2sum, "mf": mf}
    if "show_acc" in ws:   # ctr_double: exact pass-delta counters
        # pboxlint: disable-next=PB301 -- documented-cheap [N] scalar pass
        out["show_acc"] = jnp.where(touched, ws["show_acc"] + g_show,
                                    ws["show_acc"])
        # pboxlint: disable-next=PB301 -- documented-cheap [N] scalar pass
        out["click_acc"] = jnp.where(touched, ws["click_acc"] + g_click,
                                     ws["click_acc"])
    for extra in ("mf_ex", "mf_ex_g2sum"):
        if extra in ws:
            out[extra] = ws[extra]
    return out
