"""Standalone PS server process — one cluster shard per OS process.

``launch.py --ps_servers N`` supervises N in-process servers, which is
the right shape for tests and single-host chaos drills (shared fault
injection, in-memory dedup handoff).  A production fleet — and any
CPU-honest throughput measurement — runs each shard as its OWN process
so table work scales across cores instead of serializing on one
interpreter lock.  This module is that process:

    python -m paddlebox_tpu.ps.server_main --port 0 --mf_dim 8 --seed 0

It builds an identically-seeded ``ShardedHostTable`` (fresh-row defaults
are pure in (seed, key), so N such processes form one consistent key
space), optionally reloads its cluster shard from a generation
checkpoint (``--ckpt_root`` + ``--shard``, the same ``shard-<k:03d>/``
handoff PSServerSupervisor uses), serves until SIGTERM/SIGINT, then
drains.  The bound address is announced on stdout as one line

    PS_ADDR <host>:<port>

so a parent (bench.py's cluster phase, an orchestrator) can spawn with
``--port 0`` and scrape the ephemeral port.  Deliberately jax-free:
imports stay in the numpy/socket layer, so a shard comes up in well
under a second.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="paddlebox_tpu.ps.server_main",
        description="run one PS cluster shard as a standalone process")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="listen port (0 = ephemeral, announced on stdout)")
    ap.add_argument("--mf_dim", type=int, default=8,
                    help="embedding_dim of the hosted table")
    ap.add_argument("--shard_num", type=int, default=4,
                    help="host-table lock shards (NOT the cluster width)")
    ap.add_argument("--seed", type=int, default=0,
                    help="table seed — MUST match every other shard")
    ap.add_argument("--ckpt_root", default=None,
                    help="generation-checkpoint root to reload from")
    ap.add_argument("--shard", type=int, default=None,
                    help="cluster rank: reload only shard-<k:03d>/ subdirs")
    ap.add_argument("--membership", default=None,
                    help="fleet membership 'h1:p1,h2:p2,...' — enables "
                         "epoch fencing; --shard -1 joins as a pending "
                         "member (answers typed redirects until a "
                         "reshard cutover admits it)")
    ap.add_argument("--epoch", type=int, default=0,
                    help="membership epoch the address list is valid at")
    args = ap.parse_args(argv)

    from paddlebox_tpu.config import EmbeddingTableConfig
    from paddlebox_tpu.ps.host_table import ShardedHostTable
    from paddlebox_tpu.ps.service import PSServer, _dedup_read

    table = ShardedHostTable(
        EmbeddingTableConfig(embedding_dim=args.mf_dim,
                             shard_num=args.shard_num),
        seed=args.seed)
    dedup = None
    if args.ckpt_root:
        from paddlebox_tpu.io.checkpoint import TrainCheckpoint
        ck = TrainCheckpoint(args.ckpt_root)
        head = ck.load_table(table, shard=args.shard)
        if head is not None:
            sparse = os.path.join(ck._gen_dir(head), "sparse")
            if args.shard is not None:
                sparse = os.path.join(sparse, f"shard-{args.shard:03d}")
            dedup = _dedup_read(sparse)

    membership = None
    if args.membership:
        from paddlebox_tpu.ps import cluster as ps_cluster
        membership = ps_cluster.make_server_map(
            ps_cluster.parse_addrs(args.membership), epoch=args.epoch)
    srv = PSServer(table, host=args.host, port=args.port,
                   dedup_state=dedup, membership=membership,
                   shard=args.shard if args.shard is not None else 0)
    print(f"PS_ADDR {srv.addr[0]}:{srv.addr[1]}", flush=True)

    done = threading.Event()

    def _stop(signum, frame):
        done.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    done.wait()
    srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
