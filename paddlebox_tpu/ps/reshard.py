"""Live key-range handoff: grow or shrink the PS fleet WITHOUT a
save/load outage.

The driver turns "change the fleet from ``old_addrs`` to ``new_addrs``"
into the snapshot → delta catch-up → freeze → cutover sequence the
servers implement (ps/service.py ``reshard_begin`` / ``reshard_delta`` /
the ``reshard_cutover`` lifecycle verbs):

1. **Snapshot** — every OLD member dumps the rows the proposed map
   assigns elsewhere, split per destination into
   ``<workdir>/snap/src-<s>/dst-<d>/table-<name>`` (the same tmp+rename
   per-shard npz files checkpoints use — the dump IS the snapshot, no
   extra format).  Serving continues at full rate; the server starts
   recording writes into the moving range (its dirty set).
2. **Ingest** — every NEW-map member upsert-loads exactly its own
   ``dst-<d>`` slices.  Keyed upsert makes every ingest idempotent, so
   no rid pinning is needed on the data path — retries and re-runs
   re-apply the same rows to the same keys.
3. **Delta rounds** — sources re-dump their (cumulative) dirty sets,
   destinations re-ingest; last-write-wins per key converges the moved
   range while writes keep flowing.
4. **Freeze + final delta** — moving-range WRITES start drawing typed
   ``migrating`` redirects (clients back off bounded — ps/service.py
   ``_fence_recover``; non-moving keys never stall), in-flight verbs
   drain, and the closing delta ships.  Only this window blocks, and
   only for the moving range.
5. **Cutover** — one ``two_phase_lifecycle`` round ("reshard_cutover")
   across the UNION of old and new members flips everyone to the
   ``epoch+1`` map, drops rows each server no longer owns, and
   unfreezes.  The frame is self-contained (membership + assignment
   ride in it), prepare/commit rids are pinned, so a driver retry after
   any partial failure replays the SAME rids and the per-shard dedup
   windows collapse duplicates — the only non-idempotent step in the
   whole migration is exactly-once.
6. **Manifest** — the new epoch + membership commit to the checkpoint
   MANIFEST (io/checkpoint.commit_membership) AFTER the cutover: a
   crash anywhere earlier leaves the manifest pointing at the old
   membership, and rollback is an atomic pointer swap — the old fleet
   is immediately serviceable (abort unfreezes it and destination
   servers drop ingested-but-unowned rows).

Crash-anywhere story: every phase before the cutover is restartable by
re-running :func:`reshard` with a FRESH ``workdir`` — ``reshard_begin``
re-snapshots CURRENT state (nothing written between attempts can be
lost), ingest is idempotent, and an abandoned attempt's residue is
dropped by the servers' unowned-row cleanup at the next begin/abort.
The cutover itself is exactly-once via pinned rids + the epoch guard.

Assumptions (enforced by the launcher, documented in DEPLOY.md): all
fleet members share the table config — in particular the internal
``shard_num`` — so per-shard npz part files align across servers; no
``end_day``/``shrink`` runs concurrently with a migration (deletes are
not tracked by the dirty set); client retry deadlines exceed the freeze
window.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from paddlebox_tpu.ps import cluster as ps_cluster
from paddlebox_tpu.utils import flight
from paddlebox_tpu.utils.monitor import stat_add, stat_observe

__all__ = ["reshard"]


def _norm_addrs(addrs) -> List[Tuple[str, int]]:
    return [(str(h), int(p)) for h, p in addrs]


def _abort_all(admin, n: int, timeout: float) -> None:
    """Best-effort rollback fan-out: unfreeze sources, make destinations
    drop ingested-but-unowned rows.  Never raises — rollback must not
    mask the original failure."""
    for s in range(n):
        try:
            admin._call({"cmd": "lifecycle_abort",
                         "verb": "reshard_cutover"},
                        shard=s, dedup=True, timeout=timeout)
        except Exception:
            pass
    stat_add("ps.reshard.abort")


def reshard(client, new_addrs: Sequence[Tuple[str, int]], workdir: str,
            *, rounds: int = 2, settle_rows: int = 0,
            timeout: float = 120.0,
            manifest_root: Optional[str] = None) -> ps_cluster.ServerMap:
    """Migrate the live fleet behind ``client`` to ``new_addrs``.

    Every server in ``new_addrs`` that is not already a member must be
    up and reachable (started membership-aware with ``shard=-1`` — it
    answers typed ``not_owner`` redirects until the cutover admits it).
    Returns the committed new :class:`~paddlebox_tpu.ps.cluster.ServerMap`;
    ``client`` has already adopted it (its map listeners — e.g. the
    DeviceRowCache moved-range invalidation — have fired).

    ``rounds`` counts delta catch-up rounds before the freeze (≥ 1);
    a round that ships ``settle_rows`` rows or fewer cuts over early.
    ``manifest_root`` names the checkpoint root whose MANIFEST records
    the committed membership (skipped when None).
    """
    from paddlebox_tpu.ps.service import PSClient  # lazy: avoid cycle

    t0 = time.perf_counter()
    old_map = client.server_map
    new_list = _norm_addrs(new_addrs)
    if not new_list:
        raise ValueError("reshard to an empty fleet")
    if new_list == list(old_map.addrs):
        return old_map
    new_map = ps_cluster.make_server_map(new_list,
                                         epoch=old_map.epoch + 1)
    desc = new_map.describe()
    union = list(old_map.addrs) + [a for a in new_list
                                   if a not in old_map.addrs]
    assign = {f"{h}:{p}": (new_list.index((h, p))
                           if (h, p) in new_list else -1)
              for h, p in union}
    n_old = old_map.n
    flight.record("reshard_drive", epoch=new_map.epoch,
                  n_old=n_old, n_new=new_map.n)

    admin = PSClient(union, retries=None,
                     retry_sleep=getattr(client, "retry_sleep", 0.1),
                     backoff_cap=getattr(client, "backoff_cap", 2.0),
                     deadline=timeout)
    try:
        tables = sorted(admin.list_tables())

        def ingest(path: str) -> None:
            # destinations pull exactly their own dst-<d> slices; a
            # (src, dst, table) dir that was never written means no rows
            # moved along that edge this round.  RESHARD_FIELD exempts
            # these loads from the control-plane epoch fence — a pending
            # destination is not yet in any map, so no client epoch can
            # ever match it
            from paddlebox_tpu.ps.service import RESHARD_FIELD
            for d, addr in enumerate(new_list):
                u = union.index(addr)
                for s in range(n_old):
                    for name in tables:
                        p = os.path.join(path, f"src-{s:03d}",
                                         f"dst-{d:03d}", f"table-{name}")
                        if not os.path.isdir(p):
                            continue
                        admin._call({"cmd": "load", "table": name,
                                     "path": p, "mode": "upsert",
                                     RESHARD_FIELD: True},
                                    shard=u, dedup=True, timeout=timeout)

        def delta_round(path: str, freeze: bool) -> int:
            moved = 0
            for s in range(n_old):
                r = admin._call({"cmd": "reshard_delta",
                                 "path": os.path.join(path,
                                                      f"src-{s:03d}"),
                                 "freeze": freeze},
                                shard=s, dedup=True, timeout=timeout)
                moved += int(r.get("moved", 0))
            ingest(path)
            return moved

        # -- phase 1: snapshot (serving continues, dirty tracking on)
        snapped = 0
        for s in range(n_old):
            h, p = old_map.addrs[s]
            r = admin._call({"cmd": "reshard_begin", "membership": desc,
                             "self_new": assign[f"{h}:{p}"],
                             "path": os.path.join(workdir, "snap",
                                                  f"src-{s:03d}")},
                            shard=s, dedup=True, timeout=timeout)
            snapped += int(r.get("moved", 0))
        ingest(os.path.join(workdir, "snap"))
        stat_add("ps.reshard.snapshot_rows", float(snapped))

        # -- phase 2: delta catch-up (bounded rounds, early settle)
        total_delta = 0
        for i in range(1, max(1, int(rounds))):
            moved = delta_round(os.path.join(workdir, f"delta-{i}"),
                                freeze=False)
            total_delta += moved
            stat_add("ps.reshard.delta_rows", float(moved))
            if moved <= int(settle_rows):
                break

        # -- phase 3: freeze + closing delta (only the moving range
        # blocks, and only from here to the cutover commit)
        t_freeze = time.perf_counter()
        moved = delta_round(os.path.join(workdir, "freeze"), freeze=True)
        total_delta += moved
        stat_add("ps.reshard.delta_rows", float(moved))
    except BaseException:
        # pre-cutover failure: rollback is safe — no server has adopted
        # the new map, abort unfreezes and drops destination ingest
        _abort_all(admin, len(union), min(timeout, 5.0))
        admin.close()
        raise
    try:
        # -- phase 4: exactly-once cutover across the union.  A failure
        # HERE retries FORWARD (the prepare/commit rids are pinned on
        # ``admin``, so a re-drive replays the same frames and the dedup
        # windows + the epoch guard collapse duplicates); aborting a
        # half-committed cutover would strand the fleet at mixed epochs.
        # Even exhausting the retries is recoverable: re-running
        # reshard() to the SAME target recomputes epoch+1, finds nothing
        # left to move, and its cutover no-ops committed members while
        # finishing the stragglers.
        attempt = 0
        while True:
            try:
                ps_cluster.two_phase_lifecycle(
                    admin, "reshard_cutover", timeout=timeout,
                    extra={"membership": desc, "assign": assign})
                break
            except Exception:
                attempt += 1
                stat_add("ps.reshard.cutover_retry")
                if attempt >= 3:
                    raise
                time.sleep(min(0.1 * (2 ** attempt), 2.0))
        stall_ms = (time.perf_counter() - t_freeze) * 1000.0
        stat_observe("ps.reshard.cutover_stall_ms", stall_ms)
    finally:
        admin.close()

    # -- phase 5: durable membership pointer (after the cutover: a crash
    # before this line rolls back to the old epoch on restart)
    if manifest_root is not None:
        from paddlebox_tpu.io.checkpoint import commit_membership
        commit_membership(manifest_root, new_map)

    client._adopt_map(new_map)
    moved_rows = snapped + total_delta
    dt = time.perf_counter() - t0
    stat_add("ps.reshard.completed")
    stat_add("ps.reshard.rows_moved", float(moved_rows))
    if dt > 0:
        stat_observe("ps.reshard.rows_per_s", moved_rows / dt)
    flight.record("reshard_done", epoch=new_map.epoch,
                  rows=moved_rows, ms=dt * 1000.0)
    return new_map
