"""Geo-async sparse table — ≙ MemorySparseGeoTable + GeoRecorder.

Reference (ps/table/memory_sparse_geo_table.h, depends/geo_recorder.h): the
GeoSGD protocol for CPU async training — trainers push SGD updates straight
into the server copy, the table records *which* rows each trainer has not
yet seen, and ``PullGeoParam(trainer_id)`` returns exactly those touched
rows (ids + fresh values) and clears the trainer's pending set.  Trainers
thus exchange sparse *row deltas* instead of full tables.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

from paddlebox_tpu.utils import lockdep

import numpy as np


class GeoSparseTable:
    def __init__(self, dim: int, num_trainers: int,
                 learning_rate: float = 1.0):
        self.dim = dim
        self.lr = learning_rate
        self._values: Dict[int, np.ndarray] = {}
        self._pending = [set() for _ in range(num_trainers)]
        self._lock = lockdep.lock("ps.geo_table.GeoSparseTable._lock")

    # -- init / direct access ----------------------------------------------
    def push_sparse_param(self, keys: np.ndarray,
                          values: np.ndarray) -> None:
        """Overwrite rows (initial broadcast of trainer-0 params,
        ≙ PushSparseParam)."""
        with self._lock:
            for k, v in zip(keys.tolist(), values):
                self._values[k] = np.array(v, np.float32)

    def pull_sparse(self, keys: np.ndarray) -> np.ndarray:
        with self._lock:
            return np.stack([
                self._values.get(int(k), np.zeros(self.dim, np.float32))
                for k in keys])

    # -- geo protocol -------------------------------------------------------
    def push_sparse(self, keys: np.ndarray, grads: np.ndarray) -> None:
        """Apply a trainer's sparse SGD update and mark the rows pending for
        every trainer (≙ MemorySparseGeoTable::_PushSparse + GeoRecorder
        Update)."""
        with self._lock:
            for k, g in zip(keys.tolist(), grads):
                row = self._values.setdefault(
                    int(k), np.zeros(self.dim, np.float32))
                row -= self.lr * np.asarray(g, np.float32)
            for pend in self._pending:
                pend.update(int(k) for k in keys.tolist())

    def pull_geo_param(self, trainer_id: int
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """Rows touched since this trainer's last geo pull (≙ PullGeoParam:
        GeoRecorder GetAndClear + values gather)."""
        with self._lock:
            ids = sorted(self._pending[trainer_id])
            self._pending[trainer_id].clear()
            if not ids:
                return (np.zeros((0,), np.uint64),
                        np.zeros((0, self.dim), np.float32))
            vals = np.stack([self._values[k] for k in ids])
            return np.asarray(ids, np.uint64), vals

    def size(self) -> int:
        with self._lock:
            return len(self._values)
