"""Typed binary wire codec for the PS service — no pickle on network bytes.

≙ the brpc PS protocol's typed request/response messages (sendrecv.proto:
VariableMessage dtype/shape/raw-bytes framing + PsService cmd ids,
ps/service/sendrecv.proto, brpc_ps_server.h): a message is a flat dict of
scalars / strings / ndarrays / one-level dicts-of-ndarrays, encoded as
tagged fields with dtype+shape headers and raw little-endian buffers.
Arrays decode with np.frombuffer (zero parsing of untrusted structure
beyond bounded headers) — a malicious peer can at worst produce a garbage
array or a clean DecodeError, never code execution.

Frame layout (all little-endian):
  u32 field count, then per field:
    u16 key-len, key utf8
    u8 tag:  0 None | 1 bool | 2 int | 3 float | 4 str | 5 ndarray | 6 dict
             | 7 quantized ndarray
    value:
      bool  -> u8
      int   -> i64
      float -> f64
      str   -> u32 len + utf8
      ndarray -> u8 dtype-len + dtype.str ascii, u8 ndim, u64*ndim shape,
                 raw C-order bytes
      dict  -> nested encoding (depth limited to 1 nesting level)
      quantized ndarray (tag 7, FLAGS_ps_wire_dtype ∈ {f16, i8}) ->
                 u8 orig-dtype-len + orig dtype.str ascii,
                 u8 enc-dtype-len + enc dtype.str ascii,
                 f64 scale, u8 ndim, u64*ndim shape, raw encoded bytes.
                 The scale is PER FIELD PER FRAME (per chunk): i8 stores
                 round(x/scale) with scale = max|x|/127; f16 stores the
                 IEEE half directly (scale 1.0).  decode() dequantizes
                 transparently back to the original float dtype, so table
                 state and caller arithmetic stay full precision.

Request ids: retryable non-idempotent requests carry a conventional
string field ``RID_FIELD`` ("rid") of the form ``<client-token>:<seq>``
(chunked verbs suffix ``.<chunk>``); the server echoes it on the matching
response and dedups resends through its bounded window (ps/service.py
_DedupWindow).  The echo also lets a client reject a stale frame that
surfaces on a reused stream after a timeout.
"""

from __future__ import annotations

import struct
from typing import Any, Dict

import numpy as np

from paddlebox_tpu.utils.monitor import stat_add

MAX_FRAME = 1 << 32          # hard cap: one frame can't ask for >4 GiB
MAX_FIELDS = 4096
MAX_KEY = 1 << 16
_MAX_NDIM = 16

# exactly-once request-id field (see module docstring): service.py stamps
# it on mutating requests and echoes it on responses
RID_FIELD = "rid"

# optional Dapper-style trace-context field riding beside the rid: a
# string ``<trace_id>/<span_id>`` naming the originating client span
# (utils/trace.py).  The server parents its dispatch span to it, so one
# trace id follows a verb across the process boundary; retries resend
# the SAME context, and dedup-window replays never open a second server
# span — trace topology survives the exactly-once protocol unchanged.
TRACE_FIELD = "tctx"

# legal FLAGS_ps_wire_dtype values (f32 = exact passthrough, no tag 7)
WIRE_DTYPES = ("f32", "f16", "i8")
_F16_MAX = 65504.0


class DecodeError(ValueError):
    pass


class QuantArray:
    """A float ndarray held in its reduced-precision wire encoding (tag 7).

    Built by :func:`quantize_rows` on the SENDING side only; ``decode``
    dequantizes transparently, so receivers always see plain float
    ndarrays and never handle this type."""

    __slots__ = ("data", "orig_dtype", "scale")

    def __init__(self, data: np.ndarray, orig_dtype: np.dtype, scale: float):
        self.data = data
        self.orig_dtype = np.dtype(orig_dtype)
        self.scale = float(scale)


def quantize(a: np.ndarray, wire_dtype: str) -> QuantArray:
    """One float32 array → its wire encoding with a per-array scale."""
    a = np.ascontiguousarray(a)
    if wire_dtype == "f16":
        return QuantArray(np.clip(a, -_F16_MAX, _F16_MAX)
                          .astype(np.float16), a.dtype, 1.0)
    if wire_dtype == "i8":
        amax = float(np.max(np.abs(a))) if a.size else 0.0
        scale = (amax / 127.0) or 1.0
        q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
        return QuantArray(q, a.dtype, scale)
    raise ValueError(f"unknown wire dtype {wire_dtype!r} "
                     f"(want one of {WIRE_DTYPES})")


def quantize_rows(rows: Dict[str, Any], wire_dtype: str,
                  verb: str = "") -> Dict[str, Any]:
    """Encode the float32 fields of a rows dict for the wire.

    Only float32 payloads quantize — f64 fields (ctr_double show/click
    counters) and integer planes stay exact; ``f32`` is a counted
    passthrough.  Bumps ``ps.wire.<verb>.raw_bytes`` / ``.quant_bytes``
    so the raw-vs-encoded bandwidth win is observable per verb."""
    if wire_dtype not in WIRE_DTYPES:
        raise ValueError(f"unknown wire dtype {wire_dtype!r} "
                         f"(want one of {WIRE_DTYPES})")
    out: Dict[str, Any] = {}
    raw = enc = 0
    for f, v in rows.items():
        a = np.asarray(v)
        raw += a.nbytes
        if wire_dtype != "f32" and a.dtype == np.float32:
            qa = quantize(a, wire_dtype)
            enc += qa.data.nbytes
            out[f] = qa
        else:
            enc += a.nbytes
            out[f] = v
    if verb:
        stat_add(f"ps.wire.{verb}.raw_bytes", float(raw))
        stat_add(f"ps.wire.{verb}.quant_bytes", float(enc))
    return out


def _enc_value(out: list, v: Any, depth: int) -> None:
    if v is None:
        out.append(b"\x00")
    elif isinstance(v, (bool, np.bool_)):
        out.append(b"\x01" + struct.pack("<B", int(v)))
    elif isinstance(v, (int, np.integer)):
        out.append(b"\x02" + struct.pack("<q", int(v)))
    elif isinstance(v, (float, np.floating)):
        out.append(b"\x03" + struct.pack("<d", float(v)))
    elif isinstance(v, str):
        b = v.encode("utf-8")
        out.append(b"\x04" + struct.pack("<I", len(b)) + b)
    elif isinstance(v, np.ndarray):
        if v.dtype.hasobject:
            raise TypeError(
                "object-dtype arrays are not wire-safe (raw pointers); "
                "convert to a fixed-width dtype first")
        a = np.ascontiguousarray(v)
        dt = a.dtype.str.encode("ascii")
        head = struct.pack("<B", len(dt)) + dt + struct.pack("<B", a.ndim)
        head += struct.pack(f"<{a.ndim}Q", *a.shape) if a.ndim else b""
        out.append(b"\x05" + head)
        out.append(a.tobytes())
    elif isinstance(v, QuantArray):
        a = np.ascontiguousarray(v.data)
        odt = v.orig_dtype.str.encode("ascii")
        edt = a.dtype.str.encode("ascii")
        head = struct.pack("<B", len(odt)) + odt
        head += struct.pack("<B", len(edt)) + edt
        head += struct.pack("<d", v.scale) + struct.pack("<B", a.ndim)
        head += struct.pack(f"<{a.ndim}Q", *a.shape) if a.ndim else b""
        out.append(b"\x07" + head)
        out.append(a.tobytes())
    elif isinstance(v, dict):
        if depth >= 1:
            raise TypeError("wire dicts nest at most one level")
        out.append(b"\x06")
        _enc_fields(out, v, depth + 1)
    else:
        raise TypeError(f"wire cannot encode {type(v).__name__}")


def _enc_fields(out: list, msg: Dict[str, Any], depth: int) -> None:
    out.append(struct.pack("<I", len(msg)))
    for k, v in msg.items():
        kb = k.encode("utf-8")
        out.append(struct.pack("<H", len(kb)) + kb)
        _enc_value(out, v, depth)


def encode(msg: Dict[str, Any]) -> bytes:
    out: list = []
    _enc_fields(out, msg, 0)
    return b"".join(out)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> bytes:
        if n < 0 or self.pos + n > len(self.buf):
            raise DecodeError("frame truncated")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def u8(self) -> int:
        return self.take(1)[0]

    def unpack(self, fmt: str):
        return struct.unpack(fmt, self.take(struct.calcsize(fmt)))


def _dec_value(r: _Reader, depth: int) -> Any:
    tag = r.u8()
    if tag == 0:
        return None
    if tag == 1:
        return bool(r.u8())
    if tag == 2:
        return r.unpack("<q")[0]
    if tag == 3:
        return r.unpack("<d")[0]
    if tag == 4:
        (n,) = r.unpack("<I")
        return r.take(n).decode("utf-8")
    if tag == 5:
        dt_len = r.u8()
        dt = np.dtype(r.take(dt_len).decode("ascii"))
        if dt.hasobject:
            raise DecodeError("object dtypes are not wire-safe")
        ndim = r.u8()
        if ndim > _MAX_NDIM:
            raise DecodeError("ndim too large")
        shape = r.unpack(f"<{ndim}Q") if ndim else ()
        count = 1
        for s in shape:
            count *= s
        nbytes = count * dt.itemsize
        if nbytes > MAX_FRAME:
            raise DecodeError("array exceeds frame cap")
        raw = r.take(int(nbytes))
        return np.frombuffer(raw, dtype=dt).reshape(shape).copy()
    if tag == 6:
        if depth >= 1:
            raise DecodeError("dict nesting exceeds limit")
        return _dec_fields(r, depth + 1)
    if tag == 7:
        odt = np.dtype(r.take(r.u8()).decode("ascii"))
        edt = np.dtype(r.take(r.u8()).decode("ascii"))
        if odt.hasobject or edt.hasobject:
            raise DecodeError("object dtypes are not wire-safe")
        if odt.kind != "f":
            raise DecodeError("quantized arrays must dequantize to float")
        (scale,) = r.unpack("<d")
        ndim = r.u8()
        if ndim > _MAX_NDIM:
            raise DecodeError("ndim too large")
        shape = r.unpack(f"<{ndim}Q") if ndim else ()
        count = 1
        for s in shape:
            count *= s
        nbytes = count * edt.itemsize
        if nbytes > MAX_FRAME:
            raise DecodeError("array exceeds frame cap")
        raw = r.take(int(nbytes))
        q = np.frombuffer(raw, dtype=edt).reshape(shape)
        # dequantize HERE: receivers only ever see full-precision floats
        out = q.astype(odt)
        if scale != 1.0:
            out = out * odt.type(scale)
        return out
    raise DecodeError(f"unknown tag {tag}")


def _dec_fields(r: _Reader, depth: int) -> Dict[str, Any]:
    (n,) = r.unpack("<I")
    if n > MAX_FIELDS:
        raise DecodeError("too many fields")
    out: Dict[str, Any] = {}
    for _ in range(n):
        (klen,) = r.unpack("<H")
        if klen > MAX_KEY:
            raise DecodeError("key too long")
        k = r.take(klen).decode("utf-8")
        out[k] = _dec_value(r, depth)
    return out


def decode(buf: bytes) -> Dict[str, Any]:
    r = _Reader(buf)
    msg = _dec_fields(r, 0)
    if r.pos != len(buf):
        raise DecodeError("trailing bytes in frame")
    return msg
