"""MXU sparse step path: pull/pool and push/update via sorted_spmm kernels.

Third-generation hot path (v1 `embedding.py` gathers → v2 `fast_path.py`
tiling-aware scatters → v3 this): the per-batch embedding traffic runs
through the sorted one-hot-matmul kernels (ops/sorted_spmm.py), which turn
TPU's serial gather/scatter into MXU block-sparse matmuls.  The optimizer
is the unchanged full-table `ps.optimizer.apply_push` — the scatter kernel
materializes the same merged per-row accumulators (`g_show`, `g_click`,
`g_embed`, `g_embedx`, slot) the v1 path built with
`.at[].add`, so every optimizer rule (adagrad / shared_adam / naive) works
and semantics match optimizer.cuh.h exactly (up to f32 summation order;
the kernels' hi/lo bf16 split carries ~1e-5 relative error).

≙ reference hot path: PullSparseCaseGPU + CopyForPull
(box_wrapper_impl.h:25, box_wrapper.cu:945), PushMergeCopy merge-by-key
(box_wrapper.cu:417), HashTable::update (hashtable_kernel.cu).

Layout notes: occurrence order is canonical [S, L, B] flattened; the plan's
`perm`/`inv_perm` move between canonical and sorted domains (one XLA row
gather each way, the only serial-ish ops left, ~2.6ms at 426k rows).  The
pull table is feature-major [W, n_kernel] with W = 3 + D (+ Dex) + 1
(rows: show, click, embed_w, mf×D, optional expand mf_ex×Dex, mf_size) so
kernel blocks tile perfectly and the build is W row writes, not an
[N, D] relayout.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import SparseSGDConfig
from paddlebox_tpu.ops import sorted_spmm as sp
from paddlebox_tpu.ps import optimizer as sparse_opt


def make_dims(num_occurrences: int, num_rows: int) -> sp.SpmmDims:
    return sp.spmm_dims(num_occurrences, num_rows)


def build_plan(idx_slb: jnp.ndarray, dims: sp.SpmmDims,
               eff: sp.SpmmDims = None):
    """idx_slb [S, L, B] pass rows (0 = reserved/padding row)."""
    return sp.build_plan(idx_slb.reshape(-1), dims, eff)


def plan_eff_dims(plan, dims: sp.SpmmDims) -> Optional[sp.SpmmDims]:
    """Trimmed kernel geometry a plan was built with, recovered from its
    static array shapes (None = untrimmed) — so consumers need no side
    channel and jit retraces correctly when the trim width changes."""
    n_chunks = plan[0].shape[0]
    if n_chunks == dims.n_chunks:
        return None
    return sp.with_p_pad(dims, n_chunks * dims.chunk)


def _ex_dim(ws: Dict[str, jnp.ndarray]) -> int:
    """Expand ("NNCross") embedding width, 0 without one — the ex columns
    ride the same feature-major table/payload directly after mf, so the
    kernels (width-agnostic) and the pooling (everything between col 3 and
    the trailing mf_size is an embedding masked by created) need no
    branches."""
    return ws["mf_ex"].shape[1] if "mf_ex" in ws else 0


def _pull_table(ws: Dict[str, jnp.ndarray], dims: sp.SpmmDims) -> jnp.ndarray:
    """Feature-major pull view [3 + D (+ Dex) + 1, n_kernel]."""
    from paddlebox_tpu.ps.embedding import mf_values
    n = ws["show"].shape[0]
    d = ws["mf"].shape[1]
    dx = _ex_dim(ws)
    tab = jnp.zeros((3 + d + dx + 1, dims.n_kernel), jnp.float32)
    tab = tab.at[0, :n].set(ws["show"])
    tab = tab.at[1, :n].set(ws["click"])
    tab = tab.at[2, :n].set(ws["embed_w"])
    # pboxlint: disable-next=PB301 -- documented pull-table build cost (one relayout per step, not per-row math)
    tab = tab.at[3:3 + d, :n].set(mf_values(ws, ws["mf"]).T)
    if dx:
        # pboxlint: disable-next=PB301 -- documented pull-table build cost (one relayout per step, not per-row math)
        tab = tab.at[3 + d:3 + d + dx, :n].set(ws["mf_ex"].T)
    # pboxlint: disable-next=PB301 -- documented pull-table build cost (one relayout per step, not per-row math)
    tab = tab.at[3 + d + dx, :n].set(ws["mf_size"].astype(jnp.float32))
    return tab


def pool_cvm_values(v: jnp.ndarray, use_cvm: bool = True,
                    premasked: bool = False) -> jnp.ndarray:
    """Canonical per-occurrence pull values [S, L, B, 3+D+1] (last col =
    mf_size) → pooled [B, S, 3+D].  Shared by the single-chip path and the
    shard_map'd multi-chip step (which pools its LOCAL batch shard).

    premasked: v is [S, L, B, 3+D] with the created mask already applied
    to the mf columns (the mxu path does this in the SORTED domain so the
    mf_size column never rides the crossing)."""
    d = v.shape[-1] - (3 if premasked else 4)
    mf = v[..., 3:3 + d]
    if not premasked:
        mf = mf * (v[..., 3 + d:] > 0).astype(v.dtype)     # [S,L,B,1] mask
    show = jnp.sum(v[..., 0], axis=1)                      # [S, B]
    click = jnp.sum(v[..., 1], axis=1)
    w = jnp.sum(v[..., 2], axis=1)
    mf = jnp.sum(mf, axis=1)                               # [S, B, D]
    if use_cvm:
        show_t = jnp.log(show + 1.0)
        click_t = jnp.log(click + 1.0) - show_t
    else:
        show_t, click_t = show, click
    head = jnp.stack([show_t, click_t, w], axis=-1)        # [S, B, 3]
    pooled = jnp.concatenate([head, mf], axis=-1)
    return jnp.transpose(pooled, (1, 0, 2))                # [B, S, E]


def push_payload(d_pooled: jnp.ndarray, ins_cvm: jnp.ndarray,
                 slot_ids: jnp.ndarray,
                 shape_slb: Tuple[int, int, int]) -> jnp.ndarray:
    """Canonical per-occurrence push payload [S, L, B, D+4]:
    g_show, g_click, g_embed, g_mf x D, slot (reference push semantics —
    cols 0,1 of d_pooled are ignored, replaced by the instance cvm,
    box_wrapper_impl.h:373)."""
    s, l, b = shape_slb
    d = d_pooled.shape[-1] - 3
    g_show = jnp.broadcast_to(ins_cvm[None, None, :, 0], (s, l, b))
    g_click = jnp.broadcast_to(ins_cvm[None, None, :, 1], (s, l, b))
    d_w = jnp.transpose(d_pooled[:, :, 2], (1, 0))         # [S, B]
    g_embed = jnp.broadcast_to(d_w[:, None, :], (s, l, b))
    d_mf = jnp.transpose(d_pooled[:, :, 3:], (1, 0, 2))    # [S, B, D]
    g_mf = jnp.broadcast_to(d_mf[:, None], (s, l, b, d))
    slot_col = jnp.broadcast_to(
        slot_ids.astype(jnp.float32)[:, None, None], (s, l, b))
    return jnp.concatenate(
        [jnp.stack([g_show, g_click, g_embed], axis=-1), g_mf,
         slot_col[..., None]], axis=-1)                    # [S,L,B,D+4]


def acc_from_delta(delta: jnp.ndarray, n: int,
                   d_main: int = None) -> Dict[str, jnp.ndarray]:
    """Merged per-row accumulators for ps.optimizer.apply_push from the
    scatter output [D(+Dex)+4, >=n] (slot column already
    first-occurrence-exact).  d_main: the mf width when the payload also
    carries expand-embedding columns (they split into g_embedx_ex)."""
    d = delta.shape[0] - 4
    if d_main is None:
        d_main = d
    acc = {
        "g_show": delta[0, :n],
        "g_click": delta[1, :n],
        "g_embed": delta[2, :n],
        "g_embedx": delta[3:3 + d_main, :n].T,
        "slot": jnp.rint(delta[d + 3, :n]).astype(jnp.int32),
    }
    if d_main < d:
        acc["g_embedx_ex"] = delta[3 + d_main:3 + d, :n].T
    return acc


def pull_pool_cvm(ws: Dict[str, jnp.ndarray], plan, dims: sp.SpmmDims,
                  shape_slb: Tuple[int, int, int], use_cvm: bool = True,
                  interpret: bool = False,
                  crossing: str = "take") -> jnp.ndarray:
    """Fused pull + seqpool + CVM → pooled [B, S, 3 + D].

    Row 0 and the sentinel tile hold zeros, so padding occurrences and
    unseen keys contribute nothing — no length mask needed on the pull side.
    crossing: sorted→canonical lowering (ops/crossing.py) — "take" gathers
    by inv_perm, "sort" re-sorts keyed by perm (the destination index).
    """
    from paddlebox_tpu import flags
    from paddlebox_tpu.ops import crossing as cx
    assert crossing in ("take", "sort"), crossing
    s, l, b = shape_slb
    d = ws["mf"].shape[1] + _ex_dim(ws)
    rows2d, perm, inv_perm, ch, tl, fg, fs, first_occ = plan[:8]
    eff = plan_eff_dims(plan, dims)
    tab = _pull_table(ws, dims)
    g = sp.gather_sorted(tab, rows2d, ch, tl, fg, eff or dims,
                         interpret=interpret)              # [3+D+1, p_pad]
    # created-mask the mf rows in the SORTED domain: the mf_size column is
    # consumed here and never rides the crossing (w shrinks by 1, and the
    # canonical-domain mask multiply disappears)
    created = (g[3 + d:4 + d] > 0).astype(g.dtype)         # [1, p_pad]
    g = jnp.concatenate([g[:3], g[3:3 + d] * created], axis=0)
    w = 3 + d
    if flags.get_flags("mxu_crossing_bf16"):
        g = g.astype(jnp.bfloat16)
    if crossing == "sort":
        if eff is not None:
            # dropped (row-0) positions re-enter as leading zero columns —
            # exactly the value row 0 holds
            p0 = dims.p_pad - eff.p_pad
            g = jnp.concatenate([jnp.zeros((w, p0), g.dtype), g], axis=1)
        v = cx.permute_by_dest(tuple(g[:, :dims.p]), perm).T  # [p, W]
    elif eff is None:
        v = jnp.take(g.T[:dims.p], inv_perm, axis=0)       # canonical [p,W]
    else:
        # trimmed plan: dropped positions (inv_perm < 0) were row-0
        # occurrences whose pull value is exactly zero — clamp + mask
        v = jnp.take(g.T, jnp.maximum(inv_perm, 0), axis=0)
        v = v * (inv_perm >= 0).astype(v.dtype)[:, None]
    v = v.reshape(s, l, b, w).astype(jnp.float32)
    return pool_cvm_values(v, use_cvm, premasked=True)


def push_and_update(ws: Dict[str, jnp.ndarray], plan, dims: sp.SpmmDims,
                    idx_slb: jnp.ndarray, d_pooled: jnp.ndarray,
                    ins_cvm: jnp.ndarray, slot_ids: jnp.ndarray,
                    cfg: SparseSGDConfig,
                    interpret: bool = False,
                    crossing: str = "take") -> Dict[str, jnp.ndarray]:
    """Merged push + sparse optimizer.

    d_pooled [B, S, 3+D] — cols 0,1 are ignored and replaced by the
    instance cvm (reference push semantics, box_wrapper_impl.h:373);
    ins_cvm [B, 2]; slot_ids [S].
    crossing: canonical→sorted lowering (ops/crossing.py) — "take" gathers
    by perm, "sort" re-sorts keyed by inv_perm (the destination index).

    When the plan carries static sorted-domain planes (len > 8: bs,
    labelcol, slotcol — pass_feed builds them at feed time), only the
    DYNAMIC payload columns cross (g_embed + D×g_mf = 1+D channels):
    g_show ≡ 1 rides as a constant, g_click and slot are feed-time planes
    (the label and slot of an occurrence never change within a pass), and
    the crossing gathers from the [B*S, 1+D] pooled-grad matrix instead of
    a materialized [S, L, B, D+4] broadcast — the payload is constant over
    L, so the broadcast carried 3x redundant rows through the crossing.
    ≙ CopyForPush building the payload directly per key slot,
    box_wrapper.cu:1168.
    """
    from paddlebox_tpu import flags
    from paddlebox_tpu.ops import crossing as cx
    assert crossing in ("take", "sort"), crossing
    s, l, b = idx_slb.shape
    d = ws["mf"].shape[1] + _ex_dim(ws)
    n = ws["show"].shape[0]
    w = d + 4
    rows2d, perm, inv_perm, ch, tl, fg, fs, first_occ = plan[:8]
    eff = plan_eff_dims(plan, dims)
    kd = eff or dims
    bf16 = bool(flags.get_flags("mxu_crossing_bf16"))

    if len(plan) > 8:
        bs_ids, labelcol, slotcol = plan[8], plan[9], plan[10]
        # dynamic columns only: [B*S, 1+D] (b-major, bs = b*S + s)
        p2 = d_pooled[:, :, 2:].reshape(b * s, 1 + d)
        if bf16:
            p2 = p2.astype(jnp.bfloat16)
        if crossing == "sort":
            # canonical flat [(s,l,b), 1+D] — broadcast over L only here,
            # in the narrow dynamic slice
            can = jnp.broadcast_to(
                jnp.transpose(p2.reshape(b, s, 1 + d), (1, 0, 2))[:, None],
                (s, l, b, 1 + d)).reshape(dims.p, 1 + d)
            dyn = cx.permute_by_dest(tuple(can.T), inv_perm)   # [1+D, p]
            if eff is not None:
                dyn = dyn[:, dims.p_pad - eff.p_pad:]
            pad = kd.p_pad - dyn.shape[1]
            dyn = jnp.concatenate(
                [dyn, jnp.zeros((1 + d, pad), dyn.dtype)], axis=1)
        else:
            dyn = jnp.take(p2, bs_ids, axis=0).T               # [1+D, p_pad]
        dyn = dyn.astype(jnp.float32)
        ones = jnp.ones((1, kd.p_pad), jnp.float32)
        srt_cm = jnp.concatenate(
            [ones, labelcol[None], dyn, slotcol[None]], axis=0)
    else:
        # NOTE: mxu_crossing_bf16 is intentionally NOT applied here — the
        # legacy payload carries the slot-id column, which must stay exact
        # (ids beyond 8 mantissa bits would round in bf16 and silently
        # break the optimizer's exact slot matches: nodeid_slot,
        # slot_mf_dims), so the bandwidth lever only pays on the planes
        # path where slot rides a separate static f32 plane.
        payload = push_payload(d_pooled, ins_cvm, slot_ids, (s, l, b))
        flat = payload.reshape(dims.p, w)
        if crossing == "sort":
            # destination = this element's sorted position (shifted
            # kept-domain position when trimmed: negatives sort first =
            # dropped prefix)
            srt_cm = cx.permute_by_dest(tuple(flat.T), inv_perm)   # [w, p]
            if eff is not None:
                srt_cm = srt_cm[:, dims.p_pad - eff.p_pad:]
            pad = kd.p_pad - srt_cm.shape[1]
            srt_cm = jnp.concatenate(
                [srt_cm, jnp.zeros((w, pad), srt_cm.dtype)], axis=1)
        elif eff is None:
            srt = jnp.take(flat, perm, axis=0)             # sorted domain
            srt_cm = jnp.concatenate(
                [srt, jnp.zeros((dims.p_pad - dims.p, w), srt.dtype)]).T
        else:
            # trimmed plan: keep the suffix of the full bijection — dropped
            # row-0 occurrences never scatter (row 0 is reserved,
            # optimizer.py:17) and sentinel tail positions read canonical 0
            # but land in the discarded sentinel tile
            p0 = dims.p_pad - eff.p_pad
            perm_k = jnp.concatenate(
                [perm, jnp.zeros((dims.p_pad - dims.p,), jnp.int32)])[p0:]
            srt_cm = jnp.take(flat, perm_k, axis=0).T
        srt_cm = srt_cm.astype(jnp.float32)
        # slot column: keep only each row's FIRST occurrence (plan mask), so
        # the scatter-sum returns that occurrence's slot exactly — no
        # averaging, and keys appearing under several slots resolve
        # deterministically (≙ the reference's per-key slot from its merge
        # position, box_wrapper.cu:417 PushMergeCopy)
        srt_cm = srt_cm.at[w - 1, :].mul(first_occ)
    delta = sp.scatter_add_sorted(srt_cm, rows2d, ch, tl, fs, kd,
                                  interpret=interpret)     # [D+4, n_kernel]
    acc = acc_from_delta(delta, n, d_main=ws["mf"].shape[1])
    return sparse_opt.apply_push(ws, acc, cfg)
