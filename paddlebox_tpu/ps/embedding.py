"""Device-resident pass working set: pull (gather) and push (scatter-add).

TPU-native replacement for the HBM hash table + HeterComm all2all
(hashtable.h:114, heter_comm_inl.h:1117-1996) and the BoxWrapper pull/push
hot path (box_wrapper_impl.h:25-632, copy kernels box_wrapper.cu:75-600):

* key→row translation happens ON HOST at batch-pack time against the pass's
  sorted unique key array (PassKeyMapper below, ≙ DedupKeysAndFillIdx +
  build-pass dedup PreBuildTask ps_gpu_wrapper.cc:114) — so the device side
  is a pure dense-index gather/scatter that XLA tiles onto the MXU/HBM with
  no hash probes or dynamic shapes;
* cross-chip routing is GSPMD: the working set is row-sharded over the mesh
  (HybridTopology.table_spec) and jit-compiled gathers lower to the same
  all-to-all pattern HeterComm hand-codes.

Row 0 is the reserved zero row: padding positions and (optionally) key 0 pull
zeros and push nothing (≙ FLAGS_enable_pull_box_padding_zero).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from paddlebox_tpu.config import EmbeddingTableConfig

# Device pytree fields (all [N] except mf/mf_g2sum)
DEVICE_FIELDS = ("show", "click", "delta_score", "slot", "embed_w",
                 "embed_g2sum", "mf_size", "mf_g2sum", "mf")


def round_up(n: int, align: int) -> int:
    return ((n + align - 1) // align) * align


def size_bucket(n: int, align: int = 8) -> int:
    """Grow-only size buckets so per-pass working sets of similar size reuse
    the same compiled step (≙ DCacheBuffer grow-only realloc,
    box_wrapper.h:198)."""
    n = max(n, align)
    bucket = align
    while bucket < n:
        bucket *= 2
    # intermediate steps between powers of two cap padding waste at ~14%
    for frac in (5 * bucket // 8, 3 * bucket // 4, 7 * bucket // 8):
        if frac >= n and frac % align == 0:
            return frac
    return bucket


class PassKeyMapper:
    """Host-side key→pass-row translation over the sorted unique key array.

    Row 0 is reserved (zero row); real keys map to rows 1..n.  Above a size
    threshold the lookups run through the native open-addressing hash
    (native/hash_shard.cc — threaded, ~6x faster than np.searchsorted over
    a multi-MB key array); the numpy binary search remains the fallback.
    """

    _NATIVE_MIN = 65_536  # below this searchsorted wins (no build cost)

    def __init__(self, sorted_keys: np.ndarray):
        self.sorted_keys = sorted_keys  # unique, ascending, excludes 0
        self._native = None
        self._native_tried = False

    def _native_hash(self):
        if not self._native_tried:
            self._native_tried = True
            try:
                from paddlebox_tpu.native import hash_map
                if hash_map.available():
                    h = hash_map.NativeKeyHash(len(self.sorted_keys))
                    # insertion order == sorted order, so row i+1 matches
                    # the searchsorted contract exactly
                    h.upsert(self.sorted_keys)
                    self._native = h
            except Exception:
                self._native = None
        return self._native

    def __call__(self, keys: np.ndarray) -> np.ndarray:
        if len(self.sorted_keys) == 0:
            return np.zeros(len(keys), np.int32)
        if len(keys) >= self._NATIVE_MIN and len(self.sorted_keys) >= 1024:
            h = self._native_hash()
            if h is not None:
                return h.find_rows1_i32(np.asarray(keys, np.uint64))
        pos = np.searchsorted(self.sorted_keys, keys)
        pos_c = np.minimum(pos, len(self.sorted_keys) - 1)
        found = self.sorted_keys[pos_c] == keys
        return np.where(found, pos_c + 1, 0).astype(np.int32)

    @property
    def num_keys(self) -> int:
        return len(self.sorted_keys)


def build_working_set(host_soa: Dict[str, np.ndarray], mf_dim: int,
                      pad_to: Optional[int] = None,
                      sharding=None,
                      buffers: Optional[Dict[str, np.ndarray]] = None
                      ) -> Dict[str, jnp.ndarray]:
    """Assemble the device pytree from host rows (row 0 = zeros) and place it
    with the given NamedSharding (row-sharded over the mesh).

    The reserved all-zero row 0 is load-bearing for every step path:
    fast/mxu point padding occurrences at it so they pool as exact 0.0,
    and the ragged CSR plan (ps/ragged_path.py) additionally pins row 0
    as [U]-position 0 — its pad/unknown sink whose gathered values and
    scattered-back updates are both provably zero.

    ≙ BuildGPUTask's HBM pool fill (ps_gpu_wrapper.cc:684-760) — a single
    chunked H2D per field instead of 500k-key memcpy loops.

    ``buffers``, if given, is a caller-owned staging-buffer pool keyed by
    field: when the bucketed size is unchanged from the previous pass the
    padded host array is reused instead of reallocated (only the reserved
    row and the stale tail are re-zeroed; metered as
    ``ps.engine.ws_buffer_reuse``).  Reused staging is always *copied* to
    the device (never aliased) so mutating the buffer next pass cannot
    corrupt a live working set.
    """
    from paddlebox_tpu.utils.monitor import stat_add
    n = len(host_soa["show"])
    total = (pad_to if pad_to is not None else size_bucket(n + 1))
    assert total >= n + 1
    ws = {}
    reused = 0
    for f in host_soa:
        if f == "unseen_days":  # host-only lifecycle field
            continue
        src = host_soa[f]
        shape = (total,) + src.shape[1:]
        arr = None
        if buffers is not None:
            prev = buffers.get(f)
            if prev is not None and prev.shape == shape \
                    and prev.dtype == src.dtype:
                arr = prev
                arr[0] = 0          # reserved zero row
                arr[n + 1:] = 0     # stale rows from a larger prior pass
                reused += 1
        if arr is None:
            arr = np.zeros(shape, src.dtype)
            if buffers is not None:
                buffers[f] = arr
        arr[1:n + 1] = src
        dtype = jnp.int32 if src.dtype == np.int32 else jnp.float32
        if sharding is not None:
            ws[f] = jax.device_put(arr.astype(dtype), sharding)
        elif buffers is not None:
            # the staging buffer outlives this pass — force a device copy
            ws[f] = jnp.array(arr, dtype=dtype, copy=True)
        else:
            ws[f] = jnp.asarray(arr, dtype=dtype)
    if reused:
        stat_add("ps.engine.ws_buffer_reuse", float(reused))
    return ws


def scatter_device_rows(ws: Dict[str, jnp.ndarray], rows,
                        values: Dict[str, jnp.ndarray]
                        ) -> Dict[str, jnp.ndarray]:
    """Cached-plane working-set fill: scatter already-device-resident row
    values (a DeviceRowCache gather) into the pass working set — no host
    staging and no H2D for these rows.  Dtypes must already match the
    working set's (the cache stores build_working_set's exact casts), so
    ``pull_sparse``/``push_sparse_grads`` see bits identical to a wire
    pull.  Returns the updated pytree (functional, like every ws op)."""
    rows_d = jnp.asarray(rows)
    for f, v in values.items():
        if f in ws:
            ws[f] = ws[f].at[rows_d].set(v)
    return ws


def dump_working_set(ws: Dict[str, jnp.ndarray], n: int
                     ) -> Dict[str, np.ndarray]:
    """Device→host for end_pass write-back (≙ dump_pool_to_cpu_func,
    ps_gpu_wrapper.cc:983+ / accessor DumpFill).  Table-wide scalars
    (e.g. a serving freeze's mf_scale) are not row data and are skipped."""
    return {f: np.asarray(ws[f])[1:n + 1] for f in ws
            if getattr(ws[f], "ndim", 1) >= 1}


def quantize_working_set(ws: Dict[str, jnp.ndarray], quant_bits: int = 16,
                         scale: float = 1.0 / 32767.0
                         ) -> Dict[str, jnp.ndarray]:
    """Serving-mode freeze: re-encode mf as int16 grid points so embedx
    pulls read half the HBM bytes and the table holds half the memory
    (≙ the quant feature value + EmbedxQuantOp dequant-on-pull,
    box_wrapper.cu:37-44, table-wide pull_embedx_scale box_wrapper.h:655).

    The quantized working set is PULL-ONLY — pushes require the f32 store
    (the reference likewise quantizes only dumped/serving tables)."""
    if quant_bits != 16:
        raise ValueError("only quant_bits=16 (int16 grid) is supported")
    out = dict(ws)
    q = jnp.clip(jnp.round(ws["mf"] / scale), -32767, 32767)
    out["mf"] = q.astype(jnp.int16)
    out["mf_scale"] = jnp.float32(scale)
    return out


def mf_values(ws: Dict[str, jnp.ndarray], gathered: jnp.ndarray
              ) -> jnp.ndarray:
    """Dequantize gathered mf rows when the working set is frozen int16
    (EmbedxQuantOp: dest = int16 * scale); identity for the f32 store."""
    if jnp.issubdtype(gathered.dtype, jnp.integer) and "mf_scale" in ws:
        return gathered.astype(jnp.float32) * ws["mf_scale"]
    return gathered


def is_quantized(ws: Dict[str, jnp.ndarray]) -> bool:
    return "mf_scale" in ws


def pull_sparse(ws: Dict[str, jnp.ndarray], indices: jnp.ndarray
                ) -> jnp.ndarray:
    """Gather pull values [*, 3+D]: (show, click, embed_w, embedx×D).

    ≙ PullSparseCaseGPU + CopyForPull (box_wrapper_impl.h:25,
    box_wrapper.cu:945).  mf is masked until created (mf_size>0 —
    CommonPullValue semantics, feature_value.h:161); a serving-frozen
    int16 table dequantizes after the gather (half the gather bytes,
    ≙ EmbedxQuantOp).
    """
    show = ws["show"][indices]
    click = ws["click"][indices]
    embed_w = ws["embed_w"][indices]
    created = (ws["mf_size"][indices] > 0).astype(jnp.float32)
    mf = mf_values(ws, ws["mf"][indices]) * created[..., None]
    return jnp.concatenate(
        [show[..., None], click[..., None], embed_w[..., None], mf], axis=-1)


def pull_sparse_extended(ws: Dict[str, jnp.ndarray], indices: jnp.ndarray
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """≙ pull_box_extended_sparse / PullCopyNNCross (box_wrapper.cu:147):
    base pull value plus the expand ("NNCross") embedding, gated by the same
    mf-created mask."""
    base = pull_sparse(ws, indices)
    created = (ws["mf_size"][indices] > 0).astype(ws["mf_ex"].dtype)
    emb_ex = ws["mf_ex"][indices] * created[..., None]
    return base, emb_ex


def push_sparse_grads(ws: Dict[str, jnp.ndarray], indices: jnp.ndarray,
                      grads: jnp.ndarray, slot_ids: jnp.ndarray
                      ) -> Dict[str, jnp.ndarray]:
    """Accumulate per-row push values by scatter-add (merge-by-key,
    ≙ PushMergeCopyAtomic box_wrapper.cu:476 / dynamic_merge_grad).

    indices: [S,B,L] pass rows; grads: [S,B,L,3+D] where cols are
    (g_show, g_click, g_embed, g_embedx...); slot_ids: [S] int32.
    Returns accumulators dict with g_show/g_click/g_embed/g_embedx [N(,D)]
    and the per-row slot id.  Row 0 (padding) accumulates too but is ignored
    by the optimizer mask.
    """
    n = ws["show"].shape[0]
    flat_idx = indices.reshape(-1)
    flat_g = grads.reshape(-1, grads.shape[-1])
    S, B, L = indices.shape
    flat_slot = jnp.broadcast_to(
        slot_ids[:, None, None], (S, B, L)).reshape(-1)
    # padding / masked positions carry all-zero grads already (seqpool bwd
    # masks by key validity); zero their index to the reserved row anyway.
    zeros = jnp.zeros((n,), flat_g.dtype)
    acc = {
        "g_show": zeros.at[flat_idx].add(flat_g[:, 0]),
        "g_click": zeros.at[flat_idx].add(flat_g[:, 1]),
        "g_embed": zeros.at[flat_idx].add(flat_g[:, 2]),
        "g_embedx": jnp.zeros_like(ws["mf"]).at[flat_idx].add(flat_g[:, 3:]),
        # only valid occurrences vote (the show grad column carries the
        # seqpool key mask: ins_show > 0 exactly where the key is real)
        "slot": jnp.zeros((n,), jnp.int32).at[flat_idx].max(
            jnp.where(flat_g[:, 0] > 0, flat_slot.astype(jnp.int32), 0)),
    }
    return acc


def push_sparse_grads_extended(ws, indices, grads, grads_ex, slot_ids):
    """Extended push: base accumulators + expand-embedding grads
    (≙ push_box_extended_sparse)."""
    acc = push_sparse_grads(ws, indices, grads, slot_ids)
    flat_idx = indices.reshape(-1)
    flat_gx = grads_ex.reshape(-1, grads_ex.shape[-1])
    acc["g_embedx_ex"] = jnp.zeros_like(ws["mf_ex"]).at[flat_idx].add(flat_gx)
    return acc
