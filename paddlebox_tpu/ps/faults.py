"""Deterministic fault injection for the PS tier — the chaos harness.

The exactly-once retry protocol (ps/service.py: request ids + the server
dedup window + backoff-under-deadline) is only trustworthy if failures
are *reproducible under test*.  This module provides that reproducibility
two ways, both driven by one seedable :class:`FaultPlan`:

  * **in-process hooks** at five named sites — ``connect`` (client about
    to dial), ``send`` / ``recv`` (either peer's frame I/O), ``dispatch``
    (server about to run a verb), and ``lifecycle`` (trainer-side
    SIGKILL-schedule points: ``ckpt_sparse`` mid-checkpoint-write,
    ``ckpt_commit`` between generation assembly and the MANIFEST pointer
    swap, ``end_pass`` before the pass write-back, and the live-reshard
    windows ``reshard_snapshot`` / ``reshard_catchup`` /
    ``reshard_cutover`` — io/checkpoint.py, ps/pass_manager.py and
    ps/reshard.py fire them).  The hooks can drop the connection,
    delay it, truncate a frame mid-write, kill the server abruptly
    mid-verb, or simulate a process SIGKILL at a lifecycle point (the
    kill-anywhere chaos soak's seeded schedule).  Production pays zero
    cost:
    the service path checks one module global (``faults.ACTIVE``) that
    stays ``None`` unless :func:`install` ran, and ``install`` refuses
    unless the registered flag ``FLAGS_ps_fault_injection`` is set.

  * a **chaos TCP proxy** (:class:`ChaosProxy`) that sits between a real
    ``PSClient`` and ``PSServer`` (possibly in other processes) and
    applies the same plan frame-by-frame on the wire — ``connect`` on a
    new client connection, ``send`` for client→server frames, ``recv``
    for server→client frames.

A plan is a list of rules.  Each rule names a site, optionally a role
(``client``/``server``/``proxy``), and triggers either at explicit hit
indices (``at=(3, 9)`` — the 4th and 10th invocation of that site+role
counter) or probabilistically from the plan's seeded RNG.  Given the
same call sequence, a plan fires identically — the chaos soak test
(tests/test_chaos_soak.py) leans on this to replay a schedule.

Injected faults raise :class:`InjectedFault` (a ``ConnectionError``
subclass) so they flow through exactly the retry paths a real network
failure would.  Every fire bumps ``ps.fault.<site>.<kind>`` in
utils/monitor.StatRegistry.
"""

from __future__ import annotations

import dataclasses
import random
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from paddlebox_tpu import flags
from paddlebox_tpu.utils import flight, lockdep
from paddlebox_tpu.utils.monitor import stat_add

flags.define_flag(
    "ps_fault_injection", False,
    "allow faults.install() to arm in-process PS fault hooks (chaos "
    "testing only — production keeps this off and pays zero cost)")


class InjectedFault(ConnectionError):
    """An injected network/server fault (subclasses ConnectionError so it
    takes the same retry path a real failure would)."""


@dataclasses.dataclass(frozen=True)
class FaultAction:
    # "drop" | "delay" | "truncate" | "kill_server" | "kill" (lifecycle
    # site: simulate an abrupt process death at a named point)
    kind: str
    delay_s: float = 0.0


@dataclasses.dataclass
class _Rule:
    site: str
    role: Optional[str]
    action: FaultAction
    at: Tuple[int, ...] = ()
    prob: float = 0.0
    limit: Optional[int] = None   # max fires (None = unbounded)
    cmd: Optional[str] = None     # dispatch site only: match one verb
    seen: int = 0                 # matching invocations so far (at= index)
    fired: int = 0

    def matches(self, site: str, role: Optional[str],
                cmd: Optional[str]) -> bool:
        return (self.site == site
                and (self.role is None or self.role == role)
                and (self.cmd is None or self.cmd == cmd))


class FaultPlan:
    """Seedable, deterministic schedule of fault injections.

    Build with the fluent helpers (each returns ``self``)::

        plan = (FaultPlan(seed=7)
                .drop("send", role="client", at=(2, 5))
                .delay("recv", 0.01, prob=0.2)
                .truncate("send", at=(9,))
                .kill_server(at=(40,)))

    ``at`` indices are 0-based positions in the RULE's own sequence of
    matching invocations (``at=(2, 5)`` → its 3rd and 6th match);
    ``cmd=`` narrows a dispatch-site rule to one verb.  Thread-safe.
    """

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._rules: List[_Rule] = []
        self._hits: Dict[Tuple[str, Optional[str]], int] = {}
        self._lock = lockdep.lock("ps.faults.FaultPlan._lock")
        self.killed = threading.Event()   # set when a kill_server fires

    # -- builders ------------------------------------------------------------
    def add_rule(self, site: str, action: FaultAction,
                 role: Optional[str] = None, at: Tuple[int, ...] = (),
                 prob: float = 0.0, limit: Optional[int] = None,
                 cmd: Optional[str] = None) -> "FaultPlan":
        if site not in ("connect", "send", "recv", "dispatch", "lifecycle"):
            raise ValueError(f"unknown fault site {site!r}")
        with self._lock:
            self._rules.append(_Rule(site, role, action, tuple(at),
                                     float(prob), limit, cmd))
        return self

    def drop(self, site: str, role: Optional[str] = None,
             at: Tuple[int, ...] = (), prob: float = 0.0,
             limit: Optional[int] = None,
             cmd: Optional[str] = None) -> "FaultPlan":
        return self.add_rule(site, FaultAction("drop"), role, at, prob,
                             limit, cmd)

    def delay(self, site: str, seconds: float, role: Optional[str] = None,
              at: Tuple[int, ...] = (), prob: float = 0.0,
              limit: Optional[int] = None,
              cmd: Optional[str] = None) -> "FaultPlan":
        return self.add_rule(site, FaultAction("delay", seconds), role, at,
                             prob, limit, cmd)

    def truncate(self, site: str = "send", role: Optional[str] = None,
                 at: Tuple[int, ...] = (), prob: float = 0.0,
                 limit: Optional[int] = None,
                 cmd: Optional[str] = None) -> "FaultPlan":
        return self.add_rule(site, FaultAction("truncate"), role, at, prob,
                             limit, cmd)

    def kill_server(self, at: Tuple[int, ...] = (), prob: float = 0.0,
                    cmd: Optional[str] = None,
                    limit: Optional[int] = 1) -> "FaultPlan":
        """Abrupt server death mid-verb (dispatch site).  ``limit``
        defaults to 1 for the single-restart soaks; the kill-anywhere
        soak raises it and pairs each fire with a supervisor restart
        (launch.PSServerSupervisor)."""
        return self.add_rule("dispatch", FaultAction("kill_server"),
                             "server", at, prob, limit=limit, cmd=cmd)

    def kill_at(self, point: str, at: Tuple[int, ...] = (),
                prob: float = 0.0,
                limit: Optional[int] = None) -> "FaultPlan":
        """Seeded SIGKILL schedule at a named lifecycle point
        (``ckpt_sparse`` / ``ckpt_commit`` / ``end_pass``, or the
        migration windows ``reshard_snapshot`` — moving rows dumped but
        no cutover staged, ``reshard_catchup`` — deltas shipped and the
        moving range frozen, ``reshard_cutover`` — between the 2-phase
        prepare and commit): the producer site raises InjectedFault
        there, simulating an abrupt trainer/driver death whose kill
        points replay from this one plan/seed."""
        return self.add_rule("lifecycle", FaultAction("kill"), None, at,
                             prob, limit=limit, cmd=point)

    @classmethod
    def default_chaos(cls, seed: int = 0) -> "FaultPlan":
        """A modest background-noise plan for soak runs / the launcher's
        ``--chaos_backend`` proxy: occasional connection drops and small
        delays, never a kill."""
        return (cls(seed)
                .drop("connect", prob=0.02)
                .drop("send", prob=0.01)
                .drop("recv", prob=0.01)
                .truncate("send", prob=0.005)
                .delay("send", 0.005, prob=0.05))

    # -- firing --------------------------------------------------------------
    def fire(self, site: str, role: Optional[str] = None,
             cmd: Optional[str] = None) -> Optional[FaultAction]:
        """Count one invocation of the site and return the action of the
        first matching rule (or None).  Deterministic given the same call
        sequence: one RNG draw per probabilistic rule per match."""
        with self._lock:
            self._hits[(site, role)] = self._hits.get((site, role), 0) + 1
            hit: Optional[FaultAction] = None
            for rule in self._rules:
                if not rule.matches(site, role, cmd):
                    continue
                idx = rule.seen
                rule.seen += 1
                scheduled = idx in rule.at
                if rule.prob > 0.0:
                    # always draw, so later decisions stay aligned even
                    # when an earlier rule already matched
                    scheduled = (self._rng.random() < rule.prob) or scheduled
                if scheduled and hit is None and (
                        rule.limit is None or rule.fired < rule.limit):
                    rule.fired += 1
                    hit = rule.action
        if hit is not None:
            stat_add(f"ps.fault.{site}.{hit.kind}")
            flight.record("fault_injected", site=site, action=hit.kind,
                          role=role, cmd=cmd)
        return hit

    def hits(self, site: str, role: Optional[str] = None) -> int:
        with self._lock:
            return self._hits.get((site, role), 0)


# ---------------------------------------------------------------------------
# In-process hook surface (called from ps/service.py when ACTIVE is set).
# ---------------------------------------------------------------------------

ACTIVE: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> FaultPlan:
    """Arm the in-process hooks.  Refuses unless FLAGS_ps_fault_injection
    is set — production never reaches the injection branches."""
    global ACTIVE
    if not flags.get_flags("ps_fault_injection"):
        raise RuntimeError(
            "fault injection is disabled — set_flags({'ps_fault_injection':"
            " True}) (or FLAGS_ps_fault_injection=1) before install()")
    ACTIVE = plan
    return plan


def uninstall() -> None:
    global ACTIVE
    ACTIVE = None


def on_connect(role: str) -> None:
    plan = ACTIVE
    if plan is None:
        return
    act = plan.fire("connect", role)
    if act is None:
        return
    if act.kind == "delay":
        time.sleep(act.delay_s)
    elif act.kind == "drop":
        raise InjectedFault(f"injected: connect refused ({role})")


def on_send(sock: socket.socket, frame: bytes, role: str) -> None:
    """May send a truncated prefix of ``frame`` and sever, or raise before
    any byte moves; returns normally when no fault fires (the caller then
    sends the full frame)."""
    plan = ACTIVE
    if plan is None:
        return
    act = plan.fire("send", role)
    if act is None:
        return
    if act.kind == "delay":
        time.sleep(act.delay_s)
    elif act.kind == "drop":
        raise InjectedFault(f"injected: connection dropped before send "
                            f"({role})")
    elif act.kind == "truncate":
        try:
            sock.sendall(frame[:max(1, len(frame) // 2)])
            sock.shutdown(socket.SHUT_WR)   # peer sees a truncated frame
        except OSError:
            pass
        raise InjectedFault(f"injected: frame truncated mid-send ({role})")


def on_recv(role: str) -> None:
    plan = ACTIVE
    if plan is None:
        return
    act = plan.fire("recv", role)
    if act is None:
        return
    if act.kind == "delay":
        time.sleep(act.delay_s)
    elif act.kind == "drop":
        raise InjectedFault(f"injected: connection dropped before recv "
                            f"({role})")


def on_dispatch(cmd: Optional[str], server) -> None:
    plan = ACTIVE
    if plan is None:
        return
    act = plan.fire("dispatch", "server", cmd)
    if act is None:
        return
    if act.kind == "delay":
        time.sleep(act.delay_s)
    elif act.kind == "drop":
        # verb never runs; connection dies without a response — the
        # client's retry (same rid) re-executes cleanly
        raise InjectedFault(f"injected: dispatch dropped ({cmd})")
    elif act.kind == "kill_server":
        # abrupt mid-verb server death, BEFORE the verb applies (crash-
        # before-commit): the kill runs off-thread so this handler can
        # unwind while the listener + every live connection is torn down
        threading.Thread(target=server.kill, daemon=True).start()
        plan.killed.set()
        raise InjectedFault(f"injected: server killed mid-verb ({cmd})")


def on_lifecycle(point: str) -> None:
    """Trainer-side SIGKILL-schedule site: io/checkpoint.py fires it at
    ``ckpt_sparse`` (shard files down, generation not assembled) and
    ``ckpt_commit`` (generation assembled, MANIFEST not yet swapped);
    ps/pass_manager.py fires ``end_pass`` before the pass write-back;
    ps/reshard.py fires ``reshard_snapshot`` / ``reshard_catchup`` /
    ``reshard_cutover`` at the three migration crash windows.
    A matching ``kill`` rule raises InjectedFault — the abrupt-death
    simulation the auto-resume path (fleet.train_passes) must survive."""
    plan = ACTIVE
    if plan is None:
        return
    act = plan.fire("lifecycle", None, point)
    if act is None:
        return
    if act.kind == "delay":
        time.sleep(act.delay_s)
    elif act.kind in ("kill", "drop", "kill_server"):
        plan.killed.set()
        raise InjectedFault(f"injected: killed at lifecycle point "
                            f"({point})")


# ---------------------------------------------------------------------------
# Chaos TCP proxy — the out-of-process face of the same plan.
# ---------------------------------------------------------------------------

def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _close_quietly(sock: Optional[socket.socket]) -> None:
    if sock is None:
        return
    # shutdown BEFORE close: close() alone defers the FIN while a sibling
    # pump thread is still blocked in recv() on the same fd (Linux fput
    # semantics) — the peer would hang to its timeout instead of seeing a
    # clean sever
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class ChaosProxy:
    """Frame-aware TCP proxy between a PSClient and a PSServer applying a
    FaultPlan on the wire: ``connect`` fires per accepted client
    connection, ``send`` per client→server frame, ``recv`` per
    server→client frame (all with role="proxy").  drop severs both
    directions, truncate forwards half a frame then severs, delay sleeps
    before forwarding.  The backend address can be repointed live
    (:meth:`set_backend`) after a server restart on a new port."""

    def __init__(self, backend: Tuple[str, int], plan: FaultPlan,
                 host: str = "127.0.0.1", port: int = 0):
        self._plan = plan
        self._stop = threading.Event()
        self._lock = lockdep.lock("ps.faults.ChaosProxy._lock")
        self._backend: Tuple[str, int] = tuple(backend)
        self._conns: set = set()
        self._listener = socket.create_server((host, port))
        self.addr: Tuple[str, int] = self._listener.getsockname()
        # pboxlint: disable-next=PB405 -- chaos-proxy listener pump; close() stops it via listener shutdown
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()

    def set_backend(self, backend: Tuple[str, int]) -> None:
        with self._lock:
            self._backend = tuple(backend)

    def backend(self) -> Tuple[str, int]:
        with self._lock:
            return self._backend

    def shutdown(self) -> None:
        self._stop.set()
        _close_quietly(self._listener)
        with self._lock:
            conns = list(self._conns)
        for s in conns:
            _close_quietly(s)

    # -- internals -----------------------------------------------------------
    def _track(self, sock: socket.socket, add: bool) -> None:
        with self._lock:
            if add:
                self._conns.add(sock)
            else:
                self._conns.discard(sock)

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            # pboxlint: disable-next=PB405 -- per-connection fault injector; dies with its socket pair
            threading.Thread(target=self._serve_conn, args=(client,),
                             daemon=True).start()

    def _serve_conn(self, client: socket.socket) -> None:
        upstream: Optional[socket.socket] = None
        try:
            act = self._plan.fire("connect", "proxy")
            if act is not None:
                if act.kind == "delay":
                    time.sleep(act.delay_s)
                else:                       # drop/truncate both sever here
                    return
            upstream = socket.create_connection(self.backend(), timeout=10)
        except OSError:
            _close_quietly(client)
            _close_quietly(upstream)
            return
        finally:
            if upstream is None:
                _close_quietly(client)
        self._track(client, True)
        self._track(upstream, True)
        pair = (client, upstream)

        def pump(src: socket.socket, dst: socket.socket, site: str) -> None:
            try:
                while not self._stop.is_set():
                    head = _read_exact(src, 8)
                    (length,) = struct.unpack("<Q", head)
                    payload = _read_exact(src, length)
                    act = self._plan.fire(site, "proxy")
                    if act is not None:
                        if act.kind == "delay":
                            time.sleep(act.delay_s)
                        elif act.kind == "drop":
                            raise ConnectionError("injected proxy drop")
                        elif act.kind == "truncate":
                            frame = head + payload
                            dst.sendall(frame[:max(1, len(frame) // 2)])
                            raise ConnectionError("injected proxy truncate")
                    dst.sendall(head + payload)
            except (ConnectionError, OSError):
                pass
            finally:
                # sever BOTH directions so the client sees a clean failure
                for s in pair:
                    self._track(s, False)
                    _close_quietly(s)

        # pboxlint: disable-next=PB405 -- byte pump dies when either socket closes
        threading.Thread(target=pump, args=(client, upstream, "send"),
                         daemon=True).start()
        # pboxlint: disable-next=PB405 -- byte pump dies when either socket closes
        threading.Thread(target=pump, args=(upstream, client, "recv"),
                         daemon=True).start()
