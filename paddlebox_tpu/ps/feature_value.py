"""Feature value schema — struct-of-arrays on host and device.

≙ CommonFeatureValue (heter_ps/feature_value.h:44-57 layout comment:
delta_score, show, click, slot, embed_w, embed_g2sum, mf_dim, mf_size,
mf_g2sum?, embedx...) and CommonPullValue/CommonPushValue
(feature_value.h:161,185).  Instead of packed float rows with index
arithmetic, each field is its own array — the layout XLA/TPU wants (no
byte-offset gymnastics, every field contiguously vectorizable).

Pull value layout delivered to the model is [show, click, embed_w,
embedx x D] — the first two columns feed the CVM transform (cvm_offset=2),
col 2 is the lr/"join" scalar weight (what PaddleBox models call the q value).
Push value is the same width plus implicit slot: [g_show, g_click, g_embed,
g_embedx x D].
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import numpy as np

CVM_COLS = 2          # show, click
PULL_EXTRA = 3        # show, click, embed_w


HOST_FIELDS = (
    # (name, dtype, per-key shape suffix)
    ("show", np.float32, ()),
    ("click", np.float32, ()),
    ("delta_score", np.float32, ()),
    ("slot", np.int32, ()),
    ("embed_w", np.float32, ()),
    ("embed_g2sum", np.float32, ()),
    ("mf_size", np.int32, ()),      # 0 until mf created (lazy, threshold)
    ("mf_g2sum", np.float32, ()),
    ("unseen_days", np.float32, ()),
    ("mf", np.float32, ("D",)),     # embedx weights (random candidate init
                                    # until mf_size > 0 — see optimizer.py)
)

# optional expand ("NNCross") embedding fields, present when
# EmbeddingTableConfig.expand_dim > 0 (≙ PullCopyNNCross box_wrapper.cu:147
# and pull_box_extended_sparse_op)
EXPAND_FIELDS = (
    ("mf_ex", np.float32, ("E",)),
    ("mf_ex_g2sum", np.float32, ()),
)

# extra per-row state for the (shared-)adam optimizers: shared first/second
# moments + beta-power trackers for the embed and embedx groups
# (≙ SparseAdamSharedOptimizer state layout, optimizer.cuh.h:455-467:
# GSum/G2Sum/Beta1Pow/Beta2Pow — here G2Sum reuses embed_g2sum/mf_g2sum)
ADAM_FIELDS = (
    ("embed_gsum", np.float32, ()),
    ("embed_b1p", np.float32, ()),
    ("embed_b2p", np.float32, ()),
    ("mf_gsum", np.float32, ()),
    ("mf_b1p", np.float32, ()),
    ("mf_b2p", np.float32, ()),
)


# per-dim optimizer state (≙ CPU SparseAdamSGDRule sparse_sgd_rule.h:126 /
# GPU SparseAdamOptimizer optimizer.cuh.h:148, and StdAdaGradSGDRule
# sparse_sgd_rule.h:109): embedx moments/g2sum per dimension
DIM_ADAM_FIELDS = (
    ("mf_gsum_d", np.float32, ("D",)),
    ("mf_g2sum_d", np.float32, ("D",)),
)
DIM_ADAGRAD_FIELDS = (
    ("mf_g2sum_d", np.float32, ("D",)),
)


def state_fields(optimizer: str):
    """Extra per-row state fields an optimizer rule needs."""
    return {
        "shared_adam": ADAM_FIELDS,
        "adam": ADAM_FIELDS + DIM_ADAM_FIELDS,
        "std_adagrad": DIM_ADAGRAD_FIELDS,
    }.get(optimizer, ())


def empty_soa(n: int, mf_dim: int, expand_dim: int = 0, adam: bool = False,
              optimizer: str = "",
              double_stats: bool = False) -> Dict[str, np.ndarray]:
    """double_stats: f64 show/click on the host tier — the
    CtrDoubleAccessor layout (ctr_double_accessor.h: DownpourCtrDouble
    keeps show/click as double so billion-impression counters never
    saturate f32's 2^24 integer range)."""
    out = {}
    extra = state_fields(optimizer) if optimizer else \
        (ADAM_FIELDS if adam else ())
    fields = HOST_FIELDS + (EXPAND_FIELDS if expand_dim > 0 else ()) \
        + extra
    for name, dtype, suffix in fields:
        if double_stats and name in ("show", "click"):
            dtype = np.float64
        shape = (n,) + tuple(
            mf_dim if s == "D" else (expand_dim if s == "E" else s)
            for s in suffix)
        out[name] = np.zeros(shape, dtype=dtype)
    return out


def default_rows(n: int, mf_dim: int, rng: np.random.Generator,
                 mf_initial_range: float, initial_range: float = 0.0,
                 expand_dim: int = 0, adam: bool = False,
                 beta1: float = 0.9, beta2: float = 0.999,
                 optimizer: str = "",
                 double_stats: bool = False) -> Dict[str, np.ndarray]:
    """Fresh feature rows for keys unseen by the host table.

    embed_w ~ U(-initial_range, initial_range) (CPU rule init; default range 0
    ⇒ 0, optimizer_conf.h:29); mf gets its creation-time candidate init
    ~ U(0, mf_initial_range) (≙ curand_uniform * mf_initial_range,
    optimizer.cuh.h:119-121) which stays masked until mf_size > 0.
    """
    soa = empty_soa(n, mf_dim, expand_dim, adam, optimizer, double_stats)
    if initial_range > 0:
        soa["embed_w"] = rng.uniform(
            -initial_range, initial_range, size=(n,)).astype(np.float32)
    soa["mf"] = rng.uniform(
        0.0, mf_initial_range, size=(n, mf_dim)).astype(np.float32)
    if expand_dim > 0:
        soa["mf_ex"] = rng.uniform(
            0.0, mf_initial_range, size=(n, expand_dim)).astype(np.float32)
    if "embed_b1p" in soa:
        # fresh features start their beta-power trackers at the decay rates
        # (≙ creation init optimizer.cuh.h:436-441 / adam accessor InitValue)
        soa["embed_b1p"][:] = beta1
        soa["embed_b2p"][:] = beta2
        soa["mf_b1p"][:] = beta1
        soa["mf_b2p"][:] = beta2
    return soa


_MASK64 = (1 << 64) - 1


def _mix64(x: int) -> int:
    """splitmix64 finalizer on a Python int (scalar seeds/column ids)."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
    return x ^ (x >> 31)


def _keyed_hash(keys: np.ndarray, salt: int) -> np.ndarray:
    """Vectorized splitmix64 of (key ^ salt) — uint64 in, uint64 out."""
    z = (keys.astype(np.uint64) ^ np.uint64(salt)) + \
        np.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def keyed_uniform(keys: np.ndarray, seed: int, col: int,
                  lo: float, hi: float) -> np.ndarray:
    """U(lo, hi) as a PURE FUNCTION of (seed, key, col) — float32, one
    value per key.  Used for fresh-row defaults so initialization is
    invariant to pull order, retries, and which worker pulls first."""
    h = _keyed_hash(np.asarray(keys, np.uint64), _mix64(seed * 2654435761
                                                        + col))
    u = (h >> np.uint64(11)).astype(np.float64) * (1.0 / (1 << 53))
    return (lo + (hi - lo) * u).astype(np.float32)


def default_rows_keyed(keys: np.ndarray, mf_dim: int, seed: int,
                       mf_initial_range: float, initial_range: float = 0.0,
                       expand_dim: int = 0, adam: bool = False,
                       beta1: float = 0.9, beta2: float = 0.999,
                       optimizer: str = "",
                       double_stats: bool = False) -> Dict[str, np.ndarray]:
    """:func:`default_rows`, but KEY-DETERMINISTIC: every random init is a
    pure function of (table seed, feasign, column) via a splitmix64 hash
    instead of a shared stateful Generator.  Two pulls of the same unseen
    key — across retries, chunk orders, or workers — produce identical
    rows, which is what makes a chaos-replayed day bit-identical to the
    fault-free run (tests/test_chaos_soak.py) and multi-trainer bases
    consistent without relying on who pulls first."""
    keys = np.asarray(keys, np.uint64)
    n = len(keys)
    soa = empty_soa(n, mf_dim, expand_dim, adam, optimizer, double_stats)
    if initial_range > 0:
        soa["embed_w"] = keyed_uniform(keys, seed, 0,
                                       -initial_range, initial_range)
    soa["mf"] = np.stack(
        [keyed_uniform(keys, seed, 1 + d, 0.0, mf_initial_range)
         for d in range(mf_dim)], axis=1) if mf_dim else \
        np.zeros((n, 0), np.float32)
    if expand_dim > 0:
        soa["mf_ex"] = np.stack(
            [keyed_uniform(keys, seed, 1 + mf_dim + d,
                           0.0, mf_initial_range)
             for d in range(expand_dim)], axis=1)
    if "embed_b1p" in soa:
        soa["embed_b1p"][:] = beta1
        soa["embed_b2p"][:] = beta2
        soa["mf_b1p"][:] = beta1
        soa["mf_b2p"][:] = beta2
    return soa


def select_rows(soa: Dict[str, np.ndarray], idx: np.ndarray
                ) -> Dict[str, np.ndarray]:
    return {k: v[idx] for k, v in soa.items()}


def concat_soa(parts) -> Dict[str, np.ndarray]:
    keys = parts[0].keys()
    return {k: np.concatenate([p[k] for p in parts]) for k in keys}
