"""SSD tier below the DRAM host table.

≙ SSDSparseTable (ps/table/ssd_sparse_table.{h,cc}): cold features live on
disk (the reference embeds RocksDB, ssd_sparse_table.h:81), hot ones stay in
DRAM; a cache threshold decides promotion, Save/SaveCache/Shrink traverse
both tiers.

TPU-first simplification (no RocksDB in the image): an append-only
log-structured store per shard — fixed-width binary rows in a data file plus
an in-memory key→offset index (rebuilt from the file on open).  Point reads
are one pread; pass-batched reads are sorted-offset sequential scans.
Compaction rewrites live rows (≙ rocksdb compaction, triggered by Shrink).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from paddlebox_tpu.ps import feature_value as fv
from paddlebox_tpu.ps import heat
from paddlebox_tpu.utils import lockdep, workpool

_MAGIC = b"PBOXSSD1"


class SSDShard:
    """One shard's log file: rows of (key u64 | field payload f32[width])."""

    def __init__(self, path: str, mf_dim: int):
        self.path = path
        self.mf_dim = mf_dim
        # payload field order mirrors feature_value.HOST_FIELDS
        self.scalar_fields = [f for f, _, s in fv.HOST_FIELDS if s == ()]
        self.width = len(self.scalar_fields) + mf_dim
        self.row_bytes = 8 + 4 * self.width
        self.index: Dict[int, int] = {}   # key → byte offset of latest row
        self._lock = lockdep.lock("ps.ssd_table.SSDShard._lock")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if os.path.exists(path):
            self._rebuild_index()
        else:
            with open(path, "wb") as f:
                f.write(_MAGIC)

    def _rebuild_index(self) -> None:
        with open(self.path, "rb") as f:
            assert f.read(8) == _MAGIC, "corrupt ssd shard file"
            raw = f.read()
        usable = len(raw) // self.row_bytes * self.row_bytes
        rec = np.frombuffer(raw[:usable], self._rec_dtype)
        rb = self.row_bytes
        for i, k in enumerate(rec["k"].tolist()):
            self.index[k] = 8 + i * rb   # later rows win (log order)

    @property
    def _rec_dtype(self) -> np.dtype:
        return np.dtype([("k", "<u8"), ("v", "<f4", (self.width,))])

    def write_rows(self, keys: np.ndarray, soa: Dict[str, np.ndarray]) -> None:
        """One pack + one write per block (≙ rocksdb WriteBatch): the whole
        batch serializes vectorized into a structured record array."""
        n = len(keys)
        if n == 0:
            return
        rec = np.empty((n,), self._rec_dtype)
        rec["k"] = np.asarray(keys, np.uint64)
        for j, f in enumerate(self.scalar_fields):
            rec["v"][:, j] = soa[f]
        rec["v"][:, len(self.scalar_fields):] = soa["mf"]
        # the log file IS the locked resource: append offset + index.
        # PB502: append-only WAL — a torn tail is invisible because the
        # in-memory index only advances after the write returns, and
        # tmp+rename cannot express an append
        # pboxlint: disable-next=PB104,PB502 -- atomic vs compact; WAL
        with self._lock, open(self.path, "ab") as fh:
            off0 = fh.tell()
            fh.write(rec.tobytes())
            rb = self.row_bytes
            idx = self.index
            for i, k in enumerate(np.asarray(keys, np.uint64).tolist()):
                idx[k] = off0 + i * rb

    def read_rows(self, keys: np.ndarray
                  ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        """→ (soa rows aligned to keys, found mask); missing rows zeroed.
        Offsets sort + coalesce into contiguous runs, so a pass's rows
        (written together) come back as a handful of sequential reads."""
        n = len(keys)
        soa = fv.empty_soa(n, self.mf_dim)
        found = np.zeros(n, bool)
        # one lock span for offsets + reads: a concurrent compact() swaps
        # the file and would invalidate a pre-snapshotted offset list
        with self._lock:
            offs = np.array([self.index.get(int(k), -1) for k in keys],
                            np.int64)
            hit = offs >= 0
            if not hit.any():
                return soa, found
            found[:] = hit
            hit_idx = np.nonzero(hit)[0]
            order = np.argsort(offs[hit_idx], kind="stable")
            hit_idx = hit_idx[order]
            sorted_offs = offs[hit_idx]
            rb = self.row_bytes
            # coalesce adjacent rows into runs: one pread per run
            breaks = np.nonzero(np.diff(sorted_offs) != rb)[0] + 1
            starts = np.concatenate([[0], breaks])
            ends = np.concatenate([breaks, [len(sorted_offs)]])
            vals = np.empty((len(sorted_offs), self.width), np.float32)
            # reads must hold the lock: a concurrent compact() swaps
            # pboxlint: disable-next=PB104 -- the file under the offsets
            with open(self.path, "rb") as fh:
                for s, e in zip(starts, ends):
                    fh.seek(sorted_offs[s])
                    raw = fh.read(int((e - s) * rb))
                    rec = np.frombuffer(raw, self._rec_dtype)
                    vals[s:e] = rec["v"]
        for j, f in enumerate(self.scalar_fields):
            soa[f][hit_idx] = vals[:, j]
        soa["mf"][hit_idx] = vals[:, len(self.scalar_fields):]
        return soa, found

    def delete(self, keys: np.ndarray) -> None:
        with self._lock:
            for k in keys:
                self.index.pop(int(k), None)

    def keys(self) -> np.ndarray:
        with self._lock:
            return np.fromiter(self.index.keys(), np.uint64,
                               len(self.index))

    def compact(self) -> None:
        """Rewrite only live rows (≙ rocksdb compaction / Shrink)."""
        with self._lock:
            live = list(self.index.items())
            tmp = self.path + ".compact"
            # compaction swaps the file; writers/readers are excluded
            # pboxlint: disable-next=PB104 -- for the whole rewrite
            with open(self.path, "rb") as src, open(tmp, "wb") as dst:
                dst.write(_MAGIC)
                new_index = {}
                for key, off in live:
                    src.seek(off)
                    row = src.read(self.row_bytes)
                    new_index[key] = dst.tell()
                    dst.write(row)
            os.replace(tmp, self.path)
            self.index = new_index

    def __len__(self):
        return len(self.index)


class SSDTieredTable:
    """DRAM + SSD two-tier wrapper around ShardedHostTable.

    spill(): demote cold rows (score below cache threshold ≙
    `_cache_tk_size` top-k policy, ssd_sparse_table.h:82) to the SSD shards;
    bulk_pull transparently faults them back in.
    """

    def __init__(self, host_table, directory: str):
        self.host = host_table
        self.dir = directory
        self.shards = [
            SSDShard(os.path.join(directory, f"shard-{i:04d}.log"),
                     host_table.mf_dim)
            for i in range(host_table.shard_num)]

    def _shard_ids(self, keys):
        return self.host._shard_ids(keys)

    def spill_topk(self, cache_rows: int) -> int:
        """Keep only the `cache_rows` highest-scoring rows in DRAM, demote
        the rest (≙ the `_cache_tk_size` top-k cache-threshold policy,
        ssd_sparse_table.h:82: the threshold is the k-th score, computed
        over the whole table, not a fixed constant)."""
        scores = []
        for s in self.host._shards:
            with s.lock:   # a concurrent upsert replaces soa field arrays
                scores.append(np.array(self.host._score(s.soa)))
        all_scores = np.concatenate(scores) if scores else np.empty((0,))
        if len(all_scores) <= cache_rows:
            return 0
        if cache_rows <= 0:
            return self.spill(np.inf)   # demote everything
        # threshold = (n - cache_rows)-th smallest → top cache_rows stay
        thr = np.partition(all_scores, len(all_scores) - cache_rows)[
            len(all_scores) - cache_rows]
        return self.spill(thr)

    def spill(self, score_threshold: float) -> int:
        """Demote host rows with score < threshold to SSD.  One task per
        shard on the shared pool (each pairs a host shard with its own
        SSD log — no cross-shard state)."""

        def spill_shard(si: int) -> int:
            shard = self.host._shards[si]
            with shard.lock:
                score = self.host._score(shard.soa)
                cold = score < score_threshold
                if not cold.any():
                    return 0
                keys = shard.keys[cold]
                soa = {f: arr[cold] for f, arr in shard.soa.items()}
                self.shards[si].write_rows(keys, soa)
                shard.filter_keep(~cold)
                return int(cold.sum())

        return sum(workpool.table_pool().map(
            spill_shard, range(self.host.shard_num)))

    def bulk_pull(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        """Host rows, faulting SSD-resident rows back into DRAM
        (≙ LoadSSD2Mem box_wrapper.h:640).  The batched fault-in fans one
        task per shard: every key a task touches lives in that shard, so
        promotion upserts the host shard DIRECTLY (never back through the
        pooled bulk_write — a pool task waiting on nested pool futures
        could starve)."""
        out = self.host.bulk_pull(keys)
        # determine which keys were absent from DRAM → try SSD
        sid = self._shard_ids(keys)

        def fault_in(si: int) -> None:
            sel = np.nonzero(sid == si)[0]
            if not len(sel):
                return
            _, in_dram = self.host._shards[si].lookup(keys[sel])
            miss = sel[~in_dram]
            if not len(miss):
                return
            soa, found = self.shards[si].read_rows(keys[miss])
            hit = miss[found]
            if len(hit):
                if heat.ACTIVE is not None:
                    # SSD→DRAM promotions = the live working-set frontier
                    heat.ACTIVE.observe("fault_in", keys[hit])
                for f in out:
                    out[f][hit] = soa[f][found]
                # promote back to DRAM and drop from SSD
                self.host._shards[si].upsert(
                    keys[hit], {f: out[f][hit] for f in out})
                self.shards[si].delete(keys[hit])

        workpool.table_pool().map(fault_in, range(self.host.shard_num))
        return out

    def total_size(self) -> int:
        return self.host.size() + sum(len(s) for s in self.shards)

    def compact(self) -> None:
        workpool.table_pool().map(lambda s: s.compact(), self.shards)
