"""Device-side sparse optimizers over the pass working set.

≙ heter_ps/optimizer.cuh.h — SparseAdagradOptimizer (:31), SparseAdamOptimizer
(:148), SparseAdamSharedOptimizer (:330) — re-expressed as whole-table
vectorized updates: push accumulators hold the merged per-row gradients
(zero for untouched rows), the update is masked by ``touched = g_show > 0``
so untouched rows are bit-identical no-ops.  All [N]- or [N,D]-shaped
elementwise math → trivially fused by XLA behind the scatter-adds.

Exact semantics reproduced from dy_mf_update_value (optimizer.cuh.h:82-130):
  show  += g_show ; click += g_click
  delta_score += nonclk_coeff*(g_show-g_click) + clk_coeff*g_click
  embed_w: adagrad with lr scaled by sqrt(g0/(g0+g2sum)), grad scaled by
           1/g_show, clip to [min_bound, max_bound], g2sum += mean sq grad
  mf: created lazily when nonclk_coeff*(show-click)+clk_coeff*click crosses
      mf_create_thresholds (:104-112); then same adagrad with mf_* params.
Row 0 (reserved zero/padding row) is never updated.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import SparseSGDConfig


def _adagrad_update(w, g2sum, g, scale, lr, initial_g2sum, min_bound,
                    max_bound, touched, n_dim):
    """≙ update_value_work (optimizer.cuh.h:43-73), vectorized over rows.

    w: [N] or [N,D]; g2sum: [N]; g: same shape as w; scale: [N] (g_show).
    n_dim: the embedx group width — a scalar, or per-row [N] ints for
    dynamic mf dims (≙ CtrDymfAccessor: the mean-square divisor is the
    row's TRUE dim; tail-column grads arrive as exact zeros).
    """
    safe_scale = jnp.where(scale > 0, scale, 1.0)
    ratio = lr * jnp.sqrt(initial_g2sum / (initial_g2sum + g2sum))
    if w.ndim == 2:
        scaled_grad = g / safe_scale[:, None]
        new_w = w + scaled_grad * ratio[:, None]
        add_g2sum = jnp.sum(scaled_grad * scaled_grad, axis=1) / n_dim
    else:
        scaled_grad = g / safe_scale
        new_w = w + scaled_grad * ratio
        add_g2sum = scaled_grad * scaled_grad
    new_w = jnp.clip(new_w, min_bound, max_bound)
    mask = touched if w.ndim == 1 else touched[:, None]
    return (jnp.where(mask, new_w, w),
            jnp.where(touched, g2sum + add_g2sum, g2sum))


def push_touched(ws, acc):
    """THE touched mask: rows this push updates (g_show > 0, reserved row
    0 excluded).  Single source for every rule, the fast path, and the
    ctr_double delta counters — they must agree bit-exactly."""
    row = jnp.arange(ws["show"].shape[0])
    return (acc["g_show"] > 0) & (row != 0)


def _common_stats(ws, acc, cfg):
    """Shared show/click/delta accumulation + touched mask (the common
    prologue of every rule, ≙ optimizer.cuh.h:84-101)."""
    touched = push_touched(ws, acc)
    show = jnp.where(touched, ws["show"] + acc["g_show"], ws["show"])
    click = jnp.where(touched, ws["click"] + acc["g_click"], ws["click"])
    delta = jnp.where(
        touched,
        ws["delta_score"] + cfg.nonclk_coeff * (acc["g_show"] - acc["g_click"])
        + cfg.clk_coeff * acc["g_click"],
        ws["delta_score"])
    return touched, show, click, delta


def _mf_create(ws, cfg, touched, show, click, mf_dim):
    """Lazy mf creation on the post-accumulation show/click
    (optimizer.cuh.h:104-112); rows created this push keep their candidate
    init (the reference returns right after initialization, :113-127).
    mf_dim may be per-row [N] for dynamic dims (created rows get THEIR
    slot's width, ≙ CtrDymfAccessor feature_value.h:42)."""
    score = cfg.nonclk_coeff * (show - click) + cfg.clk_coeff * click
    create = touched & (ws["mf_size"] == 0) & \
        (score >= cfg.mf_create_thresholds)
    mf_size = jnp.where(create, mf_dim, ws["mf_size"])
    mf_touched = touched & (ws["mf_size"] > 0)
    return create, mf_size, mf_touched



def _dym_dims(cfg, slot, mf_dim):
    """Per-row mf dims from the merged slot ids via a fused where-chain
    (NOT a gather — k compares over [N] cost ~nothing; ≙ CtrDymfAccessor
    resolving dim by slot, ctr_dymf_accessor.h).  None when the config has
    no dynamic dims."""
    if not getattr(cfg, "slot_mf_dims", ()):
        return None
    dims = jnp.full(slot.shape, mf_dim, jnp.int32)
    for sid, d in cfg.slot_mf_dims:
        dims = jnp.where(slot == sid, d, dims)
    return dims


def sparse_adagrad_apply(ws: Dict[str, jnp.ndarray],
                         acc: Dict[str, jnp.ndarray],
                         cfg: SparseSGDConfig,
                         dims_row=None) -> Dict[str, jnp.ndarray]:
    """One merged push → working-set update (≙ HashTable::update with
    SparseAdagradOptimizer, hashtable_kernel.cu + optimizer.cuh.h:31)."""
    touched, show, click, delta = _common_stats(ws, acc, cfg)
    slot = jnp.where(touched, acc["slot"], ws["slot"])

    # embed_w (1-dim lr weight); slot-dependent lr (optimizer.cuh.h:52-56)
    lr_embed = jnp.where(slot == cfg.nodeid_slot, cfg.learning_rate,
                         cfg.feature_learning_rate)
    safe_scale = jnp.where(acc["g_show"] > 0, acc["g_show"], 1.0)
    ratio = lr_embed * jnp.sqrt(cfg.initial_g2sum /
                                (cfg.initial_g2sum + ws["embed_g2sum"]))
    sg = acc["g_embed"] / safe_scale
    new_embed = jnp.clip(ws["embed_w"] + sg * ratio, cfg.min_bound,
                         cfg.max_bound)
    embed_w = jnp.where(touched, new_embed, ws["embed_w"])
    embed_g2sum = jnp.where(touched, ws["embed_g2sum"] + sg * sg,
                            ws["embed_g2sum"])

    # lazy mf creation on the *post-accumulation* show/click
    # (optimizer.cuh.h:104-112)
    mf_dim = ws["mf"].shape[1]
    if dims_row is None:
        dims_row = _dym_dims(cfg, slot, mf_dim)
    group_dim = dims_row if dims_row is not None else mf_dim
    create, mf_size, mf_touched = _mf_create(ws, cfg, touched, show, click,
                                             group_dim)
    mf, mf_g2sum = _adagrad_update(
        ws["mf"], ws["mf_g2sum"], acc["g_embedx"], acc["g_show"],
        cfg.mf_learning_rate, cfg.mf_initial_g2sum, cfg.mf_min_bound,
        cfg.mf_max_bound, mf_touched, group_dim)

    out = {"show": show, "click": click, "delta_score": delta, "slot": slot,
           "embed_w": embed_w, "embed_g2sum": embed_g2sum,
           "mf_size": mf_size, "mf_g2sum": mf_g2sum, "mf": mf}
    if "mf_ex" in ws:  # expand (NNCross) embedding trains like mf
        if "g_embedx_ex" in acc:
            mf_ex, mf_ex_g2 = _adagrad_update(
                ws["mf_ex"], ws["mf_ex_g2sum"], acc["g_embedx_ex"],
                acc["g_show"], cfg.mf_learning_rate, cfg.mf_initial_g2sum,
                cfg.mf_min_bound, cfg.mf_max_bound, mf_touched,
                ws["mf_ex"].shape[1])
            out["mf_ex"], out["mf_ex_g2sum"] = mf_ex, mf_ex_g2
        else:
            out["mf_ex"], out["mf_ex_g2sum"] = ws["mf_ex"], ws["mf_ex_g2sum"]
    return out


def _shared_adam_group(w, m1, m2, b1p, b2p, g, scale, lr, beta1, beta2,
                       min_bound, max_bound, touched, n_dim: int,
                       eps: float = 1e-8):
    """≙ SparseAdamSharedOptimizer::update_value_work
    (optimizer.cuh.h:341-386): ONE shared (moment1, moment2, beta-pow) per
    row for the whole group; per-dim new moments derive from the shared old
    moment, updated w per dim, then the stored moments are the per-dim
    means and the beta powers decay once."""
    safe_scale = jnp.where(scale > 0, scale, 1.0)
    ratio = lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
    per_row_dim = getattr(n_dim, "ndim", 0) > 0
    if w.ndim == 2:
        sg = g / safe_scale[:, None]
        new_m1 = beta1 * m1[:, None] + (1 - beta1) * sg
        new_m2 = beta2 * m2[:, None] + (1 - beta2) * sg * sg
        upd = new_m1 / (jnp.sqrt(new_m2) + eps)
        if per_row_dim:
            # dynamic mf dims: only the row's true columns update, and the
            # shared moments are means over those columns alone
            dmask = (jnp.arange(w.shape[1])[None, :]
                     < n_dim[:, None]).astype(w.dtype)
            upd = upd * dmask
            m1_out = jnp.sum(new_m1 * dmask, axis=1) / n_dim
            m2_out = jnp.sum(new_m2 * dmask, axis=1) / n_dim
        else:
            m1_out = jnp.mean(new_m1, axis=1)
            m2_out = jnp.mean(new_m2, axis=1)
        new_w = w + ratio[:, None] * upd
        mask = touched[:, None]
    else:
        sg = g / safe_scale
        new_m1 = beta1 * m1 + (1 - beta1) * sg
        new_m2 = beta2 * m2 + (1 - beta2) * sg * sg
        new_w = w + ratio * (new_m1 / (jnp.sqrt(new_m2) + eps))
        m1_out, m2_out = new_m1, new_m2
        mask = touched
    new_w = jnp.clip(new_w, min_bound, max_bound)
    return (jnp.where(mask, new_w, w),
            jnp.where(touched, m1_out, m1),
            jnp.where(touched, m2_out, m2),
            jnp.where(touched, b1p * beta1, b1p),
            jnp.where(touched, b2p * beta2, b2p))


def sparse_adam_apply(ws: Dict[str, jnp.ndarray], acc: Dict[str, jnp.ndarray],
                      cfg: SparseSGDConfig,
                         dims_row=None) -> Dict[str, jnp.ndarray]:
    """Exact SparseAdamShared (optimizer.cuh.h:330-477): shared per-row
    moments in embed_gsum/embed_g2sum (+ beta powers) for the lr weight and
    mf_gsum/mf_g2sum for the embedx group.  Requires the adam state fields
    (feature_value.ADAM_FIELDS — created when config.sgd.optimizer is
    adam/shared_adam)."""
    touched, show, click, delta = _common_stats(ws, acc, cfg)
    slot = jnp.where(touched, acc["slot"], ws["slot"])

    embed_w, e_m1, e_m2, e_b1, e_b2 = _shared_adam_group(
        ws["embed_w"], ws["embed_gsum"], ws["embed_g2sum"],
        ws["embed_b1p"], ws["embed_b2p"], acc["g_embed"], acc["g_show"],
        cfg.learning_rate, cfg.beta1_decay_rate, cfg.beta2_decay_rate,
        cfg.mf_min_bound, cfg.mf_max_bound, touched, 1, cfg.ada_epsilon)

    mf_dim = ws["mf"].shape[1]
    if dims_row is None:
        dims_row = _dym_dims(cfg, slot, mf_dim)
    group_dim = dims_row if dims_row is not None else mf_dim
    create, mf_size, mf_touched = _mf_create(ws, cfg, touched, show, click,
                                             group_dim)
    mf, m_m1, m_m2, m_b1, m_b2 = _shared_adam_group(
        ws["mf"], ws["mf_gsum"], ws["mf_g2sum"], ws["mf_b1p"], ws["mf_b2p"],
        acc["g_embedx"], acc["g_show"], cfg.mf_learning_rate,
        cfg.beta1_decay_rate, cfg.beta2_decay_rate,
        cfg.mf_min_bound, cfg.mf_max_bound, mf_touched, group_dim,
        cfg.ada_epsilon)
    # rows created this push reset their beta powers to the decay rates
    # (creation init, optimizer.cuh.h:436-441)
    m_b1 = jnp.where(create, cfg.beta1_decay_rate, m_b1)
    m_b2 = jnp.where(create, cfg.beta2_decay_rate, m_b2)

    out = {"show": show, "click": click, "delta_score": delta,
           "slot": slot,
           "embed_w": embed_w, "embed_g2sum": e_m2, "embed_gsum": e_m1,
           "embed_b1p": e_b1, "embed_b2p": e_b2,
           "mf_size": mf_size, "mf_g2sum": m_m2, "mf_gsum": m_m1,
           "mf_b1p": m_b1, "mf_b2p": m_b2, "mf": mf}
    for extra in ("mf_ex", "mf_ex_g2sum"):
        if extra in ws:
            out[extra] = ws[extra]
    return out


def sparse_naive_apply(ws: Dict[str, jnp.ndarray],
                       acc: Dict[str, jnp.ndarray],
                       cfg: SparseSGDConfig,
                         dims_row=None) -> Dict[str, jnp.ndarray]:
    """SparseNaiveSGDRule (sparse_sgd_rule.h:77): plain SGD with bound
    clipping, show-scaled grads; g2sum fields unused."""
    touched, show, click, delta = _common_stats(ws, acc, cfg)
    slot = jnp.where(touched, acc["slot"], ws["slot"])
    safe_scale = jnp.where(acc["g_show"] > 0, acc["g_show"], 1.0)
    embed_w = jnp.where(
        touched,
        jnp.clip(ws["embed_w"] + cfg.learning_rate *
                 acc["g_embed"] / safe_scale, cfg.min_bound, cfg.max_bound),
        ws["embed_w"])
    mf_dim = ws["mf"].shape[1]
    if dims_row is None:
        dims_row = _dym_dims(cfg, slot, mf_dim)
    group_dim = dims_row if dims_row is not None else mf_dim
    create, mf_size, mf_touched = _mf_create(ws, cfg, touched, show, click,
                                             group_dim)
    mf = jnp.where(
        mf_touched[:, None],
        jnp.clip(ws["mf"] + cfg.mf_learning_rate *
                 acc["g_embedx"] / safe_scale[:, None],
                 cfg.mf_min_bound, cfg.mf_max_bound),
        ws["mf"])
    out = {"show": show, "click": click, "delta_score": delta,
           "slot": slot,
           "embed_w": embed_w, "embed_g2sum": ws["embed_g2sum"],
           "mf_size": mf_size, "mf_g2sum": ws["mf_g2sum"], "mf": mf}
    for extra in ("mf_ex", "mf_ex_g2sum"):
        if extra in ws:
            out[extra] = ws[extra]
    return out


def sparse_std_adagrad_apply(ws: Dict[str, jnp.ndarray],
                             acc: Dict[str, jnp.ndarray],
                             cfg: SparseSGDConfig,
                         dims_row=None) -> Dict[str, jnp.ndarray]:
    """StdAdaGradSGDRule (sparse_sgd_rule.h:109, UpdateValueWork in
    sparse_sgd_rule.cc): adagrad with a *per-dimension* g2sum for the embedx
    group (field mf_g2sum_d [N, D]) instead of the shared per-row scalar.
    The 1-dim lr weight is identical to plain adagrad."""
    touched, show, click, delta = _common_stats(ws, acc, cfg)
    slot = jnp.where(touched, acc["slot"], ws["slot"])
    lr_embed = jnp.where(slot == cfg.nodeid_slot, cfg.learning_rate,
                         cfg.feature_learning_rate)
    safe_scale = jnp.where(acc["g_show"] > 0, acc["g_show"], 1.0)
    ratio = lr_embed * jnp.sqrt(cfg.initial_g2sum /
                                (cfg.initial_g2sum + ws["embed_g2sum"]))
    sg = acc["g_embed"] / safe_scale
    embed_w = jnp.where(
        touched,
        jnp.clip(ws["embed_w"] + sg * ratio, cfg.min_bound, cfg.max_bound),
        ws["embed_w"])
    embed_g2sum = jnp.where(touched, ws["embed_g2sum"] + sg * sg,
                            ws["embed_g2sum"])

    mf_dim = ws["mf"].shape[1]
    if dims_row is None:
        dims_row = _dym_dims(cfg, slot, mf_dim)
    group_dim = dims_row if dims_row is not None else mf_dim
    create, mf_size, mf_touched = _mf_create(ws, cfg, touched, show, click,
                                             group_dim)
    sg_mf = acc["g_embedx"] / safe_scale[:, None]             # [N, D]
    ratio_d = cfg.mf_learning_rate * jnp.sqrt(
        cfg.mf_initial_g2sum / (cfg.mf_initial_g2sum + ws["mf_g2sum_d"]))
    mf = jnp.where(
        mf_touched[:, None],
        jnp.clip(ws["mf"] + sg_mf * ratio_d, cfg.mf_min_bound,
                 cfg.mf_max_bound),
        ws["mf"])
    mf_g2sum_d = jnp.where(mf_touched[:, None],
                           ws["mf_g2sum_d"] + sg_mf * sg_mf,
                           ws["mf_g2sum_d"])

    out = {"show": show, "click": click, "delta_score": delta, "slot": slot,
           "embed_w": embed_w, "embed_g2sum": embed_g2sum,
           "mf_size": mf_size, "mf_g2sum": ws["mf_g2sum"],
           "mf_g2sum_d": mf_g2sum_d, "mf": mf}
    for extra in ("mf_ex", "mf_ex_g2sum"):
        if extra in ws:
            out[extra] = ws[extra]
    return out


def sparse_adam_dim_apply(ws: Dict[str, jnp.ndarray],
                          acc: Dict[str, jnp.ndarray],
                          cfg: SparseSGDConfig,
                         dims_row=None) -> Dict[str, jnp.ndarray]:
    """Per-dimension SparseAdam (CPU SparseAdamSGDRule sparse_sgd_rule.h:126
    / GPU SparseAdamOptimizer optimizer.cuh.h:148): embedx keeps full [N, D]
    first/second moments (mf_gsum_d / mf_g2sum_d) with shared scalar
    beta-power trackers; the 1-dim lr weight uses the scalar moment fields
    (identical to the shared rule at dim 1)."""
    eps = cfg.ada_epsilon
    b1, b2 = cfg.beta1_decay_rate, cfg.beta2_decay_rate
    touched, show, click, delta = _common_stats(ws, acc, cfg)
    slot = jnp.where(touched, acc["slot"], ws["slot"])
    safe_scale = jnp.where(acc["g_show"] > 0, acc["g_show"], 1.0)

    embed_w, e_m1, e_m2, e_b1, e_b2 = _shared_adam_group(
        ws["embed_w"], ws["embed_gsum"], ws["embed_g2sum"],
        ws["embed_b1p"], ws["embed_b2p"], acc["g_embed"], acc["g_show"],
        cfg.learning_rate, b1, b2, cfg.mf_min_bound, cfg.mf_max_bound,
        touched, 1, eps)

    mf_dim = ws["mf"].shape[1]
    if dims_row is None:
        dims_row = _dym_dims(cfg, slot, mf_dim)
    group_dim = dims_row if dims_row is not None else mf_dim
    create, mf_size, mf_touched = _mf_create(ws, cfg, touched, show, click,
                                             group_dim)

    sg = acc["g_embedx"] / safe_scale[:, None]                # [N, D]
    new_m1 = b1 * ws["mf_gsum_d"] + (1 - b1) * sg
    new_m2 = b2 * ws["mf_g2sum_d"] + (1 - b2) * sg * sg
    lr_t = cfg.mf_learning_rate * jnp.sqrt(1.0 - ws["mf_b2p"]) \
        / (1.0 - ws["mf_b1p"])
    new_mf = jnp.clip(ws["mf"] + lr_t[:, None]
                      * (new_m1 / (jnp.sqrt(new_m2) + eps)),
                      cfg.mf_min_bound, cfg.mf_max_bound)
    mask = mf_touched[:, None]
    mf = jnp.where(mask, new_mf, ws["mf"])
    mf_gsum_d = jnp.where(mask, new_m1, ws["mf_gsum_d"])
    mf_g2sum_d = jnp.where(mask, new_m2, ws["mf_g2sum_d"])
    mf_b1p = jnp.where(mf_touched, ws["mf_b1p"] * b1, ws["mf_b1p"])
    mf_b2p = jnp.where(mf_touched, ws["mf_b2p"] * b2, ws["mf_b2p"])
    # rows created this push reset their beta powers to the decay rates
    # (creation init, optimizer.cuh.h:260-268)
    mf_b1p = jnp.where(create, b1, mf_b1p)
    mf_b2p = jnp.where(create, b2, mf_b2p)

    out = {"show": show, "click": click, "delta_score": delta,
           "slot": slot,
           "embed_w": embed_w, "embed_gsum": e_m1, "embed_g2sum": e_m2,
           "embed_b1p": e_b1, "embed_b2p": e_b2,
           "mf_size": mf_size, "mf": mf,
           "mf_gsum_d": mf_gsum_d, "mf_g2sum_d": mf_g2sum_d,
           "mf_gsum": ws["mf_gsum"], "mf_g2sum": ws["mf_g2sum"],
           "mf_b1p": mf_b1p, "mf_b2p": mf_b2p}
    for extra in ("mf_ex", "mf_ex_g2sum"):
        if extra in ws:
            out[extra] = ws[extra]
    return out


OPTIMIZERS = {
    "adagrad": sparse_adagrad_apply,
    "shared_adam": sparse_adam_apply,
    "adam": sparse_adam_dim_apply,
    "std_adagrad": sparse_std_adagrad_apply,
    "naive": sparse_naive_apply,
}


def apply_push(ws, acc, cfg: SparseSGDConfig, dims_row=None):
    """dims_row: optional per-row [N] mf dims (dynamic-dim accessor,
    ≙ CtrDymfAccessor) — rules divide/mask by the row's true width.

    Row-count generic: every rule is elementwise over axis 0, so callers
    may pass the full [N] working set (fast/mxu paths) OR a gathered
    [U]-row sub-SoA with matching [U] accumulators (ps/ragged_path.py) —
    the rules run verbatim on the smaller domain and the caller scatters
    the result back.  Nothing here may assume ws spans the whole pass."""
    out = OPTIMIZERS[cfg.optimizer](ws, acc, cfg, dims_row)
    # ctr_double accessor support: exact pass-delta counters ride along —
    # small magnitudes, so the f32 adds are exact even when the absolute
    # show has outgrown f32's integer range; end_pass merges them into the
    # host's f64 stats (≙ DownpourCtrDoubleAccessor's double update)
    if "show_acc" in ws:
        touched = push_touched(ws, acc)
        out["show_acc"] = jnp.where(touched, ws["show_acc"] + acc["g_show"],
                                    ws["show_acc"])
        out["click_acc"] = jnp.where(
            touched, ws["click_acc"] + acc["g_click"], ws["click_acc"])
    return out
