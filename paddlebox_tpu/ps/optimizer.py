"""Device-side sparse optimizers over the pass working set.

≙ heter_ps/optimizer.cuh.h — SparseAdagradOptimizer (:31), SparseAdamOptimizer
(:148), SparseAdamSharedOptimizer (:330) — re-expressed as whole-table
vectorized updates: push accumulators hold the merged per-row gradients
(zero for untouched rows), the update is masked by ``touched = g_show > 0``
so untouched rows are bit-identical no-ops.  All [N]- or [N,D]-shaped
elementwise math → trivially fused by XLA behind the scatter-adds.

Exact semantics reproduced from dy_mf_update_value (optimizer.cuh.h:82-130):
  show  += g_show ; click += g_click
  delta_score += nonclk_coeff*(g_show-g_click) + clk_coeff*g_click
  embed_w: adagrad with lr scaled by sqrt(g0/(g0+g2sum)), grad scaled by
           1/g_show, clip to [min_bound, max_bound], g2sum += mean sq grad
  mf: created lazily when nonclk_coeff*(show-click)+clk_coeff*click crosses
      mf_create_thresholds (:104-112); then same adagrad with mf_* params.
Row 0 (reserved zero/padding row) is never updated.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import SparseSGDConfig


def _adagrad_update(w, g2sum, g, scale, lr, initial_g2sum, min_bound,
                    max_bound, touched, n_dim: int):
    """≙ update_value_work (optimizer.cuh.h:43-73), vectorized over rows.

    w: [N] or [N,D]; g2sum: [N]; g: same shape as w; scale: [N] (g_show).
    """
    safe_scale = jnp.where(scale > 0, scale, 1.0)
    ratio = lr * jnp.sqrt(initial_g2sum / (initial_g2sum + g2sum))
    if w.ndim == 2:
        scaled_grad = g / safe_scale[:, None]
        new_w = w + scaled_grad * ratio[:, None]
        add_g2sum = jnp.sum(scaled_grad * scaled_grad, axis=1) / n_dim
    else:
        scaled_grad = g / safe_scale
        new_w = w + scaled_grad * ratio
        add_g2sum = scaled_grad * scaled_grad
    new_w = jnp.clip(new_w, min_bound, max_bound)
    mask = touched if w.ndim == 1 else touched[:, None]
    return (jnp.where(mask, new_w, w),
            jnp.where(touched, g2sum + add_g2sum, g2sum))


def sparse_adagrad_apply(ws: Dict[str, jnp.ndarray],
                         acc: Dict[str, jnp.ndarray],
                         cfg: SparseSGDConfig) -> Dict[str, jnp.ndarray]:
    """One merged push → working-set update (≙ HashTable::update with
    SparseAdagradOptimizer, hashtable_kernel.cu + optimizer.cuh.h:31)."""
    n = ws["show"].shape[0]
    row = jnp.arange(n)
    touched = (acc["g_show"] > 0) & (row != 0)

    show = jnp.where(touched, ws["show"] + acc["g_show"], ws["show"])
    click = jnp.where(touched, ws["click"] + acc["g_click"], ws["click"])
    delta = jnp.where(
        touched,
        ws["delta_score"] + cfg.nonclk_coeff * (acc["g_show"] - acc["g_click"])
        + cfg.clk_coeff * acc["g_click"],
        ws["delta_score"])
    slot = jnp.where(touched, acc["slot"], ws["slot"])

    # embed_w (1-dim lr weight); slot-dependent lr (optimizer.cuh.h:52-56)
    lr_embed = jnp.where(slot == cfg.nodeid_slot, cfg.learning_rate,
                         cfg.feature_learning_rate)
    safe_scale = jnp.where(acc["g_show"] > 0, acc["g_show"], 1.0)
    ratio = lr_embed * jnp.sqrt(cfg.initial_g2sum /
                                (cfg.initial_g2sum + ws["embed_g2sum"]))
    sg = acc["g_embed"] / safe_scale
    new_embed = jnp.clip(ws["embed_w"] + sg * ratio, cfg.min_bound,
                         cfg.max_bound)
    embed_w = jnp.where(touched, new_embed, ws["embed_w"])
    embed_g2sum = jnp.where(touched, ws["embed_g2sum"] + sg * sg,
                            ws["embed_g2sum"])

    # lazy mf creation on the *post-accumulation* show/click
    # (optimizer.cuh.h:104-112)
    mf_dim = ws["mf"].shape[1]
    score = cfg.nonclk_coeff * (show - click) + cfg.clk_coeff * click
    create = touched & (ws["mf_size"] == 0) & \
        (score >= cfg.mf_create_thresholds)
    mf_size = jnp.where(create, mf_dim, ws["mf_size"])
    # rows train only when already created BEFORE this push (created-now rows
    # keep their candidate init this step, as the reference returns right
    # after initialization, optimizer.cuh.h:113-127)
    mf_touched = touched & (ws["mf_size"] > 0)
    mf, mf_g2sum = _adagrad_update(
        ws["mf"], ws["mf_g2sum"], acc["g_embedx"], acc["g_show"],
        cfg.mf_learning_rate, cfg.mf_initial_g2sum, cfg.mf_min_bound,
        cfg.mf_max_bound, mf_touched, mf_dim)

    out = {"show": show, "click": click, "delta_score": delta, "slot": slot,
           "embed_w": embed_w, "embed_g2sum": embed_g2sum,
           "mf_size": mf_size, "mf_g2sum": mf_g2sum, "mf": mf}
    if "mf_ex" in ws:  # expand (NNCross) embedding trains like mf
        if "g_embedx_ex" in acc:
            mf_ex, mf_ex_g2 = _adagrad_update(
                ws["mf_ex"], ws["mf_ex_g2sum"], acc["g_embedx_ex"],
                acc["g_show"], cfg.mf_learning_rate, cfg.mf_initial_g2sum,
                cfg.mf_min_bound, cfg.mf_max_bound, mf_touched,
                ws["mf_ex"].shape[1])
            out["mf_ex"], out["mf_ex_g2sum"] = mf_ex, mf_ex_g2
        else:
            out["mf_ex"], out["mf_ex_g2sum"] = ws["mf_ex"], ws["mf_ex_g2sum"]
    return out


def _shared_adam_group(w, m1, m2, b1p, b2p, g, scale, lr, beta1, beta2,
                       min_bound, max_bound, touched, n_dim: int):
    """≙ SparseAdamSharedOptimizer::update_value_work
    (optimizer.cuh.h:341-386): ONE shared (moment1, moment2, beta-pow) per
    row for the whole group; per-dim new moments derive from the shared old
    moment, updated w per dim, then the stored moments are the per-dim
    means and the beta powers decay once."""
    eps = 1e-8
    safe_scale = jnp.where(scale > 0, scale, 1.0)
    ratio = lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
    if w.ndim == 2:
        sg = g / safe_scale[:, None]
        new_m1 = beta1 * m1[:, None] + (1 - beta1) * sg
        new_m2 = beta2 * m2[:, None] + (1 - beta2) * sg * sg
        new_w = w + ratio[:, None] * (new_m1 / (jnp.sqrt(new_m2) + eps))
        m1_out = jnp.mean(new_m1, axis=1)
        m2_out = jnp.mean(new_m2, axis=1)
        mask = touched[:, None]
    else:
        sg = g / safe_scale
        new_m1 = beta1 * m1 + (1 - beta1) * sg
        new_m2 = beta2 * m2 + (1 - beta2) * sg * sg
        new_w = w + ratio * (new_m1 / (jnp.sqrt(new_m2) + eps))
        m1_out, m2_out = new_m1, new_m2
        mask = touched
    new_w = jnp.clip(new_w, min_bound, max_bound)
    return (jnp.where(mask, new_w, w),
            jnp.where(touched, m1_out, m1),
            jnp.where(touched, m2_out, m2),
            jnp.where(touched, b1p * beta1, b1p),
            jnp.where(touched, b2p * beta2, b2p))


def sparse_adam_apply(ws: Dict[str, jnp.ndarray], acc: Dict[str, jnp.ndarray],
                      cfg: SparseSGDConfig) -> Dict[str, jnp.ndarray]:
    """Exact SparseAdamShared (optimizer.cuh.h:330-477): shared per-row
    moments in embed_gsum/embed_g2sum (+ beta powers) for the lr weight and
    mf_gsum/mf_g2sum for the embedx group.  Requires the adam state fields
    (feature_value.ADAM_FIELDS — created when config.sgd.optimizer is
    adam/shared_adam)."""
    n = ws["show"].shape[0]
    row = jnp.arange(n)
    touched = (acc["g_show"] > 0) & (row != 0)
    show = jnp.where(touched, ws["show"] + acc["g_show"], ws["show"])
    click = jnp.where(touched, ws["click"] + acc["g_click"], ws["click"])
    delta = jnp.where(
        touched,
        ws["delta_score"] + cfg.nonclk_coeff * (acc["g_show"] - acc["g_click"])
        + cfg.clk_coeff * acc["g_click"],
        ws["delta_score"])

    embed_w, e_m1, e_m2, e_b1, e_b2 = _shared_adam_group(
        ws["embed_w"], ws["embed_gsum"], ws["embed_g2sum"],
        ws["embed_b1p"], ws["embed_b2p"], acc["g_embed"], acc["g_show"],
        cfg.learning_rate, cfg.beta1_decay_rate, cfg.beta2_decay_rate,
        cfg.mf_min_bound, cfg.mf_max_bound, touched, 1)

    mf_dim = ws["mf"].shape[1]
    score = cfg.nonclk_coeff * (show - click) + cfg.clk_coeff * click
    create = touched & (ws["mf_size"] == 0) & \
        (score >= cfg.mf_create_thresholds)
    mf_size = jnp.where(create, mf_dim, ws["mf_size"])
    mf_touched = touched & (ws["mf_size"] > 0)
    mf, m_m1, m_m2, m_b1, m_b2 = _shared_adam_group(
        ws["mf"], ws["mf_gsum"], ws["mf_g2sum"], ws["mf_b1p"], ws["mf_b2p"],
        acc["g_embedx"], acc["g_show"], cfg.mf_learning_rate,
        cfg.beta1_decay_rate, cfg.beta2_decay_rate,
        cfg.mf_min_bound, cfg.mf_max_bound, mf_touched, mf_dim)
    # rows created this push reset their beta powers to the decay rates
    # (creation init, optimizer.cuh.h:436-441)
    m_b1 = jnp.where(create, cfg.beta1_decay_rate, m_b1)
    m_b2 = jnp.where(create, cfg.beta2_decay_rate, m_b2)

    out = {"show": show, "click": click, "delta_score": delta,
           "slot": jnp.where(touched, acc["slot"], ws["slot"]),
           "embed_w": embed_w, "embed_g2sum": e_m2, "embed_gsum": e_m1,
           "embed_b1p": e_b1, "embed_b2p": e_b2,
           "mf_size": mf_size, "mf_g2sum": m_m2, "mf_gsum": m_m1,
           "mf_b1p": m_b1, "mf_b2p": m_b2, "mf": mf}
    for extra in ("mf_ex", "mf_ex_g2sum"):
        if extra in ws:
            out[extra] = ws[extra]
    return out


def sparse_naive_apply(ws: Dict[str, jnp.ndarray],
                       acc: Dict[str, jnp.ndarray],
                       cfg: SparseSGDConfig) -> Dict[str, jnp.ndarray]:
    """SparseNaiveSGDRule (sparse_sgd_rule.h:77): plain SGD with bound
    clipping, show-scaled grads; g2sum fields unused."""
    n = ws["show"].shape[0]
    row = jnp.arange(n)
    touched = (acc["g_show"] > 0) & (row != 0)
    show = jnp.where(touched, ws["show"] + acc["g_show"], ws["show"])
    click = jnp.where(touched, ws["click"] + acc["g_click"], ws["click"])
    delta = jnp.where(
        touched,
        ws["delta_score"] + cfg.nonclk_coeff * (acc["g_show"] - acc["g_click"])
        + cfg.clk_coeff * acc["g_click"],
        ws["delta_score"])
    safe_scale = jnp.where(acc["g_show"] > 0, acc["g_show"], 1.0)
    embed_w = jnp.where(
        touched,
        jnp.clip(ws["embed_w"] + cfg.learning_rate *
                 acc["g_embed"] / safe_scale, cfg.min_bound, cfg.max_bound),
        ws["embed_w"])
    mf_dim = ws["mf"].shape[1]
    score = cfg.nonclk_coeff * (show - click) + cfg.clk_coeff * click
    create = touched & (ws["mf_size"] == 0) & \
        (score >= cfg.mf_create_thresholds)
    mf_size = jnp.where(create, mf_dim, ws["mf_size"])
    mf_touched = touched & (ws["mf_size"] > 0)
    mf = jnp.where(
        mf_touched[:, None],
        jnp.clip(ws["mf"] + cfg.mf_learning_rate *
                 acc["g_embedx"] / safe_scale[:, None],
                 cfg.mf_min_bound, cfg.mf_max_bound),
        ws["mf"])
    out = {"show": show, "click": click, "delta_score": delta,
           "slot": jnp.where(touched, acc["slot"], ws["slot"]),
           "embed_w": embed_w, "embed_g2sum": ws["embed_g2sum"],
           "mf_size": mf_size, "mf_g2sum": ws["mf_g2sum"], "mf": mf}
    for extra in ("mf_ex", "mf_ex_g2sum"):
        if extra in ws:
            out[extra] = ws[extra]
    return out


OPTIMIZERS = {
    "adagrad": sparse_adagrad_apply,
    "shared_adam": sparse_adam_apply,
    "adam": sparse_adam_apply,
    "naive": sparse_naive_apply,
}


def apply_push(ws, acc, cfg: SparseSGDConfig):
    return OPTIMIZERS[cfg.optimizer](ws, acc, cfg)
