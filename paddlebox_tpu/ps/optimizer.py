"""Device-side sparse optimizers over the pass working set.

≙ heter_ps/optimizer.cuh.h — SparseAdagradOptimizer (:31), SparseAdamOptimizer
(:148), SparseAdamSharedOptimizer (:330) — re-expressed as whole-table
vectorized updates: push accumulators hold the merged per-row gradients
(zero for untouched rows), the update is masked by ``touched = g_show > 0``
so untouched rows are bit-identical no-ops.  All [N]- or [N,D]-shaped
elementwise math → trivially fused by XLA behind the scatter-adds.

Exact semantics reproduced from dy_mf_update_value (optimizer.cuh.h:82-130):
  show  += g_show ; click += g_click
  delta_score += nonclk_coeff*(g_show-g_click) + clk_coeff*g_click
  embed_w: adagrad with lr scaled by sqrt(g0/(g0+g2sum)), grad scaled by
           1/g_show, clip to [min_bound, max_bound], g2sum += mean sq grad
  mf: created lazily when nonclk_coeff*(show-click)+clk_coeff*click crosses
      mf_create_thresholds (:104-112); then same adagrad with mf_* params.
Row 0 (reserved zero/padding row) is never updated.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import SparseSGDConfig


def _adagrad_update(w, g2sum, g, scale, lr, initial_g2sum, min_bound,
                    max_bound, touched, n_dim: int):
    """≙ update_value_work (optimizer.cuh.h:43-73), vectorized over rows.

    w: [N] or [N,D]; g2sum: [N]; g: same shape as w; scale: [N] (g_show).
    """
    safe_scale = jnp.where(scale > 0, scale, 1.0)
    ratio = lr * jnp.sqrt(initial_g2sum / (initial_g2sum + g2sum))
    if w.ndim == 2:
        scaled_grad = g / safe_scale[:, None]
        new_w = w + scaled_grad * ratio[:, None]
        add_g2sum = jnp.sum(scaled_grad * scaled_grad, axis=1) / n_dim
    else:
        scaled_grad = g / safe_scale
        new_w = w + scaled_grad * ratio
        add_g2sum = scaled_grad * scaled_grad
    new_w = jnp.clip(new_w, min_bound, max_bound)
    mask = touched if w.ndim == 1 else touched[:, None]
    return (jnp.where(mask, new_w, w),
            jnp.where(touched, g2sum + add_g2sum, g2sum))


def sparse_adagrad_apply(ws: Dict[str, jnp.ndarray],
                         acc: Dict[str, jnp.ndarray],
                         cfg: SparseSGDConfig) -> Dict[str, jnp.ndarray]:
    """One merged push → working-set update (≙ HashTable::update with
    SparseAdagradOptimizer, hashtable_kernel.cu + optimizer.cuh.h:31)."""
    n = ws["show"].shape[0]
    row = jnp.arange(n)
    touched = (acc["g_show"] > 0) & (row != 0)

    show = jnp.where(touched, ws["show"] + acc["g_show"], ws["show"])
    click = jnp.where(touched, ws["click"] + acc["g_click"], ws["click"])
    delta = jnp.where(
        touched,
        ws["delta_score"] + cfg.nonclk_coeff * (acc["g_show"] - acc["g_click"])
        + cfg.clk_coeff * acc["g_click"],
        ws["delta_score"])
    slot = jnp.where(touched, acc["slot"], ws["slot"])

    # embed_w (1-dim lr weight); slot-dependent lr (optimizer.cuh.h:52-56)
    lr_embed = jnp.where(slot == cfg.nodeid_slot, cfg.learning_rate,
                         cfg.feature_learning_rate)
    safe_scale = jnp.where(acc["g_show"] > 0, acc["g_show"], 1.0)
    ratio = lr_embed * jnp.sqrt(cfg.initial_g2sum /
                                (cfg.initial_g2sum + ws["embed_g2sum"]))
    sg = acc["g_embed"] / safe_scale
    new_embed = jnp.clip(ws["embed_w"] + sg * ratio, cfg.min_bound,
                         cfg.max_bound)
    embed_w = jnp.where(touched, new_embed, ws["embed_w"])
    embed_g2sum = jnp.where(touched, ws["embed_g2sum"] + sg * sg,
                            ws["embed_g2sum"])

    # lazy mf creation on the *post-accumulation* show/click
    # (optimizer.cuh.h:104-112)
    mf_dim = ws["mf"].shape[1]
    score = cfg.nonclk_coeff * (show - click) + cfg.clk_coeff * click
    create = touched & (ws["mf_size"] == 0) & \
        (score >= cfg.mf_create_thresholds)
    mf_size = jnp.where(create, mf_dim, ws["mf_size"])
    # rows train only when already created BEFORE this push (created-now rows
    # keep their candidate init this step, as the reference returns right
    # after initialization, optimizer.cuh.h:113-127)
    mf_touched = touched & (ws["mf_size"] > 0)
    mf, mf_g2sum = _adagrad_update(
        ws["mf"], ws["mf_g2sum"], acc["g_embedx"], acc["g_show"],
        cfg.mf_learning_rate, cfg.mf_initial_g2sum, cfg.mf_min_bound,
        cfg.mf_max_bound, mf_touched, mf_dim)

    out = {"show": show, "click": click, "delta_score": delta, "slot": slot,
           "embed_w": embed_w, "embed_g2sum": embed_g2sum,
           "mf_size": mf_size, "mf_g2sum": mf_g2sum, "mf": mf}
    if "mf_ex" in ws:  # expand (NNCross) embedding trains like mf
        if "g_embedx_ex" in acc:
            mf_ex, mf_ex_g2 = _adagrad_update(
                ws["mf_ex"], ws["mf_ex_g2sum"], acc["g_embedx_ex"],
                acc["g_show"], cfg.mf_learning_rate, cfg.mf_initial_g2sum,
                cfg.mf_min_bound, cfg.mf_max_bound, mf_touched,
                ws["mf_ex"].shape[1])
            out["mf_ex"], out["mf_ex_g2sum"] = mf_ex, mf_ex_g2
        else:
            out["mf_ex"], out["mf_ex_g2sum"] = ws["mf_ex"], ws["mf_ex_g2sum"]
    return out


def sparse_adam_apply(ws: Dict[str, jnp.ndarray], acc: Dict[str, jnp.ndarray],
                      cfg: SparseSGDConfig) -> Dict[str, jnp.ndarray]:
    """SparseAdamShared-style update (optimizer.cuh.h:330): shared scalar
    moments per row (beta1/beta2 powers folded into g2sum-like slots).

    Round-1 scope: moments stored in embed_g2sum/mf_g2sum as EMA of squared
    grads (RMSProp-flavored shared-adam); exact beta-power tracking needs two
    extra [N] slots — planned alongside the adam accessor.
    """
    n = ws["show"].shape[0]
    row = jnp.arange(n)
    touched = (acc["g_show"] > 0) & (row != 0)
    show = jnp.where(touched, ws["show"] + acc["g_show"], ws["show"])
    click = jnp.where(touched, ws["click"] + acc["g_click"], ws["click"])
    delta = jnp.where(
        touched,
        ws["delta_score"] + cfg.nonclk_coeff * (acc["g_show"] - acc["g_click"])
        + cfg.clk_coeff * acc["g_click"],
        ws["delta_score"])

    safe_scale = jnp.where(acc["g_show"] > 0, acc["g_show"], 1.0)
    b2 = cfg.beta2_decay_rate
    sg = acc["g_embed"] / safe_scale
    v = jnp.where(touched, b2 * ws["embed_g2sum"] + (1 - b2) * sg * sg,
                  ws["embed_g2sum"])
    new_embed = ws["embed_w"] + cfg.learning_rate * sg / \
        (jnp.sqrt(v) + cfg.ada_epsilon)
    embed_w = jnp.where(touched,
                        jnp.clip(new_embed, cfg.min_bound, cfg.max_bound),
                        ws["embed_w"])

    mf_dim = ws["mf"].shape[1]
    score = cfg.nonclk_coeff * (show - click) + cfg.clk_coeff * click
    create = touched & (ws["mf_size"] == 0) & \
        (score >= cfg.mf_create_thresholds)
    mf_size = jnp.where(create, mf_dim, ws["mf_size"])
    mf_touched = touched & (ws["mf_size"] > 0)
    sgx = acc["g_embedx"] / safe_scale[:, None]
    vx = jnp.where(mf_touched,
                   b2 * ws["mf_g2sum"] + (1 - b2) * jnp.mean(sgx * sgx, 1),
                   ws["mf_g2sum"])
    new_mf = ws["mf"] + cfg.mf_learning_rate * sgx / \
        (jnp.sqrt(vx)[:, None] + cfg.ada_epsilon)
    mf = jnp.where(mf_touched[:, None],
                   jnp.clip(new_mf, cfg.mf_min_bound, cfg.mf_max_bound),
                   ws["mf"])

    out = {"show": show, "click": click, "delta_score": delta,
           "slot": jnp.where(touched, acc["slot"], ws["slot"]),
           "embed_w": embed_w, "embed_g2sum": v,
           "mf_size": mf_size, "mf_g2sum": vx, "mf": mf}
    for extra in ("mf_ex", "mf_ex_g2sum"):
        if extra in ws:
            out[extra] = ws[extra]
    return out


OPTIMIZERS = {
    "adagrad": sparse_adagrad_apply,
    "shared_adam": sparse_adam_apply,
    "adam": sparse_adam_apply,
}


def apply_push(ws, acc, cfg: SparseSGDConfig):
    return OPTIMIZERS[cfg.optimizer](ws, acc, cfg)
