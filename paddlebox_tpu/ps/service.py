"""RPC parameter-server service — the CPU PS tier over the network.

≙ PSCORE's brpc server/client (ps/service/brpc_ps_server.{h,cc},
brpc_ps_client.{h,cc}): push/pull sparse & dense against tables sharded by
``key % shard_num``, plus save/load/shrink/barrier control verbs.  The
TPU rebuild keeps the same wire verbs over length-prefixed TCP frames in
the typed binary codec (ps/wire.py — dtype/shape headers + raw buffers,
like sendrecv.proto's VariableMessage; NO pickle touches network bytes).
Several named tables ride one service (≙ brpc's table_id-routed cmds /
the_one_ps multi-table deployment); trainers on other hosts pull pass
working sets from, and flush them to, this service instead of their local
DRAM (the multi-host BuildPull path, ps_gpu_wrapper.cc:337-419, including
the retry-then-fail discipline :388-419).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Dict, Optional, Tuple, Union

import numpy as np

from paddlebox_tpu.ps import wire
from paddlebox_tpu.ps.host_table import ShardedHostTable

DEFAULT_TABLE = "embedding"


def _send(sock, msg: Dict) -> None:
    payload = wire.encode(msg)
    if len(payload) > wire.MAX_FRAME:
        # non-retryable by construction (RuntimeError, not ConnectionError):
        # the peer would reject it anyway — fail once with the real reason
        raise RuntimeError(
            f"frame of {len(payload)} bytes exceeds wire cap "
            f"{wire.MAX_FRAME} — split the request (fewer keys per call)")
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv(sock) -> Dict:
    head = b""
    while len(head) < 8:
        chunk = sock.recv(8 - len(head))
        if not chunk:
            raise ConnectionError("peer closed")
        head += chunk
    (length,) = struct.unpack("<Q", head)
    if length > wire.MAX_FRAME:
        raise ConnectionError(f"oversized frame ({length} bytes)")
    buf = bytearray()
    while len(buf) < length:
        chunk = sock.recv(min(1 << 20, length - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return wire.decode(bytes(buf))


class PSServer:
    """Hosts named ShardedHostTables + a dense blob store behind TCP verbs:
    pull_sparse/push_sparse/pull_dense/push_dense/save/load/shrink/
    end_day/size/barrier/list_tables (the BrpcPsService cmd surface with
    table-name routing ≙ table_id)."""

    def __init__(self, table: Union[ShardedHostTable,
                                    Dict[str, ShardedHostTable]],
                 host: str = "127.0.0.1", port: int = 0):
        if isinstance(table, dict):
            self.tables: Dict[str, ShardedHostTable] = dict(table)
        else:
            self.tables = {DEFAULT_TABLE: table}
        self.dense: Dict[str, np.ndarray] = {}
        self._dense_lock = threading.Lock()
        # per-table: delta merges need read-modify-write atomicity only
        # against the SAME table; unrelated tables stay concurrent
        self._delta_locks = {name: threading.Lock() for name in self.tables}
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        # keyed cross-worker array allreduce (metric aggregation —
        # ≙ fleet.metrics gloo all_reduce of stat_pos/stat_neg,
        # fleet/metrics/metric.py:144)
        self._reduce_cv = threading.Condition()
        self._reduces: Dict[str, Dict] = {}
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        req = _recv(self.request)
                    except (ConnectionError, OSError, wire.DecodeError):
                        # malformed frame → stream sync is gone; drop the
                        # connection (client reconnects + retries)
                        return
                    try:
                        resp = outer._dispatch(req)
                    except Exception as e:  # noqa: BLE001
                        resp = {"ok": False, "error": repr(e)}
                    _send(self.request, resp)

        self._srv = socketserver.ThreadingTCPServer((host, port), Handler,
                                                    bind_and_activate=True)
        self._srv.daemon_threads = True
        self.addr: Tuple[str, int] = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def table(self) -> ShardedHostTable:
        """Back-compat single-table accessor (the default table)."""
        return self.tables[DEFAULT_TABLE]

    def _table(self, req: Dict) -> ShardedHostTable:
        name = req.get("table") or DEFAULT_TABLE
        t = self.tables.get(name)
        if t is None:
            raise KeyError(f"unknown table {name!r} "
                           f"(have {sorted(self.tables)})")
        return t

    def _dispatch(self, req: Dict) -> Dict:
        cmd = req["cmd"]
        if cmd == "pull_sparse":
            t = self._table(req)
            if req.get("create"):
                # persist fresh-row defaults on first pull so every worker
                # of a multi-trainer job sees identical base values
                # (delta write-back sums against a common base)
                with self._delta_locks[req.get("table") or DEFAULT_TABLE]:
                    rows = t.bulk_pull(req["keys"])
                    t.bulk_write(req["keys"], rows)
            else:
                rows = t.bulk_pull(req["keys"])
            return {"ok": True, "rows": rows}
        if cmd == "push_sparse":
            self._table(req).bulk_write(req["keys"], req["rows"])
            return {"ok": True}
        if cmd == "push_sparse_delta":
            # geo/Hogwild-style merge for concurrent trainers: read-modify-
            # write under a lock so two workers' pass deltas SUM instead of
            # last-wins (≙ multi-node grad aggregation,
            # heter_comm_inl.h:2027 gather_one_node_grad + local merge).
            # Non-summable fields (slot, mf_size, beta powers) arrive as
            # absolute values and overwrite.
            t = self._table(req)
            with self._delta_locks[req.get("table") or DEFAULT_TABLE]:
                cur = t.bulk_pull(req["keys"])
                for f, d in req["rows"].items():
                    if f in cur:
                        cur[f] = cur[f] + d
                for f, v in (req.get("rows_abs") or {}).items():
                    if f in cur:
                        cur[f] = v
                if "unseen_days" in cur:
                    cur["unseen_days"] = np.zeros_like(cur["unseen_days"])
                t.bulk_write(req["keys"], cur)
            return {"ok": True}
        if cmd == "pull_dense":
            with self._dense_lock:
                return {"ok": True, "value": self.dense.get(req["name"])}
        if cmd == "push_dense":
            with self._dense_lock:
                if req.get("add"):
                    cur = self.dense.get(req["name"])
                    self.dense[req["name"]] = (req["value"] if cur is None
                                               else cur + req["value"])
                else:
                    self.dense[req["name"]] = req["value"]
            return {"ok": True}
        if cmd == "save":
            n = self._table(req).save(req["path"], req.get("mode", "all"))
            return {"ok": True, "saved": n}
        if cmd == "load":
            return {"ok": True, "loaded": self._table(req).load(req["path"])}
        if cmd == "shrink":
            return {"ok": True, "removed": self._table(req).shrink()}
        if cmd == "end_day":
            self._table(req).end_day()
            return {"ok": True}
        if cmd == "size":
            return {"ok": True, "size": self._table(req).size()}
        if cmd == "list_tables":
            return {"ok": True,
                    "tables": {n: t.size() for n, t in self.tables.items()}}
        if cmd == "barrier":
            world = req["world"]
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= world:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    try:
                        while self._barrier_gen == gen:
                            if not self._barrier_cv.wait(timeout=60):
                                raise TimeoutError("ps barrier timeout")
                    except TimeoutError:
                        # roll back this waiter's arrival or every later
                        # barrier releases one participant short
                        if self._barrier_gen == gen:
                            self._barrier_count -= 1
                        raise
            return {"ok": True}
        if cmd == "allreduce":
            # keyed sum-allreduce of named arrays across `world` callers:
            # the exact distributed-metrics primitive (global AUC = AUC of
            # the SUMMED pos/neg bucket tables, ≙ fleet.metrics.auc,
            # fleet/metrics/metric.py:144).  Each key is one collective;
            # last reader cleans up, so keys are reusable across passes.
            key, world = req["key"], int(req["world"])
            with self._reduce_cv:
                st = self._reduces.setdefault(
                    key, {"sum": None, "count": 0, "readers": 0,
                          "done": False})
                if st["done"]:
                    raise RuntimeError(
                        f"allreduce key {key!r} still draining readers — "
                        "use a fresh key per collective (e.g. suffix the "
                        "pass id)")
                if st["sum"] is None:
                    st["sum"] = dict(req["arrs"])
                    st["world"] = world
                else:
                    if st["world"] != world:
                        raise ValueError(
                            f"allreduce key {key!r}: participants disagree "
                            f"on world size ({st['world']} vs {world}) — a "
                            "smaller world would complete the collective "
                            "early with a partial sum")
                    if set(st["sum"]) != set(req["arrs"]):
                        raise ValueError(
                            f"allreduce key {key!r}: participants disagree "
                            f"on array names ({sorted(st['sum'])} vs "
                            f"{sorted(req['arrs'])})")
                    st["sum"] = {k: st["sum"][k] + v
                                 for k, v in req["arrs"].items()}
                st["count"] += 1
                if st["count"] >= world:
                    st["done"] = True
                    self._reduce_cv.notify_all()
                else:
                    while not st["done"]:
                        if not self._reduce_cv.wait(timeout=60):
                            if st["done"]:
                                break     # completed as the clock expired
                            # roll back the WHOLE contribution (count AND
                            # the summed arrays) so a retry on the same
                            # key cannot double-count this worker
                            st["count"] -= 1
                            if st["count"] == 0:
                                # last waiter out: drop the entry entirely
                                # so a resized-world retry on the same key
                                # does not trip the world-agreement check
                                del self._reduces[key]
                            else:
                                st["sum"] = {k: st["sum"][k] - v
                                             for k, v in req["arrs"].items()}
                            raise TimeoutError("ps allreduce timeout")
                result = st["sum"]
                st["readers"] += 1
                if st["readers"] >= world:
                    del self._reduces[key]
            return {"ok": True, "arrs": result}
        return {"ok": False, "error": f"unknown cmd {cmd}"}

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class PSClient:
    """≙ BrpcPsClient: sticky connection, bulk verbs, bounded retries
    (3-retry-then-raise ≙ ps_gpu_wrapper.cc:388-419)."""

    def __init__(self, addr: Tuple[str, int], retries: int = 3,
                 retry_sleep: float = 0.5,
                 max_frame: int = wire.MAX_FRAME):
        self.addr = tuple(addr)
        self.retries = retries
        self.retry_sleep = retry_sleep
        # soft frame budget for transparent chunking of the row verbs
        # (≙ brpc_ps_client splitting a bulk request over shard requests):
        # callers never split by hand; a whole-pass pull through
        # RemoteTableAdapter chunks here instead of tripping _send's cap
        self.max_frame = max_frame
        # learned row width PER TABLE (bytes), adapted from observed
        # responses — a narrow table's estimate must never size a wide
        # table's first chunk past the wire cap; guarded by _lock so a
        # client shared across threads cannot interleave updates
        self._row_bytes_est: Dict[str, int] = {}
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _per_chunk(self, bytes_per_row: int) -> int:
        """Keys per frame so each stays well under max_frame (4x headroom
        for codec overhead + field alignment) — the single chunk-budget
        policy for every row verb."""
        return max(1, int(self.max_frame // 4 // max(bytes_per_row, 1)))

    def _chunk_counts(self, n_keys: int, bytes_per_row: int):
        per = self._per_chunk(bytes_per_row)
        out = []
        done = 0
        while done < n_keys:
            c = min(per, n_keys - done)
            out.append((done, c))
            done += c
        return out or [(0, 0)]

    @staticmethod
    def _rows_bytes(rows: Dict[str, np.ndarray]) -> int:
        """Wire bytes per row of a rows dict (key + per-field payload)."""
        tot = 8    # key
        for v in rows.values():
            a = np.asarray(v)
            tot += a.dtype.itemsize * (int(np.prod(a.shape[1:])) or 1)
        return tot

    def _call(self, req: Dict, retry: bool = True,
              timeout: float = 60) -> Dict:
        """retry=False for non-idempotent verbs (delta merges, barrier):
        a resend after an ambiguous failure could apply twice — fail loud
        and let the pass-level recovery decide."""
        last_err = None
        for _ in range(self.retries if retry else 1):
            try:
                with self._lock:
                    if self._sock is None:
                        self._sock = socket.create_connection(self.addr,
                                                              timeout=60)
                    self._sock.settimeout(timeout)
                    _send(self._sock, req)
                    resp = _recv(self._sock)
                if not resp.get("ok"):
                    raise RuntimeError(resp.get("error", "ps error"))
                return resp
            except (ConnectionError, OSError) as e:
                last_err = e
                with self._lock:
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                if not retry:
                    raise ConnectionError(
                        f"ps call {req.get('cmd')!r} failed (not retried — "
                        f"non-idempotent): {last_err}") from e
                time.sleep(self.retry_sleep)
        raise ConnectionError(f"ps unreachable after retries: {last_err}")

    # -- verbs (table=None → the default table) -----------------------------
    def pull_sparse(self, keys: np.ndarray, table: Optional[str] = None,
                    create: bool = False) -> Dict[str, np.ndarray]:
        keys = np.asarray(keys)
        tname = table or DEFAULT_TABLE
        parts = []
        lo = 0
        while True:
            # re-derive the chunk width each round: the first response
            # teaches the real row width, so the rest of THIS call already
            # uses right-sized chunks (not just future calls)
            with self._lock:
                learned = self._row_bytes_est.get(tname)
            per = self._per_chunk(learned if learned is not None else 512)
            if learned is None:
                # unlearned TABLE (this one — another table's learned
                # width says nothing about this schema): a wide schema
                # could overshoot the hard wire cap on a huge first chunk
                # — probe small, then the learned width governs
                per = min(per, 65536)
            c = min(per, len(keys) - lo)
            rows = self._call({"cmd": "pull_sparse",
                               "keys": keys[lo:lo + c],
                               "table": table, "create": create})["rows"]
            if c:   # adapt this table's estimate to its real schema width
                per_row = max(self._rows_bytes(rows), 8)
                with self._lock:
                    self._row_bytes_est[tname] = per_row
            parts.append(rows)
            lo += c
            if lo >= len(keys):
                break
        if len(parts) == 1:
            return parts[0]
        return {f: np.concatenate([p[f] for p in parts])
                for f in parts[0]}

    def push_sparse(self, keys: np.ndarray, rows: Dict[str, np.ndarray],
                    table: Optional[str] = None):
        keys = np.asarray(keys)
        per_row = self._rows_bytes(rows)
        for lo, c in self._chunk_counts(len(keys), per_row):
            self._call({"cmd": "push_sparse", "keys": keys[lo:lo + c],
                        "rows": {f: np.asarray(v)[lo:lo + c]
                                 for f, v in rows.items()},
                        "table": table})

    def push_sparse_delta(self, keys: np.ndarray,
                          rows: Dict[str, np.ndarray],
                          rows_abs: Optional[Dict[str, np.ndarray]] = None,
                          table: Optional[str] = None):
        # chunked like push_sparse; each chunk stays non-idempotent (no
        # retry) — a mid-sequence failure leaves earlier chunks applied,
        # the same partial-application contract a single oversized frame
        # already had at the pass level
        keys = np.asarray(keys)
        rows_abs = rows_abs or {}
        per_row = self._rows_bytes(rows) + self._rows_bytes(rows_abs)
        for lo, c in self._chunk_counts(len(keys), per_row):
            self._call({"cmd": "push_sparse_delta",
                        "keys": keys[lo:lo + c],
                        "rows": {f: np.asarray(v)[lo:lo + c]
                                 for f, v in rows.items()},
                        "rows_abs": {f: np.asarray(v)[lo:lo + c]
                                     for f, v in rows_abs.items()},
                        "table": table}, retry=False)

    def pull_dense(self, name: str) -> Optional[np.ndarray]:
        return self._call({"cmd": "pull_dense", "name": name})["value"]

    def push_dense(self, name: str, value: np.ndarray, add: bool = False):
        self._call({"cmd": "push_dense", "name": name,
                    "value": np.asarray(value), "add": add})

    def save(self, path: str, mode: str = "all",
             table: Optional[str] = None) -> int:
        return self._call({"cmd": "save", "path": path, "mode": mode,
                           "table": table})["saved"]

    def load(self, path: str, table: Optional[str] = None) -> int:
        return self._call({"cmd": "load", "path": path,
                           "table": table})["loaded"]

    def shrink(self, table: Optional[str] = None) -> int:
        return self._call({"cmd": "shrink", "table": table})["removed"]

    def end_day(self, table: Optional[str] = None) -> None:
        self._call({"cmd": "end_day", "table": table})

    def size(self, table: Optional[str] = None) -> int:
        return self._call({"cmd": "size", "table": table})["size"]

    def list_tables(self) -> Dict[str, int]:
        return self._call({"cmd": "list_tables"})["tables"]

    def barrier(self, world: int, timeout: float = 120) -> None:
        # no retry (a resend would double-register this participant) and a
        # client timeout LONGER than the server's wait window, so the
        # server side always resolves (release or rollback) first
        self._call({"cmd": "barrier", "world": world}, retry=False,
                   timeout=timeout)

    def allreduce(self, arrs: Dict[str, np.ndarray], world: int, key: str,
                  timeout: float = 120) -> Dict[str, np.ndarray]:
        """Sum the named arrays across `world` workers (every caller gets
        the same result).  Non-idempotent like barrier — no retry.  Use a
        fresh key per collective (e.g. f"auc-{pass_id}")."""
        out = self._call({"cmd": "allreduce", "key": key, "world": world,
                          "arrs": dict(arrs)}, retry=False, timeout=timeout)
        return out["arrs"]


class RemoteTableAdapter:
    """Duck-types ShardedHostTable's pass-batched surface over a PSClient so
    BoxPSEngine can run against a remote PS
    (engine.table = RemoteTableAdapter(client[, table])).

    delta_mode=True is the multi-trainer contract: bulk_pull snapshots the
    pulled rows (and asks the server to persist fresh-row defaults so every
    worker shares one base), bulk_write sends (new - snapshot) and the
    server SUMS concurrent workers' deltas — pass-granular Hogwild, the
    pass-lifecycle analogue of multi-node sparse grad aggregation
    (heter_comm_inl.h:2027/2131)."""

    def __init__(self, client: PSClient, table: Optional[str] = None,
                 delta_mode: bool = False):
        self.client = client
        self.table = table
        self.delta_mode = delta_mode
        # snapshots keyed by key-set digest: the engine pulls from several
        # sites (pass build, async preload of the NEXT pass, stale-row
        # refresh) and a single slot would be clobbered before write-back
        self._snaps: Dict[bytes, Dict[str, np.ndarray]] = {}
        self._snap_cap = 4

    def bulk_pull(self, keys):
        rows = self.client.pull_sparse(keys, table=self.table,
                                       create=self.delta_mode)
        if self.delta_mode:
            digest = np.asarray(keys, np.uint64).tobytes()
            if len(self._snaps) >= self._snap_cap:
                self._snaps.pop(next(iter(self._snaps)))  # oldest out
            self._snaps[digest] = {f: np.array(v, copy=True)
                                   for f, v in rows.items()}
        return rows

    # fields where "sum of two workers' changes" is wrong — sent absolute
    NON_ACCUMULABLE = ("slot", "mf_size")
    NON_ACCUMULABLE_SUFFIX = ("_b1p", "_b2p")

    def _is_abs(self, f: str) -> bool:
        return (f in self.NON_ACCUMULABLE
                or f.endswith(self.NON_ACCUMULABLE_SUFFIX))

    def patch_snapshot(self, full_keys, sub_keys, rows) -> None:
        """The engine refreshed a SUBSET of an earlier pull (stale-row
        refresh after an async preload): fold the fresh values into the
        full pull's snapshot, or the next delta re-applies whatever peers
        (and this worker's previous pass) already pushed for those rows.
        Also drops the subset pull's own snapshot (it will never be
        written back)."""
        if not self.delta_mode:
            return
        full = np.asarray(full_keys, np.uint64)
        sub = np.asarray(sub_keys, np.uint64)
        self._snaps.pop(sub.tobytes(), None)
        snap = self._snaps.get(full.tobytes())
        if snap is None:
            return
        pos = np.searchsorted(full, sub)   # full pass keys are sorted
        for f, v in rows.items():
            if f in snap:
                snap[f][pos] = v

    def bulk_write(self, keys, soa):
        if not self.delta_mode:
            return self.client.push_sparse(keys, soa, table=self.table)
        digest = np.asarray(keys, np.uint64).tobytes()
        snap = self._snaps.pop(digest, None)
        if snap is None:
            raise RuntimeError(
                "delta_mode write-back without a matching pull snapshot — "
                "the written key set must equal a previously pulled one")
        delta = {f: v - snap[f] for f, v in soa.items()
                 if f in snap and f != "unseen_days"
                 and not self._is_abs(f)}
        rows_abs = {f: np.asarray(v) for f, v in soa.items()
                    if self._is_abs(f)}
        self.client.push_sparse_delta(keys, delta, rows_abs=rows_abs,
                                      table=self.table)

    def end_day(self):
        self.client.end_day(table=self.table)

    def shrink(self):
        return self.client.shrink(table=self.table)

    def save(self, path, mode="all"):
        return self.client.save(path, mode, table=self.table)

    def load(self, path):
        return self.client.load(path, table=self.table)

    def size(self):
        return self.client.size(table=self.table)
