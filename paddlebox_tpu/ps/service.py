"""RPC parameter-server service — the CPU PS tier over the network.

≙ PSCORE's brpc server/client (ps/service/brpc_ps_server.{h,cc},
brpc_ps_client.{h,cc}): push/pull sparse & dense against tables sharded by
``key % shard_num``, plus save/load/shrink/barrier control verbs.  The
TPU rebuild keeps the same wire verbs over length-prefixed TCP messages
(zero-egress pods: no brpc/grpc dependency) — trainers on other hosts pull
pass working sets from, and flush them to, this service instead of their
local DRAM (the multi-host BuildPull path, ps_gpu_wrapper.cc:337-419,
including the retry-then-fail discipline :388-419).
"""

from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from paddlebox_tpu.config import EmbeddingTableConfig
from paddlebox_tpu.ps.host_table import ShardedHostTable


def _send(sock, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv(sock):
    head = b""
    while len(head) < 8:
        chunk = sock.recv(8 - len(head))
        if not chunk:
            raise ConnectionError("peer closed")
        head += chunk
    (length,) = struct.unpack("<Q", head)
    buf = bytearray()
    while len(buf) < length:
        chunk = sock.recv(min(1 << 20, length - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return pickle.loads(bytes(buf))


class PSServer:
    """Hosts one ShardedHostTable + a dense blob store behind TCP verbs:
    pull_sparse/push_sparse/pull_dense/push_dense/save/load/shrink/
    end_day/size/barrier (the BrpcPsService cmd surface)."""

    def __init__(self, table: ShardedHostTable, host: str = "127.0.0.1",
                 port: int = 0):
        self.table = table
        self.dense: Dict[str, np.ndarray] = {}
        self._dense_lock = threading.Lock()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        req = _recv(self.request)
                    except (ConnectionError, OSError):
                        return
                    try:
                        resp = outer._dispatch(req)
                    except Exception as e:  # noqa: BLE001
                        resp = {"ok": False, "error": repr(e)}
                    _send(self.request, resp)

        self._srv = socketserver.ThreadingTCPServer((host, port), Handler,
                                                    bind_and_activate=True)
        self._srv.daemon_threads = True
        self.addr: Tuple[str, int] = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def _dispatch(self, req: Dict) -> Dict:
        cmd = req["cmd"]
        if cmd == "pull_sparse":
            rows = self.table.bulk_pull(req["keys"])
            return {"ok": True, "rows": rows}
        if cmd == "push_sparse":
            self.table.bulk_write(req["keys"], req["rows"])
            return {"ok": True}
        if cmd == "pull_dense":
            with self._dense_lock:
                return {"ok": True, "value": self.dense.get(req["name"])}
        if cmd == "push_dense":
            with self._dense_lock:
                if req.get("add"):
                    cur = self.dense.get(req["name"])
                    self.dense[req["name"]] = (req["value"] if cur is None
                                               else cur + req["value"])
                else:
                    self.dense[req["name"]] = req["value"]
            return {"ok": True}
        if cmd == "save":
            n = self.table.save(req["path"], req.get("mode", "all"))
            return {"ok": True, "saved": n}
        if cmd == "load":
            return {"ok": True, "loaded": self.table.load(req["path"])}
        if cmd == "shrink":
            return {"ok": True, "removed": self.table.shrink()}
        if cmd == "end_day":
            self.table.end_day()
            return {"ok": True}
        if cmd == "size":
            return {"ok": True, "size": self.table.size()}
        if cmd == "barrier":
            world = req["world"]
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= world:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    while self._barrier_gen == gen:
                        if not self._barrier_cv.wait(timeout=60):
                            raise TimeoutError("ps barrier timeout")
            return {"ok": True}
        return {"ok": False, "error": f"unknown cmd {cmd}"}

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class PSClient:
    """≙ BrpcPsClient: sticky connection, bulk verbs, bounded retries
    (3-retry-then-raise ≙ ps_gpu_wrapper.cc:388-419)."""

    def __init__(self, addr: Tuple[str, int], retries: int = 3,
                 retry_sleep: float = 0.5):
        self.addr = tuple(addr)
        self.retries = retries
        self.retry_sleep = retry_sleep
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _call(self, req: Dict) -> Dict:
        last_err = None
        for _ in range(self.retries):
            try:
                with self._lock:
                    if self._sock is None:
                        self._sock = socket.create_connection(self.addr,
                                                              timeout=60)
                    _send(self._sock, req)
                    resp = _recv(self._sock)
                if not resp.get("ok"):
                    raise RuntimeError(resp.get("error", "ps error"))
                return resp
            except (ConnectionError, OSError) as e:
                last_err = e
                with self._lock:
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                time.sleep(self.retry_sleep)
        raise ConnectionError(f"ps unreachable after retries: {last_err}")

    # -- verbs --------------------------------------------------------------
    def pull_sparse(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        return self._call({"cmd": "pull_sparse", "keys": keys})["rows"]

    def push_sparse(self, keys: np.ndarray, rows: Dict[str, np.ndarray]):
        self._call({"cmd": "push_sparse", "keys": keys, "rows": rows})

    def pull_dense(self, name: str) -> Optional[np.ndarray]:
        return self._call({"cmd": "pull_dense", "name": name})["value"]

    def push_dense(self, name: str, value: np.ndarray, add: bool = False):
        self._call({"cmd": "push_dense", "name": name, "value": value,
                    "add": add})

    def save(self, path: str, mode: str = "all") -> int:
        return self._call({"cmd": "save", "path": path, "mode": mode})["saved"]

    def load(self, path: str) -> int:
        return self._call({"cmd": "load", "path": path})["loaded"]

    def shrink(self) -> int:
        return self._call({"cmd": "shrink"})["removed"]

    def end_day(self) -> None:
        self._call({"cmd": "end_day"})

    def size(self) -> int:
        return self._call({"cmd": "size"})["size"]

    def barrier(self, world: int) -> None:
        self._call({"cmd": "barrier", "world": world})


class RemoteTableAdapter:
    """Duck-types ShardedHostTable's pass-batched surface over a PSClient so
    BoxPSEngine can run against a remote PS
    (engine.table = RemoteTableAdapter(client))."""

    def __init__(self, client: PSClient):
        self.client = client

    def bulk_pull(self, keys):
        return self.client.pull_sparse(keys)

    def bulk_write(self, keys, soa):
        self.client.push_sparse(keys, soa)

    def end_day(self):
        self.client.end_day()

    def shrink(self):
        return self.client.shrink()

    def save(self, path, mode="all"):
        return self.client.save(path, mode)

    def load(self, path):
        return self.client.load(path)

    def size(self):
        return self.client.size()
