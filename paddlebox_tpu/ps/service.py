"""RPC parameter-server service — the CPU PS tier over the network.

≙ PSCORE's brpc server/client (ps/service/brpc_ps_server.{h,cc},
brpc_ps_client.{h,cc}): push/pull sparse & dense against tables sharded by
``key % shard_num``, plus save/load/shrink/barrier control verbs.  The
TPU rebuild keeps the same wire verbs over length-prefixed TCP frames in
the typed binary codec (ps/wire.py — dtype/shape headers + raw buffers,
like sendrecv.proto's VariableMessage; NO pickle touches network bytes).
Several named tables ride one service (≙ brpc's table_id-routed cmds /
the_one_ps multi-table deployment); trainers on other hosts pull pass
working sets from, and flush them to, this service instead of their local
DRAM (the multi-host BuildPull path, ps_gpu_wrapper.cc:337-419).

Retry discipline (upgraded from the reference's retry-then-fail,
ps_gpu_wrapper.cc:388-419): EVERY verb is safely retryable.  Idempotent
verbs simply resend; non-idempotent verbs (``push_sparse_delta``,
``push_dense``, ``barrier``, ``allreduce``, ``end_day``) carry a
client-generated request id (``rid`` = client token + monotonic seq,
wire.RID_FIELD) that the server dedups through a bounded per-client
window in :class:`PSServer` — a resend of an applied-but-unacknowledged
mutation returns the cached response instead of applying twice
(exactly-once under ambiguous failure).  The client backs off
exponentially with jitter under an overall deadline budget
(utils/backoff.Backoff).  Fault injection hooks (ps/faults.py) ride the
``connect``/``send``/``recv``/``dispatch`` sites when armed; production
pays one ``is None`` check per site.

Wire-path pipelining (≙ BoxPS hiding PS latency behind the pass
lifecycle — the multi-stream BuildPull / EndPass dump of
ps_gpu_wrapper.cc:337-419,983): a :class:`PSClient` owns a pool of
``FLAGS_ps_streams`` connections and drives multi-chunk row verbs as a
sliding window of up to ``FLAGS_ps_window`` frames in flight across the
pool (:class:`_PipelineRun`).  Responses match their requests by the rid
echo, so chunks complete out of order across streams; a failed stream's
in-flight chunks requeue and resend — through the dedup window — on any
surviving (or reconnected) stream, which is what makes pipelining
compose with the exactly-once protocol and with pinned-rid pass-level
replay.  No client-wide lock ever covers network I/O: ``_lock`` guards
rid allocation and the learned row-width estimate only (lint rule PB104
enforces this package-wide); each pooled stream is exclusively checked
out by one verb/pump for the duration of its frame I/O.

Optional payload quantization (EQuARX-style reduced-precision wire
traffic): ``FLAGS_ps_wire_dtype`` ∈ {f32, f16, i8} encodes the float32
row fields of pull_sparse responses and push_sparse/push_sparse_delta
requests at reduced precision with per-chunk-per-field scales
(wire.quantize_rows, tag 7).  Decode dequantizes transparently, so the
server's table state stays fp32 and a delta-mode RemoteTableAdapter's
pull snapshot is automatically the DEQUANTIZED values — write-back
deltas stay consistent (a zero training delta writes back exact zeros).
``rows_abs`` metadata (slot, mf_size, beta powers) and f64 counters are
never quantized.
"""

from __future__ import annotations

import logging
import os
import queue
import socket
import socketserver
import struct
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from paddlebox_tpu import flags
from paddlebox_tpu.ps import cluster as ps_cluster
from paddlebox_tpu.ps import faults, heat, wire
from paddlebox_tpu.ps.host_table import ShardedHostTable
from paddlebox_tpu.utils import flight, lockdep, trace
from paddlebox_tpu.utils.backoff import Backoff
from paddlebox_tpu.utils.monitor import (stat_add, stat_max, stat_observe,
                                         stat_snapshot)

DEFAULT_TABLE = "embedding"

flags.define_flag(
    "ps_dedup_window", 1024,
    "per-client-token cap of the PS server's rid->response dedup window; "
    "exactly-once holds for resends within the newest <window> requests "
    "of a client (must exceed the chunk count of one logical delta push)")
flags.define_flag(
    "ps_streams", 4,
    "PSClient connection-pool size: multi-chunk row verbs pipeline their "
    "chunks across this many concurrent wire streams; 1 restores "
    "stop-and-wait")
flags.define_flag(
    "ps_window", 8,
    "max chunk frames in flight across a PSClient's stream pool during a "
    "pipelined multi-chunk verb (clamped to >= ps_streams)")
flags.define_flag(
    "ps_wire_dtype", "f32",
    "wire encoding of float32 row fields in pull_sparse/push_sparse/"
    "push_sparse_delta frames: f32 (exact), f16, or i8 (per-chunk-per-"
    "field scales; ~2x/4x fewer wire bytes).  Server table state stays "
    "fp32 — payloads dequantize at decode")
flags.define_flag(
    "obs_slow_verb_ms", 0.0,
    "server-side slow-verb threshold in milliseconds: a dispatch slower "
    "than this logs a warning and bumps ps.server.slow_verb (0 = off).  "
    "Latency histograms (ps.server.<verb>.latency_s.*) record "
    "regardless")
flags.define_flag(
    "ps_snap_cap", 4,
    "RemoteTableAdapter cap on concurrent delta-mode pull snapshots; "
    "raise it when pipelined next-pass preload overlaps several pulls, "
    "or an evicted snapshot fails its later write-back")


def _send(sock, msg: Dict, role: str = "client") -> None:
    payload = wire.encode(msg)
    if role == "client" and "cmd" in msg:
        stat_add(f"ps.wire.{msg['cmd']}.tx_bytes", float(len(payload)))
    if len(payload) > wire.MAX_FRAME:
        # non-retryable by construction (RuntimeError, not ConnectionError):
        # the peer would reject it anyway — fail once with the real reason
        raise RuntimeError(
            f"frame of {len(payload)} bytes exceeds wire cap "
            f"{wire.MAX_FRAME} — split the request (fewer keys per call)")
    frame = struct.pack("<Q", len(payload)) + payload
    if faults.ACTIVE is not None:
        faults.on_send(sock, frame, role)
    sock.sendall(frame)


def _recv(sock, role: str = "client") -> Dict:
    if faults.ACTIVE is not None:
        faults.on_recv(role)
    head = b""
    while len(head) < 8:
        chunk = sock.recv(8 - len(head))
        if not chunk:
            raise ConnectionError("peer closed")
        head += chunk
    (length,) = struct.unpack("<Q", head)
    if length > wire.MAX_FRAME:
        raise ConnectionError(f"oversized frame ({length} bytes)")
    buf = bytearray()
    while len(buf) < length:
        chunk = sock.recv(min(1 << 20, length - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return wire.decode(bytes(buf))


class _DedupWindow:
    """Bounded per-client rid → cached-response window (the server half of
    the exactly-once protocol).

    A rid is ``<token>:<tail>``; entries group by token.  ``begin`` either
    admits a new rid (returns None — caller executes the verb and must
    ``commit`` or ``drop``), returns the cached response of a completed
    duplicate, or blocks while the original is still executing (a blocking
    verb like barrier whose first connection died keeps its handler thread
    registered — the resend must WAIT for that execution, never start a
    second one).

    Bounded-memory contract: at most ``cap`` completed entries per token
    and ``token_cap`` tokens (LRU); in-flight entries are never evicted.
    A resend older than the newest ``cap`` rids of its client re-executes
    — callers keep ``cap`` above the chunk count of one logical verb.
    """

    def __init__(self, cap: int = 1024, token_cap: int = 1024,
                 wait_timeout: float = 120.0):
        self.cap = cap
        self.token_cap = token_cap
        self.wait_timeout = wait_timeout
        self._cv = lockdep.condition("ps.service._DedupWindow._cv")
        # token -> OrderedDict[rid -> [done, resp]]
        self._by_token: "OrderedDict[str, OrderedDict]" = OrderedDict()

    @staticmethod
    def _token(rid: str) -> str:
        return rid.rsplit(":", 1)[0]

    def begin(self, rid: str) -> Optional[Dict]:
        tok = self._token(rid)
        deadline = time.monotonic() + self.wait_timeout
        with self._cv:
            while True:
                entries = self._by_token.get(tok)
                if entries is not None:
                    self._by_token.move_to_end(tok)
                entry = None if entries is None else entries.get(rid)
                if entry is None:
                    if entries is None:
                        entries = self._by_token[tok] = OrderedDict()
                        while len(self._by_token) > self.token_cap:
                            self._by_token.popitem(last=False)
                            stat_add("ps.server.dedup_token_evict")
                    entries[rid] = [False, None]    # in-flight
                    return None
                if entry[0]:                        # done → replay
                    stat_add("ps.server.dedup_hit")
                    flight.record("dedup_hit", rid=rid)
                    return entry[1]
                # original still executing on another handler thread
                stat_add("ps.server.dedup_wait")
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return {"ok": False,
                            "error": f"duplicate of rid {rid} still "
                                     f"executing after {self.wait_timeout}s"}
                self._cv.wait(rem)

    def commit(self, rid: str, resp: Dict) -> None:
        tok = self._token(rid)
        with self._cv:
            entries = self._by_token.get(tok)
            if entries is not None and rid in entries:
                entries[rid][:] = [True, resp]
                # eviction is by COMPLETION order: the entry just
                # committed must outlive older completions, or a tiny cap
                # could evict the response a blocked duplicate is waiting
                # for before it wakes
                entries.move_to_end(rid)
                done = [r for r, e in entries.items() if e[0]]
                evicted = done[:max(0, len(done) - self.cap)]
                for r in evicted:
                    del entries[r]
                    stat_add("ps.server.dedup_evict")
                if evicted:
                    flight.record("dedup_evict", n=len(evicted))
            self._cv.notify_all()

    def drop(self, rid: str) -> None:
        """The verb raised (nothing committed, or it rolled back — e.g. a
        barrier timeout): forget the rid so a resend re-executes."""
        tok = self._token(rid)
        with self._cv:
            entries = self._by_token.get(tok)
            if entries is not None:
                entries.pop(rid, None)
            self._cv.notify_all()

    def export(self) -> List[Tuple[str, bytes]]:
        """Durable snapshot of the window: every DONE entry as
        (rid, wire-encoded response), in token/completion order.  In-flight
        entries are deliberately skipped — their verbs never committed, so
        a resend after restore re-executing them is exactly the correct
        at-most-once-became-zero-times outcome.  Captured alongside the
        table state it describes (the checkpoint's save verb / an
        in-process ``PSServer.dedup_state`` handoff) so a client retrying
        across a server death replays instead of double-applying."""
        out: List[Tuple[str, bytes]] = []
        with self._cv:
            for entries in self._by_token.values():
                for rid, entry in entries.items():
                    if entry[0]:
                        out.append((rid, wire.encode(entry[1])))
        return out

    def restore(self, state: List[Tuple[str, bytes]]) -> int:
        """Full-replace the window from an ``export`` snapshot (restore
        order follows the checkpoint chain, so the HEAD generation's
        snapshot — restored last — wins).  Entries come back marked done;
        eviction bookkeeping restarts fresh."""
        with self._cv:
            self._by_token.clear()
            for rid, raw in state:
                tok = self._token(rid)
                entries = self._by_token.get(tok)
                if entries is None:
                    entries = self._by_token[tok] = OrderedDict()
                entries[rid] = [True, wire.decode(raw)]
            self._cv.notify_all()
            return sum(len(e) for e in self._by_token.values())


# verbs whose rid is an ECHO ONLY (response matching on pipelined
# streams), never a dedup-window entry: they are idempotent, and caching
# e.g. a bulk pull response would blow the window's bounded memory
_RID_ECHO_ONLY = frozenset({"pull_sparse", "pull_dense", "size",
                            "list_tables", "health", "save", "load",
                            "forward", "dump_xbox"})

# sparse data verbs that carry the client's membership epoch ("ep") and
# are epoch/ownership-fenced on a membership-aware server (ps/reshard.py)
_FENCED_VERBS = frozenset({"pull_sparse", "push_sparse",
                           "push_sparse_delta", "forward"})

# cluster control-plane verbs fenced on the epoch alone (they address
# whole shards, so there is no per-key ownership to check): a client
# fanning these out over a STALE map would fork the fleet — end_day
# decays only the shards the old map names, save commits a
# partial-width dump, load restores into a partition nobody routes by.
# Exempt: the reshard driver's own traffic — lifecycle frames whose
# verb is "reshard_cutover" (the cutover crosses the epoch by design
# and its commit is epoch-guarded idempotent) and ingest loads marked
# with RESHARD_FIELD (they target pending members that are not yet in
# any map).
_FENCED_CONTROL_VERBS = frozenset({"end_day", "save", "load", "shrink",
                                   "lifecycle_prepare",
                                   "lifecycle_commit",
                                   "lifecycle_abort"})

# epoch field riding fenced requests (kept short like wire.RID_FIELD)
EPOCH_FIELD = "ep"

# marks a frame as the reshard driver's own data path (ps/reshard.py
# ingest) — skipped by the control-plane fence
RESHARD_FIELD = "rsd"


class FenceError(Exception):
    """Server-side typed epoch/ownership rejection.

    Raised from the fence check that runs AFTER the dedup-window echo
    (an applied duplicate still replays its cached ack) and BEFORE any
    table mutation — so a ``not_owner``/``wrong_epoch`` response PROVES
    the request was not applied, and ``_dispatch_dedup`` dropping the rid
    on the way out means a later re-drive under the new map re-executes
    cleanly.  ``dispatch_one`` renders it as a typed response
    (``{"ok": False, "<kind>": True, "epoch": E, "membership": desc}``)
    the client resolves by refreshing its map and re-driving only the
    affected chunks — never a user-visible error."""

    def __init__(self, kind: str, membership) -> None:
        super().__init__(kind)
        self.kind = kind            # "wrong_epoch" | "not_owner" | "migrating"
        self.membership = membership

    def resp(self) -> Dict:
        out = {"ok": False, self.kind: True,
               "error": f"fence: {self.kind}"}
        if self.membership is not None:
            out["epoch"] = self.membership.epoch
            out["membership"] = self.membership.describe()
        return out


class _FenceRedirect(RuntimeError):
    """Client-side image of a typed fence response (or an aggregate of
    them across a pipelined fan-out).  ``hint`` is the freshest membership
    descriptor the servers offered; ``partial`` maps shard -> the
    per-chunk response list of that shard's pipeline run (``None`` =
    chunk never resolved, ``ok: False`` + typed field = provably not
    applied) so a non-idempotent verb can re-drive exactly the unapplied
    chunks."""

    def __init__(self, kind: str, hint: Optional[Dict] = None,
                 partial: Optional[Dict[int, List[Optional[Dict]]]] = None):
        super().__init__(f"fence redirect: {kind}")
        self.kind = kind
        self.hint = hint
        self.partial = partial


def _fence_kind(resp: Dict) -> Optional[str]:
    """The typed fence marker of a failed response, if any."""
    for kind in ("wrong_epoch", "not_owner", "migrating"):
        if resp.get(kind):
            return kind
    return None

# dedup-window snapshot rides in the checkpointed sparse dir, next to the
# shard files it must stay consistent with
DEDUP_FILE = "DEDUP.bin"


def _dedup_dump(path: str, state: List[Tuple[str, bytes]]) -> None:
    """Write a dedup-window snapshot as length-prefixed records
    ([rid_len][rid utf8][resp_len][wire-encoded resp]...) via tmp+rename —
    a crash mid-write leaves the previous file (or none) intact."""
    final = os.path.join(path, DEDUP_FILE)
    tmp = final + ".tmp"
    with open(tmp, "wb") as fh:
        for rid, raw in state:
            rb = rid.encode("utf-8")
            fh.write(struct.pack("<Q", len(rb)))
            fh.write(rb)
            fh.write(struct.pack("<Q", len(raw)))
            fh.write(raw)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, final)


def _dedup_read(path: str) -> Optional[List[Tuple[str, bytes]]]:
    fname = os.path.join(path, DEDUP_FILE)
    if not os.path.exists(fname):
        return None
    out: List[Tuple[str, bytes]] = []
    with open(fname, "rb") as fh:
        buf = fh.read()
    off = 0
    while off < len(buf):
        (rl,) = struct.unpack_from("<Q", buf, off)
        off += 8
        rid = buf[off:off + rl].decode("utf-8")
        off += rl
        (bl,) = struct.unpack_from("<Q", buf, off)
        off += 8
        out.append((rid, buf[off:off + bl]))
        off += bl
    return out


class _ThreadingTCPServer(socketserver.ThreadingTCPServer):
    # chaos restarts rebind the same port while old sockets drain TIME_WAIT
    allow_reuse_address = True
    daemon_threads = True


class PSServer:
    """Hosts named ShardedHostTables + a dense blob store behind TCP verbs:
    pull_sparse/push_sparse/pull_dense/push_dense/save/load/shrink/
    end_day/size/barrier/allreduce/list_tables/health (the BrpcPsService
    cmd surface with table-name routing ≙ table_id).  Requests carrying a
    rid are routed through the dedup window (exactly-once); ``shutdown``
    drains gracefully (stop accepting, finish in-flight verbs) and
    ``kill`` is the chaos harness's abrupt mid-verb death."""

    def __init__(self, table: Union[ShardedHostTable,
                                    Dict[str, ShardedHostTable]],
                 host: str = "127.0.0.1", port: int = 0,
                 dedup_state: Optional[List[Tuple[str, bytes]]] = None,
                 membership: Optional[Dict] = None, shard: int = 0):
        if isinstance(table, dict):
            self.tables: Dict[str, ShardedHostTable] = dict(table)
        else:
            self.tables = {DEFAULT_TABLE: table}
        heat.maybe_enable_from_flags()
        # elastic membership identity: the fleet map this server believes
        # in (None = legacy single-server, never fences) and its own index
        # in it (-1 = not a member — a retiring source after cutover, or a
        # joining destination before it).  Fenced sparse verbs are checked
        # against these; ps/reshard.py changes them via reshard_cutover.
        self.membership: Optional[ps_cluster.ServerMap] = None  # pboxlint: guarded-by=ps.service.PSServer._reshard_lock
        if membership is not None:
            self.membership = (membership
                               if isinstance(membership, ps_cluster.ServerMap)
                               else ps_cluster.map_from_desc(membership))
        self.shard = int(shard)
        # in-progress migration staging (reshard_begin .. cutover):
        # {"map": new ServerMap, "self_new": index-in-new-map (-1 leaving),
        #  "dirty": {table: set(moved keys written since snapshot)},
        #  "frozen": bool} — guarded by _reshard_lock
        self._reshard_lock = lockdep.lock("ps.service.PSServer._reshard_lock")
        self._reshard: Optional[Dict] = None
        self.dense: Dict[str, np.ndarray] = {}
        self._dense_lock = lockdep.lock("ps.service.PSServer._dense_lock")
        # per-table: delta merges need read-modify-write atomicity only
        # against the SAME table; unrelated tables stay concurrent
        self._delta_locks = {
            name: lockdep.lock("ps.service.PSServer._delta_locks")
            for name in self.tables}
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = lockdep.condition("ps.service.PSServer._barrier_cv")
        # keyed cross-worker array allreduce (metric aggregation —
        # ≙ fleet.metrics gloo all_reduce of stat_pos/stat_neg,
        # fleet/metrics/metric.py:144)
        self._reduce_cv = lockdep.condition("ps.service.PSServer._reduce_cv")
        self._reduces: Dict[str, Dict] = {}
        # 2-phase cluster lifecycle staging (ps/cluster.py): txn id ->
        # {verb, table}.  Observability/abort bookkeeping only — the
        # commit frame is self-contained (carries the verb), so a
        # supervisor restart that loses this dict cannot lose a commit.
        self._staged_lock = lockdep.lock("ps.service.PSServer._staged_lock")
        self._staged: Dict[str, Dict] = {}
        self._dedup = _DedupWindow(cap=flags.get_flags("ps_dedup_window"))
        if dedup_state:
            # restart-durable exactly-once: a supervisor restarting a dead
            # server hands the old instance's window over (the table object
            # survived in-process, so state + window stay consistent)
            n = self._dedup.restore(dedup_state)
            stat_add("ps.server.dedup_restore_entries", n)
            flight.record("dedup_restore", entries=n, source="handoff")
        # lifecycle: _life_lock guards the dead flag (shutdown/kill may
        # race from a fault hook thread); _inflight_cv counts verbs being
        # executed so a graceful drain can wait them out
        self._life_lock = lockdep.lock("ps.service.PSServer._life_lock")
        # role tag surfaced by the health verb: "train" for the mutable
        # PS tier; the read-only serving tier (ps/serving.py) overrides
        # to "serving" so scrapers/routers can tell replicas apart
        self.mode = "train"
        self._dead = False
        self._draining = False
        self._inflight = 0
        self._inflight_cv = lockdep.condition("ps.service.PSServer._inflight_cv")
        self._conns_lock = lockdep.lock("ps.service.PSServer._conns_lock")
        self._conns: set = set()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def setup(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)

            def finish(self):
                with outer._conns_lock:
                    outer._conns.discard(self.request)

            def handle(self):
                # per-connection decode/apply pipeline: this thread recvs
                # AND DECODES frame i+1 while the dispatcher thread applies
                # frame i to the table and sends its response — a pipelined
                # multi-chunk verb overlaps chunk decode with the previous
                # chunk's table apply.  Responses stay strictly in request
                # order (one dispatcher, FIFO queue), which the client's
                # per-stream receiver requires.  The bounded queue (one
                # decoded frame of lookahead) keeps memory flat.
                q: "queue.Queue" = queue.Queue(maxsize=2)
                state = {"open": True}

                def abort_conn():
                    # wake this handler out of a blocked _recv so handle()
                    # returns and socketserver closes the connection (the
                    # client sees the same drop as the old inline path)
                    try:
                        self.request.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass

                def dispatch_one(req) -> bool:
                    """Apply + respond to one decoded request; False ends
                    the connection (same exits as the old inline loop)."""
                    with outer._inflight_cv:
                        outer._inflight += 1
                    try:
                        try:
                            resp = outer._dispatch(req)
                        except faults.InjectedFault:
                            # injected mid-verb death: no response — the
                            # client's retry resolves through the dedup
                            # window (or a clean re-execute)
                            return False
                        except FenceError as e:
                            # typed epoch/ownership rejection (raised
                            # before any mutation; the rid was dropped):
                            # the client refreshes its map off the carried
                            # descriptor and re-drives the chunk
                            resp = e.resp()
                            if wire.RID_FIELD in req:
                                resp[wire.RID_FIELD] = req[wire.RID_FIELD]
                        except Exception as e:  # noqa: BLE001
                            resp = {"ok": False, "error": repr(e)}
                            if wire.RID_FIELD in req:
                                # echo even on failure: a pipelined client
                                # matches the error to the right chunk
                                resp[wire.RID_FIELD] = req[wire.RID_FIELD]
                        try:
                            _send(self.request, resp, role="server")
                        except RuntimeError as e:
                            # oversized RESPONSE: dying silently here would
                            # show the client a bare ConnectionError and it
                            # would re-pull the same oversized chunk — reply
                            # with the real reason instead (non-retryable)
                            err = {"ok": False,
                                   "error": f"response exceeds wire cap — "
                                            f"{e} (pull fewer keys per "
                                            f"call)"}
                            if wire.RID_FIELD in req:
                                err[wire.RID_FIELD] = req[wire.RID_FIELD]
                            try:
                                _send(self.request, err, role="server")
                            except (RuntimeError, ConnectionError, OSError):
                                return False
                        except (ConnectionError, OSError):
                            return False
                    finally:
                        with outer._inflight_cv:
                            outer._inflight -= 1
                            outer._inflight_cv.notify_all()
                    return not outer._draining  # drain: finish-current, out

                def dispatcher():
                    while True:
                        try:
                            req = q.get(timeout=0.25)
                        except queue.Empty:
                            if not state["open"]:
                                return
                            continue
                        if not dispatch_one(req):
                            abort_conn()
                            return

                t = threading.Thread(target=dispatcher, daemon=True)
                t.start()
                try:
                    while True:
                        try:
                            req = _recv(self.request, role="server")
                        except (ConnectionError, OSError, wire.DecodeError):
                            # malformed frame → stream sync is gone; drop
                            # the connection (client reconnects + retries)
                            return
                        while t.is_alive():
                            try:
                                q.put(req, timeout=0.25)
                                break
                            except queue.Full:
                                continue
                        if not t.is_alive():
                            return      # dispatcher ended the connection
                finally:
                    state["open"] = False
                    t.join()

        self._srv = _ThreadingTCPServer((host, port), Handler,
                                        bind_and_activate=True)
        self.addr: Tuple[str, int] = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def table(self) -> ShardedHostTable:
        """Back-compat single-table accessor (the default table)."""
        return self.tables[DEFAULT_TABLE]

    def _table(self, req: Dict) -> ShardedHostTable:
        name = req.get("table") or DEFAULT_TABLE
        t = self.tables.get(name)
        if t is None:
            raise KeyError(f"unknown table {name!r} "
                           f"(have {sorted(self.tables)})")
        return t

    # -- elastic membership fence -------------------------------------------
    def _membership_view(self):
        """Atomic (membership, shard, reshard) snapshot.  The trio is
        co-mutated under ``_reshard_lock`` in ``_adopt_membership``;
        reading the three words bare can observe the new map with the
        old shard index mid-cutover — every multi-word reader goes
        through this instead (PB902)."""
        with self._reshard_lock:
            return self.membership, self.shard, self._reshard

    def _fence(self, req: Dict) -> None:
        """Epoch + ownership check for a fenced sparse verb.  Runs AFTER
        the dedup echo (an applied duplicate replays its cached ack first)
        and BEFORE any mutation, so every rejection proves non-application
        and the dropped rid lets a re-drive under the new map execute
        cleanly.  Ordering: epoch first (a stale client must refresh
        before ownership means anything), then ownership, then the
        migration freeze (writes into a frozen moving range)."""
        m, shard, rs = self._membership_view()
        ep = req.get(EPOCH_FIELD)
        if ep is None:
            # unfenced legacy frame: serve while no reshard ever happened,
            # reject loudly (typed, with the map) once one has — silently
            # applying to a range this server may no longer own would
            # corrupt the moved rows
            if m.epoch <= 0:
                return
            stat_add("ps.server.fence_wrong_epoch")
            raise FenceError("wrong_epoch", m)
        if int(ep) != m.epoch:
            # EITHER direction: a stale client refreshes off the carried
            # descriptor; a client AHEAD of this server backs off bounded
            # (the cutover commit fan-out is still reaching us)
            stat_add("ps.server.fence_wrong_epoch")
            raise FenceError("wrong_epoch", m)
        if shard < 0:
            # epoch matched but this server left the fleet (owned_mask
            # degenerates to all-True at n == 1, so check explicitly)
            stat_add("ps.server.fence_not_owner")
            raise FenceError("not_owner", m)
        keys = req.get("keys")
        if keys is not None and m.n > 1:
            keys = np.asarray(keys, np.uint64)
            if len(keys) and not ps_cluster.owned_mask(
                    keys, shard, m.n).all():
                stat_add("ps.server.fence_not_owner")
                raise FenceError("not_owner", m)
        if rs is not None and rs["frozen"] \
                and req["cmd"] in ("push_sparse", "push_sparse_delta"):
            # cutover freeze: only WRITES touching the moving range block
            # (pulls still serve — the frozen values are consistent);
            # non-moving keys of this shard keep full write rate
            if keys is None:
                keys = np.asarray(req.get("keys", ()), np.uint64)
            if len(keys) and bool(
                    (rs["map"].shard_of_keys(keys)
                     != rs["self_new"]).any()):
                stat_add("ps.server.fence_migrating")
                raise FenceError("migrating", m)

    def _track_dirty(self, req: Dict) -> None:
        """Record moved-range keys a write touched during the un-frozen
        migration window — the delta catch-up set (reshard_delta ships
        exactly these rows)."""
        rs = self._reshard
        if rs is None or rs["frozen"]:
            return
        keys = np.asarray(req["keys"], np.uint64)
        if not len(keys):
            return
        moving = rs["map"].shard_of_keys(keys) != rs["self_new"]
        if moving.any():
            tname = req.get("table") or DEFAULT_TABLE
            with self._reshard_lock:
                if self._reshard is rs:
                    rs["dirty"].setdefault(tname, set()).update(
                        int(k) for k in keys[moving])

    def _moving_keys(self, tname: str, rs: Dict) -> np.ndarray:
        """Keys of ``tname`` resident on this server that the staged new
        map assigns elsewhere — the migration snapshot's row set."""
        t = self.tables[tname]
        return t.select_keys(
            lambda k: rs["map"].shard_of_keys(k) != rs["self_new"])

    def _dump_by_dst(self, tname: str, mk: np.ndarray, rs: Dict,
                     path: str) -> int:
        """Dump ``mk`` rows of ``tname`` split per DESTINATION shard into
        ``<path>/dst-<d:03d>/table-<tname>`` — each destination ingests
        only its own slice, so no server ever holds (or later re-ships)
        rows it will not own.  Missing keys are skipped by save(mode=
        "rows"), making retries after evictions harmless."""
        dst = rs["map"].shard_of_keys(mk)
        t = self.tables[tname]       # server-local dump, not a fleet send
        moved = 0
        for d in np.unique(dst):
            moved += t.save(
                os.path.join(path, f"dst-{int(d):03d}",
                             f"table-{tname}"),
                "rows", keys=np.sort(mk[dst == d]))
        return moved

    def _drop_unowned(self) -> int:
        """Drop every resident row this server does not own under its
        CURRENT membership — the cleanup that makes abandoned-migration
        ingest (rows upserted into a destination before an abort)
        invisible to later snapshots and to the union fleet state."""
        m, shard, _rs = self._membership_view()
        if m is None:
            return 0
        removed = 0
        for t in self.tables.values():
            if shard < 0:
                removed += t.filter_keys(
                    lambda k: np.zeros(len(k), bool))
            elif m.n > 1:
                removed += t.filter_keys(
                    lambda k: ps_cluster.owned_mask(k, shard, m.n))
        return removed

    def _adopt_membership(self, desc: Dict, assign: Optional[Dict]) -> bool:
        """Cutover commit: flip to the new map (idempotent — a duplicate
        or late commit with a non-advancing epoch is a no-op), drop the
        rows this server no longer owns, and unfreeze.  ``assign`` maps
        "host:port" -> new shard index; absent/-1 = leaving the fleet
        (the server keeps answering typed redirects until stopped)."""
        new_map = ps_cluster.map_from_desc(desc)
        me = f"{self.addr[0]}:{self.addr[1]}"
        new_idx = int((assign or {}).get(me, -1))
        with self._reshard_lock:
            cur = self.membership
            if cur is not None and new_map.epoch <= cur.epoch:
                return False
            self.membership = new_map
            self.shard = new_idx
            self._reshard = None
        removed = 0
        for t in self.tables.values():
            if new_idx >= 0:
                removed += t.filter_keys(
                    lambda k: ps_cluster.owned_mask(k, new_idx, new_map.n))
            else:
                # leaving: every row was shipped — drop them all so a
                # late unfenced read cannot see stale values
                removed += t.filter_keys(
                    lambda k: np.zeros(len(k), bool))
        stat_add("ps.server.reshard_rows_dropped", float(removed))
        flight.record("reshard_cutover", epoch=new_map.epoch,
                      shard=new_idx, dropped=removed)
        return True

    def _dispatch(self, req: Dict) -> Dict:
        """Fault hook + exactly-once wrapper around the verb switch.
        Observes every verb's server-side dispatch latency (dedup replays
        included — they are dispatches, just fast ones) and flags
        dispatches past ``FLAGS_obs_slow_verb_ms``."""
        if faults.ACTIVE is not None:
            faults.on_dispatch(req.get("cmd"), self)
        cmd = req.get("cmd")
        t0 = time.monotonic()
        try:
            return self._dispatch_dedup(req)
        finally:
            dt = time.monotonic() - t0
            stat_observe(f"ps.server.{cmd}.latency_s", dt)
            slow_ms = float(flags.get_flags("obs_slow_verb_ms"))
            if slow_ms > 0 and dt * 1000.0 >= slow_ms:
                stat_add("ps.server.slow_verb")
                logging.getLogger(__name__).warning(
                    "slow verb: %s took %.1fms (threshold %gms, rid=%s)",
                    cmd, dt * 1000.0, slow_ms, req.get(wire.RID_FIELD))

    def _dispatch_dedup(self, req: Dict) -> Dict:
        rid = req.get(wire.RID_FIELD)
        if rid is None:
            return self._exec(req)
        if req.get("cmd") in _RID_ECHO_ONLY:
            resp = self._exec(req)
            resp[wire.RID_FIELD] = rid
            return resp
        cached = self._dedup.begin(rid)
        if cached is not None:
            return cached
        try:
            resp = self._exec(req)
        except BaseException:
            # nothing applied, or the verb rolled itself back (barrier/
            # allreduce timeout paths) — a resend must re-execute
            self._dedup.drop(rid)
            raise
        resp[wire.RID_FIELD] = rid      # echo: client rejects stale frames
        self._dedup.commit(rid, resp)
        return resp

    def _exec(self, req: Dict) -> Dict:
        """Span wrapper around the verb switch: a server dispatch span
        opens only when the verb actually EXECUTES (a dedup-window
        replay returns before reaching here — chaos retries never
        duplicate server spans) and parents to the originating client
        span via the wire trace context."""
        if self.membership is not None:
            cmd = req.get("cmd")
            if cmd in _FENCED_VERBS \
                    or (cmd in _FENCED_CONTROL_VERBS
                        and req.get("verb") != "reshard_cutover"
                        and not req.get(RESHARD_FIELD)):
                self._fence(req)
        tr = trace.ACTIVE
        if tr is None:
            return self._exec_verb(req)
        cmd = req.get("cmd")
        with tr.span(f"ps.server.{cmd}",
                     parent=req.get(wire.TRACE_FIELD),
                     rid=req.get(wire.RID_FIELD)):
            return self._exec_verb(req)

    def _exec_verb(self, req: Dict) -> Dict:
        cmd = req["cmd"]
        if cmd == "pull_sparse":
            t = self._table(req)
            if req.get("create"):
                # persist fresh-row defaults on first pull so every worker
                # of a multi-trainer job sees identical base values
                # (delta write-back sums against a common base)
                # The per-table delta lock exists to serialize whole verbs
                # (read-modify-write atomicity for concurrent trainers), so
                # the pool fan-out inside bulk ops is intentionally part of
                # the guarded region — the "blocking" is the work itself.
                with self._delta_locks[req.get("table") or DEFAULT_TABLE]:
                    rows = t.bulk_pull(req["keys"])   # pboxlint: disable=PB602 -- verb-serialization by design
                    t.bulk_write(req["keys"], rows)   # pboxlint: disable=PB602 -- verb-serialization by design
                if self._reshard is not None:
                    # fresh-row defaults persisted mid-migration are
                    # moved-range state too — catch-up must ship them
                    self._track_dirty(req)
            else:
                rows = t.bulk_pull(req["keys"])
            wd = req.get("wire_dtype")
            if wd and wd != "f32":
                # reduced-precision RESPONSE payload; the table keeps the
                # exact fp32 rows written above — only the wire narrows
                rows = wire.quantize_rows(rows, wd, verb="pull_sparse")
            return {"ok": True, "rows": rows}
        if cmd == "push_sparse":
            self._table(req).bulk_write(req["keys"], req["rows"])
            if self._reshard is not None:
                self._track_dirty(req)
            return {"ok": True}
        if cmd == "push_sparse_delta":
            # geo/Hogwild-style merge for concurrent trainers: read-modify-
            # write under a lock so two workers' pass deltas SUM instead of
            # last-wins (≙ multi-node grad aggregation,
            # heter_comm_inl.h:2027 gather_one_node_grad + local merge).
            # Non-summable fields (slot, mf_size, beta powers) arrive as
            # absolute values and overwrite.
            t = self._table(req)
            # Delta-lock + pool fan-out: same deliberate verb-serialization
            # as the pull_sparse create path above.
            with self._delta_locks[req.get("table") or DEFAULT_TABLE]:
                cur = t.bulk_pull(req["keys"])   # pboxlint: disable=PB602 -- verb-serialization by design
                for f, d in req["rows"].items():
                    if f in cur:
                        cur[f] = cur[f] + d
                for f, v in (req.get("rows_abs") or {}).items():
                    if f in cur:
                        cur[f] = v
                if "unseen_days" in cur:
                    cur["unseen_days"] = np.zeros_like(cur["unseen_days"])
                t.bulk_write(req["keys"], cur)   # pboxlint: disable=PB602 -- verb-serialization by design
            if self._reshard is not None:
                self._track_dirty(req)
            return {"ok": True}
        if cmd == "pull_dense":
            with self._dense_lock:
                return {"ok": True, "value": self.dense.get(req["name"])}
        if cmd == "push_dense":
            with self._dense_lock:
                if req.get("add"):
                    cur = self.dense.get(req["name"])
                    self.dense[req["name"]] = (req["value"] if cur is None
                                               else cur + req["value"])
                else:
                    self.dense[req["name"]] = req["value"]
            return {"ok": True}
        if cmd == "save":
            keys = req.get("keys")
            if keys is not None:
                n = self._table(req).save(req["path"],
                                          req.get("mode", "all"), keys=keys)
            else:
                n = self._table(req).save(req["path"],
                                          req.get("mode", "all"))
            # the dedup window is PART of the table's durable state: a
            # checkpoint that restored rows without the rids that wrote
            # them would double-apply a client's post-restart retry
            _dedup_dump(req["path"], self._dedup.export())
            return {"ok": True, "saved": n}
        if cmd == "load":
            owner = req.get("owner")
            if owner is not None:
                # reshard-on-load (ps/cluster.cluster_load): the dump
                # width differs from the fleet width — walk EVERY source
                # subdir, then keep only the keys this shard owns under
                # the current map.  Clear-first preserves replace
                # semantics across the multi-dir upsert.  DEDUP.bin is
                # deliberately NOT restored: rid windows describe a
                # same-width server's history and don't map across
                # widths (clients are fresh after an offline reshard).
                t = self._table(req)
                shard_idx, n_width = int(owner[0]), int(owner[1])
                src = int(req.get("src_shards", 0))
                if req.get("mode", "replace") == "replace":
                    t.filter_keys(lambda k: np.zeros(len(k), bool))
                dirs = ([req["path"]] if src == 0 else
                        [ps_cluster.shard_dir(req["path"], k)
                         for k in range(src)])
                n = 0
                for d in dirs:
                    n += t.load(d, "upsert")
                n -= t.filter_keys(
                    lambda k: ps_cluster.owned_mask(k, shard_idx, n_width))
                stat_add("ps.server.reshard_on_load")
                return {"ok": True, "loaded": n}
            n = self._table(req).load(req["path"],
                                      req.get("mode", "replace"))
            state = _dedup_read(req["path"])
            if state is not None:
                restored = self._dedup.restore(state)
                stat_add("ps.server.dedup_restore_entries", restored)
                flight.record("dedup_restore", entries=restored,
                              source="checkpoint")
            return {"ok": True, "loaded": n}
        if cmd == "shrink":
            return {"ok": True, "removed": self._table(req).shrink()}
        if cmd == "end_day":
            self._table(req).end_day()
            return {"ok": True}
        if cmd == "lifecycle_prepare":
            # phase 1 of the cluster-wide 2-phase lifecycle
            # (ps/cluster.two_phase_lifecycle): validate + stage, execute
            # NOTHING.  The rid entering the dedup window here is what
            # makes a caller retry after partial failure exactly-once.
            verb = req.get("verb")
            if verb not in ps_cluster.LIFECYCLE_VERBS:
                raise ValueError(f"unknown lifecycle verb: {verb!r}")
            if verb == "reshard_cutover":
                # validate the self-contained commit CAN execute: the
                # frame must carry the new membership.  A mid-migration
                # restart that lost _reshard staging still prepares —
                # the commit executes from the frame alone.
                if not req.get("membership"):
                    raise ValueError("reshard_cutover prepare without a "
                                     "membership descriptor")
            else:
                self._table(req)  # raises on unknown table before staging
            with self._staged_lock:
                lockdep.guards(self, "_staged")
                self._staged[req["txn"]] = {"verb": verb,
                                            "table": req.get("table")}
            stat_add("ps.server.lifecycle_prepare")
            return {"ok": True, "staged": True}
        if cmd == "lifecycle_commit":
            # phase 2: self-contained — executes from the frame's own
            # verb/table, so a post-restart server with an empty _staged
            # dict still applies it (the dedup window, which DID survive
            # via handoff/DEDUP.bin, collapses duplicate commits)
            verb = req.get("verb")
            with self._staged_lock:
                self._staged.pop(req.get("txn") or "", None)
            if verb == "end_day":
                self._table(req).end_day()
            elif verb == "reshard_cutover":
                # adopt the frame's membership (idempotent on a duplicate
                # commit — the epoch guard makes it a no-op), drop moved
                # rows, unfreeze.  Self-contained like end_day's commit.
                if faults.ACTIVE is not None:
                    faults.on_lifecycle("reshard_cutover")
                self._adopt_membership(req["membership"],
                                       req.get("assign"))
            else:
                raise ValueError(f"unknown lifecycle verb: {verb!r}")
            stat_add("ps.server.lifecycle_commit")
            return {"ok": True}
        if cmd == "lifecycle_abort":
            with self._staged_lock:
                self._staged.pop(req.get("txn") or "", None)
            if req.get("verb") == "reshard_cutover":
                # abandon the migration: discard staging + dirty set,
                # unfreeze, and drop any rows ingested as a destination —
                # the old membership keeps serving exactly its own key
                # range (rollback is the MANIFEST's old epoch; owned
                # table state never changed)
                with self._reshard_lock:
                    self._reshard = None
                dropped = self._drop_unowned()
                flight.record("reshard_abort", shard=self.shard,
                              dropped=dropped)
            stat_add("ps.server.lifecycle_abort")
            return {"ok": True}
        if cmd == "reshard_begin":
            # migration phase 1 (ps/reshard.py): stage the proposed map,
            # start tracking writes into the moving range, and dump the
            # moving rows of EVERY table as the migration snapshot (the
            # same tmp+rename'd per-shard dump files checkpoints use).
            # Dedup'd + idempotent-by-re-snapshot: a retry (dropped rid,
            # or a restarted driver with a fresh rid) re-stages and
            # re-dumps CURRENT state, so nothing written between
            # attempts can be lost.
            new_map = ps_cluster.map_from_desc(req["membership"])
            self_new = int(req.get("self_new", -1))
            # self-clean first: an abandoned earlier migration may have
            # left ingested rows this server doesn't own — shipping those
            # stale copies would race the true owner's fresh dump
            self._drop_unowned()
            with self._reshard_lock:
                self._reshard = {"map": new_map, "self_new": self_new,
                                 "dirty": {}, "frozen": False}
            rs = self._reshard
            moved = 0
            for name in sorted(self.tables):
                mk = self._moving_keys(name, rs)
                if not len(mk):
                    continue
                moved += self._dump_by_dst(name, mk, rs, req["path"])
            if faults.ACTIVE is not None:
                faults.on_lifecycle("reshard_snapshot")
            stat_add("ps.server.reshard_snapshot_rows", float(moved))
            flight.record("reshard_begin", shard=self.shard,
                          epoch=new_map.epoch, rows=moved)
            return {"ok": True, "moved": moved}
        if cmd == "reshard_delta":
            # migration phase 2: ship the dirty (moved-range rows written
            # since the snapshot) set.  CUMULATIVE — the dirty set is not
            # cleared until cutover, so a kill between the dump and the
            # ack can never lose a row (the retry re-ships it; the
            # destination's keyed upsert is idempotent).  ``freeze=True``
            # is the final round: moving-range WRITES start answering
            # ``migrating``, in-flight verbs drain, then the closing
            # delta is collected — nothing can dirty the range after it.
            rs = self._reshard
            if rs is None:
                raise RuntimeError("reshard_delta without reshard_begin")
            if bool(req.get("freeze")):
                with self._reshard_lock:
                    rs["frozen"] = True
                with self._inflight_cv:
                    deadline = time.monotonic() + 5.0
                    while self._inflight > 1:
                        rem = deadline - time.monotonic()
                        if rem <= 0:
                            break
                        self._inflight_cv.wait(rem)
            with self._reshard_lock:
                dirty = {name: np.sort(np.fromiter(ks, np.uint64,
                                                   count=len(ks)))
                         for name, ks in rs["dirty"].items() if ks}
            moved = 0
            for name, mk in sorted(dirty.items()):
                moved += self._dump_by_dst(name, mk, rs, req["path"])
            if faults.ACTIVE is not None:
                faults.on_lifecycle("reshard_catchup")
            stat_add("ps.server.reshard_delta_rows", float(moved))
            return {"ok": True, "moved": moved,
                    "frozen": bool(rs["frozen"])}
        if cmd == "dump_xbox":
            # server-side xbox dump of THIS shard's rows (cluster fan-out
            # writes per-shard part files the client concatenates); lazy
            # import avoids a ps -> io import at module load
            from paddlebox_tpu.io.checkpoint import dump_table_xbox
            n = dump_table_xbox(
                self._table(req), req["path"],
                base=bool(req.get("base", True)),
                base_threshold=float(req.get("base_threshold", 0.0)),
                delta_threshold=float(req.get("delta_threshold", 0.0)),
                quant_bits=int(req.get("quant_bits", 0)))
            return {"ok": True, "dumped": n}
        if cmd == "size":
            return {"ok": True, "size": self._table(req).size()}
        if cmd == "list_tables":
            return {"ok": True,
                    "tables": {n: t.size() for n, t in self.tables.items()}}
        if cmd == "health":
            # heartbeat: cheap liveness + drain visibility for clients and
            # the launcher's replica watch.  The stats sub-dict makes a
            # remote liveness check double as a metrics pull (verb-latency
            # percentiles included) even with FLAGS_obs_port off
            with self._inflight_cv:
                inflight = self._inflight
            out = {"ok": True, "mode": self.mode,
                   "draining": self._draining,
                   "inflight": inflight,
                   "tables": ",".join(sorted(self.tables)),
                   "stats": {k: float(v)
                             for k, v in stat_snapshot("ps.").items()}}
            hs = heat.summary()
            if hs is not None:
                # skew pull rides the liveness probe (≙ the stats
                # sub-dict) even with the obs exporter off
                out["heat"] = hs
            m, shard, rs = self._membership_view()
            if m is not None:
                # membership authority surface: clients refresh their
                # ServerMap from ANY live member's health (shard 0
                # preferred, falling through dead entries)
                out["membership"] = m.describe()
                out["shard"] = shard
                out["migrating"] = rs is not None
            return out
        if cmd == "barrier":
            world = req["world"]
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= world:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    try:
                        while self._barrier_gen == gen:
                            if not self._barrier_cv.wait(timeout=60):
                                raise TimeoutError("ps barrier timeout")
                    except TimeoutError:
                        # roll back this waiter's arrival or every later
                        # barrier releases one participant short
                        if self._barrier_gen == gen:
                            self._barrier_count -= 1
                        raise
            return {"ok": True}
        if cmd == "allreduce":
            # keyed sum-allreduce of named arrays across `world` callers:
            # the exact distributed-metrics primitive (global AUC = AUC of
            # the SUMMED pos/neg bucket tables, ≙ fleet.metrics.auc,
            # fleet/metrics/metric.py:144).  Each key is one collective;
            # last reader cleans up, so keys are reusable across passes.
            key, world = req["key"], int(req["world"])
            with self._reduce_cv:
                st = self._reduces.setdefault(
                    key, {"sum": None, "count": 0, "readers": 0,
                          "done": False})
                if st["done"]:
                    raise RuntimeError(
                        f"allreduce key {key!r} still draining readers — "
                        "use a fresh key per collective (e.g. suffix the "
                        "pass id)")
                if st["sum"] is None:
                    st["sum"] = dict(req["arrs"])
                    st["world"] = world
                else:
                    if st["world"] != world:
                        raise ValueError(
                            f"allreduce key {key!r}: participants disagree "
                            f"on world size ({st['world']} vs {world}) — a "
                            "smaller world would complete the collective "
                            "early with a partial sum")
                    if set(st["sum"]) != set(req["arrs"]):
                        raise ValueError(
                            f"allreduce key {key!r}: participants disagree "
                            f"on array names ({sorted(st['sum'])} vs "
                            f"{sorted(req['arrs'])})")
                    st["sum"] = {k: st["sum"][k] + v
                                 for k, v in req["arrs"].items()}
                st["count"] += 1
                if st["count"] >= world:
                    st["done"] = True
                    self._reduce_cv.notify_all()
                else:
                    while not st["done"]:
                        if not self._reduce_cv.wait(timeout=60):
                            if st["done"]:
                                break     # completed as the clock expired
                            # roll back the WHOLE contribution (count AND
                            # the summed arrays) so a retry on the same
                            # key cannot double-count this worker
                            st["count"] -= 1
                            if st["count"] == 0:
                                # last waiter out: drop the entry entirely
                                # so a resized-world retry on the same key
                                # does not trip the world-agreement check
                                del self._reduces[key]
                            else:
                                st["sum"] = {k: st["sum"][k] - v
                                             for k, v in req["arrs"].items()}
                            raise TimeoutError("ps allreduce timeout")
                result = st["sum"]
                st["readers"] += 1
                if st["readers"] >= world:
                    del self._reduces[key]
            return {"ok": True, "arrs": result}
        return {"ok": False, "error": f"unknown cmd {cmd}"}

    # -- lifecycle -----------------------------------------------------------
    def _mark_dead(self) -> bool:
        with self._life_lock:
            if self._dead:
                return False
            self._dead = True
            return True

    def shutdown(self, drain_timeout: float = 5.0) -> None:
        """Graceful drain: stop accepting, let in-flight verbs finish
        (bounded by ``drain_timeout``), then close every connection."""
        if not self._mark_dead():
            return
        self._draining = True
        self._srv.shutdown()            # stop accepting new connections
        with self._inflight_cv:
            deadline = time.monotonic() + drain_timeout
            while self._inflight > 0:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                self._inflight_cv.wait(rem)
        self._srv.server_close()
        self._close_conns()

    def kill(self) -> None:
        """Abrupt death (the chaos harness's mid-verb server loss): no
        drain — the listener and every live connection drop on the floor.
        Table state survives in-process; a restart on the same port
        resumes service.  Exactly-once survives the kill two ways: an
        in-process restart hands ``dedup_state()`` to the new instance
        (launch.PSServerSupervisor), and a cross-process restart reloads
        the window from the checkpoint's DEDUP.bin alongside the rows it
        describes.  Injected mid-verb kills additionally fire BEFORE the
        verb applies (crash-before-commit)."""
        if not self._mark_dead():
            return
        self._srv.shutdown()
        self._srv.server_close()
        self._close_conns()

    def dedup_state(self) -> List[Tuple[str, bytes]]:
        """Snapshot the dedup window for an in-process restart handoff:
        ``PSServer(table, port=old_port, dedup_state=old.dedup_state())``.
        Safe to call on a dead server (the window outlives the sockets)."""
        return self._dedup.export()

    def _close_conns(self) -> None:
        with self._conns_lock:
            conns = list(self._conns)
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class _Stream:
    """One pooled PS connection.  A stream is EXCLUSIVELY checked out by a
    single verb (or pipeline pump) for the duration of its frame I/O, so
    no lock is ever held across network calls (lint rule PB104).  Each
    stream is pinned to one cluster shard: it only ever dials (and its
    chunks only ever requeue onto) that shard's address — a key's data
    lives on exactly one server, so cross-shard failover of a chunk
    would be meaningless."""

    __slots__ = ("idx", "shard", "sock", "gen")

    def __init__(self, idx: int, shard: int = 0, gen: int = 0):
        self.idx = idx
        self.shard = shard
        self.sock: Optional[socket.socket] = None
        # pool generation: a membership refresh swaps the whole pool; a
        # stream from a previous generation checking back in is closed
        # and discarded instead of polluting the new pool
        self.gen = gen


class _PipelineRun:
    """Shared state of one pipelined multi-chunk verb: the chunk queue,
    the sliding window, ordered results, and the abort latch.  Stream
    pumps call in from their own threads; every mutation happens under
    the run's condition lock.  A sharded verb runs one _PipelineRun per
    shard under a shared cluster ``budget`` (ps/cluster._InflightBudget)
    capping TOTAL frames in flight across the fan-out; take() probes the
    budget under this run's cv (lock order run._cv -> budget._lock) and
    complete()/requeue() release it with no locks held."""

    def __init__(self, reqs: List[Dict], window: int,
                 retries: Optional[int] = None, budget=None):
        self._cv = lockdep.condition("ps.service._PipelineRun._cv")
        self.budget = budget
        self.n = len(reqs)
        self._queue = deque(enumerate(reqs))
        self.results: List[Optional[Dict]] = [None] * self.n
        self.window = max(1, window)
        self.retries = retries     # per-CHUNK failure budget (None = ∞)
        self._attempts = [0] * self.n
        self.inflight = 0          # chunks claimed but not yet completed
        self.done_count = 0
        self.aborted = False
        self.gave_up = False       # some chunk exhausted its retry budget
        self.error: Optional[BaseException] = None      # non-retryable
        self.net_error: Optional[BaseException] = None  # last wire failure

    def _stopped(self) -> bool:
        return self.aborted or self.gave_up

    def take(self) -> Optional[Tuple[int, Dict]]:
        """Claim the next chunk + a window slot (None when drained or
        stopped).  Time blocked on a full window is the pipeline-stall
        metric: the wire is ahead of the window."""
        job = None
        stalled = 0.0
        with self._cv:
            while not self._stopped() and self._queue:
                if self.inflight < self.window and \
                        (self.budget is None or self.budget.try_acquire()):
                    job = self._queue.popleft()
                    self.inflight += 1
                    stat_max("ps.client.inflight_hwm", float(self.inflight))
                    break
                t0 = time.monotonic()
                self._cv.wait(1.0)
                stalled += time.monotonic() - t0
        if stalled:
            stat_add("ps.client.pipeline_stall_s", stalled)
            # per-chunk wait distribution: a fat p99 here means the wire
            # is persistently ahead of the window (raise FLAGS_ps_window)
            stat_observe("ps.client.pipeline_wait_s", stalled)
        return job

    def complete(self, idx: int, resp: Dict) -> None:
        with self._cv:
            self.results[idx] = resp
            self.inflight -= 1
            self.done_count += 1
            self._cv.notify_all()
        if self.budget is not None:
            self.budget.release()

    def requeue(self, jobs: List[Tuple[int, Dict]]) -> None:
        """A stream died with these chunks unresolved — hand them back for
        any surviving or reconnected stream (the rid ride-along makes the
        resend exactly-once server-side).  Each requeue spends the
        chunk's retry budget, preserving the sequential path's per-chunk
        ``retries`` semantics; an exhausted chunk stops the run."""
        with self._cv:
            for idx, req in reversed(jobs):
                self._queue.appendleft((idx, req))
                self.inflight -= 1
                self._attempts[idx] += 1
                if self.retries is not None \
                        and self._attempts[idx] >= self.retries:
                    self.gave_up = True
            self._cv.notify_all()
        if self.budget is not None:
            self.budget.release(len(jobs))
        if self.gave_up:
            stat_add("ps.client.give_up")
            flight.record("verb_give_up", site="chunk_requeue")

    def abort(self, err: BaseException) -> None:
        """A non-retryable failure (server-side verb error, oversized
        frame): latch the first error and stop handing out chunks."""
        with self._cv:
            if self.error is None:
                self.error = err
            self.aborted = True
            self._cv.notify_all()

    def note_net_error(self, err: BaseException) -> None:
        with self._cv:
            self.net_error = err

    def finished(self) -> bool:
        with self._cv:
            return self.done_count >= self.n

    def has_work(self) -> bool:
        with self._cv:
            return bool(self._queue) and not self._stopped()


class PSClient:
    """≙ BrpcPsClient: a pool of sticky connections, bulk verbs, retries
    with exponential backoff + jitter under a deadline budget; non-
    idempotent verbs ride the rid/dedup exactly-once protocol so EVERY
    verb retries safely (the reference's 3-retry-then-fail,
    ps_gpu_wrapper.cc:388-419, upgraded).  Multi-chunk row verbs pipeline
    their chunks across the pool (module docstring, "Wire-path
    pipelining").  ``retries=None`` means attempt-unbounded
    (deadline-bounded only); ``streams``/``window``/``wire_dtype`` default
    from FLAGS_ps_streams / FLAGS_ps_window / FLAGS_ps_wire_dtype."""

    def __init__(self, addr, retries: Optional[int] = 3,
                 retry_sleep: float = 0.1,
                 max_frame: int = wire.MAX_FRAME,
                 deadline: float = 60.0, backoff_cap: float = 2.0,
                 streams: Optional[int] = None,
                 window: Optional[int] = None,
                 wire_dtype: Optional[str] = None):
        # ``addr`` is one (host, port) — the classic single server — or a
        # list of them: an N-way sharded PS cluster.  The ServerMap owns
        # the deterministic key-hash -> shard placement; every row verb
        # partitions its keys by it and fans per-shard chunk streams out
        # concurrently (ps/cluster.py).  n == 1 is byte- and rid-
        # identical to the pre-cluster client.
        if addr and isinstance(addr[0], (tuple, list)):
            addrs = [tuple(a) for a in addr]
        else:
            addrs = [tuple(addr)]
        self.server_map = ps_cluster.make_server_map(addrs)
        self.n_shards = self.server_map.n
        heat.maybe_enable_from_flags()
        self.addr = self.server_map.addrs[0]   # back-compat (shard 0)
        # elastic-membership plumbing: callbacks fired after a map
        # refresh adopts a newer epoch (the DeviceRowCache invalidates
        # its moved range here), and the pool generation counter
        self._map_listeners: List = []
        self._pool_gen = 0
        # pinned 2-phase lifecycle rid-groups keyed by (verb, table):
        # a caller retry of a partially-failed cluster lifecycle replays
        # the SAME prepare/commit rids (ps/cluster.two_phase_lifecycle)
        self._txn_groups: Dict[Tuple[str, str], str] = {}
        # delta-push rid groups in flight -> (epoch, addrs) at first
        # send; a pinned-group replay that lands after a membership
        # change resolves its chunk fates against THIS fleet (see
        # _resolve_group) instead of re-chunking under the new one
        self._group_fleets: "OrderedDict[str, Tuple[int, List]]" = \
            OrderedDict()
        self.retries = retries
        self.retry_sleep = retry_sleep      # backoff base
        self.backoff_cap = backoff_cap
        self.deadline = deadline            # per-call retry budget (s)
        # soft frame budget for transparent chunking of the row verbs
        # (≙ brpc_ps_client splitting a bulk request over shard requests):
        # callers never split by hand; a whole-pass pull through
        # RemoteTableAdapter chunks here instead of tripping _send's cap
        self.max_frame = max_frame
        self.streams = max(1, int(flags.get_flags("ps_streams")
                                  if streams is None else streams))
        self.window = max(self.streams,
                          int(flags.get_flags("ps_window")
                              if window is None else window))
        self.wire_dtype = str(flags.get_flags("ps_wire_dtype")
                              if wire_dtype is None else wire_dtype)
        if self.wire_dtype not in wire.WIRE_DTYPES:
            raise ValueError(f"ps_wire_dtype must be one of "
                             f"{wire.WIRE_DTYPES}, got {self.wire_dtype!r}")
        # learned row width PER TABLE (bytes), learned once per pull call
        # from its first response — a narrow table's estimate must never
        # size a wide table's first chunk past the wire cap.  _lock guards
        # THIS dict and rid allocation only — never network I/O (PB104)
        self._row_bytes_est: Dict[str, int] = {}
        self._lock = lockdep.lock("ps.service.PSClient._lock")
        # connection pool: ``streams`` connections PER SHARD, checked out
        # exclusively via one _pool_cv; a stream is pinned to its shard
        self._pool = [_Stream(i, shard=s, gen=self._pool_gen)
                      for s in range(self.n_shards)
                      for i in range(self.streams)]
        self._free: List[List[_Stream]] = [
            [st for st in self._pool if st.shard == s]
            for s in range(self.n_shards)]
        self._pool_cv = lockdep.condition("ps.service.PSClient._pool_cv")
        # rid = token ":" seq — unique per client instance, monotonic
        self._token = f"c{os.getpid():x}-{os.urandom(4).hex()}"
        self._seq = 0

    def _next_rid(self) -> str:
        with self._lock:
            self._seq += 1
            return f"{self._token}:{self._seq}"

    def new_rid_group(self) -> str:
        """A stable id for a multi-chunk logical mutation: chunk i is sent
        as rid ``<group>.<i>``, so a CALLER-level resend of the whole
        logical verb (pass-level recovery) reuses the same rids and
        already-applied chunks dedup server-side."""
        return self._next_rid()

    def _per_chunk(self, bytes_per_row: int) -> int:
        """Keys per frame so each stays well under max_frame (4x headroom
        for codec overhead + field alignment) — the single chunk-budget
        policy for every row verb."""
        return max(1, int(self.max_frame // 4 // max(bytes_per_row, 1)))

    @staticmethod
    def _chunk_spans(n_keys: int, per: int):
        out = []
        done = 0
        while done < n_keys:
            c = min(per, n_keys - done)
            out.append((done, c))
            done += c
        return out or [(0, 0)]

    def _chunk_counts(self, n_keys: int, bytes_per_row: int):
        return self._chunk_spans(n_keys, self._per_chunk(bytes_per_row))

    @staticmethod
    def _rows_bytes(rows: Dict[str, np.ndarray]) -> int:
        """Wire bytes per row of a rows dict (key + per-field payload)."""
        tot = 8    # key
        for v in rows.values():
            a = np.asarray(v)
            tot += a.dtype.itemsize * (int(np.prod(a.shape[1:])) or 1)
        return tot

    def _quant_rows(self, rows: Dict[str, np.ndarray],
                    verb: str) -> Dict:
        """Encode a push payload for the wire under FLAGS_ps_wire_dtype
        (counted passthrough for f32)."""
        return wire.quantize_rows(rows, self.wire_dtype, verb=verb)

    # -- stream pool ---------------------------------------------------------
    def _checkout(self, shard: int = 0) -> _Stream:
        with self._pool_cv:
            while True:
                if shard >= len(self._free):
                    # the map shrank under this verb's feet — surface as
                    # a fence redirect: the verb re-partitions + re-drives
                    raise _FenceRedirect("wrong_epoch")
                if self._free[shard]:
                    return self._free[shard].pop()
                self._pool_cv.wait()

    def _checkout_upto(self, n: int, shard: int = 0) -> List[_Stream]:
        """Up to ``n`` free streams of one shard — at least one (blocks
        for the first); a concurrent verb holding part of the pool never
        deadlocks a pipelined call, it just narrows it."""
        with self._pool_cv:
            while True:
                if shard >= len(self._free):
                    raise _FenceRedirect("wrong_epoch")
                if self._free[shard]:
                    break
                self._pool_cv.wait()
            take = min(n, len(self._free[shard]))
            out = [self._free[shard].pop() for _ in range(take)]
            return out

    def _checkin(self, *streams: _Stream) -> None:
        with self._pool_cv:
            for st in streams:
                if st.gen != self._pool_gen:
                    # stream from a pre-refresh pool: retire it
                    self._close_stream(st)
                    continue
                self._free[st.shard].append(st)
            self._pool_cv.notify_all()

    def _connect(self, stream: _Stream, timeout: float,
                 bo: Backoff) -> None:
        """Dial one pooled stream to ITS shard's address; the connect
        timeout honors the per-call timeout and never outlives the
        remaining retry budget."""
        if faults.ACTIVE is not None:
            faults.on_connect("client")
        rem = bo.remaining()
        cto = timeout if rem is None else max(min(timeout, rem), 0.001)
        addrs = self.server_map.addrs
        if stream.shard >= len(addrs):
            # a concurrent map refresh shrank the fleet — the normal
            # requeue/retry path resolves the chunks on the new map
            raise ConnectionError("stale stream shard after map refresh")
        stream.sock = socket.create_connection(
            addrs[stream.shard], timeout=cto)

    @staticmethod
    def _close_stream(stream: _Stream) -> None:
        if stream.sock is not None:
            try:
                stream.sock.close()
            except OSError:
                pass
            stream.sock = None

    def close(self) -> None:
        """Close every pooled connection (idle clients only — in-flight
        verbs own their streams)."""
        with self._pool_cv:
            for s in self._pool:
                self._close_stream(s)

    # -- elastic membership (epoch-fenced routing) --------------------------
    def on_map_change(self, cb) -> None:
        """Register ``cb(new_map)`` to fire after a refresh adopts a
        newer membership epoch — the DeviceRowCache drops exactly its
        moved range here (device_cache.update_server_map)."""
        self._map_listeners.append(cb)

    def _adopt_map(self, new_map: ps_cluster.ServerMap) -> bool:
        """Swap to a newer membership map: rebuild the stream pool (old
        streams retire as they check back in — the generation stamp keeps
        them out of the new pool) and notify listeners.  No-op unless the
        epoch actually advances."""
        with self._pool_cv:
            cur = self.server_map
            if new_map.epoch <= cur.epoch:
                return False
            self.server_map = new_map
            self.n_shards = new_map.n
            self.addr = new_map.addrs[0]
            self._pool_gen += 1
            # only FREE streams retire here; checked-out ones close
            # themselves on check-in via the generation stamp
            old_free = [st for lst in self._free for st in lst]
            self._pool = [_Stream(i, shard=s, gen=self._pool_gen)
                          for s in range(self.n_shards)
                          for i in range(self.streams)]
            self._free = [[st for st in self._pool if st.shard == s]
                          for s in range(self.n_shards)]
            for st in old_free:
                self._close_stream(st)
            self._pool_cv.notify_all()
        stat_add("ps.client.map_refresh")
        flight.record("map_refresh", epoch=new_map.epoch, n=new_map.n)
        for cb in list(self._map_listeners):
            cb(new_map)
        return True

    def _probe_membership(self, addr: Tuple[str, int],
                          timeout: float) -> Optional[Dict]:
        """One-shot health probe of a single address for its membership
        descriptor — a raw connection, never the (possibly mid-swap)
        pool."""
        with socket.create_connection(tuple(addr),
                                      timeout=timeout) as sock:
            sock.settimeout(timeout)
            _send(sock, {"cmd": "health"}, role="client")
            resp = _recv(sock, role="client")
        if resp.get("ok"):
            return resp.get("membership")
        return None

    def refresh_server_map(self, hint: Optional[Dict] = None,
                           timeout: float = 5.0) -> bool:
        """Re-learn the fleet membership and adopt the highest epoch
        seen.  Candidates: the redirect ``hint`` a fenced server carried
        (trusted directly — it is the authoritative map of a member),
        then the health surface of every address we know — current map
        first, hint addresses after — FALLING THROUGH dead entries
        instead of pinning to shard 0, so a dead authority can never
        orphan the fleet.  Returns True when a newer map was adopted."""
        best: Optional[ps_cluster.ServerMap] = None
        if hint:
            best = ps_cluster.map_from_desc(hint)
        seen = set()
        cands: List[Tuple[str, int]] = []
        for a in list(self.server_map.addrs) + (
                list(best.addrs) if best is not None else []):
            a = (a[0], int(a[1]))
            if a not in seen:
                seen.add(a)
                cands.append(a)
        for addr in cands:
            try:
                desc = self._probe_membership(addr, timeout)
            except (ConnectionError, OSError):
                stat_add("ps.client.map_probe_miss")
                continue
            if desc:
                m = ps_cluster.map_from_desc(desc)
                if best is None or m.epoch > best.epoch:
                    best = m
                break   # first LIVE answer wins (plus any newer hint)
        if best is None:
            return False
        return self._adopt_map(best)

    def _fence_recover(self, e: "_FenceRedirect", bo: Backoff,
                       attempt: int) -> None:
        """Shared verb-level recovery from a typed fence rejection:
        refresh the map off the hint; when nothing newer exists (the
        server is mid-commit behind us, or the range is frozen for the
        cutover) back off bounded — a stall here is the migration's
        blocking window, never an error."""
        stat_add("ps.client.fence_redirect")
        flight.record("fence_redirect", fence=e.kind,
                      epoch=self.server_map.epoch, attempt=attempt)
        changed = False
        try:
            changed = self.refresh_server_map(hint=e.hint)
        except (ConnectionError, OSError):
            pass
        if not changed and not bo.sleep(attempt):
            raise ConnectionError(
                f"fence redirect unresolved after {attempt} attempt(s): "
                f"{e.kind} at epoch {self.server_map.epoch}") from e

    def _call(self, req: Dict, retry: bool = True,
              timeout: float = 60, deadline: Optional[float] = None,
              dedup: bool = False, shard: int = 0) -> Dict:
        """One verb round-trip with retries on a checked-out stream.

        ``dedup=True`` stamps a fresh rid (or the caller presets
        wire.RID_FIELD itself for chunk groups): the server's dedup window
        makes the resend of an applied-but-unacknowledged mutation return
        the cached response — exactly-once, so even barrier/allreduce/
        delta verbs retry safely.  Backoff is exponential with jitter
        under ``deadline`` (default: the client's budget).

        Observability: one client span per verb (skipped when the caller
        pre-stamped a trace context — pipelined chunk requests carry
        their logical verb's span) and a client-side latency histogram
        per successful round trip, retries included."""
        if dedup and wire.RID_FIELD not in req:
            req = dict(req)
            req[wire.RID_FIELD] = self._next_rid()
        cmd = req.get("cmd")
        tr = trace.ACTIVE
        span = None
        if tr is not None and wire.TRACE_FIELD not in req:
            span = tr.start_span(f"ps.client.{cmd}",
                                 rid=req.get(wire.RID_FIELD))
            req = dict(req)
            req[wire.TRACE_FIELD] = span.context()
        t_call = time.monotonic()
        try:
            return self._call_attempts(req, retry, timeout, deadline,
                                       t_call, shard)
        finally:
            if span is not None:
                tr.finish(span)

    def _call_attempts(self, req: Dict, retry: bool, timeout: float,
                       deadline: Optional[float], t_call: float,
                       shard: int = 0) -> Dict:
        rid = req.get(wire.RID_FIELD)
        bo = Backoff(base=self.retry_sleep, cap=self.backoff_cap,
                     deadline=self.deadline if deadline is None
                     else deadline)
        attempt = 0
        while True:
            stream = self._checkout(shard)
            try:
                try:
                    if stream.sock is None:
                        self._connect(stream, timeout, bo)
                    stream.sock.settimeout(timeout)
                    _send(stream.sock, req, role="client")
                    resp = _recv(stream.sock, role="client")
                    if rid is not None \
                            and resp.get(wire.RID_FIELD, rid) != rid:
                        # a frame from a previous (timed-out) request
                        # surfaced on a reused stream — resync: reconnect
                        raise ConnectionError(
                            "stale response (rid mismatch)")
                except (ConnectionError, OSError):
                    self._close_stream(stream)
                    raise
            except (ConnectionError, OSError) as e:
                self._checkin(stream)
                attempt += 1
                stat_add("ps.client.retry")
                flight.record("verb_retry", cmd=req.get("cmd"),
                              attempt=attempt, error=type(e).__name__)
                exhausted = (self.retries is not None
                             and attempt >= self.retries)
                if not retry or exhausted or not bo.sleep(attempt):
                    stat_add("ps.client.give_up")
                    flight.record("verb_give_up", cmd=req.get("cmd"),
                                  attempt=attempt)
                    raise ConnectionError(
                        f"ps call {req.get('cmd')!r} failed after "
                        f"{attempt} attempt(s): {e}") from e
                continue
            except BaseException:
                self._checkin(stream)
                raise
            self._checkin(stream)
            if not resp.get("ok"):
                kind = _fence_kind(resp)
                if kind is not None:
                    # typed epoch/ownership rejection: provably NOT
                    # applied (the fence precedes any mutation and the
                    # rid was dropped) — the verb layer refreshes the
                    # map and re-drives
                    raise _FenceRedirect(kind,
                                         hint=resp.get("membership"))
                raise RuntimeError(resp.get("error", "ps error"))
            cmd = req.get("cmd")
            stat_observe(f"ps.client.{cmd}.latency_s",
                         time.monotonic() - t_call)
            return resp

    # -- pipelined chunk engine ---------------------------------------------
    def _pipeline(self, reqs: List[Dict], timeout: float = 60,
                  shard: int = 0) -> List[Dict]:
        """Drive chunk requests through one shard's stream pool with up
        to ``self.window`` frames in flight; returns responses in request
        order.  Every request must carry wire.RID_FIELD (the echo is the
        response-matching key).  Single-chunk calls and single-stream
        clients fall back to stop-and-wait ``_call``."""
        if not reqs:
            return []
        if len(reqs) == 1 or self.streams == 1:
            out: List[Dict] = []
            for r in reqs:
                try:
                    out.append(self._call(r, timeout=timeout,
                                          shard=shard))
                except _FenceRedirect as e:
                    # fenced chunk = provably unapplied; later chunks
                    # were never sent — mark both typed so the verb
                    # layer re-drives them without probing
                    partial: List[Optional[Dict]] = list(out)
                    partial += [{"ok": False, e.kind: True}
                                for _ in range(len(reqs) - len(out))]
                    raise _FenceRedirect(e.kind, hint=e.hint,
                                         partial={shard: partial}) \
                        from None
            return out
        streams = self._checkout_upto(min(self.streams, len(reqs)), shard)
        run = _PipelineRun(reqs, self.window, retries=self.retries)
        depth = max(1, -(-self.window // len(streams)))  # ceil division
        pumps = [threading.Thread(target=self._pump_stream,
                                  args=(s, run, timeout, depth),
                                  daemon=True)
                 for s in streams[1:]]
        for t in pumps:
            t.start()
        try:
            self._pump_stream(streams[0], run, timeout, depth)
        finally:
            for t in pumps:
                t.join()
            self._checkin(*streams)
        if run.error is not None:
            if isinstance(run.error, _FenceRedirect):
                raise _FenceRedirect(run.error.kind,
                                     hint=run.error.hint,
                                     partial={shard: list(run.results)})
            raise run.error
        if not run.finished():
            raise ConnectionError(
                f"pipelined {reqs[0].get('cmd')!r} incomplete "
                f"({run.done_count}/{run.n} chunks): {run.net_error}")
        return run.results    # type: ignore[return-value]

    def _pipeline_sharded(self, reqs_by_shard: Dict[int, List[Dict]],
                          timeout: float = 60) -> Dict[int, List[Dict]]:
        """Drive per-shard chunk request lists concurrently — one
        _PipelineRun per shard over that shard's stream pool, all under a
        SHARED inflight budget, so the fan-out multiplies wire
        concurrency (N sockets actively moving frames) without
        multiplying client memory (total frames in flight stays at the
        single-server window).  Returns {shard: responses-in-order}.

        Chunks never migrate between shards: a key's row lives on
        exactly one server, so a failed stream requeues its chunks for
        the SAME shard's surviving/reconnected streams only."""
        live = {s: r for s, r in reqs_by_shard.items() if r}
        if not live:
            return {}
        stat_observe("ps.cluster.fan_out_width", float(len(live)))
        if len(live) == 1:
            ((s, reqs),) = live.items()
            return {s: self._pipeline(reqs, timeout=timeout, shard=s)}
        budget = ps_cluster._InflightBudget(max(self.window, len(live)))
        runs: Dict[int, _PipelineRun] = {}
        held: List[_Stream] = []
        jobs: List[Tuple[_Stream, _PipelineRun, int]] = []
        finish: Dict[int, float] = {}
        for s in sorted(live):
            reqs = live[s]
            streams = self._checkout_upto(min(self.streams, len(reqs)), s)
            held.extend(streams)
            run = _PipelineRun(reqs, self.window, retries=self.retries,
                               budget=budget)
            budget.register(run._cv)
            runs[s] = run
            depth = max(1, -(-self.window // len(streams)))
            for st in streams:
                jobs.append((st, run, depth))

        def pump(st: _Stream, run: _PipelineRun, depth: int) -> None:
            try:
                self._pump_stream(st, run, timeout, depth)
            finally:
                # per-shard completion timestamp (last pump out wins):
                # the spread across shards is the slowest-shard stall
                finish[st.shard] = time.monotonic()

        pumps = [threading.Thread(target=pump, args=j, daemon=True)
                 for j in jobs[1:]]
        for t in pumps:
            t.start()
        try:
            pump(*jobs[0])
        finally:
            for t in pumps:
                t.join()
            self._checkin(*held)
        fence: Optional[_FenceRedirect] = None
        for s, run in runs.items():
            if isinstance(run.error, _FenceRedirect):
                e = run.error
                if fence is None or (
                        (e.hint or {}).get("epoch", -1)
                        > (fence.hint or {}).get("epoch", -1)):
                    fence = e
        if fence is not None:
            # aggregate: carry EVERY shard's per-chunk results so the
            # verb layer can re-drive exactly the unapplied chunks of
            # the whole fan-out (unfenced shards' unfinished chunks ride
            # along as unresolved)
            raise _FenceRedirect(fence.kind, hint=fence.hint,
                                 partial={s: list(runs[s].results)
                                          for s in runs})
        for s, run in runs.items():
            if run.error is not None:
                raise run.error
        for s, run in runs.items():
            if not run.finished():
                raise ConnectionError(
                    f"pipelined {live[s][0].get('cmd')!r} incomplete on "
                    f"shard {s} ({run.done_count}/{run.n} chunks): "
                    f"{run.net_error}")
        if len(finish) > 1:
            stat_observe("ps.cluster.slowest_shard_stall_s",
                         max(finish.values()) - min(finish.values()))
        return {s: runs[s].results    # type: ignore[misc]
                for s in live}

    def _pump_stream(self, stream: _Stream, run: _PipelineRun,
                     timeout: float, depth: int) -> None:
        """Drive one pooled connection for a pipelined verb.

        This thread SENDS; a paired receiver thread drains responses, so
        up to ``depth`` frames ride the socket concurrently and a full
        TCP buffer can never deadlock send against recv (the classic
        pipelining hazard).  Encode of the next chunk overlaps the
        send/recv of the previous ones by construction.  On a wire
        failure the stream's unresolved chunks requeue for any stream and
        this pump reconnects under the shared backoff/deadline policy;
        progress (any response landed) resets the budget."""
        bo = Backoff(base=self.retry_sleep, cap=self.backoff_cap,
                     deadline=self.deadline)
        attempt = 0
        while not run._stopped() and not run.finished():
            try:
                if stream.sock is None:
                    self._connect(stream, timeout, bo)
                stream.sock.settimeout(timeout)
            except (ConnectionError, OSError) as e:
                attempt += 1
                stat_add("ps.client.retry")
                flight.record("verb_retry", site="pump_connect",
                              attempt=attempt, error=type(e).__name__)
                run.note_net_error(e)
                exhausted = (self.retries is not None
                             and attempt >= self.retries)
                if exhausted or not bo.sleep(attempt):
                    stat_add("ps.client.give_up")
                    flight.record("verb_give_up", site="pump_connect",
                                  attempt=attempt)
                    return          # this stream gives up; others continue
                continue

            pending: "deque[Tuple[int, Dict]]" = deque()
            cv = lockdep.condition("ps.service.PSClient._pump_stream.cv")
            state = {"err": None, "done": False, "progress": False}

            def receiver(sock=stream.sock, pending=pending, cv=cv,
                         state=state):
                try:
                    while True:
                        with cv:
                            while not pending and not state["done"] \
                                    and state["err"] is None:
                                cv.wait()
                            if state["err"] is not None:
                                return
                            if not pending and state["done"]:
                                return
                            idx, req, t_sent = pending[0]
                        resp = _recv(sock, role="client")
                        rid = req[wire.RID_FIELD]
                        if resp.get(wire.RID_FIELD, rid) != rid:
                            raise ConnectionError(
                                "stale response (rid mismatch)")
                        # pipelined chunks never pass through _call — the
                        # per-rpc client latency lands here instead
                        stat_observe(f"ps.client.{req['cmd']}.latency_s",
                                     time.monotonic() - t_sent)
                        with cv:
                            pending.popleft()
                            state["progress"] = True
                            cv.notify_all()
                        if not resp.get("ok"):
                            run.complete(idx, resp)
                            kind = _fence_kind(resp)
                            if kind is not None:
                                # typed fence: stop the run; the verb
                                # layer inspects per-chunk results and
                                # re-drives only the unapplied ones
                                run.abort(_FenceRedirect(
                                    kind, hint=resp.get("membership")))
                            else:
                                run.abort(RuntimeError(
                                    resp.get("error", "ps error")))
                        else:
                            run.complete(idx, resp)
                except (ConnectionError, OSError) as e:
                    with cv:
                        if state["err"] is None:
                            state["err"] = e
                        cv.notify_all()

            rx = threading.Thread(target=receiver, daemon=True)
            rx.start()
            send_err: Optional[BaseException] = None
            try:
                while True:
                    with cv:
                        while len(pending) >= depth \
                                and state["err"] is None:
                            cv.wait()
                        if state["err"] is not None:
                            break
                    job = run.take()
                    if job is None:
                        break
                    idx, req = job
                    with cv:
                        pending.append((idx, req, time.monotonic()))
                        cv.notify_all()
                    try:
                        # encode happens inside _send — on this thread,
                        # while the receiver (and other streams) move
                        # earlier chunks
                        _send(stream.sock, req, role="client")
                    except (ConnectionError, OSError) as e:
                        send_err = e
                        break
                    except BaseException as e:
                        # non-retryable (oversized frame, raised before
                        # any byte moved): un-pend the chunk so the
                        # receiver never waits on it, poison the run
                        with cv:
                            if pending and pending[-1][0] == idx:
                                pending.pop()
                        run.abort(e)
                        break
            finally:
                with cv:
                    state["done"] = True
                    if send_err is not None and state["err"] is None:
                        state["err"] = send_err
                    cv.notify_all()
                if state["err"] is not None:
                    # unblock a receiver parked in recv on a broken pipe
                    self._close_stream(stream)
                rx.join()

            err = state["err"]
            if err is None and not run.aborted:
                # clean episode end: everything this stream sent is
                # acknowledged.  If the queue is empty the remaining
                # chunks belong to other streams — this pump is done (a
                # stream that later fails requeues and retries its own)
                if state["progress"]:
                    attempt = 0
                    bo.reset()
                if not run.has_work():
                    return
                continue
            # episode failed: requeue every unresolved chunk — each spends
            # its own per-chunk retry budget (run.requeue) and resends
            # exactly-once via its rid on any surviving or reconnected
            # stream — then reconnect under the deadline budget
            self._close_stream(stream)
            with cv:
                leftover = [(i, r) for i, r, _ in pending]
                pending.clear()
            if leftover:
                run.requeue(leftover)
            if run._stopped() or err is None:
                return
            stat_add("ps.client.stream_reconnect")
            flight.record("stream_reconnect", error=type(err).__name__,
                          requeued=len(leftover))
            run.note_net_error(err)
            if state["progress"]:
                attempt = 0
                bo.reset()
            attempt += 1
            stat_add("ps.client.retry")
            if not bo.sleep(attempt):
                stat_add("ps.client.give_up")
                flight.record("verb_give_up", site="pump_reconnect",
                              attempt=attempt)
                return

    # -- verbs (table=None → the default table) -----------------------------
    @staticmethod
    def _stamp_trace(req: Dict) -> Dict:
        """Attach the calling span's wire context (no-op when the tracer
        is off or no span is open): pipelined chunks parent their server
        spans to the enclosing logical-verb span instead of opening one
        client span per chunk."""
        ctx = trace.wire_context()
        if ctx is not None:
            req[wire.TRACE_FIELD] = ctx
        return req

    def _stamp_ep(self, req: Dict) -> Dict:
        """Ride the membership epoch on a fenced sparse verb.  Skipped
        for a plain epoch-0 single server (frames stay byte-compatible
        with the pre-elastic wire); once the fleet is sharded or any
        reshard has happened, every fenced frame carries it."""
        smap = self.server_map
        if smap.n > 1 or smap.epoch > 0:
            req[EPOCH_FIELD] = smap.epoch
        return req

    def _pull_req(self, sub_keys: np.ndarray, table: Optional[str],
                  create: bool) -> Dict:
        req = {"cmd": "pull_sparse", "keys": sub_keys, "table": table,
               "create": create, wire.RID_FIELD: self._next_rid()}
        if self.wire_dtype != "f32":
            req["wire_dtype"] = self.wire_dtype
        return self._stamp_trace(self._stamp_ep(req))

    def pull_sparse(self, keys: np.ndarray, table: Optional[str] = None,
                    create: bool = False) -> Dict[str, np.ndarray]:
        """Chunked bulk pull.  The FIRST chunk (a small probe when the
        table's row width is unlearned) teaches the call's row width —
        learned ONCE per call, then the chunk width is FROZEN for the
        remainder and the tail chunks pipeline across the stream pool:
        one estimate read + one write per call instead of per chunk, and
        deterministic chunking for a given first response."""
        keys = np.asarray(keys)
        with trace.span("ps.client.pull_sparse.bulk", keys=len(keys)):
            bo = Backoff(base=self.retry_sleep, cap=self.backoff_cap,
                         deadline=self.deadline)
            attempt = 0
            while True:
                try:
                    if self.n_shards > 1 and len(keys):
                        return self._pull_sparse_sharded(keys, table,
                                                         create)
                    return self._pull_sparse_chunked(keys, table, create)
                except _FenceRedirect as e:
                    # pulls are idempotent — refresh the map and re-pull
                    # whole (re-partitioned under the new epoch); never
                    # a user-visible error
                    attempt += 1
                    self._fence_recover(e, bo, attempt)

    def _pull_sparse_chunked(self, keys: np.ndarray, table: Optional[str],
                             create: bool) -> Dict[str, np.ndarray]:
        tname = table or DEFAULT_TABLE
        with self._lock:
            learned = self._row_bytes_est.get(tname)
        per = self._per_chunk(learned if learned is not None else 512)
        if learned is None:
            # unlearned TABLE (this one — another table's learned width
            # says nothing about this schema): a wide schema could
            # overshoot the hard wire cap on a huge first chunk — probe
            # small, then the learned width governs
            per = min(per, 65536)
        c = min(per, len(keys))
        rows = self._call(self._pull_req(keys[:c], table, create))["rows"]
        parts = [rows]
        lo = c
        if c:
            learned = max(self._rows_bytes(rows), 8)
            with self._lock:
                self._row_bytes_est[tname] = learned
        if lo < len(keys):
            per = self._per_chunk(learned)      # frozen for the remainder
            reqs = [self._pull_req(keys[lo + o:lo + o + cc], table, create)
                    for o, cc in self._chunk_spans(len(keys) - lo, per)]
            parts += [r["rows"] for r in self._pipeline(reqs)]
        if len(parts) == 1:
            return parts[0]
        return {f: np.concatenate([p[f] for p in parts])
                for f in parts[0]}

    def _pull_sparse_sharded(self, keys: np.ndarray, table: Optional[str],
                             create: bool) -> Dict[str, np.ndarray]:
        """Cluster fan-out pull: partition keys by the ServerMap, drive
        every shard's chunk stream concurrently (_pipeline_sharded), and
        reassemble rows into the caller's key order by position.  Width
        learning keeps the single probe-then-freeze discipline — the
        probe goes to the shard holding the most keys; the learned width
        then governs every shard's chunking (one schema per table)."""
        smap = self.server_map
        pos = smap.partition(keys)
        tname = table or DEFAULT_TABLE
        with self._lock:
            learned = self._row_bytes_est.get(tname)
        chunks: List[Tuple[np.ndarray, Dict[str, np.ndarray]]] = []
        if learned is None:
            probe_shard = int(np.argmax([len(p) for p in pos]))
            per = min(self._per_chunk(512), 65536)
            p = pos[probe_shard]
            c = min(per, len(p))
            sub = p[:c]
            rows = self._call(self._pull_req(keys[sub], table, create),
                              shard=probe_shard)["rows"]
            chunks.append((sub, rows))
            pos[probe_shard] = p[c:]
            learned = max(self._rows_bytes(rows), 8)
            with self._lock:
                self._row_bytes_est[tname] = learned
        per = self._per_chunk(learned)          # frozen for the fan-out
        reqs_by_shard: Dict[int, List[Dict]] = {}
        spans_by_shard: Dict[int, List[np.ndarray]] = {}
        for shard in range(smap.n):
            p = pos[shard]
            if not len(p):
                continue
            stat_add(f"ps.cluster.s{shard}.pull_keys", float(len(p)))
            stat_add(f"ps.cluster.s{shard}.est_bytes",
                     float(len(p) * per))
            if heat.ACTIVE is not None:
                heat.ACTIVE.observe_shard(shard, len(p))
            reqs = []
            spans = []
            for lo, c in self._chunk_spans(len(p), per):
                sub = p[lo:lo + c]
                reqs.append(self._pull_req(keys[sub], table, create))
                spans.append(sub)
            reqs_by_shard[shard] = reqs
            spans_by_shard[shard] = spans
        results = self._pipeline_sharded(reqs_by_shard)
        for shard, rlist in results.items():
            for sub, resp in zip(spans_by_shard[shard], rlist):
                chunks.append((sub, resp["rows"]))
        template = chunks[0][1]
        out = {f: np.empty((len(keys),) + np.asarray(v).shape[1:],
                           np.asarray(v).dtype)
               for f, v in template.items()}
        for sub, rows in chunks:
            for f in out:
                out[f][sub] = rows[f]
        return out

    def push_sparse(self, keys: np.ndarray, rows: Dict[str, np.ndarray],
                    table: Optional[str] = None):
        keys = np.asarray(keys)
        with trace.span("ps.client.push_sparse.bulk", keys=len(keys)):
            bo = Backoff(base=self.retry_sleep, cap=self.backoff_cap,
                         deadline=self.deadline)
            attempt = 0
            while True:
                try:
                    return self._push_sparse_once(keys, rows, table)
                except _FenceRedirect as e:
                    # absolute-row pushes are idempotent (re-applying
                    # the same values is a no-op), so whole-verb re-drive
                    # under the refreshed map is exact
                    attempt += 1
                    self._fence_recover(e, bo, attempt)

    def _push_sparse_once(self, keys: np.ndarray,
                          rows: Dict[str, np.ndarray],
                          table: Optional[str]):
        # single-reference snapshot: server_map/n_shards are co-mutated
        # under _pool_cv in _adopt_map — partitioning with one and
        # counting with the other mid-adopt would mis-route keys (PB902)
        sm = self.server_map
        if sm.n > 1 and len(keys):
            per_row = self._rows_bytes(rows)
            reqs_by_shard: Dict[int, List[Dict]] = {}
            for shard, p in enumerate(sm.partition(keys)):
                if not len(p):
                    continue
                stat_add(f"ps.cluster.s{shard}.push_keys",
                         float(len(p)))
                stat_add(f"ps.cluster.s{shard}.est_bytes",
                         float(len(p) * per_row))
                if heat.ACTIVE is not None:
                    heat.ACTIVE.observe_shard(shard, len(p))
                sub_rows = {f: np.asarray(v)[p]
                            for f, v in rows.items()}
                reqs = []
                for lo, c in self._chunk_counts(len(p), per_row):
                    chunk = {f: v[lo:lo + c]
                             for f, v in sub_rows.items()}
                    reqs.append(self._stamp_trace(self._stamp_ep(
                        {"cmd": "push_sparse",
                         "keys": keys[p[lo:lo + c]],
                         "rows": self._quant_rows(chunk,
                                                  "push_sparse"),
                         "table": table,
                         wire.RID_FIELD: self._next_rid()})))
                reqs_by_shard[shard] = reqs
            self._pipeline_sharded(reqs_by_shard)
            return
        per_row = self._rows_bytes(rows)
        reqs = []
        for lo, c in self._chunk_counts(len(keys), per_row):
            chunk = {f: np.asarray(v)[lo:lo + c]
                     for f, v in rows.items()}
            reqs.append(self._stamp_trace(self._stamp_ep(
                {"cmd": "push_sparse", "keys": keys[lo:lo + c],
                 "rows": self._quant_rows(chunk, "push_sparse"),
                 "table": table,
                 wire.RID_FIELD: self._next_rid()})))
        self._pipeline(reqs)

    def push_sparse_delta(self, keys: np.ndarray,
                          rows: Dict[str, np.ndarray],
                          rows_abs: Optional[Dict[str, np.ndarray]] = None,
                          table: Optional[str] = None,
                          rid_group: Optional[str] = None):
        """Chunked like push_sparse, pipelined across the pool.  Each
        chunk carries rid ``<group>.<i>`` so resends — in-call retries on
        any stream AND a caller-level replay of the whole logical push
        with the same ``rid_group`` (pass-level recovery after a
        mid-sequence failure) — apply exactly once; already-applied
        chunks return the cached ack.  Chunking is a pure function of the
        rows' raw widths, so a replay re-produces byte-identical chunk
        boundaries under identical rids."""
        keys = np.asarray(keys)
        rows_abs = rows_abs or {}
        group = rid_group or self.new_rid_group()
        with trace.span("ps.client.push_sparse_delta.bulk",
                        keys=len(keys), group=group):
            bo = Backoff(base=self.retry_sleep, cap=self.backoff_cap,
                         deadline=self.deadline)
            attempt = 0
            with self._lock:
                rec = self._group_fleets.get(group)
            if rec is not None and rec[0] != self.server_map.epoch:
                # pinned-group replay ACROSS a membership change: the
                # new partition would re-chunk under different rids, so
                # first resolve every ORIGINAL chunk's fate (same rids
                # against the recorded fleet — cached ack = applied,
                # typed fence = provably not), then re-drive only the
                # unapplied rows under the current map
                pos = self._resolve_group(keys, rows, rows_abs, table,
                                          group, rec)
                with self._lock:
                    self._group_fleets.pop(group, None)
                if not len(pos):
                    return
                keys, rows, rows_abs = self._slice_rows(
                    keys, rows, rows_abs, pos)
                group = self.new_rid_group()
            while True:
                smap = self.server_map
                with self._lock:
                    if group not in self._group_fleets:
                        self._group_fleets[group] = (smap.epoch,
                                                     list(smap.addrs))
                        while len(self._group_fleets) > 64:
                            self._group_fleets.popitem(last=False)
                reqs_by_shard, spans_by_shard = self._delta_reqs(
                    keys, rows, rows_abs, table, group, smap)
                try:
                    if smap.n == 1:
                        self._pipeline(reqs_by_shard[0])
                    else:
                        self._pipeline_sharded(reqs_by_shard)
                    with self._lock:
                        self._group_fleets.pop(group, None)
                    return
                except _FenceRedirect as e:
                    # non-idempotent verb: disambiguate every chunk
                    # before anything is re-sent under new rids
                    attempt += 1
                    pos = self._unapplied_positions(
                        reqs_by_shard, spans_by_shard, e, smap.addrs)
                    self._fence_recover(e, bo, attempt)
                    with self._lock:
                        self._group_fleets.pop(group, None)
                    if not len(pos):
                        return
                    keys, rows, rows_abs = self._slice_rows(
                        keys, rows, rows_abs, pos)
                    group = self.new_rid_group()

    @staticmethod
    def _slice_rows(keys, rows, rows_abs, pos):
        return (keys[pos],
                {f: np.asarray(v)[pos] for f, v in rows.items()},
                {f: np.asarray(v)[pos] for f, v in rows_abs.items()})

    def _delta_reqs(self, keys, rows, rows_abs, table, group,
                    smap: ps_cluster.ServerMap):
        """Partition + chunk one logical delta push under ``smap`` —
        a pure function of (keys, row widths, group, smap.n), so a
        pinned-group replay rebuilds byte-identical frames under
        identical rids.  n == 1 keeps the flat ``<group>.<i>`` rid form;
        sharded rids are ``<group>.<shard>.<i>``.  Returns
        (reqs_by_shard, spans_by_shard) with spans = each chunk's key
        positions in the caller's array."""
        per_row = self._rows_bytes(rows) + self._rows_bytes(rows_abs)
        reqs_by_shard: Dict[int, List[Dict]] = {}
        spans_by_shard: Dict[int, List[np.ndarray]] = {}
        if smap.n == 1:
            reqs: List[Dict] = []
            spans: List[np.ndarray] = []
            for i, (lo, c) in enumerate(
                    self._chunk_counts(len(keys), per_row)):
                delta = {f: np.asarray(v)[lo:lo + c]
                         for f, v in rows.items()}
                reqs.append(self._stamp_trace(self._stamp_ep(
                    {"cmd": "push_sparse_delta",
                     "keys": keys[lo:lo + c],
                     "rows": self._quant_rows(delta,
                                              "push_sparse_delta"),
                     # absolute metadata (slot, mf_size, beta powers)
                     # must survive the wire EXACT — never quantized
                     "rows_abs": {f: np.asarray(v)[lo:lo + c]
                                  for f, v in rows_abs.items()},
                     "table": table,
                     wire.RID_FIELD: f"{group}.{i}"})))
                spans.append(np.arange(lo, lo + c))
            reqs_by_shard[0] = reqs
            spans_by_shard[0] = spans
            return reqs_by_shard, spans_by_shard
        for shard, p in enumerate(smap.partition(keys)):
            if not len(p):
                continue
            stat_add(f"ps.cluster.s{shard}.push_keys", float(len(p)))
            stat_add(f"ps.cluster.s{shard}.est_bytes",
                     float(len(p) * per_row))
            if heat.ACTIVE is not None:
                heat.ACTIVE.observe_shard(shard, len(p))
            sub_rows = {f: np.asarray(v)[p] for f, v in rows.items()}
            sub_abs = {f: np.asarray(v)[p] for f, v in rows_abs.items()}
            shard_reqs = []
            spans = []
            for i, (lo, c) in enumerate(
                    self._chunk_counts(len(p), per_row)):
                delta = {f: v[lo:lo + c] for f, v in sub_rows.items()}
                shard_reqs.append(self._stamp_trace(self._stamp_ep(
                    {"cmd": "push_sparse_delta",
                     "keys": keys[p[lo:lo + c]],
                     "rows": self._quant_rows(delta,
                                              "push_sparse_delta"),
                     "rows_abs": {f: v[lo:lo + c]
                                  for f, v in sub_abs.items()},
                     "table": table,
                     wire.RID_FIELD: f"{group}.{shard}.{i}"})))
                spans.append(p[lo:lo + c])
            reqs_by_shard[shard] = shard_reqs
            spans_by_shard[shard] = spans
        return reqs_by_shard, spans_by_shard

    def _probe_chunk(self, addr: Tuple[str, int], req: Dict,
                     timeout: float = 30.0) -> bool:
        """Resolve one chunk's fate by re-sending it — SAME rid — to the
        server that originally received it (a raw one-shot connection:
        the pool may already index the new map).  A cached dedup ack (or
        a fresh execution on a server that still owns the range) proves
        applied-exactly-once; a typed fence proves never-applied.
        Raises when the server stays unreachable past the retry budget —
        the ambiguity then falls to caller-level pinned-group replay."""
        bo = Backoff(base=self.retry_sleep, cap=self.backoff_cap,
                     deadline=self.deadline)
        attempt = 0
        rid = req.get(wire.RID_FIELD)
        while True:
            try:
                with socket.create_connection(tuple(addr),
                                              timeout=timeout) as sock:
                    sock.settimeout(timeout)
                    _send(sock, req, role="client")
                    resp = _recv(sock, role="client")
                if rid is not None \
                        and resp.get(wire.RID_FIELD, rid) != rid:
                    raise ConnectionError("stale response (rid mismatch)")
            except (ConnectionError, OSError) as err:
                attempt += 1
                stat_add("ps.client.retry")
                exhausted = (self.retries is not None
                             and attempt >= self.retries)
                if exhausted or not bo.sleep(attempt):
                    raise ConnectionError(
                        f"chunk-fate probe to {addr} failed after "
                        f"{attempt} attempt(s): {err}") from err
                continue
            stat_add("ps.client.fence_probe")
            if resp.get("ok"):
                return True
            if _fence_kind(resp) is not None:
                return False
            raise RuntimeError(resp.get("error", "ps error"))

    def _unapplied_positions(self, reqs_by_shard, spans_by_shard,
                             e: "_FenceRedirect",
                             addrs: List[Tuple[str, int]]) -> np.ndarray:
        """Positions (into the verb's key array) of every chunk proven
        NOT applied.  ok chunks are done; typed-fence chunks were
        rejected before any mutation; unresolved chunks are probed
        same-rid against their original server."""
        unapplied: List[np.ndarray] = []
        partial = e.partial or {}
        for shard, reqs in reqs_by_shard.items():
            resps = partial.get(shard)
            for i, (req, span) in enumerate(
                    zip(reqs, spans_by_shard[shard])):
                resp = None if resps is None or i >= len(resps) \
                    else resps[i]
                if resp is not None and resp.get("ok"):
                    continue
                if resp is not None and _fence_kind(resp) is not None:
                    unapplied.append(span)
                    continue
                if shard < len(addrs) \
                        and self._probe_chunk(addrs[shard], req):
                    continue
                unapplied.append(span)
        if not unapplied:
            return np.zeros((0,), np.int64)
        return np.sort(np.concatenate(unapplied))

    def _resolve_group(self, keys, rows, rows_abs, table, group,
                       rec) -> np.ndarray:
        """A pinned-group replay arrived AFTER the map changed: rebuild
        the group's original frames (chunking and partition are pure
        functions, so the bytes and rids match what the failed attempt
        sent) and probe every chunk against the recorded fleet.  Returns
        the positions still unapplied — the caller re-drives exactly
        those under the current map with a fresh group."""
        epoch, addrs = rec
        old_map = ps_cluster.make_server_map(addrs, epoch=epoch)
        reqs_by_shard, spans_by_shard = self._delta_reqs(
            keys, rows, rows_abs, table, group, old_map)
        unapplied: List[np.ndarray] = []
        for shard, reqs in reqs_by_shard.items():
            for req, span in zip(reqs, spans_by_shard[shard]):
                if not self._probe_chunk(addrs[shard], req):
                    unapplied.append(span)
        stat_add("ps.client.group_replay_resolve")
        if not unapplied:
            return np.zeros((0,), np.int64)
        return np.sort(np.concatenate(unapplied))

    def pull_dense(self, name: str) -> Optional[np.ndarray]:
        return self._call({"cmd": "pull_dense", "name": name})["value"]

    def push_dense(self, name: str, value: np.ndarray, add: bool = False):
        self._call({"cmd": "push_dense", "name": name,
                    "value": np.asarray(value), "add": add}, dedup=True)

    def _control_fenced(self, fn):
        """Run a cluster control-plane verb (end_day/save/load/shrink)
        under the fence-recover loop: on a typed epoch rejection the
        call PROVABLY did not reach that shard's mutation (and the
        2-phase helper's pinned rids make any partially-applied shards
        replay cached acks), so refresh-the-map-and-re-drive is exact.
        Without this, a client holding a pre-reshard map would fan a
        lifecycle verb over only the shards the OLD map names — end_day
        decaying half a fleet is a silent table fork."""
        bo = Backoff(base=self.retry_sleep, cap=self.backoff_cap,
                     deadline=self.deadline)
        attempt = 0
        while True:
            try:
                return fn()
            except _FenceRedirect as e:
                attempt += 1
                self._fence_recover(e, bo, attempt)

    def save(self, path: str, mode: str = "all",
             table: Optional[str] = None, keys=None) -> int:
        """Durable dump — at n > 1 fans out into per-shard
        ``shard-<k:03d>/`` subdirs of ``path`` (ps/cluster.cluster_save);
        EVERY shard writes its DEDUP.bin there, so all N restart handoffs
        stay current.  n == 1 keeps the flat single-server layout."""
        return self._control_fenced(
            lambda: ps_cluster.cluster_save(self, path, mode=mode,
                                            keys=keys, table=table))

    def load(self, path: str, table: Optional[str] = None,
             mode: str = "replace") -> int:
        return self._control_fenced(
            lambda: ps_cluster.cluster_load(self, path, mode=mode,
                                            table=table))

    def shrink(self, table: Optional[str] = None) -> int:
        def run():
            if self.n_shards > 1:
                return sum(
                    int(self._call(self._stamp_ep(
                        {"cmd": "shrink", "table": table}),
                        shard=s)["removed"])
                    for s in range(self.n_shards))
            return self._call(self._stamp_ep(
                {"cmd": "shrink", "table": table}))["removed"]
        return self._control_fenced(run)

    def end_day(self, table: Optional[str] = None,
                group: Optional[str] = None) -> None:
        # non-idempotent (counter decay) → exactly-once via rid; cluster-
        # wide it is 2-phase over every shard's dedup window — ALL shards
        # decay or none (ps/cluster.two_phase_lifecycle; lint rule PB801
        # keeps every lifecycle send on this path).  ``group`` pins a
        # caller-deterministic rid group: the trainer fleet's leader
        # failover re-drives end_day under the SAME group from whichever
        # rank holds the lease, and the dedup windows collapse the
        # duplicates — decay happens exactly once per day regardless of
        # how many leaders attempted it.
        self._control_fenced(
            lambda: ps_cluster.two_phase_lifecycle(self, "end_day",
                                                   table=table,
                                                   group=group))

    def size(self, table: Optional[str] = None) -> int:
        if self.n_shards > 1:
            return sum(
                int(self._call({"cmd": "size", "table": table},
                               shard=s)["size"])
                for s in range(self.n_shards))
        return self._call({"cmd": "size", "table": table})["size"]

    def list_tables(self) -> Dict[str, int]:
        if self.n_shards > 1:
            out: Dict[str, int] = {}
            for s in range(self.n_shards):
                for name, n in self._call({"cmd": "list_tables"},
                                          shard=s)["tables"].items():
                    out[name] = out.get(name, 0) + int(n)
            return out
        return self._call({"cmd": "list_tables"})["tables"]

    def forward(self, keys: np.ndarray, lod: np.ndarray,
                table: Optional[str] = None) -> np.ndarray:
        """Serving-tier ragged inference pool (ps/serving.py): per-sample
        sum over [embed_w | mf] of each sample's keys, ``lod`` = n+1
        offsets into ``keys``.  Single-frame (serving batches are small
        by construction; the admission cap bounds them server-side).
        Read-only, so a fence redirect is a simple refresh-and-redo."""
        bo = Backoff(base=self.retry_sleep, cap=self.backoff_cap,
                     deadline=self.deadline)
        attempt = 0
        while True:
            try:
                resp = self._call(self._stamp_ep(
                    {"cmd": "forward",
                     "keys": np.asarray(keys, np.uint64),
                     "lod": np.asarray(lod, np.int64),
                     "table": table}))
                return resp["pooled"]
            except _FenceRedirect as e:
                attempt += 1
                self._fence_recover(e, bo, attempt)

    def invalidate_row_width(self, table: Optional[str] = None) -> None:
        """Drop learned row-width estimates (one table, or all when
        ``table`` is None).  Coherence point for anything that replaces
        table CONTENTS out from under this client — load_xbox, a serving
        hot-swap — where a stale estimate from the old rows would
        mis-chunk the first pull against the new schema."""
        with self._lock:
            if table is None:
                self._row_bytes_est.clear()
            else:
                self._row_bytes_est.pop(table, None)

    def health(self, timeout: float = 5.0) -> Dict:
        """Heartbeat: liveness + drain state, cheap enough to poll.  The
        report carries this client's wire-pool shape alongside the
        server's state: pool size, connected streams, window.  At n > 1
        the report AGGREGATES across shards — mode collapses when all
        agree ("mixed" otherwise), draining is any-shard, inflight and
        stats sum — and the raw per-shard reports ride in ``shards``."""
        if self.n_shards > 1:
            per = [self._call({"cmd": "health"}, timeout=timeout,
                              deadline=timeout, shard=s)
                   for s in range(self.n_shards)]
            modes = {r.get("mode") for r in per}
            stats: Dict[str, float] = {}
            for r in per:
                for k, v in (r.get("stats") or {}).items():
                    stats[k] = stats.get(k, 0.0) + float(v)
            resp = {"ok": True,
                    "mode": modes.pop() if len(modes) == 1 else "mixed",
                    "draining": any(r.get("draining") for r in per),
                    "inflight": sum(int(r.get("inflight", 0))
                                    for r in per),
                    "tables": per[0].get("tables", ""),
                    "stats": stats,
                    "n_shards": self.n_shards,
                    "shards": per}
            heats = [r.get("heat") for r in per if r.get("heat")]
            if heats:
                # cluster heat: shard key spaces are disjoint, so
                # distinct counts ADD; concentration reads as the
                # hottest member; imbalance is measured across the
                # members' observed pull totals
                totals = [float(h.get("total_keys", 0.0)) for h in heats]
                mean = sum(totals) / max(len(totals), 1)
                resp["heat"] = {
                    "topk_share": max(h.get("topk_share", 0.0)
                                      for h in heats),
                    "working_set_rows": round(
                        sum(h.get("working_set_rows", 0.0)
                            for h in heats), 1),
                    "shard_imbalance": round(max(totals) / mean, 4)
                    if mean > 0 else 0.0,
                }
        else:
            resp = self._call({"cmd": "health"}, timeout=timeout,
                              deadline=timeout)
        with self._pool_cv:
            resp["pool_streams"] = len(self._pool)
            resp["pool_connected"] = sum(
                1 for s in self._pool if s.sock is not None)
        resp["pool_window"] = self.window
        resp["wire_dtype"] = self.wire_dtype
        return resp

    def barrier(self, world: int, timeout: float = 120,
                rid: Optional[str] = None) -> None:
        # retryable via rid: a resend after a dropped connection WAITS on
        # the original registration server-side instead of double-
        # registering.  Client timeout stays LONGER than the server's wait
        # window, so the server side always resolves (release or
        # rollback) first.  ``rid`` pins a caller-deterministic request id
        # (the trainer fleet's replay-safe barriers: a restarted rank
        # re-driving its pass replays the SAME rid, so a barrier it
        # already joined answers from the dedup window instead of
        # double-registering).
        req: Dict = {"cmd": "barrier", "world": world}
        if rid is not None:
            req[wire.RID_FIELD] = rid
        self._call(req, timeout=timeout, deadline=2 * timeout, dedup=True)

    def allreduce(self, arrs: Dict[str, np.ndarray], world: int, key: str,
                  timeout: float = 120,
                  rid: Optional[str] = None) -> Dict[str, np.ndarray]:
        """Sum the named arrays across `world` workers (every caller gets
        the same result).  Exactly-once like barrier (rid-dedup'd resend;
        ``rid`` pins a caller-deterministic id for restart replay).
        Use a fresh key per collective (e.g. f"auc-{pass_id}")."""
        req: Dict = {"cmd": "allreduce", "key": key, "world": world,
                     "arrs": dict(arrs)}
        if rid is not None:
            req[wire.RID_FIELD] = rid
        out = self._call(req, timeout=timeout,
                         deadline=2 * timeout, dedup=True)
        return out["arrs"]


class RemoteTableAdapter:
    """Duck-types ShardedHostTable's pass-batched surface over a PSClient so
    BoxPSEngine can run against a remote PS
    (engine.table = RemoteTableAdapter(client[, table])).

    delta_mode=True is the multi-trainer contract: bulk_pull snapshots the
    pulled rows (and asks the server to persist fresh-row defaults so every
    worker shares one base), bulk_write sends (new - snapshot) and the
    server SUMS concurrent workers' deltas — pass-granular Hogwild, the
    pass-lifecycle analogue of multi-node sparse grad aggregation
    (heter_comm_inl.h:2027/2131).

    Pass-level recovery: a failed write-back restores the pull snapshot
    AND pins the chunk rid-group, so re-driving end_pass resends byte-
    identical chunks under the same rids — chunks that DID land before the
    failure dedup server-side instead of double-applying.

    Quantized wire mode (FLAGS_ps_wire_dtype != f32): pull_sparse hands
    back the DEQUANTIZED values (wire.decode dequantizes), and the
    snapshot copies exactly those — so the write-back delta is
    (trained - dequantized base), i.e. precisely the training delta, and
    a zero-delta write-back leaves the server's fp32 state untouched."""

    def __init__(self, client: PSClient, table: Optional[str] = None,
                 delta_mode: bool = False,
                 snap_cap: Optional[int] = None):
        self.client = client
        self.table = table
        self.delta_mode = delta_mode
        # snapshots keyed by key-set digest: the engine pulls from several
        # sites (pass build, async preload of the NEXT pass, stale-row
        # refresh) and a single slot would be clobbered before write-back.
        # The cap is FLAGS_ps_snap_cap (pipelined next-pass preload raises
        # concurrent-snapshot pressure; an eviction here fails the
        # evictee's later write-back)
        self._snaps: Dict[bytes, Dict[str, np.ndarray]] = {}
        self._snap_groups: Dict[bytes, str] = {}
        self._snap_cap = max(1, int(flags.get_flags("ps_snap_cap")
                                    if snap_cap is None else snap_cap))
        # last successful delta write-back's MATERIALIZED rows (base+delta
        # as the server computed them) — consumed by the engine's device-
        # cache fold-back; None outside delta_mode
        self._write_effect: Optional[Dict[str, np.ndarray]] = None

    @property
    def server_map(self) -> ps_cluster.ServerMap:
        """The client's key-hash -> shard placement; consumers (device
        cache sharding, checkpoint metadata) read the topology here."""
        return self.client.server_map

    def pop_write_effect(self) -> Optional[Dict[str, np.ndarray]]:
        """The server-side value of the rows the last ``bulk_write``
        landed (delta mode: base + delta in the server's arithmetic, not
        the written soa — they can differ in the last ulp).  Cleared on
        read; the device cache folds these bits so hits replay wire pulls
        exactly."""
        eff, self._write_effect = self._write_effect, None
        return eff

    def invalidate_row_width(self) -> None:
        """Forward the coherence-point invalidation to the wire client
        (load_xbox calls this through engine.table when the engine runs
        against a remote PS)."""
        self.client.invalidate_row_width(self.table)

    def bulk_pull(self, keys):
        rows = self.client.pull_sparse(keys, table=self.table,
                                       create=self.delta_mode)
        if self.delta_mode:
            digest = np.asarray(keys, np.uint64).tobytes()
            if len(self._snaps) >= self._snap_cap:
                old = next(iter(self._snaps))       # oldest out
                self._snaps.pop(old)
                self._snap_groups.pop(old, None)
                # loud at the CAUSE: the silent eviction used to surface
                # later as a confusing no-matching-snapshot RuntimeError
                # at write-back time, far from here
                logging.getLogger(__name__).warning(
                    "RemoteTableAdapter: pull-snapshot cap (%d) hit — "
                    "evicting the oldest snapshot (%d keys); a later "
                    "write-back of that key set will fail. More "
                    "concurrent pulls in flight than _snap_cap?",
                    self._snap_cap, len(old) // 8)
                stat_add("ps.adapter.snap_evict")
            self._snaps[digest] = {f: np.array(v, copy=True)
                                   for f, v in rows.items()}
        return rows

    # fields where "sum of two workers' changes" is wrong — sent absolute
    NON_ACCUMULABLE = ("slot", "mf_size")
    NON_ACCUMULABLE_SUFFIX = ("_b1p", "_b2p")

    def _is_abs(self, f: str) -> bool:
        return (f in self.NON_ACCUMULABLE
                or f.endswith(self.NON_ACCUMULABLE_SUFFIX))

    def patch_snapshot(self, full_keys, sub_keys, rows) -> None:
        """The engine refreshed a SUBSET of an earlier pull (stale-row
        refresh after an async preload): fold the fresh values into the
        full pull's snapshot, or the next delta re-applies whatever peers
        (and this worker's previous pass) already pushed for those rows.
        Also drops the subset pull's own snapshot (it will never be
        written back)."""
        if not self.delta_mode:
            return
        full = np.asarray(full_keys, np.uint64)
        sub = np.asarray(sub_keys, np.uint64)
        self._snaps.pop(sub.tobytes(), None)
        snap = self._snaps.get(full.tobytes())
        if snap is None:
            return
        pos = np.searchsorted(full, sub)   # full pass keys are sorted
        for f, v in rows.items():
            if f in snap:
                snap[f][pos] = v

    def seed_snapshot(self, full_keys, rows, consumed=()) -> None:
        """A device-cache-assisted build pulled only cache MISSES over the
        wire; the engine assembled the full pass rows itself (hits from
        its host mirror — exactly the values this worker last wrote
        back).  Install them as the write-back base for the FULL key set
        so the later ``bulk_write(full_keys, ...)`` computes correct
        deltas, and drop the partial pulls' own snapshots (``consumed``,
        never written back directly) before they pressure the cap."""
        if not self.delta_mode:
            return
        for sub in consumed:
            sub_digest = np.asarray(sub, np.uint64).tobytes()
            self._snaps.pop(sub_digest, None)
            self._snap_groups.pop(sub_digest, None)
        digest = np.asarray(full_keys, np.uint64).tobytes()
        self._snaps[digest] = {f: np.array(v, copy=True)
                               for f, v in rows.items()}

    def pin_group(self, keys, group: str) -> None:
        """Pre-pin the rid group the NEXT ``bulk_write(keys, ...)`` will
        send its chunks under (instead of a fresh ``new_rid_group()``).
        The trainer fleet pins a group deterministic in (rank, day, pass,
        slice) right before each slice's write-back, so a crashed rank's
        replayed end_pass re-drives byte-identical chunks under identical
        rids — landed chunks dedup server-side, unlanded ones apply
        exactly once."""
        if not self.delta_mode:
            return
        self._snap_groups[np.asarray(keys, np.uint64).tobytes()] = group

    def bulk_write(self, keys, soa):
        if not self.delta_mode:
            return self.client.push_sparse(keys, soa, table=self.table)
        digest = np.asarray(keys, np.uint64).tobytes()
        snap = self._snaps.pop(digest, None)
        if snap is None:
            raise RuntimeError(
                "delta_mode write-back without a matching pull snapshot — "
                "the written key set must equal a previously pulled one")
        delta = {f: v - snap[f] for f, v in soa.items()
                 if f in snap and f != "unseen_days"
                 and not self._is_abs(f)}
        rows_abs = {f: np.asarray(v) for f, v in soa.items()
                    if self._is_abs(f)}
        group = self._snap_groups.pop(digest, None) or \
            self.client.new_rid_group()
        try:
            self.client.push_sparse_delta(keys, delta, rows_abs=rows_abs,
                                          table=self.table, rid_group=group)
        except Exception:
            # pass-level recovery: restore the snapshot and PIN the rid
            # group — a re-driven end_pass resends identical chunks under
            # identical rids, so chunks that landed dedup instead of
            # double-applying
            self._snaps[digest] = snap
            self._snap_groups[digest] = group
            stat_add("ps.adapter.writeback_retry_armed")
            raise
        # what the SERVER now holds for these rows: base + delta in the
        # server's own arithmetic (cur[f] + d elementwise), absolutes
        # overwritten, unseen_days zeroed.  base+delta can differ from the
        # written soa in the last ulp, so a device cache folding rows back
        # (pass_manager.end_pass) must mirror THESE bits, not soa's —
        # otherwise a later cache hit diverges from the wire pull it
        # replaces
        effect = {}
        for f, v in soa.items():
            if f in delta:
                effect[f] = snap[f] + delta[f]
            elif f == "unseen_days":
                effect[f] = np.zeros_like(np.asarray(v))
            else:
                effect[f] = np.asarray(v)
        self._write_effect = effect

    def end_day(self):
        self.client.end_day(table=self.table)

    def shrink(self):
        return self.client.shrink(table=self.table)

    def save(self, path, mode="all", keys=None):
        return self.client.save(path, mode, table=self.table, keys=keys)

    def load(self, path, mode="replace"):
        return self.client.load(path, table=self.table, mode=mode)

    def size(self):
        return self.client.size(table=self.table)
