"""RPC parameter-server service — the CPU PS tier over the network.

≙ PSCORE's brpc server/client (ps/service/brpc_ps_server.{h,cc},
brpc_ps_client.{h,cc}): push/pull sparse & dense against tables sharded by
``key % shard_num``, plus save/load/shrink/barrier control verbs.  The
TPU rebuild keeps the same wire verbs over length-prefixed TCP frames in
the typed binary codec (ps/wire.py — dtype/shape headers + raw buffers,
like sendrecv.proto's VariableMessage; NO pickle touches network bytes).
Several named tables ride one service (≙ brpc's table_id-routed cmds /
the_one_ps multi-table deployment); trainers on other hosts pull pass
working sets from, and flush them to, this service instead of their local
DRAM (the multi-host BuildPull path, ps_gpu_wrapper.cc:337-419, including
the retry-then-fail discipline :388-419).
"""

from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Dict, Optional, Tuple, Union

import numpy as np

from paddlebox_tpu.config import EmbeddingTableConfig
from paddlebox_tpu.ps import wire
from paddlebox_tpu.ps.host_table import ShardedHostTable

DEFAULT_TABLE = "embedding"


def _send(sock, msg: Dict) -> None:
    payload = wire.encode(msg)
    if len(payload) > wire.MAX_FRAME:
        # non-retryable by construction (RuntimeError, not ConnectionError):
        # the peer would reject it anyway — fail once with the real reason
        raise RuntimeError(
            f"frame of {len(payload)} bytes exceeds wire cap "
            f"{wire.MAX_FRAME} — split the request (fewer keys per call)")
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv(sock) -> Dict:
    head = b""
    while len(head) < 8:
        chunk = sock.recv(8 - len(head))
        if not chunk:
            raise ConnectionError("peer closed")
        head += chunk
    (length,) = struct.unpack("<Q", head)
    if length > wire.MAX_FRAME:
        raise ConnectionError(f"oversized frame ({length} bytes)")
    buf = bytearray()
    while len(buf) < length:
        chunk = sock.recv(min(1 << 20, length - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return wire.decode(bytes(buf))


class PSServer:
    """Hosts named ShardedHostTables + a dense blob store behind TCP verbs:
    pull_sparse/push_sparse/pull_dense/push_dense/save/load/shrink/
    end_day/size/barrier/list_tables (the BrpcPsService cmd surface with
    table-name routing ≙ table_id)."""

    def __init__(self, table: Union[ShardedHostTable,
                                    Dict[str, ShardedHostTable]],
                 host: str = "127.0.0.1", port: int = 0):
        if isinstance(table, dict):
            self.tables: Dict[str, ShardedHostTable] = dict(table)
        else:
            self.tables = {DEFAULT_TABLE: table}
        self.dense: Dict[str, np.ndarray] = {}
        self._dense_lock = threading.Lock()
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        req = _recv(self.request)
                    except (ConnectionError, OSError, wire.DecodeError):
                        # malformed frame → stream sync is gone; drop the
                        # connection (client reconnects + retries)
                        return
                    try:
                        resp = outer._dispatch(req)
                    except Exception as e:  # noqa: BLE001
                        resp = {"ok": False, "error": repr(e)}
                    _send(self.request, resp)

        self._srv = socketserver.ThreadingTCPServer((host, port), Handler,
                                                    bind_and_activate=True)
        self._srv.daemon_threads = True
        self.addr: Tuple[str, int] = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def table(self) -> ShardedHostTable:
        """Back-compat single-table accessor (the default table)."""
        return self.tables[DEFAULT_TABLE]

    def _table(self, req: Dict) -> ShardedHostTable:
        name = req.get("table") or DEFAULT_TABLE
        t = self.tables.get(name)
        if t is None:
            raise KeyError(f"unknown table {name!r} "
                           f"(have {sorted(self.tables)})")
        return t

    def _dispatch(self, req: Dict) -> Dict:
        cmd = req["cmd"]
        if cmd == "pull_sparse":
            rows = self._table(req).bulk_pull(req["keys"])
            return {"ok": True, "rows": rows}
        if cmd == "push_sparse":
            self._table(req).bulk_write(req["keys"], req["rows"])
            return {"ok": True}
        if cmd == "pull_dense":
            with self._dense_lock:
                return {"ok": True, "value": self.dense.get(req["name"])}
        if cmd == "push_dense":
            with self._dense_lock:
                if req.get("add"):
                    cur = self.dense.get(req["name"])
                    self.dense[req["name"]] = (req["value"] if cur is None
                                               else cur + req["value"])
                else:
                    self.dense[req["name"]] = req["value"]
            return {"ok": True}
        if cmd == "save":
            n = self._table(req).save(req["path"], req.get("mode", "all"))
            return {"ok": True, "saved": n}
        if cmd == "load":
            return {"ok": True, "loaded": self._table(req).load(req["path"])}
        if cmd == "shrink":
            return {"ok": True, "removed": self._table(req).shrink()}
        if cmd == "end_day":
            self._table(req).end_day()
            return {"ok": True}
        if cmd == "size":
            return {"ok": True, "size": self._table(req).size()}
        if cmd == "list_tables":
            return {"ok": True,
                    "tables": {n: t.size() for n, t in self.tables.items()}}
        if cmd == "barrier":
            world = req["world"]
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= world:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    try:
                        while self._barrier_gen == gen:
                            if not self._barrier_cv.wait(timeout=60):
                                raise TimeoutError("ps barrier timeout")
                    except TimeoutError:
                        # roll back this waiter's arrival or every later
                        # barrier releases one participant short
                        if self._barrier_gen == gen:
                            self._barrier_count -= 1
                        raise
            return {"ok": True}
        return {"ok": False, "error": f"unknown cmd {cmd}"}

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class PSClient:
    """≙ BrpcPsClient: sticky connection, bulk verbs, bounded retries
    (3-retry-then-raise ≙ ps_gpu_wrapper.cc:388-419)."""

    def __init__(self, addr: Tuple[str, int], retries: int = 3,
                 retry_sleep: float = 0.5):
        self.addr = tuple(addr)
        self.retries = retries
        self.retry_sleep = retry_sleep
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _call(self, req: Dict) -> Dict:
        last_err = None
        for _ in range(self.retries):
            try:
                with self._lock:
                    if self._sock is None:
                        self._sock = socket.create_connection(self.addr,
                                                              timeout=60)
                    _send(self._sock, req)
                    resp = _recv(self._sock)
                if not resp.get("ok"):
                    raise RuntimeError(resp.get("error", "ps error"))
                return resp
            except (ConnectionError, OSError) as e:
                last_err = e
                with self._lock:
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                time.sleep(self.retry_sleep)
        raise ConnectionError(f"ps unreachable after retries: {last_err}")

    # -- verbs (table=None → the default table) -----------------------------
    def pull_sparse(self, keys: np.ndarray,
                    table: Optional[str] = None) -> Dict[str, np.ndarray]:
        return self._call({"cmd": "pull_sparse", "keys": np.asarray(keys),
                           "table": table})["rows"]

    def push_sparse(self, keys: np.ndarray, rows: Dict[str, np.ndarray],
                    table: Optional[str] = None):
        self._call({"cmd": "push_sparse", "keys": np.asarray(keys),
                    "rows": rows, "table": table})

    def pull_dense(self, name: str) -> Optional[np.ndarray]:
        return self._call({"cmd": "pull_dense", "name": name})["value"]

    def push_dense(self, name: str, value: np.ndarray, add: bool = False):
        self._call({"cmd": "push_dense", "name": name,
                    "value": np.asarray(value), "add": add})

    def save(self, path: str, mode: str = "all",
             table: Optional[str] = None) -> int:
        return self._call({"cmd": "save", "path": path, "mode": mode,
                           "table": table})["saved"]

    def load(self, path: str, table: Optional[str] = None) -> int:
        return self._call({"cmd": "load", "path": path,
                           "table": table})["loaded"]

    def shrink(self, table: Optional[str] = None) -> int:
        return self._call({"cmd": "shrink", "table": table})["removed"]

    def end_day(self, table: Optional[str] = None) -> None:
        self._call({"cmd": "end_day", "table": table})

    def size(self, table: Optional[str] = None) -> int:
        return self._call({"cmd": "size", "table": table})["size"]

    def list_tables(self) -> Dict[str, int]:
        return self._call({"cmd": "list_tables"})["tables"]

    def barrier(self, world: int) -> None:
        self._call({"cmd": "barrier", "world": world})


class RemoteTableAdapter:
    """Duck-types ShardedHostTable's pass-batched surface over a PSClient so
    BoxPSEngine can run against a remote PS
    (engine.table = RemoteTableAdapter(client[, table]))."""

    def __init__(self, client: PSClient, table: Optional[str] = None):
        self.client = client
        self.table = table

    def bulk_pull(self, keys):
        return self.client.pull_sparse(keys, table=self.table)

    def bulk_write(self, keys, soa):
        self.client.push_sparse(keys, soa, table=self.table)

    def end_day(self):
        self.client.end_day(table=self.table)

    def shrink(self):
        return self.client.shrink(table=self.table)

    def save(self, path, mode="all"):
        return self.client.save(path, mode, table=self.table)

    def load(self, path):
        return self.client.load(path, table=self.table)

    def size(self):
        return self.client.size(table=self.table)
