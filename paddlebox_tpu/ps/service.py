"""RPC parameter-server service — the CPU PS tier over the network.

≙ PSCORE's brpc server/client (ps/service/brpc_ps_server.{h,cc},
brpc_ps_client.{h,cc}): push/pull sparse & dense against tables sharded by
``key % shard_num``, plus save/load/shrink/barrier control verbs.  The
TPU rebuild keeps the same wire verbs over length-prefixed TCP frames in
the typed binary codec (ps/wire.py — dtype/shape headers + raw buffers,
like sendrecv.proto's VariableMessage; NO pickle touches network bytes).
Several named tables ride one service (≙ brpc's table_id-routed cmds /
the_one_ps multi-table deployment); trainers on other hosts pull pass
working sets from, and flush them to, this service instead of their local
DRAM (the multi-host BuildPull path, ps_gpu_wrapper.cc:337-419).

Retry discipline (upgraded from the reference's retry-then-fail,
ps_gpu_wrapper.cc:388-419): EVERY verb is safely retryable.  Idempotent
verbs simply resend; non-idempotent verbs (``push_sparse_delta``,
``push_dense``, ``barrier``, ``allreduce``, ``end_day``) carry a
client-generated request id (``rid`` = client token + monotonic seq,
wire.RID_FIELD) that the server dedups through a bounded per-client
window in :class:`PSServer` — a resend of an applied-but-unacknowledged
mutation returns the cached response instead of applying twice
(exactly-once under ambiguous failure).  The client backs off
exponentially with jitter under an overall deadline budget
(utils/backoff.Backoff).  Fault injection hooks (ps/faults.py) ride the
``connect``/``send``/``recv``/``dispatch`` sites when armed; production
pays one ``is None`` check per site.
"""

from __future__ import annotations

import logging
import os
import socket
import socketserver
import struct
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple, Union

import numpy as np

from paddlebox_tpu import flags
from paddlebox_tpu.ps import faults, wire
from paddlebox_tpu.ps.host_table import ShardedHostTable
from paddlebox_tpu.utils.backoff import Backoff
from paddlebox_tpu.utils.monitor import stat_add

DEFAULT_TABLE = "embedding"

flags.define_flag(
    "ps_dedup_window", 1024,
    "per-client-token cap of the PS server's rid->response dedup window; "
    "exactly-once holds for resends within the newest <window> requests "
    "of a client (must exceed the chunk count of one logical delta push)")


def _send(sock, msg: Dict, role: str = "client") -> None:
    payload = wire.encode(msg)
    if len(payload) > wire.MAX_FRAME:
        # non-retryable by construction (RuntimeError, not ConnectionError):
        # the peer would reject it anyway — fail once with the real reason
        raise RuntimeError(
            f"frame of {len(payload)} bytes exceeds wire cap "
            f"{wire.MAX_FRAME} — split the request (fewer keys per call)")
    frame = struct.pack("<Q", len(payload)) + payload
    if faults.ACTIVE is not None:
        faults.on_send(sock, frame, role)
    sock.sendall(frame)


def _recv(sock, role: str = "client") -> Dict:
    if faults.ACTIVE is not None:
        faults.on_recv(role)
    head = b""
    while len(head) < 8:
        chunk = sock.recv(8 - len(head))
        if not chunk:
            raise ConnectionError("peer closed")
        head += chunk
    (length,) = struct.unpack("<Q", head)
    if length > wire.MAX_FRAME:
        raise ConnectionError(f"oversized frame ({length} bytes)")
    buf = bytearray()
    while len(buf) < length:
        chunk = sock.recv(min(1 << 20, length - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return wire.decode(bytes(buf))


class _DedupWindow:
    """Bounded per-client rid → cached-response window (the server half of
    the exactly-once protocol).

    A rid is ``<token>:<tail>``; entries group by token.  ``begin`` either
    admits a new rid (returns None — caller executes the verb and must
    ``commit`` or ``drop``), returns the cached response of a completed
    duplicate, or blocks while the original is still executing (a blocking
    verb like barrier whose first connection died keeps its handler thread
    registered — the resend must WAIT for that execution, never start a
    second one).

    Bounded-memory contract: at most ``cap`` completed entries per token
    and ``token_cap`` tokens (LRU); in-flight entries are never evicted.
    A resend older than the newest ``cap`` rids of its client re-executes
    — callers keep ``cap`` above the chunk count of one logical verb.
    """

    def __init__(self, cap: int = 1024, token_cap: int = 1024,
                 wait_timeout: float = 120.0):
        self.cap = cap
        self.token_cap = token_cap
        self.wait_timeout = wait_timeout
        self._cv = threading.Condition()
        # token -> OrderedDict[rid -> [done, resp]]
        self._by_token: "OrderedDict[str, OrderedDict]" = OrderedDict()

    @staticmethod
    def _token(rid: str) -> str:
        return rid.rsplit(":", 1)[0]

    def begin(self, rid: str) -> Optional[Dict]:
        tok = self._token(rid)
        deadline = time.monotonic() + self.wait_timeout
        with self._cv:
            while True:
                entries = self._by_token.get(tok)
                if entries is not None:
                    self._by_token.move_to_end(tok)
                entry = None if entries is None else entries.get(rid)
                if entry is None:
                    if entries is None:
                        entries = self._by_token[tok] = OrderedDict()
                        while len(self._by_token) > self.token_cap:
                            self._by_token.popitem(last=False)
                            stat_add("ps.server.dedup_token_evict")
                    entries[rid] = [False, None]    # in-flight
                    return None
                if entry[0]:                        # done → replay
                    stat_add("ps.server.dedup_hit")
                    return entry[1]
                # original still executing on another handler thread
                stat_add("ps.server.dedup_wait")
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return {"ok": False,
                            "error": f"duplicate of rid {rid} still "
                                     f"executing after {self.wait_timeout}s"}
                self._cv.wait(rem)

    def commit(self, rid: str, resp: Dict) -> None:
        tok = self._token(rid)
        with self._cv:
            entries = self._by_token.get(tok)
            if entries is not None and rid in entries:
                entries[rid][:] = [True, resp]
                # eviction is by COMPLETION order: the entry just
                # committed must outlive older completions, or a tiny cap
                # could evict the response a blocked duplicate is waiting
                # for before it wakes
                entries.move_to_end(rid)
                done = [r for r, e in entries.items() if e[0]]
                for r in done[:max(0, len(done) - self.cap)]:
                    del entries[r]
                    stat_add("ps.server.dedup_evict")
            self._cv.notify_all()

    def drop(self, rid: str) -> None:
        """The verb raised (nothing committed, or it rolled back — e.g. a
        barrier timeout): forget the rid so a resend re-executes."""
        tok = self._token(rid)
        with self._cv:
            entries = self._by_token.get(tok)
            if entries is not None:
                entries.pop(rid, None)
            self._cv.notify_all()


class _ThreadingTCPServer(socketserver.ThreadingTCPServer):
    # chaos restarts rebind the same port while old sockets drain TIME_WAIT
    allow_reuse_address = True
    daemon_threads = True


class PSServer:
    """Hosts named ShardedHostTables + a dense blob store behind TCP verbs:
    pull_sparse/push_sparse/pull_dense/push_dense/save/load/shrink/
    end_day/size/barrier/allreduce/list_tables/health (the BrpcPsService
    cmd surface with table-name routing ≙ table_id).  Requests carrying a
    rid are routed through the dedup window (exactly-once); ``shutdown``
    drains gracefully (stop accepting, finish in-flight verbs) and
    ``kill`` is the chaos harness's abrupt mid-verb death."""

    def __init__(self, table: Union[ShardedHostTable,
                                    Dict[str, ShardedHostTable]],
                 host: str = "127.0.0.1", port: int = 0):
        if isinstance(table, dict):
            self.tables: Dict[str, ShardedHostTable] = dict(table)
        else:
            self.tables = {DEFAULT_TABLE: table}
        self.dense: Dict[str, np.ndarray] = {}
        self._dense_lock = threading.Lock()
        # per-table: delta merges need read-modify-write atomicity only
        # against the SAME table; unrelated tables stay concurrent
        self._delta_locks = {name: threading.Lock() for name in self.tables}
        self._barrier_count = 0
        self._barrier_gen = 0
        self._barrier_cv = threading.Condition()
        # keyed cross-worker array allreduce (metric aggregation —
        # ≙ fleet.metrics gloo all_reduce of stat_pos/stat_neg,
        # fleet/metrics/metric.py:144)
        self._reduce_cv = threading.Condition()
        self._reduces: Dict[str, Dict] = {}
        self._dedup = _DedupWindow(cap=flags.get_flags("ps_dedup_window"))
        # lifecycle: _life_lock guards the dead flag (shutdown/kill may
        # race from a fault hook thread); _inflight_cv counts verbs being
        # executed so a graceful drain can wait them out
        self._life_lock = threading.Lock()
        self._dead = False
        self._draining = False
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._conns_lock = threading.Lock()
        self._conns: set = set()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def setup(self):
                with outer._conns_lock:
                    outer._conns.add(self.request)

            def finish(self):
                with outer._conns_lock:
                    outer._conns.discard(self.request)

            def handle(self):
                while True:
                    try:
                        req = _recv(self.request, role="server")
                    except (ConnectionError, OSError, wire.DecodeError):
                        # malformed frame → stream sync is gone; drop the
                        # connection (client reconnects + retries)
                        return
                    with outer._inflight_cv:
                        outer._inflight += 1
                    try:
                        try:
                            resp = outer._dispatch(req)
                        except faults.InjectedFault:
                            # injected mid-verb death: no response — the
                            # client's retry resolves through the dedup
                            # window (or a clean re-execute)
                            return
                        except Exception as e:  # noqa: BLE001
                            resp = {"ok": False, "error": repr(e)}
                        try:
                            _send(self.request, resp, role="server")
                        except RuntimeError as e:
                            # oversized RESPONSE: dying silently here would
                            # show the client a bare ConnectionError and it
                            # would re-pull the same oversized chunk — reply
                            # with the real reason instead (non-retryable)
                            err = {"ok": False,
                                   "error": f"response exceeds wire cap — "
                                            f"{e} (pull fewer keys per "
                                            f"call)"}
                            if wire.RID_FIELD in req:
                                err[wire.RID_FIELD] = req[wire.RID_FIELD]
                            try:
                                _send(self.request, err, role="server")
                            except (RuntimeError, ConnectionError, OSError):
                                return
                        except (ConnectionError, OSError):
                            return
                    finally:
                        with outer._inflight_cv:
                            outer._inflight -= 1
                            outer._inflight_cv.notify_all()
                    if outer._draining:
                        return              # drain: finish-current, then out

        self._srv = _ThreadingTCPServer((host, port), Handler,
                                        bind_and_activate=True)
        self.addr: Tuple[str, int] = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def table(self) -> ShardedHostTable:
        """Back-compat single-table accessor (the default table)."""
        return self.tables[DEFAULT_TABLE]

    def _table(self, req: Dict) -> ShardedHostTable:
        name = req.get("table") or DEFAULT_TABLE
        t = self.tables.get(name)
        if t is None:
            raise KeyError(f"unknown table {name!r} "
                           f"(have {sorted(self.tables)})")
        return t

    def _dispatch(self, req: Dict) -> Dict:
        """Fault hook + exactly-once wrapper around the verb switch."""
        if faults.ACTIVE is not None:
            faults.on_dispatch(req.get("cmd"), self)
        rid = req.get(wire.RID_FIELD)
        if rid is None:
            return self._exec(req)
        cached = self._dedup.begin(rid)
        if cached is not None:
            return cached
        try:
            resp = self._exec(req)
        except BaseException:
            # nothing applied, or the verb rolled itself back (barrier/
            # allreduce timeout paths) — a resend must re-execute
            self._dedup.drop(rid)
            raise
        resp[wire.RID_FIELD] = rid      # echo: client rejects stale frames
        self._dedup.commit(rid, resp)
        return resp

    def _exec(self, req: Dict) -> Dict:
        cmd = req["cmd"]
        if cmd == "pull_sparse":
            t = self._table(req)
            if req.get("create"):
                # persist fresh-row defaults on first pull so every worker
                # of a multi-trainer job sees identical base values
                # (delta write-back sums against a common base)
                with self._delta_locks[req.get("table") or DEFAULT_TABLE]:
                    rows = t.bulk_pull(req["keys"])
                    t.bulk_write(req["keys"], rows)
            else:
                rows = t.bulk_pull(req["keys"])
            return {"ok": True, "rows": rows}
        if cmd == "push_sparse":
            self._table(req).bulk_write(req["keys"], req["rows"])
            return {"ok": True}
        if cmd == "push_sparse_delta":
            # geo/Hogwild-style merge for concurrent trainers: read-modify-
            # write under a lock so two workers' pass deltas SUM instead of
            # last-wins (≙ multi-node grad aggregation,
            # heter_comm_inl.h:2027 gather_one_node_grad + local merge).
            # Non-summable fields (slot, mf_size, beta powers) arrive as
            # absolute values and overwrite.
            t = self._table(req)
            with self._delta_locks[req.get("table") or DEFAULT_TABLE]:
                cur = t.bulk_pull(req["keys"])
                for f, d in req["rows"].items():
                    if f in cur:
                        cur[f] = cur[f] + d
                for f, v in (req.get("rows_abs") or {}).items():
                    if f in cur:
                        cur[f] = v
                if "unseen_days" in cur:
                    cur["unseen_days"] = np.zeros_like(cur["unseen_days"])
                t.bulk_write(req["keys"], cur)
            return {"ok": True}
        if cmd == "pull_dense":
            with self._dense_lock:
                return {"ok": True, "value": self.dense.get(req["name"])}
        if cmd == "push_dense":
            with self._dense_lock:
                if req.get("add"):
                    cur = self.dense.get(req["name"])
                    self.dense[req["name"]] = (req["value"] if cur is None
                                               else cur + req["value"])
                else:
                    self.dense[req["name"]] = req["value"]
            return {"ok": True}
        if cmd == "save":
            n = self._table(req).save(req["path"], req.get("mode", "all"))
            return {"ok": True, "saved": n}
        if cmd == "load":
            return {"ok": True, "loaded": self._table(req).load(req["path"])}
        if cmd == "shrink":
            return {"ok": True, "removed": self._table(req).shrink()}
        if cmd == "end_day":
            self._table(req).end_day()
            return {"ok": True}
        if cmd == "size":
            return {"ok": True, "size": self._table(req).size()}
        if cmd == "list_tables":
            return {"ok": True,
                    "tables": {n: t.size() for n, t in self.tables.items()}}
        if cmd == "health":
            # heartbeat: cheap liveness + drain visibility for clients and
            # the launcher's replica watch
            with self._inflight_cv:
                inflight = self._inflight
            return {"ok": True, "draining": self._draining,
                    "inflight": inflight,
                    "tables": ",".join(sorted(self.tables))}
        if cmd == "barrier":
            world = req["world"]
            with self._barrier_cv:
                gen = self._barrier_gen
                self._barrier_count += 1
                if self._barrier_count >= world:
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._barrier_cv.notify_all()
                else:
                    try:
                        while self._barrier_gen == gen:
                            if not self._barrier_cv.wait(timeout=60):
                                raise TimeoutError("ps barrier timeout")
                    except TimeoutError:
                        # roll back this waiter's arrival or every later
                        # barrier releases one participant short
                        if self._barrier_gen == gen:
                            self._barrier_count -= 1
                        raise
            return {"ok": True}
        if cmd == "allreduce":
            # keyed sum-allreduce of named arrays across `world` callers:
            # the exact distributed-metrics primitive (global AUC = AUC of
            # the SUMMED pos/neg bucket tables, ≙ fleet.metrics.auc,
            # fleet/metrics/metric.py:144).  Each key is one collective;
            # last reader cleans up, so keys are reusable across passes.
            key, world = req["key"], int(req["world"])
            with self._reduce_cv:
                st = self._reduces.setdefault(
                    key, {"sum": None, "count": 0, "readers": 0,
                          "done": False})
                if st["done"]:
                    raise RuntimeError(
                        f"allreduce key {key!r} still draining readers — "
                        "use a fresh key per collective (e.g. suffix the "
                        "pass id)")
                if st["sum"] is None:
                    st["sum"] = dict(req["arrs"])
                    st["world"] = world
                else:
                    if st["world"] != world:
                        raise ValueError(
                            f"allreduce key {key!r}: participants disagree "
                            f"on world size ({st['world']} vs {world}) — a "
                            "smaller world would complete the collective "
                            "early with a partial sum")
                    if set(st["sum"]) != set(req["arrs"]):
                        raise ValueError(
                            f"allreduce key {key!r}: participants disagree "
                            f"on array names ({sorted(st['sum'])} vs "
                            f"{sorted(req['arrs'])})")
                    st["sum"] = {k: st["sum"][k] + v
                                 for k, v in req["arrs"].items()}
                st["count"] += 1
                if st["count"] >= world:
                    st["done"] = True
                    self._reduce_cv.notify_all()
                else:
                    while not st["done"]:
                        if not self._reduce_cv.wait(timeout=60):
                            if st["done"]:
                                break     # completed as the clock expired
                            # roll back the WHOLE contribution (count AND
                            # the summed arrays) so a retry on the same
                            # key cannot double-count this worker
                            st["count"] -= 1
                            if st["count"] == 0:
                                # last waiter out: drop the entry entirely
                                # so a resized-world retry on the same key
                                # does not trip the world-agreement check
                                del self._reduces[key]
                            else:
                                st["sum"] = {k: st["sum"][k] - v
                                             for k, v in req["arrs"].items()}
                            raise TimeoutError("ps allreduce timeout")
                result = st["sum"]
                st["readers"] += 1
                if st["readers"] >= world:
                    del self._reduces[key]
            return {"ok": True, "arrs": result}
        return {"ok": False, "error": f"unknown cmd {cmd}"}

    # -- lifecycle -----------------------------------------------------------
    def _mark_dead(self) -> bool:
        with self._life_lock:
            if self._dead:
                return False
            self._dead = True
            return True

    def shutdown(self, drain_timeout: float = 5.0) -> None:
        """Graceful drain: stop accepting, let in-flight verbs finish
        (bounded by ``drain_timeout``), then close every connection."""
        if not self._mark_dead():
            return
        self._draining = True
        self._srv.shutdown()            # stop accepting new connections
        with self._inflight_cv:
            deadline = time.monotonic() + drain_timeout
            while self._inflight > 0:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    break
                self._inflight_cv.wait(rem)
        self._srv.server_close()
        self._close_conns()

    def kill(self) -> None:
        """Abrupt death (the chaos harness's mid-verb server loss): no
        drain — the listener and every live connection drop on the floor.
        Table state survives in-process; a restart on the same port
        resumes service (the dedup window does NOT survive — exactly-once
        across a kill holds because injected kills fire before the verb
        applies)."""
        if not self._mark_dead():
            return
        self._srv.shutdown()
        self._srv.server_close()
        self._close_conns()

    def _close_conns(self) -> None:
        with self._conns_lock:
            conns = list(self._conns)
        for s in conns:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class PSClient:
    """≙ BrpcPsClient: sticky connection, bulk verbs, retries with
    exponential backoff + jitter under a deadline budget; non-idempotent
    verbs ride the rid/dedup exactly-once protocol so EVERY verb retries
    safely (the reference's 3-retry-then-fail, ps_gpu_wrapper.cc:388-419,
    upgraded).  ``retries=None`` means attempt-unbounded (deadline-bounded
    only)."""

    def __init__(self, addr: Tuple[str, int], retries: Optional[int] = 3,
                 retry_sleep: float = 0.1,
                 max_frame: int = wire.MAX_FRAME,
                 deadline: float = 60.0, backoff_cap: float = 2.0):
        self.addr = tuple(addr)
        self.retries = retries
        self.retry_sleep = retry_sleep      # backoff base
        self.backoff_cap = backoff_cap
        self.deadline = deadline            # per-call retry budget (s)
        # soft frame budget for transparent chunking of the row verbs
        # (≙ brpc_ps_client splitting a bulk request over shard requests):
        # callers never split by hand; a whole-pass pull through
        # RemoteTableAdapter chunks here instead of tripping _send's cap
        self.max_frame = max_frame
        # learned row width PER TABLE (bytes), adapted from observed
        # responses — a narrow table's estimate must never size a wide
        # table's first chunk past the wire cap; guarded by _lock so a
        # client shared across threads cannot interleave updates
        self._row_bytes_est: Dict[str, int] = {}
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        # rid = token ":" seq — unique per client instance, monotonic
        self._token = f"c{os.getpid():x}-{os.urandom(4).hex()}"
        self._seq = 0

    def _next_rid(self) -> str:
        with self._lock:
            self._seq += 1
            return f"{self._token}:{self._seq}"

    def new_rid_group(self) -> str:
        """A stable id for a multi-chunk logical mutation: chunk i is sent
        as rid ``<group>.<i>``, so a CALLER-level resend of the whole
        logical verb (pass-level recovery) reuses the same rids and
        already-applied chunks dedup server-side."""
        return self._next_rid()

    def _per_chunk(self, bytes_per_row: int) -> int:
        """Keys per frame so each stays well under max_frame (4x headroom
        for codec overhead + field alignment) — the single chunk-budget
        policy for every row verb."""
        return max(1, int(self.max_frame // 4 // max(bytes_per_row, 1)))

    def _chunk_counts(self, n_keys: int, bytes_per_row: int):
        per = self._per_chunk(bytes_per_row)
        out = []
        done = 0
        while done < n_keys:
            c = min(per, n_keys - done)
            out.append((done, c))
            done += c
        return out or [(0, 0)]

    @staticmethod
    def _rows_bytes(rows: Dict[str, np.ndarray]) -> int:
        """Wire bytes per row of a rows dict (key + per-field payload)."""
        tot = 8    # key
        for v in rows.values():
            a = np.asarray(v)
            tot += a.dtype.itemsize * (int(np.prod(a.shape[1:])) or 1)
        return tot

    def _drop_sock(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def _call(self, req: Dict, retry: bool = True,
              timeout: float = 60, deadline: Optional[float] = None,
              dedup: bool = False) -> Dict:
        """One verb round-trip with retries.

        ``dedup=True`` stamps a fresh rid (or the caller presets
        wire.RID_FIELD itself for chunk groups): the server's dedup window
        makes the resend of an applied-but-unacknowledged mutation return
        the cached response — exactly-once, so even barrier/allreduce/
        delta verbs retry safely.  Backoff is exponential with jitter
        under ``deadline`` (default: the client's budget); the connect
        timeout honors the per-call ``timeout`` and never outlives the
        remaining budget."""
        if dedup and wire.RID_FIELD not in req:
            req = dict(req)
            req[wire.RID_FIELD] = self._next_rid()
        rid = req.get(wire.RID_FIELD)
        bo = Backoff(base=self.retry_sleep, cap=self.backoff_cap,
                     deadline=self.deadline if deadline is None
                     else deadline)
        attempt = 0
        while True:
            try:
                with self._lock:
                    if self._sock is None:
                        if faults.ACTIVE is not None:
                            faults.on_connect("client")
                        rem = bo.remaining()
                        cto = timeout if rem is None else \
                            max(min(timeout, rem), 0.001)
                        self._sock = socket.create_connection(self.addr,
                                                              timeout=cto)
                    self._sock.settimeout(timeout)
                    _send(self._sock, req, role="client")
                    resp = _recv(self._sock, role="client")
                if rid is not None and resp.get(wire.RID_FIELD, rid) != rid:
                    # a frame from a previous (timed-out) request surfaced
                    # on a reused stream — resync by reconnecting
                    raise ConnectionError("stale response (rid mismatch)")
                if not resp.get("ok"):
                    raise RuntimeError(resp.get("error", "ps error"))
                return resp
            except (ConnectionError, OSError) as e:
                self._drop_sock()
                attempt += 1
                stat_add("ps.client.retry")
                exhausted = (self.retries is not None
                             and attempt >= self.retries)
                if not retry or exhausted or not bo.sleep(attempt):
                    stat_add("ps.client.give_up")
                    raise ConnectionError(
                        f"ps call {req.get('cmd')!r} failed after "
                        f"{attempt} attempt(s): {e}") from e

    # -- verbs (table=None → the default table) -----------------------------
    def pull_sparse(self, keys: np.ndarray, table: Optional[str] = None,
                    create: bool = False) -> Dict[str, np.ndarray]:
        keys = np.asarray(keys)
        tname = table or DEFAULT_TABLE
        parts = []
        lo = 0
        while True:
            # re-derive the chunk width each round: the first response
            # teaches the real row width, so the rest of THIS call already
            # uses right-sized chunks (not just future calls)
            with self._lock:
                learned = self._row_bytes_est.get(tname)
            per = self._per_chunk(learned if learned is not None else 512)
            if learned is None:
                # unlearned TABLE (this one — another table's learned
                # width says nothing about this schema): a wide schema
                # could overshoot the hard wire cap on a huge first chunk
                # — probe small, then the learned width governs
                per = min(per, 65536)
            c = min(per, len(keys) - lo)
            rows = self._call({"cmd": "pull_sparse",
                               "keys": keys[lo:lo + c],
                               "table": table, "create": create})["rows"]
            if c:   # adapt this table's estimate to its real schema width
                per_row = max(self._rows_bytes(rows), 8)
                with self._lock:
                    self._row_bytes_est[tname] = per_row
            parts.append(rows)
            lo += c
            if lo >= len(keys):
                break
        if len(parts) == 1:
            return parts[0]
        return {f: np.concatenate([p[f] for p in parts])
                for f in parts[0]}

    def push_sparse(self, keys: np.ndarray, rows: Dict[str, np.ndarray],
                    table: Optional[str] = None):
        keys = np.asarray(keys)
        per_row = self._rows_bytes(rows)
        for lo, c in self._chunk_counts(len(keys), per_row):
            self._call({"cmd": "push_sparse", "keys": keys[lo:lo + c],
                        "rows": {f: np.asarray(v)[lo:lo + c]
                                 for f, v in rows.items()},
                        "table": table})

    def push_sparse_delta(self, keys: np.ndarray,
                          rows: Dict[str, np.ndarray],
                          rows_abs: Optional[Dict[str, np.ndarray]] = None,
                          table: Optional[str] = None,
                          rid_group: Optional[str] = None):
        """Chunked like push_sparse.  Each chunk carries rid
        ``<group>.<i>`` so resends — in-call retries AND a caller-level
        replay of the whole logical push with the same ``rid_group``
        (pass-level recovery after a mid-sequence failure) — apply
        exactly once; already-applied chunks return the cached ack."""
        keys = np.asarray(keys)
        rows_abs = rows_abs or {}
        group = rid_group or self.new_rid_group()
        per_row = self._rows_bytes(rows) + self._rows_bytes(rows_abs)
        for i, (lo, c) in enumerate(
                self._chunk_counts(len(keys), per_row)):
            self._call({"cmd": "push_sparse_delta",
                        "keys": keys[lo:lo + c],
                        "rows": {f: np.asarray(v)[lo:lo + c]
                                 for f, v in rows.items()},
                        "rows_abs": {f: np.asarray(v)[lo:lo + c]
                                     for f, v in rows_abs.items()},
                        "table": table,
                        wire.RID_FIELD: f"{group}.{i}"})

    def pull_dense(self, name: str) -> Optional[np.ndarray]:
        return self._call({"cmd": "pull_dense", "name": name})["value"]

    def push_dense(self, name: str, value: np.ndarray, add: bool = False):
        self._call({"cmd": "push_dense", "name": name,
                    "value": np.asarray(value), "add": add}, dedup=True)

    def save(self, path: str, mode: str = "all",
             table: Optional[str] = None) -> int:
        return self._call({"cmd": "save", "path": path, "mode": mode,
                           "table": table})["saved"]

    def load(self, path: str, table: Optional[str] = None) -> int:
        return self._call({"cmd": "load", "path": path,
                           "table": table})["loaded"]

    def shrink(self, table: Optional[str] = None) -> int:
        return self._call({"cmd": "shrink", "table": table})["removed"]

    def end_day(self, table: Optional[str] = None) -> None:
        # non-idempotent (counter decay) → exactly-once via rid
        self._call({"cmd": "end_day", "table": table}, dedup=True)

    def size(self, table: Optional[str] = None) -> int:
        return self._call({"cmd": "size", "table": table})["size"]

    def list_tables(self) -> Dict[str, int]:
        return self._call({"cmd": "list_tables"})["tables"]

    def health(self, timeout: float = 5.0) -> Dict:
        """Heartbeat: liveness + drain state, cheap enough to poll."""
        return self._call({"cmd": "health"}, timeout=timeout,
                          deadline=timeout)

    def barrier(self, world: int, timeout: float = 120) -> None:
        # retryable via rid: a resend after a dropped connection WAITS on
        # the original registration server-side instead of double-
        # registering.  Client timeout stays LONGER than the server's wait
        # window, so the server side always resolves (release or
        # rollback) first.
        self._call({"cmd": "barrier", "world": world}, timeout=timeout,
                   deadline=2 * timeout, dedup=True)

    def allreduce(self, arrs: Dict[str, np.ndarray], world: int, key: str,
                  timeout: float = 120) -> Dict[str, np.ndarray]:
        """Sum the named arrays across `world` workers (every caller gets
        the same result).  Exactly-once like barrier (rid-dedup'd resend).
        Use a fresh key per collective (e.g. f"auc-{pass_id}")."""
        out = self._call({"cmd": "allreduce", "key": key, "world": world,
                          "arrs": dict(arrs)}, timeout=timeout,
                         deadline=2 * timeout, dedup=True)
        return out["arrs"]


class RemoteTableAdapter:
    """Duck-types ShardedHostTable's pass-batched surface over a PSClient so
    BoxPSEngine can run against a remote PS
    (engine.table = RemoteTableAdapter(client[, table])).

    delta_mode=True is the multi-trainer contract: bulk_pull snapshots the
    pulled rows (and asks the server to persist fresh-row defaults so every
    worker shares one base), bulk_write sends (new - snapshot) and the
    server SUMS concurrent workers' deltas — pass-granular Hogwild, the
    pass-lifecycle analogue of multi-node sparse grad aggregation
    (heter_comm_inl.h:2027/2131).

    Pass-level recovery: a failed write-back restores the pull snapshot
    AND pins the chunk rid-group, so re-driving end_pass resends byte-
    identical chunks under the same rids — chunks that DID land before the
    failure dedup server-side instead of double-applying."""

    def __init__(self, client: PSClient, table: Optional[str] = None,
                 delta_mode: bool = False):
        self.client = client
        self.table = table
        self.delta_mode = delta_mode
        # snapshots keyed by key-set digest: the engine pulls from several
        # sites (pass build, async preload of the NEXT pass, stale-row
        # refresh) and a single slot would be clobbered before write-back
        self._snaps: Dict[bytes, Dict[str, np.ndarray]] = {}
        self._snap_groups: Dict[bytes, str] = {}
        self._snap_cap = 4

    def bulk_pull(self, keys):
        rows = self.client.pull_sparse(keys, table=self.table,
                                       create=self.delta_mode)
        if self.delta_mode:
            digest = np.asarray(keys, np.uint64).tobytes()
            if len(self._snaps) >= self._snap_cap:
                old = next(iter(self._snaps))       # oldest out
                self._snaps.pop(old)
                self._snap_groups.pop(old, None)
                # loud at the CAUSE: the silent eviction used to surface
                # later as a confusing no-matching-snapshot RuntimeError
                # at write-back time, far from here
                logging.getLogger(__name__).warning(
                    "RemoteTableAdapter: pull-snapshot cap (%d) hit — "
                    "evicting the oldest snapshot (%d keys); a later "
                    "write-back of that key set will fail. More "
                    "concurrent pulls in flight than _snap_cap?",
                    self._snap_cap, len(old) // 8)
                stat_add("ps.adapter.snap_evict")
            self._snaps[digest] = {f: np.array(v, copy=True)
                                   for f, v in rows.items()}
        return rows

    # fields where "sum of two workers' changes" is wrong — sent absolute
    NON_ACCUMULABLE = ("slot", "mf_size")
    NON_ACCUMULABLE_SUFFIX = ("_b1p", "_b2p")

    def _is_abs(self, f: str) -> bool:
        return (f in self.NON_ACCUMULABLE
                or f.endswith(self.NON_ACCUMULABLE_SUFFIX))

    def patch_snapshot(self, full_keys, sub_keys, rows) -> None:
        """The engine refreshed a SUBSET of an earlier pull (stale-row
        refresh after an async preload): fold the fresh values into the
        full pull's snapshot, or the next delta re-applies whatever peers
        (and this worker's previous pass) already pushed for those rows.
        Also drops the subset pull's own snapshot (it will never be
        written back)."""
        if not self.delta_mode:
            return
        full = np.asarray(full_keys, np.uint64)
        sub = np.asarray(sub_keys, np.uint64)
        self._snaps.pop(sub.tobytes(), None)
        snap = self._snaps.get(full.tobytes())
        if snap is None:
            return
        pos = np.searchsorted(full, sub)   # full pass keys are sorted
        for f, v in rows.items():
            if f in snap:
                snap[f][pos] = v

    def bulk_write(self, keys, soa):
        if not self.delta_mode:
            return self.client.push_sparse(keys, soa, table=self.table)
        digest = np.asarray(keys, np.uint64).tobytes()
        snap = self._snaps.pop(digest, None)
        if snap is None:
            raise RuntimeError(
                "delta_mode write-back without a matching pull snapshot — "
                "the written key set must equal a previously pulled one")
        delta = {f: v - snap[f] for f, v in soa.items()
                 if f in snap and f != "unseen_days"
                 and not self._is_abs(f)}
        rows_abs = {f: np.asarray(v) for f, v in soa.items()
                    if self._is_abs(f)}
        group = self._snap_groups.pop(digest, None) or \
            self.client.new_rid_group()
        try:
            self.client.push_sparse_delta(keys, delta, rows_abs=rows_abs,
                                          table=self.table, rid_group=group)
        except Exception:
            # pass-level recovery: restore the snapshot and PIN the rid
            # group — a re-driven end_pass resends identical chunks under
            # identical rids, so chunks that landed dedup instead of
            # double-applying
            self._snaps[digest] = snap
            self._snap_groups[digest] = group
            stat_add("ps.adapter.writeback_retry_armed")
            raise

    def end_day(self):
        self.client.end_day(table=self.table)

    def shrink(self):
        return self.client.shrink(table=self.table)

    def save(self, path, mode="all"):
        return self.client.save(path, mode, table=self.table)

    def load(self, path):
        return self.client.load(path, table=self.table)

    def size(self):
        return self.client.size(table=self.table)
