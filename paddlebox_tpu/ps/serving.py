"""Online serving tier — read-only xbox replicas, atomic day hot-swap,
multi-tenant inference traffic (ROADMAP item 3: the BoxPS loop's third
leg, train → dump → **serve**).

The reference feeds a serving fleet from the xbox base/delta dumps
(box_wrapper.cc:1286 SaveBase/SaveDelta); this module is the consumer
side.  Three pieces:

* :class:`FrozenHostTable` — an immutable snapshot of a
  ``ShardedHostTable``: keys sorted once at load, SoA row arrays frozen,
  lookups are pure numpy ``searchsorted`` gathers.  **No shard locks on
  the read path** (lint rule PB701 proves no table-mutating verb, shard
  lock, or optimizer call is reachable from it); misses serve the same
  key-deterministic defaults training would (``fv.default_rows_keyed``),
  so replica responses are bit-identical to an engine-side pull.

* :class:`ServingReplica` — a :class:`~paddlebox_tpu.ps.service.PSServer`
  whose verb switch is replaced with a read-only serving surface over
  the same wire protocol (so ``PSClient``'s multi-stream pipelining,
  rids, and quantized payloads all apply unchanged): batched
  ``pull_sparse``, a ragged ``forward`` (per-sample sum-pool over
  [embed_w | mf] — the gather+pool inference kernel shape), ``size`` /
  ``list_tables`` / extended ``health``, and a ``swap`` control verb.
  Tables are namespaced ``<tenant>/<table>`` (≙ PSCORE's table
  hierarchy); per-tenant admission control bounds in-flight queries and
  sheds with a typed overload error (:data:`OVERLOADED` marker, so the
  router can tell shed from death); per-tenant
  ``serving.<tenant>.{qps,latency_s→p50/p99,inflight,shed}`` flow
  through the obs stack (/statz, timeline sampler, SLO watchdog).

  **Hot swap**: ``hot_swap(path)`` loads the next day's dump into a
  fresh generation off the serving path, flips one reference (a single
  attribute store — readers that already entered the old generation
  finish on its frozen tables), invalidates the attached DeviceRowCache
  at the flip, then retires the old generation after its in-flight
  queries drain.  The dump itself arrives via save_xbox's tmp+rename,
  and the day pointer via the xbox swap manifest
  (io/checkpoint.publish_xbox_manifest) — tmp+rename end to end; a
  replica watching the manifest (``watch_manifest``) swaps on a
  generation advance.

* :class:`ServingRouter` — client-side fan-over: one ``PSClient`` per
  replica, primary-first with failover on replica death
  (``pull_sparse``/``forward`` are rid-echo idempotent verbs, and
  replicas loaded from one dump answer bit-identically, so a retry on
  the survivor is safe and exact).  A typed :class:`ServingOverload`
  surfaces shed instead of blind retry; ``observe_generation`` clears
  every client's learned row-width estimates when the fleet's
  generation advances (the client side of the hot-swap coherence
  point).

Scale-out (ROADMAP item 3's remaining gap, closed here) — three layers
on top of the single-replica story above:

* **Sharded fleet**: a replica built with ``shard``/``n_shards`` keeps
  only its splitmix64 key range (``ps.cluster.owned_mask`` — the SAME
  placement as the training PS cluster) plus the replicated hot set, so
  serving capacity scales past one host's memory.  The router's
  ``shard_groups`` mode fans ``pull_sparse`` per shard through ONE
  multi-address ``PSClient`` — ps/cluster.py's partition, shared
  inflight budget, per-shard stats, and order-preserving position merge
  apply wholesale — and pools ``forward`` client-side with the exact
  replica kernel so N-shard answers stay bit-identical to one full
  table.

* **Delta freshness**: ``watch_ckpt`` streams io/checkpoint.py
  ``save_pass`` delta generations.  New chain links build a patched
  plane set OFF the serving path (:meth:`FrozenHostTable.patched` —
  copy-on-write, upserts applied in generation order) and flip through
  the same one-reference ``_Generation`` swap as a day hot-swap: zero
  failed requests during a flip, online-learned rows reach inference in
  one poll interval (``serving.staleness_s``).  A compaction or day
  rollover (the chain re-bases) falls back to a full rebuild of the new
  chain.  Torn MANIFEST reads (mid-rename) retry with bounded backoff
  and a ``manifest_retry`` flight event instead of killing the watcher.

* **Heat-driven hot-key replication**: the top-K keys of the serving
  ``HeatMap`` sketches (``heat.serving_hot_keys``,
  ``FLAGS_serving_hot_keys``) are replicated into EVERY shard group's
  frozen planes at build/patch time; the router routes hot keys by
  power-of-two-choices over live per-group load EWMAs, so one hot key's
  traffic spreads across the fleet instead of melting its owner shard.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu import flags
from paddlebox_tpu.config import EmbeddingTableConfig
from paddlebox_tpu.ps import cluster as ps_cluster
from paddlebox_tpu.ps import feature_value as fv
from paddlebox_tpu.ps import heat
from paddlebox_tpu.ps import wire
from paddlebox_tpu.ps.host_table import ShardedHostTable
from paddlebox_tpu.ps.service import DEFAULT_TABLE, PSClient, PSServer
from paddlebox_tpu.utils import flight, lockdep
from paddlebox_tpu.utils.monitor import (stat_add, stat_observe, stat_set,
                                         stat_snapshot)

flags.define_flag(
    "serve_max_inflight", 64,
    "per-tenant admission cap on a ServingReplica: queries in flight for "
    "one tenant beyond this shed with a typed overload error instead of "
    "queueing (0 = unbounded)")
flags.define_flag(
    "serve_tenants", "default",
    "comma-separated tenant namespaces a ServingReplica serves; each "
    "tenant sees the loaded tables as <tenant>/<table> and gets its own "
    "admission budget + serving.<tenant>.* metrics")
flags.define_flag(
    "serve_drain_s", 30.0,
    "hot-swap drain budget: seconds to wait for the old generation's "
    "in-flight queries before retiring it (the flip itself is atomic "
    "and never waits)")
flags.define_flag(
    "serving_hot_keys", 0,
    "hot-key replication set size for a sharded serving fleet: the top-K "
    "keys of the serve.* heat sketches are replicated into EVERY shard "
    "group's frozen planes at build/patch time so the router can spread "
    "their traffic power-of-two-choices across groups (0 = off; needs "
    "FLAGS_obs_heat unless an explicit hot set is passed)")
flags.define_flag(
    "serving_patch_poll_s", 2.0,
    "ckpt-manifest poll cadence for the delta-streaming watcher "
    "(ServingReplica.watch_ckpt): how often a replica looks for new "
    "save_pass generations to patch in — the freshness floor")
flags.define_flag(
    "serving_manifest_retries", 4,
    "bounded retry budget for a torn manifest read in a watcher poll "
    "(a writer mid-rename): each retry backs off 50ms doubling, emits a "
    "manifest_retry flight event, and the poll is abandoned (not the "
    "watcher) when the budget runs out")

# marker embedded in the shed error string: it survives the wire and the
# client's RuntimeError re-raise, so a router can type the failure
# without a schema change to the error path
OVERLOADED = "serving_overloaded"

_METERED_VERBS = frozenset({"pull_sparse", "forward"})
_READ_VERBS = frozenset({"pull_sparse", "forward", "size", "list_tables"})


class ServingOverload(RuntimeError):
    """Per-tenant admission shed — the replica is alive but this tenant
    is at its in-flight cap.  Deliberately NOT a ConnectionError: a shed
    must not trigger failover/retry storms against the next replica."""


class FrozenHostTable:
    """Immutable lookup-only snapshot of one embedding table.

    Built once at load (sort by key, copy the SoA into contiguous
    arrays); after that every ``lookup_rows`` is a pure numpy gather —
    no locks, no growth, no mutation surface at all.  Swaps replace the
    whole object by one reference flip.  Misses get the identical
    key-deterministic defaults a training-side ``bulk_pull`` would
    (``fv.default_rows_keyed`` with the same config + seed), which is
    what makes replica responses bit-identical to the engine."""

    def __init__(self, config: EmbeddingTableConfig, keys: np.ndarray,
                 soa: Dict[str, np.ndarray], seed: int = 0):
        self.config = config
        self.mf_dim = config.embedding_dim
        self.expand_dim = config.expand_dim
        self.adam = config.sgd.optimizer in ("adam", "shared_adam")
        self.optimizer = config.sgd.optimizer
        self.double_stats = config.accessor.accessor_type == "ctr_double"
        self._seed = seed
        keys = np.asarray(keys, np.uint64)
        order = np.argsort(keys, kind="stable")
        self._keys = np.ascontiguousarray(keys[order])
        self._soa = {f: np.ascontiguousarray(a[order])
                     for f, a in soa.items()}

    @classmethod
    def freeze(cls, table: ShardedHostTable) -> "FrozenHostTable":
        """Snapshot a live ShardedHostTable (load/control path — this
        DOES take the shard locks once; the resulting object never
        does)."""
        keys = table.export_keys()
        soa = table.bulk_pull(keys)
        return cls(table.config, keys, soa, seed=table._seed)

    def size(self) -> int:
        return int(len(self._keys))

    def resident_mask(self, keys: np.ndarray) -> np.ndarray:
        """Boolean mask of ``keys`` resident in the frozen planes (pure
        searchsorted probe — lock-free like every read here)."""
        keys = np.asarray(keys, np.uint64)
        if not len(self._keys) or not len(keys):
            return np.zeros(len(keys), bool)
        pos = np.minimum(np.searchsorted(self._keys, keys),
                         len(self._keys) - 1)
        return self._keys[pos] == keys

    def restrict(self, mask: np.ndarray) -> "FrozenHostTable":
        """Copy-on-write row filter: a NEW FrozenHostTable holding only
        the masked rows (shard-ownership / hot-set selection at build
        time) — this object's planes are never written (PB702)."""
        mask = np.asarray(mask, bool)
        return FrozenHostTable(
            self.config, self._keys[mask],
            {f: a[mask] for f, a in self._soa.items()}, seed=self._seed)

    def patched(self, updates: Sequence[Tuple[np.ndarray,
                                              Dict[str, np.ndarray]]]
                ) -> "FrozenHostTable":
        """Copy-on-write upsert chain: a NEW FrozenHostTable equal to
        this one with ``updates`` — ordered ``(keys, soa)`` pairs, later
        entries win — applied over it.  This is the delta-generation
        patch builder (watch_ckpt): the merge happens entirely off the
        serving path on fresh arrays, the live planes are never written
        (lint rule PB702 proves that structurally), and the caller
        publishes the result with the one-reference generation flip.

        Within the concatenated update stream, last-wins dedup falls out
        of a stable sort (equal keys keep arrival order; the tail of
        each equal-run is the newest generation's row — exactly
        ShardedHostTable.load(mode="upsert") replayed in chain order)."""
        ks = [np.asarray(k, np.uint64) for k, _ in updates]
        live = [i for i, k in enumerate(ks) if len(k)]
        if not live:
            return self
        allk = np.concatenate([ks[i] for i in live])
        cat = {f: np.concatenate(
            [np.asarray(updates[i][1][f]) for i in live])
            for f in self._soa}
        order = np.argsort(allk, kind="stable")
        sk = allk[order]
        newest = np.ones(len(sk), bool)
        newest[:-1] = sk[1:] != sk[:-1]
        sel = order[newest]                 # last occurrence per key
        upd_keys = sk[newest]               # sorted unique
        upd_soa = {}
        for f, tmpl in self._soa.items():
            a = cat[f][sel]
            # template dtype wins (the host_table.load from_ckpt rule)
            upd_soa[f] = a.astype(tmpl.dtype) \
                if a.dtype != tmpl.dtype else a
        if len(self._keys):
            pos = np.minimum(np.searchsorted(self._keys, upd_keys),
                             len(self._keys) - 1)
            hit = self._keys[pos] == upd_keys
            keep = np.ones(len(self._keys), bool)
            keep[pos[hit]] = False
            merged_keys = np.concatenate([self._keys[keep], upd_keys])
            merged_soa = {f: np.concatenate([a[keep], upd_soa[f]])
                          for f, a in self._soa.items()}
        else:
            merged_keys, merged_soa = upd_keys, upd_soa
        return FrozenHostTable(self.config, merged_keys, merged_soa,
                               seed=self._seed)

    def lookup_rows(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        """Rows for ``keys`` — resident rows from the frozen snapshot,
        misses as key-deterministic defaults.  Lock-free by
        construction: every array here is immutable after __init__."""
        keys = np.asarray(keys, np.uint64)
        out = fv.default_rows_keyed(keys, self.mf_dim, self._seed,
                                    self.config.sgd.mf_initial_range,
                                    self.config.sgd.initial_range,
                                    self.expand_dim, self.adam,
                                    self.config.sgd.beta1_decay_rate,
                                    self.config.sgd.beta2_decay_rate,
                                    self.optimizer, self.double_stats)
        if len(self._keys) and len(keys):
            pos = np.searchsorted(self._keys, keys)
            pos = np.minimum(pos, len(self._keys) - 1)
            found = self._keys[pos] == keys
            if found.any():
                src = pos[found]
                for f, arr in self._soa.items():
                    out[f][found] = arr[src]
        return out


class _Generation:
    """One loaded day: the frozen table namespace plus an in-flight
    counter so a hot swap can retire it only after the queries that
    entered it drain (readers grab the generation BEFORE touching its
    tables and exit in a finally)."""

    def __init__(self, tables: Dict[str, FrozenHostTable],
                 generation: int, day: str):
        self.tables = tables
        self.generation = int(generation)
        self.day = day
        self._inflight = 0
        self._cv = lockdep.condition("ps.serving._Generation._cv")

    def enter(self) -> None:
        with self._cv:
            self._inflight += 1

    def exit(self) -> None:
        with self._cv:
            self._inflight -= 1
            self._cv.notify_all()

    def drain(self, timeout: float) -> bool:
        """Wait for in-flight queries to reach zero; False on timeout
        (the straggler still holds its table references — retirement is
        reference-drop, never destruction, so it stays safe)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._inflight > 0:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                self._cv.wait(rem)
        return True


class _LoadTarget:
    """Minimal engine shim for io.checkpoint.load_xbox: a serving-mode
    loader writing into a scratch ShardedHostTable that is frozen and
    dropped right after (the replica never exposes the mutable table)."""

    def __init__(self, config: EmbeddingTableConfig, seed: int):
        self.mode = "serving"
        self.config = config
        self.table = ShardedHostTable(config, seed=seed)
        self.cache = None


class ServingReplica(PSServer):
    """Read-only PSServer serving frozen xbox generations (docstring at
    module top).  Construct with the day-1 dump, then ``hot_swap`` (or
    the ``swap`` wire verb / ``watch_manifest``) to later days.

    Sharded fleet member: ``shard``/``n_shards`` make this replica keep
    only its splitmix64 key range (ps.cluster.owned_mask — the training
    cluster's placement) plus the replicated hot set, filtered at every
    build/patch point.  ``ckpt_root`` builds the initial generation from
    a TrainCheckpoint chain instead of an xbox dump; ``watch_ckpt``
    streams later delta generations in."""

    def __init__(self, config: Optional[EmbeddingTableConfig] = None,
                 xbox_path: Optional[str] = None,
                 tenants: Optional[Sequence[str]] = None,
                 max_inflight: Optional[int] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 day: str = "", generation: int = 1,
                 seed: int = 0, dedup_state=None,
                 shard: int = 0, n_shards: int = 1,
                 ckpt_root: Optional[str] = None,
                 hot_keys: Optional[np.ndarray] = None):
        self._config = config or EmbeddingTableConfig()
        self._seed = seed
        heat.maybe_enable_from_flags()
        if tenants is None:
            tenants = [t.strip() for t in
                       str(flags.get_flags("serve_tenants")).split(",")
                       if t.strip()]
        self.tenants: List[str] = list(tenants) or ["default"]
        self._max_inflight = int(
            flags.get_flags("serve_max_inflight")
            if max_inflight is None else max_inflight)
        self._adm_lock = lockdep.lock("ps.serving.ServingReplica._adm_lock")
        self._tenant_inflight = {t: 0 for t in self.tenants}
        self._swap_lock = lockdep.lock("ps.serving.ServingReplica._swap_lock")
        self._swapping = False
        # optional DeviceRowCache hook: a co-resident forward model's row
        # cache registered here is invalidated at every swap point
        self.cache = None
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        # sharded-fleet placement (set BEFORE the gen-0 build: the build
        # filters rows through _row_mask)
        self._shard = int(shard)
        self.n_shards = max(1, int(n_shards))
        self._hot_override = (None if hot_keys is None else
                              np.sort(np.asarray(hot_keys, np.uint64)))
        self._hot_keys = np.zeros(0, np.uint64)
        self._refresh_hot_keys()
        self._ckpt_root = ckpt_root
        self._applied_head: Optional[int] = None
        self._applied_chain: List[int] = []
        if ckpt_root is not None:
            gen0 = self._build_from_ckpt()
        else:
            gen0 = self._build_generation(xbox_path, day, int(generation))
        self._gen = gen0
        super().__init__(gen0.tables, host=host, port=port,
                         dedup_state=dedup_state, shard=self._shard)
        self.mode = "serving"

    # -- sharded placement ----------------------------------------------------
    def _refresh_hot_keys(self) -> None:
        """Re-resolve the replicated hot set: an explicit ctor override
        wins (deterministic fleets, tests); otherwise the measured top-K
        of the serve.* heat sketches (FLAGS_serving_hot_keys).  Only
        meaningful at n_shards > 1 — a full-table replica already holds
        every row."""
        if self._hot_override is not None:
            self._hot_keys = self._hot_override
            return
        if self.n_shards <= 1:
            self._hot_keys = np.zeros(0, np.uint64)
            return
        k = int(flags.get_flags("serving_hot_keys"))
        self._hot_keys = heat.serving_hot_keys(k)

    def _row_mask(self, keys: np.ndarray) -> np.ndarray:
        """Rows this replica answers for: its splitmix64 ownership range
        plus the replicated hot set (sorted searchsorted probe)."""
        keys = np.asarray(keys, np.uint64)
        mask = ps_cluster.owned_mask(keys, self._shard, self.n_shards)
        hot = self._hot_keys
        if len(hot) and len(keys):
            pos = np.minimum(np.searchsorted(hot, keys), len(hot) - 1)
            mask = mask | (hot[pos] == keys)
        return mask

    def _template_rows(self) -> Dict[str, np.ndarray]:
        """One default row as the field-set/dtype template for checkpoint
        reads (io.checkpoint.read_gen_rows) — the same generator the miss
        path uses, so chain replays conform to serving's row schema."""
        c = self._config
        return fv.default_rows_keyed(
            np.zeros(1, np.uint64), c.embedding_dim, self._seed,
            c.sgd.mf_initial_range, c.sgd.initial_range, c.expand_dim,
            c.sgd.optimizer in ("adam", "shared_adam"),
            c.sgd.beta1_decay_rate, c.sgd.beta2_decay_rate,
            c.sgd.optimizer,
            c.accessor.accessor_type == "ctr_double")

    def _missing_fill(self) -> Dict[str, float]:
        # host_table.load from_ckpt rule: adam beta-power trackers a dump
        # lacks init to the config decay rates, everything else to 0
        return {"_b1p": self._config.sgd.beta1_decay_rate,
                "_b2p": self._config.sgd.beta2_decay_rate}

    def _tables_ns(self, frozen: FrozenHostTable
                   ) -> Dict[str, FrozenHostTable]:
        tables: Dict[str, FrozenHostTable] = {DEFAULT_TABLE: frozen}
        for t in self.tenants:
            tables[f"{t}/{DEFAULT_TABLE}"] = frozen
        return tables

    # -- generation load / swap ----------------------------------------------
    def _build_generation(self, xbox_path: Optional[str], day: str,
                          generation: int) -> _Generation:
        t0 = time.monotonic()
        if xbox_path:
            from paddlebox_tpu.io.checkpoint import load_xbox
            shim = _LoadTarget(self._config, self._seed)
            load_xbox(shim, xbox_path)
            frozen = FrozenHostTable.freeze(shim.table)
        else:
            frozen = FrozenHostTable.freeze(
                ShardedHostTable(self._config, seed=self._seed))
        if self.n_shards > 1:
            frozen = frozen.restrict(self._row_mask(frozen._keys))
        g = _Generation(self._tables_ns(frozen), generation, day)
        stat_set("serving.generation", float(generation))
        stat_observe("serving.load_s", time.monotonic() - t0)
        flight.record("serving_load", generation=generation, day=day,
                      rows=frozen.size(), source=xbox_path or "<empty>")
        return g

    def _frozen_from_chain(self, ck, chain: Sequence[int]
                           ) -> FrozenHostTable:
        """From-scratch chain replay: the base generation's rows (shard-
        filtered) frozen, then every delta generation upserted in chain
        order through the copy-on-write patch builder — the reference
        state every incremental patch must stay bit-identical to."""
        tmpl = self._template_rows()
        fill = self._missing_fill()
        keys, soa = ck.read_gen_rows(chain[0], tmpl, fill)
        mask = self._row_mask(keys)
        frozen = FrozenHostTable(self._config, keys[mask],
                                 {f: a[mask] for f, a in soa.items()},
                                 seed=self._seed)
        updates = []
        for n in chain[1:]:
            dk, dsoa = ck.read_gen_rows(n, tmpl, fill)
            dm = self._row_mask(dk)
            updates.append((dk[dm], {f: a[dm] for f, a in dsoa.items()}))
        return frozen.patched(updates)

    def _build_from_ckpt(self) -> _Generation:
        """Initial generation from a TrainCheckpoint chain (ckpt_root
        mode): head's base + deltas, or an empty generation 0 when
        nothing has committed yet (watch_ckpt picks up the first
        commit)."""
        from paddlebox_tpu.io.checkpoint import TrainCheckpoint
        ck = TrainCheckpoint(self._ckpt_root)
        head = ck.head()
        if head is None:
            return self._build_generation(None, "", 0)
        t0 = time.monotonic()
        st = ck.gen_state(head)
        chain = [int(c) for c in st.get("chain", [head])]
        frozen = self._frozen_from_chain(ck, chain)
        g = _Generation(self._tables_ns(frozen), head,
                        str(st.get("day_id", "")))
        self._applied_head, self._applied_chain = head, chain
        stat_set("serving.generation", float(head))
        stat_observe("serving.load_s", time.monotonic() - t0)
        flight.record("serving_load", generation=head,
                      day=str(st.get("day_id", "")), rows=frozen.size(),
                      source=f"ckpt:{self._ckpt_root}")
        return g

    def _swap_in(self, new: _Generation,
              drain_timeout: Optional[float] = None
              ) -> Tuple[_Generation, bool]:
        """THE swap, shared by day hot-swaps and streamed delta patches:
        one reference store under _swap_lock (a reader that already did
        ``g = self._gen; g.enter()`` finishes on the old generation's
        frozen tables; every later reader sees the new one whole — zero
        failed requests by construction), cache coherence point, then
        retire the old generation after its in-flight queries drain."""
        with self._swap_lock:
            old = self._gen
            self._gen = new
            self.tables = dict(new.tables)
        cache = self.cache
        if cache is not None:
            # coherence point: any device-resident rows mirror the
            # RETIRED generation now
            cache.invalidate("serving_swap")
        budget = float(flags.get_flags("serve_drain_s")
                       if drain_timeout is None else drain_timeout)
        drained = old.drain(budget)
        if not drained:
            stat_add("serving.swap_drain_timeout")
        stat_set("serving.generation", float(new.generation))
        return old, drained

    def hot_swap(self, xbox_path: str, day: str = "",
                 generation: Optional[int] = None,
                 drain_timeout: Optional[float] = None) -> int:
        """Load ``xbox_path`` as the next generation, flip atomically,
        retire the old generation after its in-flight queries drain.
        Serialized against concurrent swaps; the flip never blocks the
        serving path (readers see either generation whole)."""
        with self._swap_lock:
            if self._swapping:
                raise RuntimeError("hot_swap already in progress")
            self._swapping = True
        try:
            cur = self._gen
            gen_no = (cur.generation + 1 if generation is None
                      else int(generation))
            new = self._build_generation(xbox_path, day, gen_no)
            old, drained = self._swap_in(new, drain_timeout)
        finally:
            with self._swap_lock:
                self._swapping = False
        stat_add("serving.swap")
        flight.record("serving_swap", generation=gen_no, day=day,
                      prev_generation=old.generation, drained=drained)
        return gen_no

    def _manifest_poll(self, fn, what: str):
        """Run a manifest/STATE read tolerating a torn file (a publisher
        mid-rename): retry on decode/IO error with bounded 50ms-doubling
        backoff (FLAGS_serving_manifest_retries attempts), a
        ``manifest_retry`` flight event per retry, and None — the POLL
        abandoned, never the watcher — when the budget runs out."""
        retries = max(0, int(flags.get_flags("serving_manifest_retries")))
        for i in range(retries + 1):
            try:
                return fn()
            except (ValueError, KeyError, OSError) as e:
                # json.JSONDecodeError is a ValueError: the torn-read case
                if i >= retries:
                    stat_add("serving.manifest_giveup")
                    flight.record("manifest_giveup", what=what,
                                  error=type(e).__name__)
                    return None
                stat_add("serving.manifest_retry")
                flight.record("manifest_retry", what=what, attempt=i + 1,
                              error=type(e).__name__)
                if self._watch_stop.wait(min(0.05 * (2 ** i), 0.5)):
                    return None
        return None

    def watch_manifest(self, root: str, poll_s: float = 2.0) -> None:
        """Poll the xbox swap manifest under ``root`` and hot-swap when
        its generation advances past the loaded one (the replica side of
        the train→publish→serve day loop).  A torn manifest read rides
        the bounded-backoff manifest_retry discipline instead of burning
        a whole poll interval."""
        from paddlebox_tpu.io.checkpoint import read_xbox_manifest

        def run() -> None:
            while not self._watch_stop.wait(poll_s):
                try:
                    man = self._manifest_poll(
                        lambda: read_xbox_manifest(root), "xbox_manifest")
                    if man and int(man["generation"]) > self._gen.generation:
                        self.hot_swap(man["path"],
                                      day=str(man.get("day", "")),
                                      generation=int(man["generation"]))
                except Exception:  # noqa: BLE001 — the watcher must outlive a bad day
                    stat_add("serving.watch_errors")

        # pboxlint: disable-next=PB405 -- joined in shutdown() via _watch_stop
        self._watch_thread = threading.Thread(
            target=run, name="pbox-serving-watch", daemon=True)
        self._watch_thread.start()

    # -- streamed delta freshness (TrainCheckpoint chain) --------------------
    def _poll_ckpt(self, ck) -> None:
        """One delta-stream poll: when the committed head advanced, build
        the next plane set OFF the serving path and flip it.

        The cheap common case — the new chain EXTENDS the applied one —
        patches only the unseen delta generations onto the live frozen
        planes (copy-on-write, never a write to them: PB702).  A re-based
        chain (compaction cadence hit, day rollover, or a replica that
        fell behind the GC horizon) rebuilds from the new chain's base;
        that is also where the hot-key replication set re-resolves from
        the current heat sketches."""
        head = self._manifest_poll(ck.head, "ckpt_manifest")
        with self._swap_lock:
            applied_head = self._applied_head
            applied = list(self._applied_chain)
        if head is None or head == applied_head:
            return
        st = self._manifest_poll(lambda: ck.gen_state(head), "ckpt_state")
        if st is None:
            return
        chain = [int(c) for c in st.get("chain", [head])]
        cur = self._gen
        t0 = time.monotonic()
        incremental = (bool(applied) and len(chain) > len(applied)
                       and chain[:len(applied)] == applied)
        if incremental:
            tmpl = self._template_rows()
            fill = self._missing_fill()
            updates = []
            for n in chain[len(applied):]:
                got = self._manifest_poll(
                    lambda g=n: ck.read_gen_rows(g, tmpl, fill),
                    "ckpt_gen_rows")
                if got is None:
                    return              # torn mid-GC read: next poll retries
                dk, dsoa = got
                dm = self._row_mask(dk)
                updates.append(
                    (dk[dm], {f: a[dm] for f, a in dsoa.items()}))
            frozen = cur.tables[DEFAULT_TABLE].patched(updates)
        else:
            self._refresh_hot_keys()
            got = self._manifest_poll(
                lambda: self._frozen_from_chain(ck, chain), "ckpt_chain")
            if got is None:
                return
            frozen = got
        new = _Generation(self._tables_ns(frozen), head,
                          str(st.get("day_id", "")))
        with self._swap_lock:
            if self._swapping:
                return                  # a day hot-swap owns the flip
            self._swapping = True
        try:
            old, drained = self._swap_in(new)
            with self._swap_lock:
                self._applied_head, self._applied_chain = head, chain
        finally:
            with self._swap_lock:
                self._swapping = False
        mt = self._manifest_poll(lambda: ck.gen_mtime(head), "ckpt_mtime")
        staleness = max(0.0, time.time() - mt) if mt is not None else 0.0
        stat_add("serving.delta_flip")
        stat_observe("serving.staleness_s", staleness)
        stat_observe("serving.patch_s", time.monotonic() - t0)
        flight.record("serving_delta_flip", generation=head,
                      prev_generation=old.generation,
                      chain=len(chain), incremental=incremental,
                      rows=frozen.size(), drained=drained,
                      staleness_s=round(staleness, 3))

    def watch_ckpt(self, root: Optional[str] = None,
                   poll_s: Optional[float] = None) -> None:
        """Stream save_pass delta generations from a TrainCheckpoint
        under ``root`` (default: the ctor's ckpt_root): poll the
        committed head every FLAGS_serving_patch_poll_s and flip patched
        plane sets in as it advances — online-learned rows reach
        inference one poll interval after they commit
        (``serving.staleness_s``)."""
        from paddlebox_tpu.io.checkpoint import TrainCheckpoint
        with self._swap_lock:
            root = self._ckpt_root if root is None else root
            if root is None:
                raise ValueError("watch_ckpt needs a ckpt root (ctor "
                                 "ckpt_root= or the root argument)")
            self._ckpt_root = root
        ck = TrainCheckpoint(root)
        cadence = float(flags.get_flags("serving_patch_poll_s")
                        if poll_s is None else poll_s)

        def run() -> None:
            while not self._watch_stop.wait(cadence):
                try:
                    self._poll_ckpt(ck)
                except Exception:  # noqa: BLE001 — the watcher must outlive a bad gen
                    stat_add("serving.watch_errors")

        # pboxlint: disable-next=PB405 -- joined in shutdown() via _watch_stop
        self._watch_thread = threading.Thread(
            target=run, name="pbox-serving-ckpt-watch", daemon=True)
        self._watch_thread.start()

    def shutdown(self, drain_timeout: float = 5.0) -> None:
        self._watch_stop.set()
        t = self._watch_thread
        if t is not None:
            t.join(timeout=5.0)
        super().shutdown(drain_timeout)

    def kill(self) -> None:
        self._watch_stop.set()      # abrupt death: no join, but no swaps
        super().kill()

    # -- verb surface ---------------------------------------------------------
    def _exec_verb(self, req: Dict) -> Dict:
        cmd = req["cmd"]
        if cmd == "health":
            return self._health_verb()
        if cmd == "swap":
            return self._swap_verb(req)
        if cmd in _READ_VERBS:
            return self._serve_read(req)
        return {"ok": False, "readonly": True,
                "error": f"serving replica: verb {cmd!r} not available "
                         f"on the read-only tier (reads: "
                         f"{sorted(_READ_VERBS)}, control: health/swap)"}

    def _swap_verb(self, req: Dict) -> Dict:
        gen = self.hot_swap(req["path"], day=str(req.get("day", "")),
                            generation=req.get("generation"),
                            drain_timeout=req.get("drain_timeout"))
        return {"ok": True, "generation": gen}

    def _health_verb(self) -> Dict:
        with self._inflight_cv:
            inflight = self._inflight
        g = self._gen
        with self._adm_lock:
            per_tenant = dict(self._tenant_inflight)
        out = {"ok": True, "mode": "serving", "draining": self._draining,
               "inflight": inflight,
               "generation": g.generation, "day": g.day,
               "shard": self._shard, "n_shards": self.n_shards,
               "hot_keys": ",".join(str(int(k)) for k in self._hot_keys),
               "tenants": ",".join(self.tenants),
               "tenant_inflight": per_tenant,
               "tables": ",".join(sorted(g.tables)),
               "stats": {k: float(v)
                         for k, v in stat_snapshot("serving.").items()}}
        hs = heat.summary()
        if hs is not None:
            out["heat"] = hs
        return out

    def _serve_read(self, req: Dict) -> Dict:
        """THE serving read path — lint rule PB701 proves no
        table-mutating verb, shard-lock acquisition, or optimizer call
        is transitively reachable from here."""
        cmd = req["cmd"]
        name = req.get("table") or DEFAULT_TABLE
        tenant = name.split("/", 1)[0] if "/" in name else "default"
        metered = cmd in _METERED_VERBS
        cap = self._max_inflight
        with self._adm_lock:
            cur = self._tenant_inflight.get(tenant)
            if cur is None:
                return {"ok": False,
                        "error": f"unknown tenant {tenant!r} (serving "
                                 f"{sorted(self._tenant_inflight)})"}
            if metered and cap > 0 and cur >= cap:
                stat_add(f"serving.{tenant}.shed")
                return {"ok": False, "shed": True, "tenant": tenant,
                        "error": f"{OVERLOADED}: tenant {tenant!r} at "
                                 f"max inflight {cap}"}
            self._tenant_inflight[tenant] = cur + 1
        stat_set(f"serving.{tenant}.inflight", float(cur + 1))
        g = self._gen
        g.enter()
        t0 = time.monotonic()
        try:
            tab = g.tables.get(name)
            if tab is None:
                return {"ok": False,
                        "error": f"unknown table {name!r} "
                                 f"(have {sorted(g.tables)})"}
            if cmd == "size":
                return {"ok": True, "size": tab.size(),
                        "generation": g.generation}
            if cmd == "list_tables":
                return {"ok": True, "generation": g.generation,
                        "tables": {n: t.size()
                                   for n, t in g.tables.items()}}
            if self.n_shards > 1:
                # misrouted keys would silently serve miss-defaults
                # instead of their owner shard's rows — reject typed so
                # the router bug surfaces, never corrupt
                bad = ~self._row_mask(req["keys"])
                if bad.any():
                    stat_add("serving.not_owner")
                    return {"ok": False, "not_owner": True,
                            "error": f"not_owner: {int(bad.sum())} keys "
                                     f"outside shard {self._shard}/"
                                     f"{self.n_shards} + hot set"}
            if heat.ACTIVE is not None:
                heat.ACTIVE.observe(f"serve.{tenant}", req["keys"])
            if cmd == "forward":
                pooled = self._forward(tab, req["keys"], req["lod"])
                return {"ok": True, "pooled": pooled,
                        "generation": g.generation}
            rows = tab.lookup_rows(req["keys"])
            wd = req.get("wire_dtype")
            if wd and wd != "f32":
                rows = wire.quantize_rows(rows, wd, verb="pull_sparse")
            return {"ok": True, "rows": rows, "generation": g.generation}
        finally:
            g.exit()
            if metered:
                stat_add(f"serving.{tenant}.qps")
                stat_observe(f"serving.{tenant}.latency_s",
                             time.monotonic() - t0)
            with self._adm_lock:
                self._tenant_inflight[tenant] -= 1
                left = self._tenant_inflight[tenant]
            stat_set(f"serving.{tenant}.inflight", float(left))

    @staticmethod
    def _forward(tab: FrozenHostTable, keys: np.ndarray,
                 lod: np.ndarray) -> np.ndarray:
        """Ragged inference pool: per-sample sum over [embed_w | mf] of
        that sample's keys (``lod`` = n+1 offsets into ``keys``) — the
        batched gather+pool kernel shape of sparse-CTR serving."""
        return _pool_rows(tab.lookup_rows(keys), lod)


def _pool_rows(rows: Dict[str, np.ndarray], lod: np.ndarray) -> np.ndarray:
    """THE forward pool kernel, shared by the replica verb and the
    router's client-side pooling over sharded pulls: exact segment sums
    via f64 prefix differences (reduceat mishandles empty segments).
    One implementation is what keeps an N-shard fleet's ``forward``
    bit-identical to a single full-table replica's — the rows are merged
    back into caller key order BEFORE pooling, so the cumsum walks the
    exact same sequence either way (f64 addition is not reorderable)."""
    emb = np.concatenate([rows["embed_w"][:, None], rows["mf"]], axis=1)
    lod = np.asarray(lod, np.int64)
    csum = np.concatenate(
        [np.zeros((1, emb.shape[1]), np.float64),
         np.cumsum(emb.astype(np.float64), axis=0)], axis=0)
    return (csum[lod[1:]] - csum[lod[:-1]]).astype(np.float32)


class ServingRouter:
    """Client-side fan-over across serving replicas: primary-first with
    failover on replica death.  ``pull_sparse``/``forward`` are rid-echo
    idempotent verbs and every replica of one generation answers
    bit-identically, so a failover retry cannot duplicate or corrupt a
    query — exactly one response per query, byte-equal to a
    single-replica run.  Shed (:data:`OVERLOADED` in the error) raises
    the typed :class:`ServingOverload` instead of failing over: the
    fleet is alive, the tenant is just over budget.

    **Sharded mode** (``shard_groups``): group k's replicas own shard k
    of the splitmix64 key space (ServingReplica ``shard=k, n_shards=N``).
    ``pull_sparse`` fans per shard through ONE multi-address
    :class:`PSClient` over the current group primaries — ps/cluster.py's
    partition, shared inflight budget, and order-preserving position
    merge apply wholesale, and group order IS shard order, so the fan
    client's ServerMap routes each key to its owner group.  Keys in the
    router's replicated hot set instead route power-of-two-choices over
    live per-group (outstanding, latency-EWMA) load, spreading one hot
    key's traffic across the whole fleet.  ``forward`` pulls routed and
    pools client-side with the replica's exact kernel (:func:`_pool_rows`)
    — N-shard answers stay bit-identical to a full-table replica.  A
    dead primary rotates to a probed-live group member (supervisors
    restart in place, so primaries also come back)."""

    def __init__(self, addrs: Optional[Sequence[Tuple[str, int]]] = None,
                 tenant: str = "default",
                 shard_groups: Optional[
                     Sequence[Sequence[Tuple[str, int]]]] = None,
                 hot_keys: Optional[np.ndarray] = None,
                 seed: int = 0, **client_kwargs):
        client_kwargs.setdefault("retries", 1)
        client_kwargs.setdefault("deadline", 10.0)
        self.tenant = tenant
        self._client_kwargs = dict(client_kwargs)
        self._lock = lockdep.lock("ps.serving.ServingRouter._lock")
        self._last_generation: Optional[int] = None
        self.sharded = shard_groups is not None
        if not self.sharded:
            if addrs is None:
                raise ValueError("ServingRouter needs addrs or "
                                 "shard_groups")
            self._clients = [PSClient(tuple(a), **client_kwargs)
                             for a in addrs]
            self._dead = [False] * len(self._clients)
            self._primary = 0
            return
        self._groups = [[tuple(a) for a in g] for g in shard_groups]
        if not self._groups or not all(self._groups):
            raise ValueError("shard_groups must be non-empty groups of "
                             "replica addrs (group k = shard k)")
        n = len(self._groups)
        self._gprimary = [0] * n
        self._gdead = [[False] * len(g) for g in self._groups]
        self._gload = [0] * n                 # outstanding hot routes
        self._gewma = [0.0] * n               # hot-route latency EWMA (s)
        self._rng = random.Random(seed)
        self._hot = (np.sort(np.asarray(hot_keys, np.uint64))
                     if hot_keys is not None else np.zeros(0, np.uint64))
        self._gclients = [PSClient(self._groups[g][0], **client_kwargs)
                          for g in range(n)]
        self._fan_client = PSClient(
            [self._groups[g][0] for g in range(n)], **client_kwargs)

    def _order(self) -> List[Tuple[int, PSClient]]:
        with self._lock:
            idxs = list(range(len(self._clients)))
            order = idxs[self._primary:] + idxs[:self._primary]
            return [(i, self._clients[i]) for i in order
                    if not self._dead[i]]

    def _mark_dead(self, idx: int) -> None:
        with self._lock:
            self._dead[idx] = True
            live = [i for i in range(len(self._clients))
                    if not self._dead[i]]
            if live:
                self._primary = live[0]

    def _qualify(self, table: Optional[str]) -> str:
        name = table or DEFAULT_TABLE
        return name if "/" in name else f"{self.tenant}/{name}"

    def _resurrect(self) -> bool:
        """Second-chance probe when the live set is empty: a supervisor
        (launch.ServingReplicaSupervisor) restarts a dead replica IN
        PLACE on the same port, so a dead address can come back.  Each
        dead slot gets a fresh client (the old one's sockets died with
        the peer) and a health probe; responders rejoin the rotation."""
        with self._lock:
            dead = [(i, self._clients[i].addr)
                    for i, d in enumerate(self._dead) if d]
        revived = False
        for i, addr in dead:
            probe = PSClient(addr, **self._client_kwargs)
            try:
                probe.health(timeout=2.0)
            except (ConnectionError, RuntimeError, OSError):
                probe.close()
                continue
            with self._lock:
                self._clients[i].close()
                self._clients[i] = probe
                self._dead[i] = False
            stat_add("serving.router.resurrect")
            flight.record("serving_resurrect", replica=i)
            revived = True
        return revived

    def _fan(self, call, verb: str):
        errs: List[str] = []
        for attempt in range(2):
            for i, c in self._order():
                try:
                    return call(c)
                except ConnectionError as e:
                    self._mark_dead(i)
                    stat_add("serving.router.failover")
                    flight.record("serving_failover", replica=i,
                                  verb=verb, error=type(e).__name__)
                    errs.append(f"replica[{i}]: {e}")
                    continue
                except RuntimeError as e:
                    if OVERLOADED in str(e):
                        stat_add("serving.router.shed")
                        raise ServingOverload(str(e)) from e
                    raise
            if attempt == 0 and not self._resurrect():
                break
        raise ConnectionError(
            f"all serving replicas failed for {verb!r}: "
            + ("; ".join(errs) or "none alive"))

    # -- sharded-mode plumbing ------------------------------------------------
    def _rebuild_fan(self) -> None:
        """Swap the fan client to the CURRENT group primaries (after a
        rotation).  In-flight calls on the old client finish or raise on
        their own sockets; it is closed once replaced."""
        with self._lock:
            prims = [self._groups[g][self._gprimary[g]]
                     for g in range(len(self._groups))]
            old, self._fan_client = self._fan_client, PSClient(
                prims, **self._client_kwargs)
        old.close()

    def _g_recover(self) -> bool:
        """Probe every group: a dead current primary rotates to a
        probed-live member (fresh client — the old one's sockets died
        with the peer); a previously-dead member that answers rejoins.
        Supervisors restart replicas IN PLACE on the same port, so a
        fully-dead group heals on a later pass.  Rebuilds the fan client
        when any primary moved."""
        rotated = False
        for g in range(len(self._groups)):
            with self._lock:
                p = self._gprimary[g]
                addr = self._groups[g][p]
            probe = PSClient(addr, **self._client_kwargs)
            try:
                probe.health(timeout=2.0)
                probe.close()
                with self._lock:
                    self._gdead[g][p] = False
                continue
            except (ConnectionError, RuntimeError, OSError):
                probe.close()
            with self._lock:
                self._gdead[g][p] = True
                members = len(self._groups[g])
            for m in range(members):
                if m == p:
                    continue
                cand = PSClient(self._groups[g][m], **self._client_kwargs)
                try:
                    cand.health(timeout=2.0)
                except (ConnectionError, RuntimeError, OSError):
                    cand.close()
                    with self._lock:
                        self._gdead[g][m] = True
                    continue
                with self._lock:
                    self._gprimary[g] = m
                    self._gdead[g][m] = False
                    old = self._gclients[g]
                    self._gclients[g] = cand
                old.close()
                stat_add("serving.router.failover")
                flight.record("serving_failover", group=g, member=m)
                rotated = True
                break
        if rotated:
            self._rebuild_fan()
        return rotated

    def _g_call(self, call, verb: str):
        """Sharded-mode call wrapper: failover-recover-retry on
        ConnectionError, typed shed passthrough."""
        errs: List[str] = []
        for _ in range(3):
            try:
                return call()
            except ConnectionError as e:
                errs.append(str(e))
                stat_add("serving.router.failover")
                self._g_recover()
                continue
            except RuntimeError as e:
                if OVERLOADED in str(e):
                    stat_add("serving.router.shed")
                    raise ServingOverload(str(e)) from e
                raise
        raise ConnectionError(
            f"sharded serving fleet failed for {verb!r}: "
            + "; ".join(errs))

    def _p2c(self) -> int:
        """Power-of-two-choices over live groups: sample two, take the
        lower (outstanding, latency-EWMA) — the classic load-balance
        result: near-best-of-N balance at O(1) probes."""
        with self._lock:
            live = [g for g in range(len(self._groups))
                    if not all(self._gdead[g])]
            if not live:
                live = list(range(len(self._groups)))
            if len(live) == 1:
                return live[0]
            a, b = self._rng.sample(live, 2)
            ka = (self._gload[a], self._gewma[a])
            kb = (self._gload[b], self._gewma[b])
            return a if ka <= kb else b

    def _hot_route(self, hkeys: np.ndarray,
                   full: str) -> Dict[str, np.ndarray]:
        """Route replicated hot keys to a p2c-chosen group (ANY group
        holds them), tracking per-group outstanding + latency EWMA.  A
        replica whose replicated set lags ours answers not_owner — we
        re-learn the fleet's common set and fall back to owner routing
        (a hot key's owner always serves it)."""
        for _ in range(2):
            g = self._p2c()
            with self._lock:
                self._gload[g] += 1
            t0 = time.monotonic()
            try:
                rows = self._gclients[g].pull_sparse(hkeys, table=full)
                stat_add("serving.router.hot_routed")
                if heat.ACTIVE is not None:
                    heat.ACTIVE.observe_shard(g, len(hkeys))
                return rows
            except ConnectionError:
                stat_add("serving.router.failover")
                self._g_recover()
                continue
            except RuntimeError as e:
                if OVERLOADED in str(e):
                    stat_add("serving.router.shed")
                    raise ServingOverload(str(e)) from e
                if "not_owner" in str(e):
                    stat_add("serving.router.hot_stale")
                    self.refresh_hot_keys()
                    break
                raise
            finally:
                dt = time.monotonic() - t0
                with self._lock:
                    self._gload[g] -= 1
                    self._gewma[g] = 0.8 * self._gewma[g] + 0.2 * dt
        return self._g_call(
            lambda: self._fan_client.pull_sparse(hkeys, table=full),
            "pull_sparse")

    def _pull_sharded(self, keys: np.ndarray,
                      full: str) -> Dict[str, np.ndarray]:
        keys = np.asarray(keys, np.uint64)
        hot = self._hot
        if len(hot) and len(keys):
            p = np.minimum(np.searchsorted(hot, keys), len(hot) - 1)
            hm = hot[p] == keys
        else:
            hm = np.zeros(len(keys), bool)
        hot_pos = np.flatnonzero(hm)
        cold_pos = np.flatnonzero(~hm)
        parts: List[Tuple[np.ndarray, Dict[str, np.ndarray]]] = []
        if len(cold_pos):
            parts.append((cold_pos, self._g_call(
                lambda: self._fan_client.pull_sparse(keys[cold_pos],
                                                     table=full),
                "pull_sparse")))
        if len(hot_pos):
            parts.append((hot_pos, self._hot_route(keys[hot_pos], full)))
        if len(parts) == 1 and len(parts[0][0]) == len(keys):
            return parts[0][1]
        if not parts:
            return self._g_call(
                lambda: self._fan_client.pull_sparse(keys, table=full),
                "pull_sparse")
        # position merge back into caller key order (bit-exact: each row
        # lands at the index its key came from)
        out: Dict[str, np.ndarray] = {}
        for f, a in parts[0][1].items():
            out[f] = np.empty((len(keys),) + a.shape[1:], a.dtype)
        for pos, rows in parts:
            for f, a in rows.items():
                out[f][pos] = a
        return out

    def refresh_hot_keys(self) -> int:
        """Adopt the intersection of the live groups' replicated hot
        sets from fleet health (a key may route anywhere only when EVERY
        group replicates it).  Returns the adopted set size; keeps the
        current set when any group is unreachable (a partial view could
        adopt keys a silent group lacks)."""
        if not self.sharded:
            return 0
        arrs: List[np.ndarray] = []
        for h in self.health():
            if h is None:
                return len(self._hot)
            s = str(h.get("hot_keys", ""))
            arrs.append(np.array([int(x) for x in s.split(",") if x],
                                 np.uint64))
        common = arrs[0]
        for a in arrs[1:]:
            common = np.intersect1d(common, a)
        with self._lock:
            self._hot = common.astype(np.uint64)
        stat_set("serving.router.hot_keys", float(len(common)))
        return len(common)

    # -- verbs ---------------------------------------------------------------
    def pull_sparse(self, keys: np.ndarray,
                    table: Optional[str] = None) -> Dict[str, np.ndarray]:
        full = self._qualify(table)
        if self.sharded:
            return self._pull_sharded(keys, full)
        return self._fan(lambda c: c.pull_sparse(keys, table=full),
                         "pull_sparse")

    def forward(self, keys: np.ndarray, lod: np.ndarray,
                table: Optional[str] = None) -> np.ndarray:
        full = self._qualify(table)
        if self.sharded:
            # routed pull (owner shards + p2c hot routes), then the
            # replica's exact pool kernel client-side: bit-identical to
            # one full-table replica's forward
            return _pool_rows(self._pull_sharded(keys, full), lod)
        return self._fan(lambda c: c.forward(keys, lod, table=full),
                         "forward")

    def health(self) -> List[Optional[Dict]]:
        """Per-replica health (None for dead/unreachable replicas) —
        mixed ``generation`` values across live replicas expose a
        half-finished fleet hot-swap.  Sharded mode reports one entry
        per GROUP (its current primary)."""
        if self.sharded:
            for attempt in range(2):
                out: List[Optional[Dict]] = []
                for g in range(len(self._groups)):
                    try:
                        out.append(self._gclients[g].health(timeout=2.0))
                    except (ConnectionError, RuntimeError, OSError):
                        out.append(None)
                if attempt == 0 and any(h is None for h in out) \
                        and self._g_recover():
                    continue            # a primary rotated: re-probe once
                return out
            return out
        with self._lock:
            any_dead = any(self._dead)
        if any_dead:
            self._resurrect()
        out = []
        for i, c in enumerate(self._clients):
            with self._lock:
                dead = self._dead[i]
            if dead:
                out.append(None)
                continue
            try:
                out.append(c.health(timeout=2.0))
            except (ConnectionError, RuntimeError, OSError):
                self._mark_dead(i)
                out.append(None)
        return out

    def generations(self) -> List[int]:
        """Distinct loaded generations across live replicas (len > 1 ⇒
        a hot-swap is in flight somewhere)."""
        gens = {int(h["generation"]) for h in self.health()
                if h and "generation" in h}
        return sorted(gens)

    def observe_generation(self) -> bool:
        """Client-side hot-swap coherence point: when the fleet's max
        generation advances past the last one seen, drop every client's
        learned row-width estimates (a new day's dump may change row
        widths; a stale estimate would mis-chunk the first pull).
        Returns True when an advance was observed."""
        gens = self.generations()
        if not gens:
            return False
        head = gens[-1]
        with self._lock:
            last = self._last_generation
            self._last_generation = head
        if last is not None and head > last:
            for c in self._all_clients():
                c.invalidate_row_width()
            stat_add("serving.router.gen_advance")
            return True
        return False

    def _all_clients(self) -> List[PSClient]:
        if self.sharded:
            with self._lock:
                return [self._fan_client] + list(self._gclients)
        return list(self._clients)

    def close(self) -> None:
        for c in self._all_clients():
            c.close()
