"""Online serving tier — read-only xbox replicas, atomic day hot-swap,
multi-tenant inference traffic (ROADMAP item 3: the BoxPS loop's third
leg, train → dump → **serve**).

The reference feeds a serving fleet from the xbox base/delta dumps
(box_wrapper.cc:1286 SaveBase/SaveDelta); this module is the consumer
side.  Three pieces:

* :class:`FrozenHostTable` — an immutable snapshot of a
  ``ShardedHostTable``: keys sorted once at load, SoA row arrays frozen,
  lookups are pure numpy ``searchsorted`` gathers.  **No shard locks on
  the read path** (lint rule PB701 proves no table-mutating verb, shard
  lock, or optimizer call is reachable from it); misses serve the same
  key-deterministic defaults training would (``fv.default_rows_keyed``),
  so replica responses are bit-identical to an engine-side pull.

* :class:`ServingReplica` — a :class:`~paddlebox_tpu.ps.service.PSServer`
  whose verb switch is replaced with a read-only serving surface over
  the same wire protocol (so ``PSClient``'s multi-stream pipelining,
  rids, and quantized payloads all apply unchanged): batched
  ``pull_sparse``, a ragged ``forward`` (per-sample sum-pool over
  [embed_w | mf] — the gather+pool inference kernel shape), ``size`` /
  ``list_tables`` / extended ``health``, and a ``swap`` control verb.
  Tables are namespaced ``<tenant>/<table>`` (≙ PSCORE's table
  hierarchy); per-tenant admission control bounds in-flight queries and
  sheds with a typed overload error (:data:`OVERLOADED` marker, so the
  router can tell shed from death); per-tenant
  ``serving.<tenant>.{qps,latency_s→p50/p99,inflight,shed}`` flow
  through the obs stack (/statz, timeline sampler, SLO watchdog).

  **Hot swap**: ``hot_swap(path)`` loads the next day's dump into a
  fresh generation off the serving path, flips one reference (a single
  attribute store — readers that already entered the old generation
  finish on its frozen tables), invalidates the attached DeviceRowCache
  at the flip, then retires the old generation after its in-flight
  queries drain.  The dump itself arrives via save_xbox's tmp+rename,
  and the day pointer via the xbox swap manifest
  (io/checkpoint.publish_xbox_manifest) — tmp+rename end to end; a
  replica watching the manifest (``watch_manifest``) swaps on a
  generation advance.

* :class:`ServingRouter` — client-side fan-over: one ``PSClient`` per
  replica, primary-first with failover on replica death
  (``pull_sparse``/``forward`` are rid-echo idempotent verbs, and
  replicas loaded from one dump answer bit-identically, so a retry on
  the survivor is safe and exact).  A typed :class:`ServingOverload`
  surfaces shed instead of blind retry; ``observe_generation`` clears
  every client's learned row-width estimates when the fleet's
  generation advances (the client side of the hot-swap coherence
  point).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu import flags
from paddlebox_tpu.config import EmbeddingTableConfig
from paddlebox_tpu.ps import feature_value as fv
from paddlebox_tpu.ps import heat
from paddlebox_tpu.ps import wire
from paddlebox_tpu.ps.host_table import ShardedHostTable
from paddlebox_tpu.ps.service import DEFAULT_TABLE, PSClient, PSServer
from paddlebox_tpu.utils import flight, lockdep
from paddlebox_tpu.utils.monitor import (stat_add, stat_observe, stat_set,
                                         stat_snapshot)

flags.define_flag(
    "serve_max_inflight", 64,
    "per-tenant admission cap on a ServingReplica: queries in flight for "
    "one tenant beyond this shed with a typed overload error instead of "
    "queueing (0 = unbounded)")
flags.define_flag(
    "serve_tenants", "default",
    "comma-separated tenant namespaces a ServingReplica serves; each "
    "tenant sees the loaded tables as <tenant>/<table> and gets its own "
    "admission budget + serving.<tenant>.* metrics")
flags.define_flag(
    "serve_drain_s", 30.0,
    "hot-swap drain budget: seconds to wait for the old generation's "
    "in-flight queries before retiring it (the flip itself is atomic "
    "and never waits)")

# marker embedded in the shed error string: it survives the wire and the
# client's RuntimeError re-raise, so a router can type the failure
# without a schema change to the error path
OVERLOADED = "serving_overloaded"

_METERED_VERBS = frozenset({"pull_sparse", "forward"})
_READ_VERBS = frozenset({"pull_sparse", "forward", "size", "list_tables"})


class ServingOverload(RuntimeError):
    """Per-tenant admission shed — the replica is alive but this tenant
    is at its in-flight cap.  Deliberately NOT a ConnectionError: a shed
    must not trigger failover/retry storms against the next replica."""


class FrozenHostTable:
    """Immutable lookup-only snapshot of one embedding table.

    Built once at load (sort by key, copy the SoA into contiguous
    arrays); after that every ``lookup_rows`` is a pure numpy gather —
    no locks, no growth, no mutation surface at all.  Swaps replace the
    whole object by one reference flip.  Misses get the identical
    key-deterministic defaults a training-side ``bulk_pull`` would
    (``fv.default_rows_keyed`` with the same config + seed), which is
    what makes replica responses bit-identical to the engine."""

    def __init__(self, config: EmbeddingTableConfig, keys: np.ndarray,
                 soa: Dict[str, np.ndarray], seed: int = 0):
        self.config = config
        self.mf_dim = config.embedding_dim
        self.expand_dim = config.expand_dim
        self.adam = config.sgd.optimizer in ("adam", "shared_adam")
        self.optimizer = config.sgd.optimizer
        self.double_stats = config.accessor.accessor_type == "ctr_double"
        self._seed = seed
        keys = np.asarray(keys, np.uint64)
        order = np.argsort(keys, kind="stable")
        self._keys = np.ascontiguousarray(keys[order])
        self._soa = {f: np.ascontiguousarray(a[order])
                     for f, a in soa.items()}

    @classmethod
    def freeze(cls, table: ShardedHostTable) -> "FrozenHostTable":
        """Snapshot a live ShardedHostTable (load/control path — this
        DOES take the shard locks once; the resulting object never
        does)."""
        keys = table.export_keys()
        soa = table.bulk_pull(keys)
        return cls(table.config, keys, soa, seed=table._seed)

    def size(self) -> int:
        return int(len(self._keys))

    def lookup_rows(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        """Rows for ``keys`` — resident rows from the frozen snapshot,
        misses as key-deterministic defaults.  Lock-free by
        construction: every array here is immutable after __init__."""
        keys = np.asarray(keys, np.uint64)
        out = fv.default_rows_keyed(keys, self.mf_dim, self._seed,
                                    self.config.sgd.mf_initial_range,
                                    self.config.sgd.initial_range,
                                    self.expand_dim, self.adam,
                                    self.config.sgd.beta1_decay_rate,
                                    self.config.sgd.beta2_decay_rate,
                                    self.optimizer, self.double_stats)
        if len(self._keys) and len(keys):
            pos = np.searchsorted(self._keys, keys)
            pos = np.minimum(pos, len(self._keys) - 1)
            found = self._keys[pos] == keys
            if found.any():
                src = pos[found]
                for f, arr in self._soa.items():
                    out[f][found] = arr[src]
        return out


class _Generation:
    """One loaded day: the frozen table namespace plus an in-flight
    counter so a hot swap can retire it only after the queries that
    entered it drain (readers grab the generation BEFORE touching its
    tables and exit in a finally)."""

    def __init__(self, tables: Dict[str, FrozenHostTable],
                 generation: int, day: str):
        self.tables = tables
        self.generation = int(generation)
        self.day = day
        self._inflight = 0
        self._cv = lockdep.condition("ps.serving._Generation._cv")

    def enter(self) -> None:
        with self._cv:
            self._inflight += 1

    def exit(self) -> None:
        with self._cv:
            self._inflight -= 1
            self._cv.notify_all()

    def drain(self, timeout: float) -> bool:
        """Wait for in-flight queries to reach zero; False on timeout
        (the straggler still holds its table references — retirement is
        reference-drop, never destruction, so it stays safe)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._inflight > 0:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    return False
                self._cv.wait(rem)
        return True


class _LoadTarget:
    """Minimal engine shim for io.checkpoint.load_xbox: a serving-mode
    loader writing into a scratch ShardedHostTable that is frozen and
    dropped right after (the replica never exposes the mutable table)."""

    def __init__(self, config: EmbeddingTableConfig, seed: int):
        self.mode = "serving"
        self.config = config
        self.table = ShardedHostTable(config, seed=seed)
        self.cache = None


class ServingReplica(PSServer):
    """Read-only PSServer serving frozen xbox generations (docstring at
    module top).  Construct with the day-1 dump, then ``hot_swap`` (or
    the ``swap`` wire verb / ``watch_manifest``) to later days."""

    def __init__(self, config: Optional[EmbeddingTableConfig] = None,
                 xbox_path: Optional[str] = None,
                 tenants: Optional[Sequence[str]] = None,
                 max_inflight: Optional[int] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 day: str = "", generation: int = 1,
                 seed: int = 0, dedup_state=None):
        self._config = config or EmbeddingTableConfig()
        self._seed = seed
        heat.maybe_enable_from_flags()
        if tenants is None:
            tenants = [t.strip() for t in
                       str(flags.get_flags("serve_tenants")).split(",")
                       if t.strip()]
        self.tenants: List[str] = list(tenants) or ["default"]
        self._max_inflight = int(
            flags.get_flags("serve_max_inflight")
            if max_inflight is None else max_inflight)
        self._adm_lock = lockdep.lock("ps.serving.ServingReplica._adm_lock")
        self._tenant_inflight = {t: 0 for t in self.tenants}
        self._swap_lock = lockdep.lock("ps.serving.ServingReplica._swap_lock")
        self._swapping = False
        # optional DeviceRowCache hook: a co-resident forward model's row
        # cache registered here is invalidated at every swap point
        self.cache = None
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        gen0 = self._build_generation(xbox_path, day, int(generation))
        self._gen = gen0
        super().__init__(gen0.tables, host=host, port=port,
                         dedup_state=dedup_state)
        self.mode = "serving"

    # -- generation load / swap ----------------------------------------------
    def _build_generation(self, xbox_path: Optional[str], day: str,
                          generation: int) -> _Generation:
        t0 = time.monotonic()
        if xbox_path:
            from paddlebox_tpu.io.checkpoint import load_xbox
            shim = _LoadTarget(self._config, self._seed)
            load_xbox(shim, xbox_path)
            frozen = FrozenHostTable.freeze(shim.table)
        else:
            frozen = FrozenHostTable.freeze(
                ShardedHostTable(self._config, seed=self._seed))
        tables: Dict[str, FrozenHostTable] = {DEFAULT_TABLE: frozen}
        for t in self.tenants:
            tables[f"{t}/{DEFAULT_TABLE}"] = frozen
        g = _Generation(tables, generation, day)
        stat_set("serving.generation", float(generation))
        stat_observe("serving.load_s", time.monotonic() - t0)
        flight.record("serving_load", generation=generation, day=day,
                      rows=frozen.size(), source=xbox_path or "<empty>")
        return g

    def hot_swap(self, xbox_path: str, day: str = "",
                 generation: Optional[int] = None,
                 drain_timeout: Optional[float] = None) -> int:
        """Load ``xbox_path`` as the next generation, flip atomically,
        retire the old generation after its in-flight queries drain.
        Serialized against concurrent swaps; the flip never blocks the
        serving path (readers see either generation whole)."""
        with self._swap_lock:
            if self._swapping:
                raise RuntimeError("hot_swap already in progress")
            self._swapping = True
        try:
            cur = self._gen
            gen_no = (cur.generation + 1 if generation is None
                      else int(generation))
            new = self._build_generation(xbox_path, day, gen_no)
            with self._swap_lock:
                old = self._gen
                # THE swap: one reference store.  A reader that already
                # did `g = self._gen; g.enter()` finishes on `old`'s
                # frozen tables; every later reader sees `new`.
                self._gen = new
                self.tables = dict(new.tables)
            cache = self.cache
            if cache is not None:
                # coherence point: any device-resident rows mirror the
                # RETIRED generation now
                cache.invalidate("serving_swap")
        finally:
            with self._swap_lock:
                self._swapping = False
        budget = float(flags.get_flags("serve_drain_s")
                       if drain_timeout is None else drain_timeout)
        drained = old.drain(budget)
        stat_add("serving.swap")
        if not drained:
            stat_add("serving.swap_drain_timeout")
        flight.record("serving_swap", generation=gen_no, day=day,
                      prev_generation=old.generation, drained=drained)
        return gen_no

    def watch_manifest(self, root: str, poll_s: float = 2.0) -> None:
        """Poll the xbox swap manifest under ``root`` and hot-swap when
        its generation advances past the loaded one (the replica side of
        the train→publish→serve day loop)."""
        from paddlebox_tpu.io.checkpoint import read_xbox_manifest

        def run() -> None:
            while not self._watch_stop.wait(poll_s):
                try:
                    man = read_xbox_manifest(root)
                    if man and int(man["generation"]) > self._gen.generation:
                        self.hot_swap(man["path"],
                                      day=str(man.get("day", "")),
                                      generation=int(man["generation"]))
                except Exception:  # noqa: BLE001 — the watcher must outlive a bad day
                    stat_add("serving.watch_errors")

        # pboxlint: disable-next=PB405 -- joined in shutdown() via _watch_stop
        self._watch_thread = threading.Thread(
            target=run, name="pbox-serving-watch", daemon=True)
        self._watch_thread.start()

    def shutdown(self, drain_timeout: float = 5.0) -> None:
        self._watch_stop.set()
        t = self._watch_thread
        if t is not None:
            t.join(timeout=5.0)
        super().shutdown(drain_timeout)

    def kill(self) -> None:
        self._watch_stop.set()      # abrupt death: no join, but no swaps
        super().kill()

    # -- verb surface ---------------------------------------------------------
    def _exec_verb(self, req: Dict) -> Dict:
        cmd = req["cmd"]
        if cmd == "health":
            return self._health_verb()
        if cmd == "swap":
            return self._swap_verb(req)
        if cmd in _READ_VERBS:
            return self._serve_read(req)
        return {"ok": False, "readonly": True,
                "error": f"serving replica: verb {cmd!r} not available "
                         f"on the read-only tier (reads: "
                         f"{sorted(_READ_VERBS)}, control: health/swap)"}

    def _swap_verb(self, req: Dict) -> Dict:
        gen = self.hot_swap(req["path"], day=str(req.get("day", "")),
                            generation=req.get("generation"),
                            drain_timeout=req.get("drain_timeout"))
        return {"ok": True, "generation": gen}

    def _health_verb(self) -> Dict:
        with self._inflight_cv:
            inflight = self._inflight
        g = self._gen
        with self._adm_lock:
            per_tenant = dict(self._tenant_inflight)
        out = {"ok": True, "mode": "serving", "draining": self._draining,
               "inflight": inflight,
               "generation": g.generation, "day": g.day,
               "tenants": ",".join(self.tenants),
               "tenant_inflight": per_tenant,
               "tables": ",".join(sorted(g.tables)),
               "stats": {k: float(v)
                         for k, v in stat_snapshot("serving.").items()}}
        hs = heat.summary()
        if hs is not None:
            out["heat"] = hs
        return out

    def _serve_read(self, req: Dict) -> Dict:
        """THE serving read path — lint rule PB701 proves no
        table-mutating verb, shard-lock acquisition, or optimizer call
        is transitively reachable from here."""
        cmd = req["cmd"]
        name = req.get("table") or DEFAULT_TABLE
        tenant = name.split("/", 1)[0] if "/" in name else "default"
        metered = cmd in _METERED_VERBS
        cap = self._max_inflight
        with self._adm_lock:
            cur = self._tenant_inflight.get(tenant)
            if cur is None:
                return {"ok": False,
                        "error": f"unknown tenant {tenant!r} (serving "
                                 f"{sorted(self._tenant_inflight)})"}
            if metered and cap > 0 and cur >= cap:
                stat_add(f"serving.{tenant}.shed")
                return {"ok": False, "shed": True, "tenant": tenant,
                        "error": f"{OVERLOADED}: tenant {tenant!r} at "
                                 f"max inflight {cap}"}
            self._tenant_inflight[tenant] = cur + 1
        stat_set(f"serving.{tenant}.inflight", float(cur + 1))
        g = self._gen
        g.enter()
        t0 = time.monotonic()
        try:
            tab = g.tables.get(name)
            if tab is None:
                return {"ok": False,
                        "error": f"unknown table {name!r} "
                                 f"(have {sorted(g.tables)})"}
            if cmd == "size":
                return {"ok": True, "size": tab.size(),
                        "generation": g.generation}
            if cmd == "list_tables":
                return {"ok": True, "generation": g.generation,
                        "tables": {n: t.size()
                                   for n, t in g.tables.items()}}
            if heat.ACTIVE is not None:
                heat.ACTIVE.observe(f"serve.{tenant}", req["keys"])
            if cmd == "forward":
                pooled = self._forward(tab, req["keys"], req["lod"])
                return {"ok": True, "pooled": pooled,
                        "generation": g.generation}
            rows = tab.lookup_rows(req["keys"])
            wd = req.get("wire_dtype")
            if wd and wd != "f32":
                rows = wire.quantize_rows(rows, wd, verb="pull_sparse")
            return {"ok": True, "rows": rows, "generation": g.generation}
        finally:
            g.exit()
            if metered:
                stat_add(f"serving.{tenant}.qps")
                stat_observe(f"serving.{tenant}.latency_s",
                             time.monotonic() - t0)
            with self._adm_lock:
                self._tenant_inflight[tenant] -= 1
                left = self._tenant_inflight[tenant]
            stat_set(f"serving.{tenant}.inflight", float(left))

    @staticmethod
    def _forward(tab: FrozenHostTable, keys: np.ndarray,
                 lod: np.ndarray) -> np.ndarray:
        """Ragged inference pool: per-sample sum over [embed_w | mf] of
        that sample's keys (``lod`` = n+1 offsets into ``keys``) — the
        batched gather+pool kernel shape of sparse-CTR serving.  Exact
        segment sums via prefix differences (reduceat mishandles empty
        segments)."""
        rows = tab.lookup_rows(keys)
        emb = np.concatenate([rows["embed_w"][:, None], rows["mf"]], axis=1)
        lod = np.asarray(lod, np.int64)
        csum = np.concatenate(
            [np.zeros((1, emb.shape[1]), np.float64),
             np.cumsum(emb.astype(np.float64), axis=0)], axis=0)
        return (csum[lod[1:]] - csum[lod[:-1]]).astype(np.float32)


class ServingRouter:
    """Client-side fan-over across serving replicas: primary-first with
    failover on replica death.  ``pull_sparse``/``forward`` are rid-echo
    idempotent verbs and every replica of one generation answers
    bit-identically, so a failover retry cannot duplicate or corrupt a
    query — exactly one response per query, byte-equal to a
    single-replica run.  Shed (:data:`OVERLOADED` in the error) raises
    the typed :class:`ServingOverload` instead of failing over: the
    fleet is alive, the tenant is just over budget."""

    def __init__(self, addrs: Sequence[Tuple[str, int]],
                 tenant: str = "default", **client_kwargs):
        client_kwargs.setdefault("retries", 1)
        client_kwargs.setdefault("deadline", 10.0)
        self.tenant = tenant
        self._client_kwargs = dict(client_kwargs)
        self._clients = [PSClient(tuple(a), **client_kwargs)
                         for a in addrs]
        self._dead = [False] * len(self._clients)
        self._lock = lockdep.lock("ps.serving.ServingRouter._lock")
        self._primary = 0
        self._last_generation: Optional[int] = None

    def _order(self) -> List[Tuple[int, PSClient]]:
        with self._lock:
            idxs = list(range(len(self._clients)))
            order = idxs[self._primary:] + idxs[:self._primary]
            return [(i, self._clients[i]) for i in order
                    if not self._dead[i]]

    def _mark_dead(self, idx: int) -> None:
        with self._lock:
            self._dead[idx] = True
            live = [i for i in range(len(self._clients))
                    if not self._dead[i]]
            if live:
                self._primary = live[0]

    def _qualify(self, table: Optional[str]) -> str:
        name = table or DEFAULT_TABLE
        return name if "/" in name else f"{self.tenant}/{name}"

    def _resurrect(self) -> bool:
        """Second-chance probe when the live set is empty: a supervisor
        (launch.ServingReplicaSupervisor) restarts a dead replica IN
        PLACE on the same port, so a dead address can come back.  Each
        dead slot gets a fresh client (the old one's sockets died with
        the peer) and a health probe; responders rejoin the rotation."""
        with self._lock:
            dead = [(i, self._clients[i].addr)
                    for i, d in enumerate(self._dead) if d]
        revived = False
        for i, addr in dead:
            probe = PSClient(addr, **self._client_kwargs)
            try:
                probe.health(timeout=2.0)
            except (ConnectionError, RuntimeError, OSError):
                probe.close()
                continue
            with self._lock:
                self._clients[i].close()
                self._clients[i] = probe
                self._dead[i] = False
            stat_add("serving.router.resurrect")
            flight.record("serving_resurrect", replica=i)
            revived = True
        return revived

    def _fan(self, call, verb: str):
        errs: List[str] = []
        for attempt in range(2):
            for i, c in self._order():
                try:
                    return call(c)
                except ConnectionError as e:
                    self._mark_dead(i)
                    stat_add("serving.router.failover")
                    flight.record("serving_failover", replica=i,
                                  verb=verb, error=type(e).__name__)
                    errs.append(f"replica[{i}]: {e}")
                    continue
                except RuntimeError as e:
                    if OVERLOADED in str(e):
                        stat_add("serving.router.shed")
                        raise ServingOverload(str(e)) from e
                    raise
            if attempt == 0 and not self._resurrect():
                break
        raise ConnectionError(
            f"all serving replicas failed for {verb!r}: "
            + ("; ".join(errs) or "none alive"))

    # -- verbs ---------------------------------------------------------------
    def pull_sparse(self, keys: np.ndarray,
                    table: Optional[str] = None) -> Dict[str, np.ndarray]:
        full = self._qualify(table)
        return self._fan(lambda c: c.pull_sparse(keys, table=full),
                         "pull_sparse")

    def forward(self, keys: np.ndarray, lod: np.ndarray,
                table: Optional[str] = None) -> np.ndarray:
        full = self._qualify(table)
        return self._fan(lambda c: c.forward(keys, lod, table=full),
                         "forward")

    def health(self) -> List[Optional[Dict]]:
        """Per-replica health (None for dead/unreachable replicas) —
        mixed ``generation`` values across live replicas expose a
        half-finished fleet hot-swap."""
        with self._lock:
            any_dead = any(self._dead)
        if any_dead:
            self._resurrect()
        out: List[Optional[Dict]] = []
        for i, c in enumerate(self._clients):
            with self._lock:
                dead = self._dead[i]
            if dead:
                out.append(None)
                continue
            try:
                out.append(c.health(timeout=2.0))
            except (ConnectionError, RuntimeError, OSError):
                self._mark_dead(i)
                out.append(None)
        return out

    def generations(self) -> List[int]:
        """Distinct loaded generations across live replicas (len > 1 ⇒
        a hot-swap is in flight somewhere)."""
        gens = {int(h["generation"]) for h in self.health()
                if h and "generation" in h}
        return sorted(gens)

    def observe_generation(self) -> bool:
        """Client-side hot-swap coherence point: when the fleet's max
        generation advances past the last one seen, drop every client's
        learned row-width estimates (a new day's dump may change row
        widths; a stale estimate would mis-chunk the first pull).
        Returns True when an advance was observed."""
        gens = self.generations()
        if not gens:
            return False
        head = gens[-1]
        with self._lock:
            last = self._last_generation
            self._last_generation = head
        if last is not None and head > last:
            for c in self._clients:
                c.invalidate_row_width()
            stat_add("serving.router.gen_advance")
            return True
        return False

    def close(self) -> None:
        for c in self._clients:
            c.close()
