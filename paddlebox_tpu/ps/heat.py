"""Key-space heat telemetry: the per-process HeatMap registry.

The obs stack observes verbs and time; this observes the *key space* —
which keys are hot, how hot, how the heat lands across PS shards, and
how big the live working set is.  It is the measured substrate for the
skew-routing roadmap items (hot-key replication, density-driven
placement): everything here is a bounded-memory streaming sketch
(:mod:`paddlebox_tpu.utils.sketch`), never a per-key dict (lint rule
PB208 enforces that package-wide).

Cost discipline is the trace.py one-check pattern: module-level
``ACTIVE`` starts ``None``; every tap site in the hot paths is a single
``if heat.ACTIVE is not None:`` — heat-off runs execute zero extra
instructions beyond that check.  Heat never touches training state, so
heat-on runs are bit-identical to heat-off (pinned by
tests/test_heat.py under serial, prefetched, and chaos schedules).

Sites (one sketch bundle per literal site name, tenant-bounded):

* ``pull`` / ``push`` — ShardedHostTable.bulk_pull / bulk_write key
  batches (the training fan).
* ``fault_in`` — SSDTieredTable promotions SSD→DRAM: the live
  working-set estimate of what training actually touches.
* ``serve.<tenant>`` — ServingReplica row lookups per tenant.

Derived gauges (published at LITERAL stat_set sites so the PB207
SloRule gate can see them; the "heat." prefix makes them timeline
gauges, not rates):

* ``heat.topk_share`` — fraction of pull traffic on the top-100 keys.
* ``heat.shard_imbalance`` — max/mean PS-shard key load (1.0 = even).
* ``heat.working_set_rows`` — HLL distinct pulled keys since day start.
* ``heat.cache_hot_coverage`` — share of pulled rows served resident
  by the device row cache.

Day boundaries decay the frequency sketches like every other day-scale
score (``decay_day`` — deliberately NOT named end_day: that name is a
table mutator and the PB701 serving-path gate bans reachable calls to
it).  Distinct counts cannot decay, so the HLLs reset: working-set
reads are per-day by contract.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

from paddlebox_tpu import flags
from paddlebox_tpu.utils import flight
from paddlebox_tpu.utils import sketch
from paddlebox_tpu.utils.monitor import stat_set

flags.define_flag(
    "obs_heat", False,
    "enable key-space heat sketches (ps/heat.py) at the engine / PS "
    "server / serving replica / PS client entry points; off = every tap "
    "site is a single is-None check and training carries zero heat cost")
flags.define_flag(
    "obs_heat_topk", 512,
    "SpaceSaving heavy-hitter capacity per site; guarantees every key "
    "with frequency > N/k is tracked, over-count ≤ N/k")
flags.define_flag(
    "obs_heat_width", 2048,
    "count-min sketch width per site (over-count ≤ (e/width)·N "
    "w.p. ≥ 1 − e^−depth; 2048×4 float64 = 64 KB/site)")
flags.define_flag(
    "obs_heat_depth", 4,
    "count-min sketch depth (rows) per site")
flags.define_flag(
    "obs_heat_decay", 0.5,
    "day-boundary multiplier applied to heat frequency sketches "
    "(count-min cells, top-K counts, shard loads) — same day-scale "
    "fade discipline as show_click_decay; HLL working sets reset "
    "instead (distinct counts cannot decay)")

# cap on distinct site bundles (site names are literal or tenant-bounded,
# but a misbehaving tenant list must not grow memory without bound)
_MAX_SITES = 64
# top-N used for the topk_share headline gauge (matches the /heatz
# "top-100 recall" acceptance bar)
TOPN = 100
# shard-imbalance level that latches a heat_imbalance flight event
# (aligned with the timeline SloRule threshold)
IMBALANCE_EVENT_THRESHOLD = 4.0


class _Site:
    """One site's sketch bundle: frequencies + heavy hitters + distinct."""

    __slots__ = ("cm", "tk", "hll", "t0")

    def __init__(self, width: int, depth: int, topk: int, t0: float):
        self.cm = sketch.CountMinSketch(width=width, depth=depth)
        self.tk = sketch.SpaceSaving(k=topk)
        self.hll = sketch.HyperLogLog()
        self.t0 = t0


class HeatMap:
    """Per-process registry of heat sketches; all methods are cheap
    relative to the bulk ops they ride on (one np.unique of an
    already-materialized key batch plus O(u) sketch updates)."""

    def __init__(self, width: Optional[int] = None,
                 depth: Optional[int] = None,
                 topk: Optional[int] = None):
        self._width = int(width if width is not None
                          else flags.get_flags("obs_heat_width"))
        self._depth = int(depth if depth is not None
                          else flags.get_flags("obs_heat_depth"))
        self._topk = int(topk if topk is not None
                         else flags.get_flags("obs_heat_topk"))
        self._sites: Dict[str, _Site] = {}
        self._loads = sketch.ShardLoad()
        self._cache_hits = 0.0
        self._cache_misses = 0.0
        self._imbalance_latched = False
        self._day_t0 = time.time()
        from paddlebox_tpu.utils import lockdep
        self._lock = lockdep.lock("ps.heat.HeatMap._lock")

    # -- taps ---------------------------------------------------------------
    def _site(self, name: str) -> Optional[_Site]:
        s = self._sites.get(name)
        if s is None:
            if len(self._sites) >= _MAX_SITES:
                return None          # bounded: drop novel sites past the cap
            s = _Site(self._width, self._depth, self._topk, time.time())
            self._sites[name] = s
        return s

    def observe(self, site: str, keys: np.ndarray) -> None:
        """Fold one key batch into ``site``'s sketches.  ``site`` must be
        a bounded literal (or tenant-derived) name — never key-derived."""
        uniq, counts = sketch.unique_with_counts(keys)
        if not len(uniq):
            return
        with self._lock:
            s = self._site(site)
            if s is None:
                return
            s.cm.update(uniq, counts)
            s.tk.update(uniq, counts)
            s.hll.update(uniq)
            if site == "pull":
                stat_set("heat.topk_share", s.tk.topk_share(TOPN))
                stat_set("heat.working_set_rows", s.hll.estimate())

    def observe_shard(self, shard: int, n_keys: int) -> None:
        """Account ``n_keys`` of fan traffic to PS shard ``shard`` and
        publish the imbalance gauge; crossing the event threshold latches
        one heat_imbalance flight event (cleared on recovery)."""
        if n_keys <= 0:
            return
        with self._lock:
            self._loads.add(shard, float(n_keys))
            imb = self._loads.imbalance()
            stat_set("heat.shard_imbalance", imb)
            if len(self._loads.loads) < 2:
                return
            if imb >= IMBALANCE_EVENT_THRESHOLD and not \
                    self._imbalance_latched:
                self._imbalance_latched = True
                flight.record("heat_imbalance", imbalance=round(imb, 3),
                              shards=len(self._loads.loads))
            elif imb < IMBALANCE_EVENT_THRESHOLD and \
                    self._imbalance_latched:
                self._imbalance_latched = False

    def hot_keys(self, k: int, site_prefix: str = "serve.") -> np.ndarray:
        """Top-``k`` hot keys merged across the ``site_prefix`` sketches
        (the serving tenants by default) — the measured replication set
        for the serving tier's hot-key planes (ps/serving.py).  Counts of
        the same key across tenants sum; ties break toward the smaller
        key so the set is deterministic for a given sketch state.
        Returns a SORTED uint64 array (at most ``k`` keys; empty when no
        matching site has traffic yet).  Pure-array aggregation — the
        candidate pool is bounded by k × matching sites, never the key
        space."""
        if k <= 0:
            return np.zeros(0, np.uint64)
        cand_keys: List[int] = []
        cand_counts: List[float] = []
        with self._lock:
            for name, s in self._sites.items():
                if not name.startswith(site_prefix):
                    continue
                for key, count, _err in s.tk.top(k):
                    cand_keys.append(int(key))
                    cand_counts.append(float(count))
        if not cand_keys:
            return np.zeros(0, np.uint64)
        keys = np.asarray(cand_keys, np.uint64)
        counts = np.asarray(cand_counts, np.float64)
        uniq, inv = np.unique(keys, return_inverse=True)
        sums = np.zeros(len(uniq), np.float64)
        np.add.at(sums, inv, counts)
        # stable sort on -count ties toward ascending key (uniq is sorted)
        order = np.argsort(-sums, kind="stable")[:k]
        return np.sort(uniq[order])

    def observe_cache(self, hits: int, misses: int) -> None:
        """Device row cache admission outcome for one pass build:
        hot-coverage = share of pulled rows served resident."""
        with self._lock:
            self._cache_hits += float(max(0, hits))
            self._cache_misses += float(max(0, misses))
            denom = self._cache_hits + self._cache_misses
            if denom > 0:
                stat_set("heat.cache_hot_coverage",
                         self._cache_hits / denom)

    # -- day boundary -------------------------------------------------------
    def decay_day(self, factor: Optional[float] = None) -> None:
        """Day-boundary fade (NOT named end_day — see module docstring):
        frequency sketches and shard loads scale by ``factor``; the HLL
        working sets reset (per-day by contract)."""
        f = float(factor if factor is not None
                  else flags.get_flags("obs_heat_decay"))
        with self._lock:
            for s in self._sites.values():
                s.cm.decay(f)
                s.tk.decay(f)
                s.hll.reset()
            self._loads.decay(f)
            self._cache_hits *= f
            self._cache_misses *= f
            self._day_t0 = time.time()
            summ = self._summary_locked()
        flight.record("heat_snapshot", topk_share=summ["topk_share"],
                      shard_imbalance=summ["shard_imbalance"],
                      working_set_rows=summ["working_set_rows"])

    # -- exports ------------------------------------------------------------
    def _summary_locked(self) -> Dict[str, float]:
        pull = self._sites.get("pull")
        return {
            "topk_share": round(pull.tk.topk_share(TOPN), 4)
            if pull else 0.0,
            "shard_imbalance": round(self._loads.imbalance(), 4),
            "working_set_rows": round(pull.hll.estimate(), 1)
            if pull else 0.0,
            # decayed pull traffic weight — the cluster health fold
            # measures cross-member imbalance from these
            "total_keys": round(pull.cm.total, 1) if pull else 0.0,
        }

    def summary(self) -> Dict[str, float]:
        """Compact heat sub-dict for the health verbs."""
        with self._lock:
            return self._summary_locked()

    def raw(self) -> Dict:
        """Mergeable wire export (the sketch.merge_heat_raw schema) —
        what /statz?raw=1 ships and the supervisor folds."""
        with self._lock:
            return {
                "sites": {name: {"cm": s.cm.raw(), "tk": s.tk.raw(),
                                 "hll": s.hll.raw()}
                          for name, s in self._sites.items()},
                "loads": self._loads.raw(),
                "cache": [self._cache_hits, self._cache_misses],
            }

    def nbytes(self) -> int:
        """Resident sketch memory (the ≤ 4 MB/process budget check)."""
        with self._lock:
            return sum(s.cm.nbytes() + s.hll.nbytes() +
                       len(s.tk) * 48 for s in self._sites.values()) \
                + int(self._loads.loads.nbytes)

    def render(self, topn: int = TOPN) -> Dict:
        """The /heatz payload: top-K keys with estimated rates, per-shard
        load shares, skew exponent fit, and the working-set curve."""
        now = time.time()
        with self._lock:
            sites_out = {}
            for name, s in self._sites.items():
                elapsed = max(1e-6, now - s.t0)
                top = s.tk.top(topn)
                counts = [c for _, c, _ in top]
                sites_out[name] = {
                    "total_keys": round(s.cm.total, 1),
                    "working_set_rows": round(s.hll.estimate(), 1),
                    "zipf_exponent": sketch.fit_zipf_exponent(counts),
                    "topk_share": round(s.tk.topk_share(topn), 6),
                    # cumulative share of traffic at increasing rank
                    # depths — the working-set curve ("how many rows
                    # cover how much traffic")
                    "share_curve": self._share_curve(counts, s.tk.total),
                    "top": [{"key": str(key),
                             "est_count": round(c, 1),
                             "err": round(e, 1),
                             "est_rate_hz": round(c / elapsed, 3)}
                            for key, c, e in top],
                }
            denom = self._cache_hits + self._cache_misses
            return {
                "sites": sites_out,
                "shards": {
                    "n": len(self._loads.loads),
                    "imbalance": round(self._loads.imbalance(), 4),
                    "shares": self._loads.shares(),
                },
                "cache_hot_coverage":
                    round(self._cache_hits / denom, 6) if denom else 0.0,
                "sketch_bytes": sum(
                    s.cm.nbytes() + s.hll.nbytes() + len(s.tk) * 48
                    for s in self._sites.values()),
                "day_age_s": round(now - self._day_t0, 1),
            }

    @staticmethod
    def _share_curve(counts: List[float], total: float) -> List[Dict]:
        if total <= 0 or not counts:
            return []
        out, acc = [], 0.0
        marks = {1, 10, 50, 100, len(counts)}
        for rank, c in enumerate(sorted(counts, reverse=True), start=1):
            acc += c
            if rank in marks:
                out.append({"rank": rank,
                            "share": round(min(1.0, acc / total), 4)})
        return out


# module-level handle — the one hot-path check (≙ trace.ACTIVE)
ACTIVE: Optional[HeatMap] = None


def enable() -> HeatMap:
    global ACTIVE
    if ACTIVE is None:
        ACTIVE = HeatMap()
    return ACTIVE


def disable() -> None:
    global ACTIVE
    ACTIVE = None


def maybe_enable_from_flags() -> Optional[HeatMap]:
    if flags.get_flags("obs_heat"):
        return enable()
    return ACTIVE


def summary() -> Optional[Dict[str, float]]:
    """Health-verb helper: compact heat dict, or None when heat is off."""
    return ACTIVE.summary() if ACTIVE is not None else None


def serving_hot_keys(k: int) -> np.ndarray:
    """The serving tier's measured hot-key set: top-``k`` keys across the
    ``serve.*`` sketch sites, or empty when heat is off / cold.  Sorted
    uint64 — directly usable as a replication set (ps/serving.py)."""
    if ACTIVE is None or k <= 0:
        return np.zeros(0, np.uint64)
    return ACTIVE.hot_keys(k)
