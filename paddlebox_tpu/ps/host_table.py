"""Host DRAM tier of the tiered parameter server.

≙ MemorySparseTable (ps/table/memory_sparse_table.{h,cc}): shard by
``key % shard_num`` (memory_sparse_table.h:46-59), bulk Pull/Push
(:61-97), Save/Load with per-shard files, Shrink via accessor policy —
and, like the reference's ``shards_task_pool_``, every per-shard loop
fans across the shared worker pool (utils/workpool.py,
``FLAGS_ps_table_threads``): the numpy gather/scatter that dominates a
shard task releases the GIL, so pull/write/end_day/shrink/save/load run
shards concurrently while staying bit-identical to the sequential walk
(keys are unique per call; append order within a shard is owned by its
single task).

TPU-first storage: each shard keeps its keys in one insertion-ordered
uint64 array with parallel SoA value arrays, indexed by the native C++
open-addressing hash (native/hash_shard.cc) — bulk lookup is one threaded
probe sweep and pass-level write-back is overwrite + append, never a
whole-shard re-sort.  Appends land in capacity-doubling buffers (a
``len``/``cap`` split per array; ``shard.keys``/``shard.soa`` are always
length-trimmed views), so a pass of fresh keys costs amortized O(1)
reallocations instead of one whole-shard ``np.concatenate`` copy per
call.  Without the native library the index falls back to a lazily
rebuilt sorted view + ``np.searchsorted``.  This matches the
pass-batched access pattern (one pull at end_feed_pass, one write-back at
end_pass) instead of the reference's per-request hash probes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddlebox_tpu.config import EmbeddingTableConfig
from paddlebox_tpu.ps import feature_value as fv
from paddlebox_tpu.ps import heat
from paddlebox_tpu.utils import lockdep, workpool
from paddlebox_tpu.utils.monitor import stat_observe

_GROW_MIN = 64      # first allocation floor (rows)


class _Shard:
    """One shard: insertion-ordered keys + SoA values in growable buffers.

    ``keys`` and ``soa`` are ALWAYS length-trimmed views over the backing
    capacity buffers — readers never see the uninitialized tail, and
    in-place mutation of a view (``soa["show"] *= decay``) writes through.
    Wholesale replacement goes through :meth:`replace` /
    :meth:`filter_keep`, never bare attribute assignment, so the
    ``len``/``cap`` split can't desync.
    """

    def __init__(self, mf_dim: int, expand_dim: int = 0, adam: bool = False,
                 optimizer: str = "", double_stats: bool = False):
        self.optimizer = optimizer
        self.mf_dim = mf_dim
        # RLock: lookup lazily builds index state (native hash / sorted
        # view) and is called both bare (readers) and from under upsert
        self.lock = lockdep.rlock("ps.host_table._Shard.lock")
        self._hash = None           # native index (row = insertion order)
        self._hash_tried = False
        self._sorted_view = None    # fallback: (sorted_keys, order)
        # growth accounting (the amortization test asserts on these):
        # grow_count counts buffer REALLOCATIONS, append_calls counts
        # appends — doubling keeps grow_count O(log rows), not O(calls)
        self.grow_count = 0
        self.append_calls = 0
        self._len = 0
        self._keys_buf = np.empty((0,), np.uint64)
        self._soa_buf = fv.empty_soa(0, mf_dim, expand_dim, adam, optimizer,
                                     double_stats)
        self._refresh_views()

    def _refresh_views(self) -> None:
        n = self._len
        self.keys = self._keys_buf[:n]
        self.soa = {f: buf[:n] for f, buf in self._soa_buf.items()}

    @property
    def size(self) -> int:
        return self._len

    @property
    def capacity(self) -> int:
        return len(self._keys_buf)

    def _grow(self, need: int) -> None:
        """Reallocate every buffer to at least ``need`` rows (doubling).
        Reentrant from upsert (which already holds the RLock)."""
        with self.lock:
            cap = max(len(self._keys_buf) * 2, need, _GROW_MIN)
            nk = np.empty((cap,), np.uint64)
            nk[:self._len] = self._keys_buf[:self._len]
            self._keys_buf = nk
            for f, buf in self._soa_buf.items():
                nb = np.empty((cap,) + buf.shape[1:], buf.dtype)
                nb[:self._len] = buf[:self._len]
                self._soa_buf[f] = nb
            self.grow_count += 1

    def replace(self, keys: np.ndarray, soa: Dict[str, np.ndarray]) -> None:
        """Swap in a wholesale new row set (load): the given arrays BECOME
        the buffers (capacity == length; the next append grows)."""
        with self.lock:
            self._keys_buf = np.ascontiguousarray(keys, np.uint64)
            self._len = len(self._keys_buf)
            self._soa_buf = {f: np.ascontiguousarray(v)
                             for f, v in soa.items()}
            self._refresh_views()
            self.rebuild_index()

    def filter_keep(self, keep: np.ndarray) -> None:
        """Drop rows where ``keep`` is False (shrink / spill), compacting
        into fresh exact-size buffers."""
        with self.lock:
            self.replace(self.keys[keep],
                         {f: v[keep] for f, v in self.soa.items()})

    def _native(self):
        # reentrant from lookup/upsert/rebuild_index, which already hold
        # the RLock — taken here too so a bare call cannot race the lazy
        # index build
        with self.lock:
            if not self._hash_tried:
                self._hash_tried = True
                try:
                    from paddlebox_tpu.native import hash_map
                    if hash_map.available():
                        h = hash_map.NativeKeyHash(max(self._len, 1024))
                        if self._len:
                            h.upsert(self.keys)
                        self._hash = h
                except Exception:
                    self._hash = None
            return self._hash

    def rebuild_index(self) -> None:
        """Call after keys/soa were replaced wholesale (load, shrink).
        Takes the shard RLock itself: callers inside load/shrink already
        hold it (reentrant), and a bare call must not race lookup's lazy
        index build."""
        with self.lock:
            self._sorted_view = None
            if self._hash is not None or self._hash_tried:
                self._hash_tried = False
                self._hash = None
                self._native()

    def lookup(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """→ (rows, found_mask); rows are insertion positions, valid where
        found.  Thread-safe: lazily builds index state under the shard
        lock (reentrant from upsert)."""
        with self.lock:
            if self._len == 0:
                return (np.zeros(len(keys), np.int64),
                        np.zeros(len(keys), bool))
            h = self._native()
            if h is not None:
                rows = h.find(np.asarray(keys, np.uint64))
                return np.maximum(rows, 0), rows >= 0
            if self._sorted_view is None:
                order = np.argsort(self.keys, kind="stable")
                self._sorted_view = (self.keys[order], order)
            sk, order = self._sorted_view
            pos = np.searchsorted(sk, keys)
            pos_c = np.minimum(pos, len(sk) - 1)
            found = sk[pos_c] == keys
            return order[pos_c], found

    def upsert(self, keys: np.ndarray, soa: Dict[str, np.ndarray]) -> None:
        """Overwrite existing rows in place, append new ones — no re-sort
        (keys must be unique within one call, which pass-level write-back
        guarantees).  Appends write into the buffer tail; a full buffer
        doubles (amortized O(1) per appended row)."""
        t_req = time.monotonic()
        with self.lock:
            # hold-time histogram: a fat p99 here is writer-side lock
            # pressure stalling concurrent pulls (the preload thread);
            # the WAIT histogram beside it is pool-induced queueing on a
            # hot shard (many tasks contending for this one lock)
            t0 = time.monotonic()
            rows, found = self.lookup(keys)
            if found.any():
                idx = rows[found]
                for f, arr in self.soa.items():
                    arr[idx] = soa[f][found]
            if (~found).any():
                new_keys = keys[~found]
                if self._hash is not None:
                    # native insertion rows continue from the current size,
                    # matching the append positions exactly
                    self._hash.upsert(new_keys)
                need = self._len + len(new_keys)
                if need > len(self._keys_buf):
                    self._grow(need)
                lockdep.guards(self, "_len")
                self._keys_buf[self._len:need] = new_keys
                for f, buf in self._soa_buf.items():
                    buf[self._len:need] = soa[f][~found]
                self._len = need
                self.append_calls += 1
                self._refresh_views()
                self._sorted_view = None
        stat_observe("ps.host_table.write_lock_wait_s", t0 - t_req)
        stat_observe("ps.host_table.write_lock_hold_s",
                     time.monotonic() - t0)


class ShardedHostTable:
    """DRAM embedding table, pass-batched API.  Per-shard loops fan across
    the shared worker pool (workpool.table_pool()); results are
    bit-identical to the sequential walk at any pool size."""

    def __init__(self, config: EmbeddingTableConfig, seed: int = 0):
        self.config = config
        self.mf_dim = config.embedding_dim
        self.expand_dim = config.expand_dim
        self.adam = config.sgd.optimizer in ("adam", "shared_adam")
        self.optimizer = config.sgd.optimizer
        self.shard_num = config.shard_num
        # f64 show/click statistics (CtrDoubleAccessor ≙): counters keep
        # exact integer semantics past f32's 2^24 range
        self.double_stats = config.accessor.accessor_type == "ctr_double"
        self._shards = [_Shard(self.mf_dim, self.expand_dim, self.adam,
                               self.optimizer, self.double_stats)
                        for _ in range(self.shard_num)]
        # fresh-row init is KEY-DETERMINISTIC (fv.default_rows_keyed): a
        # pure function of (seed, key), never a shared stateful RNG — so
        # retried/reordered pulls (exactly-once retry protocol, chaos
        # replays) and multi-worker first-pulls all see identical defaults
        self._seed = seed

    # -- introspection -------------------------------------------------------
    def size(self) -> int:
        return sum(s.size for s in self._shards)

    def grow_stats(self) -> Tuple[int, int]:
        """→ (total buffer reallocations, total append calls) across
        shards — the growth-amortization surface the tests assert on."""
        return (sum(s.grow_count for s in self._shards),
                sum(s.append_calls for s in self._shards))

    def _shard_ids(self, keys: np.ndarray) -> np.ndarray:
        return (keys % np.uint64(self.shard_num)).astype(np.int64)

    def _shard_sel(self, keys: np.ndarray) -> List[Tuple[int, np.ndarray]]:
        """Non-empty (shard_id, key-index array) groups for one call."""
        sid = self._shard_ids(keys)
        out = []
        for s in range(self.shard_num):
            sel = np.nonzero(sid == s)[0]
            if len(sel):
                out.append((s, sel))
        return out

    # -- pass-batched pull/push ---------------------------------------------
    def bulk_pull(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        """Read rows for unique `keys` (read-only; unseen keys get fresh
        default rows — insertion happens at write-back, matching the
        build-pass flow ps_gpu_wrapper.cc:337-760).  One gather task per
        shard on the pool; tasks write DISJOINT row sets of ``out``."""
        if heat.ACTIVE is not None:
            heat.ACTIVE.observe("pull", keys)
        out = fv.default_rows_keyed(keys, self.mf_dim, self._seed,
                                    self.config.sgd.mf_initial_range,
                                    self.config.sgd.initial_range,
                                    self.expand_dim, self.adam,
                                    self.config.sgd.beta1_decay_rate,
                                    self.config.sgd.beta2_decay_rate,
                                    self.optimizer, self.double_stats)

        def pull_shard(group):
            s, sel = group
            shard = self._shards[s]
            t_req = time.monotonic()
            # under the shard lock: the pipelined preload thread pulls
            # concurrently with main-thread upserts that rebuild keys/soa
            with shard.lock:
                t0 = time.monotonic()
                pos, found = shard.lookup(keys[sel])
                hit = sel[found]
                if len(hit):
                    src = pos[found]
                    for f, arr in shard.soa.items():
                        out[f][hit] = arr[src]
            stat_observe("ps.host_table.pull_lock_wait_s", t0 - t_req)
            stat_observe("ps.host_table.pull_lock_hold_s",
                         time.monotonic() - t0)

        workpool.table_pool().map(pull_shard, self._shard_sel(keys))
        return out

    def export_keys(self) -> np.ndarray:
        """Every resident key, one per-shard copy under that shard's lock
        (the serving tier freezes a loaded table from this + bulk_pull;
        order is shard-major — callers needing an order sort)."""
        def keys_shard(shard) -> np.ndarray:
            with shard.lock:
                return np.array(shard.keys, copy=True)

        parts = workpool.table_pool().map(keys_shard, self._shards)
        parts = [p for p in parts if len(p)]
        if not parts:
            return np.zeros((0,), np.uint64)
        return np.concatenate(parts).astype(np.uint64, copy=False)

    def bulk_write(self, keys: np.ndarray, soa: Dict[str, np.ndarray]) -> None:
        if heat.ACTIVE is not None:
            heat.ACTIVE.observe("push", keys)

        def write_shard(group):
            s, sel = group
            self._shards[s].upsert(keys[sel], fv.select_rows(soa, sel))

        workpool.table_pool().map(write_shard, self._shard_sel(keys))

    # -- lifecycle policy (≙ CtrCommonAccessor, ctr_accessor.cc) ------------
    def _score(self, soa: Dict[str, np.ndarray]) -> np.ndarray:
        sgd = self.config.sgd
        return (sgd.nonclk_coeff * (soa["show"] - soa["click"])
                + sgd.clk_coeff * soa["click"])

    def end_day(self) -> None:
        """Day rollover: decay show/click, age unseen features
        (≙ CtrCommonAccessor::UpdateStatAfterSave / show_click_decay)."""
        decay = self.config.accessor.show_click_decay_rate

        def decay_shard(shard):
            with shard.lock:
                shard.soa["show"] *= decay
                shard.soa["click"] *= decay
                shard.soa["unseen_days"] += 1.0

        workpool.table_pool().map(decay_shard, self._shards)

    def shrink(self) -> int:
        """Evict dead features (≙ Table::Shrink via accessor thresholds:
        score < delete_threshold or unseen too long)."""
        acc = self.config.accessor

        def shrink_shard(shard) -> int:
            with shard.lock:
                score = self._score(shard.soa)
                keep = ~((score < acc.delete_threshold) |
                         (shard.soa["unseen_days"]
                          > acc.delete_after_unseen_days))
                removed = int((~keep).sum())
                if removed:
                    shard.filter_keep(keep)
                return removed

        return sum(workpool.table_pool().map(shrink_shard, self._shards))

    def filter_keys(self, keep_fn) -> int:
        """Drop rows whose key fails ``keep_fn(keys) -> bool mask`` —
        the reshard source-side moved-row drop (cutover commit) and the
        reshard-on-load owner filter.  Returns rows removed."""
        def filter_shard(shard) -> int:
            with shard.lock:
                keep = np.asarray(keep_fn(shard.keys), bool)
                removed = int((~keep).sum())
                if removed:
                    shard.filter_keep(keep)
                return removed

        return sum(workpool.table_pool().map(filter_shard, self._shards))

    def select_keys(self, mask_fn) -> np.ndarray:
        """Resident keys for which ``mask_fn(keys) -> bool mask`` holds —
        the reshard snapshot's moving-row enumeration (ps/service.py
        ``reshard_begin``).  Shard-major order like export_keys; callers
        needing determinism sort."""
        def sel_shard(shard) -> np.ndarray:
            with shard.lock:
                keys = np.asarray(shard.keys, np.uint64)
                if not len(keys):
                    return keys
                return keys[np.asarray(mask_fn(keys), bool)]

        parts = [p for p in workpool.table_pool().map(sel_shard,
                                                      self._shards)
                 if len(p)]
        if not parts:
            return np.zeros((0,), np.uint64)
        return np.concatenate(parts)

    # -- persistence (≙ SaveBase/SaveDelta box_wrapper.cc:1286; per-shard
    #    files with .shard suffix, memory_sparse_table.h:34) ----------------
    def save(self, path: str, mode: str = "base",
             keys: Optional[np.ndarray] = None) -> int:
        """Per-shard npz dumps under `path`, which may be any registered
        filesystem scheme — e.g. hdfs://... through ShellFS
        (≙ SaveBase/SaveDelta's AFS paths, box_wrapper.h:721-743).  Shard
        files write in parallel on the pool; each lands atomically
        (tmp name + rename when the filesystem supports it), and delta
        mode resets ``delta_score`` only AFTER its shard file is safely
        down — a mid-save filesystem failure can't lose deltas.

        mode="rows" saves exactly the rows of ``keys`` (missing keys are
        skipped) — the checkpoint-delta primitive (io/checkpoint.py
        generation chain): per-pass cost ∝ the pass's written key set,
        and the resulting dump applies over a base via
        ``load(path, mode="upsert")``."""
        from paddlebox_tpu.io import fs as pfs
        filesystem = pfs.get_fs(path)
        filesystem.mkdir(path)
        acc = self.config.accessor
        if mode == "rows":
            if keys is None:
                raise ValueError("save(mode='rows') requires keys")
            keys = np.asarray(keys, np.uint64)
            row_sel = dict(self._shard_sel(keys))

        def save_shard(item) -> int:
            i, shard = item
            with shard.lock:
                if mode == "rows":
                    sel = row_sel.get(i)
                    pos, found = (shard.lookup(keys[sel])
                                  if sel is not None and len(sel)
                                  else (np.zeros(0, np.int64),
                                        np.zeros(0, bool)))
                    idx = pos[found]
                    data = {f: arr[idx] for f, arr in shard.soa.items()}
                    data["keys"] = (keys[sel][found] if sel is not None
                                    else np.zeros(0, np.uint64))
                else:
                    score = self._score(shard.soa)
                    if mode == "base":
                        keep = score >= acc.base_threshold
                    elif mode == "delta":
                        keep = np.abs(shard.soa["delta_score"]) \
                            >= acc.delta_threshold
                    else:  # "all" / checkpoint
                        keep = np.ones(shard.size, bool)
                    data = {f: arr[keep] for f, arr in shard.soa.items()}
                    data["keys"] = shard.keys[keep]
                part = f"{path.rstrip('/')}/part-{i:05d}.shard.npz"
                try:
                    tmp = part + ".tmp"
                    with filesystem.open_write(tmp) as tmp_fh:
                        np.savez(tmp_fh, **data)
                    filesystem.rename(tmp, part)
                except NotImplementedError:
                    # scheme without a rename verb: direct write (the
                    # pre-atomic behavior; delta reset still gated on the
                    # write completing without raising)
                    # pboxlint: disable-next=PB502 -- no rename verb here
                    with filesystem.open_write(part) as fh:
                        # pboxlint: disable-next=PB502 -- same fallback
                        np.savez(fh, **data)
                if mode == "delta":
                    # only now is the shard file known to have landed —
                    # zeroing before the write/rename could lose deltas
                    # to a mid-save failure
                    shard.soa["delta_score"][keep] = 0.0
                return len(data["keys"])

        return sum(workpool.table_pool().map(
            save_shard, list(enumerate(self._shards))))

    def load(self, path: str, mode: str = "replace") -> int:
        """Read per-shard npz dumps.  mode="replace" (default) swaps each
        shard's row set wholesale; mode="upsert" merges the dumped rows
        over the current contents — the delta-chain apply of the
        generation-chained checkpoint (io/checkpoint.py)."""
        from io import BytesIO

        from paddlebox_tpu.io import fs as pfs
        filesystem = pfs.get_fs(path)

        def load_shard(item) -> int:
            i, shard = item
            f = f"{path.rstrip('/')}/part-{i:05d}.shard.npz"
            if not filesystem.exists(f):
                return 0
            fh = filesystem.open_read(f)
            # np.load needs seek; only pipe-backed streams buffer fully
            src = fh if fh.seekable() else BytesIO(fh.read())
            with np.load(src) as z:
                with shard.lock:
                    new_keys = z["keys"]
                    n = len(new_keys)
                    # checkpoints from a different optimizer config may
                    # lack some state fields (e.g. adam moments when the
                    # save ran under adagrad) — init those like fresh rows
                    # instead of KeyErroring: moments/g2sums start at 0,
                    # beta-power trackers at the decay rates (the adam
                    # creation init, ≙ optimizer.cuh.h:436-441)
                    sgd = self.config.sgd
                    fresh = {"_b1p": sgd.beta1_decay_rate,
                             "_b2p": sgd.beta2_decay_rate}

                    def init_missing(name, tmpl):
                        fill = next((v for suf, v in fresh.items()
                                     if name.endswith(suf)), 0.0)
                        return np.full((n,) + tmpl.shape[1:], fill,
                                       tmpl.dtype)

                    def from_ckpt(name, tmpl):
                        if name not in z.files:
                            return init_missing(name, tmpl)
                        arr = z[name]
                        # accessor migration (e.g. ctr -> ctr_double):
                        # the template dtype wins or appended rows would
                        # mix dtypes and f64 exactness silently degrades
                        return arr.astype(tmpl.dtype) \
                            if arr.dtype != tmpl.dtype else arr

                    soa = {name: from_ckpt(name, tmpl)
                           for name, tmpl in shard.soa.items()}
                    if mode == "upsert":
                        if n:
                            shard.upsert(new_keys, soa)
                    else:
                        shard.replace(new_keys, soa)
            fh.close()
            return n if mode == "upsert" else shard.size

        return sum(workpool.table_pool().map(
            load_shard, list(enumerate(self._shards))))
