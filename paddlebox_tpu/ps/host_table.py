"""Host DRAM tier of the tiered parameter server.

≙ MemorySparseTable (ps/table/memory_sparse_table.{h,cc}): shard by
``key % shard_num`` (memory_sparse_table.h:46-59), bulk Pull/Push
(:61-97), Save/Load with per-shard files, Shrink via accessor policy.

TPU-first storage: each shard keeps its keys in one insertion-ordered
uint64 array with parallel SoA value arrays, indexed by the native C++
open-addressing hash (native/hash_shard.cc) — bulk lookup is one threaded
probe sweep and pass-level write-back is overwrite + append, never a
whole-shard re-sort.  Without the native library the index falls back to a
lazily rebuilt sorted view + ``np.searchsorted``.  This matches the
pass-batched access pattern (one pull at end_feed_pass, one write-back at
end_pass) instead of the reference's per-request hash probes.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from paddlebox_tpu.config import EmbeddingTableConfig
from paddlebox_tpu.ps import feature_value as fv
from paddlebox_tpu.utils.monitor import stat_observe


class _Shard:
    def __init__(self, mf_dim: int, expand_dim: int = 0, adam: bool = False,
                 optimizer: str = "", double_stats: bool = False):
        self.optimizer = optimizer
        self.keys = np.empty((0,), np.uint64)
        self.soa = fv.empty_soa(0, mf_dim, expand_dim, adam, optimizer,
                                double_stats)
        self.mf_dim = mf_dim
        # RLock: lookup lazily builds index state (native hash / sorted
        # view) and is called both bare (readers) and from under upsert
        self.lock = threading.RLock()
        self._hash = None           # native index (row = insertion order)
        self._hash_tried = False
        self._sorted_view = None    # fallback: (sorted_keys, order)

    @property
    def size(self) -> int:
        return len(self.keys)

    def _native(self):
        # reentrant from lookup/upsert/rebuild_index, which already hold
        # the RLock — taken here too so a bare call cannot race the lazy
        # index build
        with self.lock:
            if not self._hash_tried:
                self._hash_tried = True
                try:
                    from paddlebox_tpu.native import hash_map
                    if hash_map.available():
                        h = hash_map.NativeKeyHash(max(len(self.keys),
                                                       1024))
                        if len(self.keys):
                            h.upsert(self.keys)
                        self._hash = h
                except Exception:
                    self._hash = None
            return self._hash

    def rebuild_index(self) -> None:
        """Call after keys/soa were replaced wholesale (load, shrink).
        Takes the shard RLock itself: callers inside load/shrink already
        hold it (reentrant), and a bare call must not race lookup's lazy
        index build."""
        with self.lock:
            self._sorted_view = None
            if self._hash is not None or self._hash_tried:
                self._hash_tried = False
                self._hash = None
                self._native()

    def lookup(self, keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """→ (rows, found_mask); rows are insertion positions, valid where
        found.  Thread-safe: lazily builds index state under the shard
        lock (reentrant from upsert)."""
        with self.lock:
            if len(self.keys) == 0:
                return (np.zeros(len(keys), np.int64),
                        np.zeros(len(keys), bool))
            h = self._native()
            if h is not None:
                rows = h.find(np.asarray(keys, np.uint64))
                return np.maximum(rows, 0), rows >= 0
            if self._sorted_view is None:
                order = np.argsort(self.keys, kind="stable")
                self._sorted_view = (self.keys[order], order)
            sk, order = self._sorted_view
            pos = np.searchsorted(sk, keys)
            pos_c = np.minimum(pos, len(sk) - 1)
            found = sk[pos_c] == keys
            return order[pos_c], found

    def upsert(self, keys: np.ndarray, soa: Dict[str, np.ndarray]) -> None:
        """Overwrite existing rows in place, append new ones — no re-sort
        (keys must be unique within one call, which pass-level write-back
        guarantees)."""
        with self.lock:
            # hold-time histogram: a fat p99 here is writer-side lock
            # pressure stalling concurrent pulls (the preload thread)
            t0 = time.monotonic()
            rows, found = self.lookup(keys)
            if found.any():
                idx = rows[found]
                for f, arr in self.soa.items():
                    arr[idx] = soa[f][found]
            if (~found).any():
                new_keys = keys[~found]
                if self._hash is not None:
                    # native insertion rows continue from the current size,
                    # matching the append positions exactly
                    self._hash.upsert(new_keys)
                self.keys = np.concatenate([self.keys, new_keys])
                for f in self.soa:
                    self.soa[f] = np.concatenate(
                        [self.soa[f], soa[f][~found]])
                self._sorted_view = None
        stat_observe("ps.host_table.write_lock_hold_s",
                     time.monotonic() - t0)


class ShardedHostTable:
    """DRAM embedding table, pass-batched API."""

    def __init__(self, config: EmbeddingTableConfig, seed: int = 0):
        self.config = config
        self.mf_dim = config.embedding_dim
        self.expand_dim = config.expand_dim
        self.adam = config.sgd.optimizer in ("adam", "shared_adam")
        self.optimizer = config.sgd.optimizer
        self.shard_num = config.shard_num
        # f64 show/click statistics (CtrDoubleAccessor ≙): counters keep
        # exact integer semantics past f32's 2^24 range
        self.double_stats = config.accessor.accessor_type == "ctr_double"
        self._shards = [_Shard(self.mf_dim, self.expand_dim, self.adam,
                               self.optimizer, self.double_stats)
                        for _ in range(self.shard_num)]
        # fresh-row init is KEY-DETERMINISTIC (fv.default_rows_keyed): a
        # pure function of (seed, key), never a shared stateful RNG — so
        # retried/reordered pulls (exactly-once retry protocol, chaos
        # replays) and multi-worker first-pulls all see identical defaults
        self._seed = seed

    # -- introspection -------------------------------------------------------
    def size(self) -> int:
        return sum(s.size for s in self._shards)

    def _shard_ids(self, keys: np.ndarray) -> np.ndarray:
        return (keys % np.uint64(self.shard_num)).astype(np.int64)

    # -- pass-batched pull/push ---------------------------------------------
    def bulk_pull(self, keys: np.ndarray) -> Dict[str, np.ndarray]:
        """Read rows for unique `keys` (read-only; unseen keys get fresh
        default rows — insertion happens at write-back, matching the
        build-pass flow ps_gpu_wrapper.cc:337-760)."""
        out = fv.default_rows_keyed(keys, self.mf_dim, self._seed,
                                    self.config.sgd.mf_initial_range,
                                    self.config.sgd.initial_range,
                                    self.expand_dim, self.adam,
                                    self.config.sgd.beta1_decay_rate,
                                    self.config.sgd.beta2_decay_rate,
                                    self.optimizer, self.double_stats)
        sid = self._shard_ids(keys)
        for s, shard in enumerate(self._shards):
            sel = np.nonzero(sid == s)[0]
            if not len(sel):
                continue
            # under the shard lock: the pipelined preload thread pulls
            # concurrently with main-thread upserts that rebuild keys/soa
            with shard.lock:
                t0 = time.monotonic()
                pos, found = shard.lookup(keys[sel])
                hit = sel[found]
                if len(hit):
                    src = pos[found]
                    for f, arr in shard.soa.items():
                        out[f][hit] = arr[src]
            stat_observe("ps.host_table.pull_lock_hold_s",
                         time.monotonic() - t0)
        return out

    def bulk_write(self, keys: np.ndarray, soa: Dict[str, np.ndarray]) -> None:
        sid = self._shard_ids(keys)
        for s, shard in enumerate(self._shards):
            sel = np.nonzero(sid == s)[0]
            if len(sel):
                shard.upsert(keys[sel], fv.select_rows(soa, sel))

    # -- lifecycle policy (≙ CtrCommonAccessor, ctr_accessor.cc) ------------
    def _score(self, soa: Dict[str, np.ndarray]) -> np.ndarray:
        sgd = self.config.sgd
        return (sgd.nonclk_coeff * (soa["show"] - soa["click"])
                + sgd.clk_coeff * soa["click"])

    def end_day(self) -> None:
        """Day rollover: decay show/click, age unseen features
        (≙ CtrCommonAccessor::UpdateStatAfterSave / show_click_decay)."""
        decay = self.config.accessor.show_click_decay_rate
        for shard in self._shards:
            with shard.lock:
                shard.soa["show"] *= decay
                shard.soa["click"] *= decay
                shard.soa["unseen_days"] += 1.0

    def shrink(self) -> int:
        """Evict dead features (≙ Table::Shrink via accessor thresholds:
        score < delete_threshold or unseen too long)."""
        acc = self.config.accessor
        removed = 0
        for shard in self._shards:
            with shard.lock:
                score = self._score(shard.soa)
                keep = ~((score < acc.delete_threshold) |
                         (shard.soa["unseen_days"] > acc.delete_after_unseen_days))
                removed += int((~keep).sum())
                shard.keys = shard.keys[keep]
                for f in shard.soa:
                    shard.soa[f] = shard.soa[f][keep]
                shard.rebuild_index()
        return removed

    # -- persistence (≙ SaveBase/SaveDelta box_wrapper.cc:1286; per-shard
    #    files with .shard suffix, memory_sparse_table.h:34) ----------------
    def save(self, path: str, mode: str = "base") -> int:
        """Per-shard npz dumps under `path`, which may be any registered
        filesystem scheme — e.g. hdfs://... through ShellFS
        (≙ SaveBase/SaveDelta's AFS paths, box_wrapper.h:721-743)."""
        from paddlebox_tpu.io import fs as pfs
        filesystem = pfs.get_fs(path)
        filesystem.mkdir(path)
        acc = self.config.accessor
        saved = 0
        for i, shard in enumerate(self._shards):
            with shard.lock:
                score = self._score(shard.soa)
                if mode == "base":
                    keep = score >= acc.base_threshold
                elif mode == "delta":
                    keep = np.abs(shard.soa["delta_score"]) >= acc.delta_threshold
                else:  # "all" / checkpoint
                    keep = np.ones(shard.size, bool)
                data = {f: arr[keep] for f, arr in shard.soa.items()}
                data["keys"] = shard.keys[keep]
                part = f"{path.rstrip('/')}/part-{i:05d}.shard.npz"
                with filesystem.open_write(part) as fh:
                    np.savez(fh, **data)
                saved += int(keep.sum())
                if mode == "delta":
                    shard.soa["delta_score"][keep] = 0.0
        return saved

    def load(self, path: str) -> int:
        from io import BytesIO

        from paddlebox_tpu.io import fs as pfs
        filesystem = pfs.get_fs(path)
        loaded = 0
        for i, shard in enumerate(self._shards):
            f = f"{path.rstrip('/')}/part-{i:05d}.shard.npz"
            if not filesystem.exists(f):
                continue
            fh = filesystem.open_read(f)
            # np.load needs seek; only pipe-backed streams buffer fully
            src = fh if fh.seekable() else BytesIO(fh.read())
            with np.load(src) as z:
                with shard.lock:
                    shard.keys = z["keys"]
                    n = len(shard.keys)
                    # checkpoints from a different optimizer config may
                    # lack some state fields (e.g. adam moments when the
                    # save ran under adagrad) — init those like fresh rows
                    # instead of KeyErroring: moments/g2sums start at 0,
                    # beta-power trackers at the decay rates (the adam
                    # creation init, ≙ optimizer.cuh.h:436-441)
                    sgd = self.config.sgd
                    fresh = {"_b1p": sgd.beta1_decay_rate,
                             "_b2p": sgd.beta2_decay_rate}

                    def init_missing(name, tmpl):
                        fill = next((v for suf, v in fresh.items()
                                     if name.endswith(suf)), 0.0)
                        return np.full((n,) + tmpl.shape[1:], fill,
                                       tmpl.dtype)

                    def from_ckpt(name, tmpl):
                        if name not in z.files:
                            return init_missing(name, tmpl)
                        arr = z[name]
                        # accessor migration (e.g. ctr -> ctr_double):
                        # the template dtype wins or appended rows would
                        # mix dtypes and f64 exactness silently degrades
                        return arr.astype(tmpl.dtype) \
                            if arr.dtype != tmpl.dtype else arr

                    shard.soa = {name: from_ckpt(name, tmpl)
                                 for name, tmpl in shard.soa.items()}
                    shard.rebuild_index()
            fh.close()
            loaded += shard.size
        return loaded
