from paddlebox_tpu.ps.host_table import ShardedHostTable  # noqa: F401
from paddlebox_tpu.ps.pass_manager import BoxPSEngine  # noqa: F401
