"""Explicit cross-chip embedding exchange — the HeterComm equivalent.

≙ HeterComm's sharded pull/push (heter_comm_inl.h): split_input_to_shard
(:1117, key % device_count), walk_to_dest/walk_to_src P2P hops (:303,316),
merged gradient push (:1730) and the inter-node allgather (:2027,2131).

TPU-first redesign inside shard_map over the table axis:
* the pass working set is row-sharded in CONTIGUOUS blocks (device d owns
  rows [d*rows_loc, (d+1)*rows_loc)) — owner = row // rows_loc, no hash;
* pull: all_gather the batch's row ids (ids are tiny vs values), each
  device gathers the rows it owns (masked), and one reduce_scatter returns
  exactly the requesting device's slice — two ICI collectives replacing the
  reference's per-pair cudaMemcpyPeer walks;
* push: the transpose — all_gather the grads' target ids + values?  No:
  grads all_gather is the reduce_scatter transpose, so we all_gather the
  (ids, grad) pairs and every device scatter-adds the rows it owns locally
  (≙ gather_one_node_grad's allgather + local merge, heter_comm_inl.h:2027).

Use when GSPMD's automatic layout of `table[idx]` is not wanted; the pjit
path (embedding.py + HybridTopology.table_spec) remains the default.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def pull_rows_sharded(table_local: jnp.ndarray, idx_local: jnp.ndarray,
                      axis: str) -> jnp.ndarray:
    """Inside shard_map.  table_local: [rows_loc, D] (this device's block of
    the [N, D] table); idx_local: [P_loc] global row ids needed by this
    device's batch shard.  → [P_loc, D]."""
    n_dev = lax.axis_size(axis)
    rows_loc = table_local.shape[0]
    me = lax.axis_index(axis)
    # 1. everyone learns everyone's requests (ids only — cheap)
    idx_all = lax.all_gather(idx_local, axis, axis=0, tiled=True)  # [P]
    # 2. gather the rows I own; zeros elsewhere
    local = idx_all - me * rows_loc
    mine = (local >= 0) & (local < rows_loc)
    vals = table_local[jnp.clip(local, 0, rows_loc - 1)] \
        * mine[:, None].astype(table_local.dtype)          # [P, D]
    # 3. sum over devices, returning each requester its slice
    return lax.psum_scatter(vals, axis, scatter_dimension=0, tiled=True)


def push_rows_sharded(table_local: jnp.ndarray, idx_local: jnp.ndarray,
                      grads_local: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Scatter-add grads into the row-sharded table (merge-by-key lands on
    the owner, ≙ push_sparse_multi_node).  grads_local: [P_loc, D]."""
    n_dev = lax.axis_size(axis)
    rows_loc = table_local.shape[0]
    me = lax.axis_index(axis)
    idx_all = lax.all_gather(idx_local, axis, axis=0, tiled=True)   # [P]
    g_all = lax.all_gather(grads_local, axis, axis=0, tiled=True)   # [P, D]
    local = idx_all - me * rows_loc
    mine = (local >= 0) & (local < rows_loc)
    safe = jnp.where(mine, local, 0)
    g_masked = g_all * mine[:, None].astype(g_all.dtype)
    # row 0 of device 0 is the global reserved row; non-owned writes go to
    # local row 0 with zero grads, so they are no-ops
    return table_local.at[safe].add(g_masked)


# ---------------------------------------------------------------------------
# MXU-kernel variants: same collectives, but the per-device random access
# runs through the sorted one-hot-matmul kernels (ops/sorted_spmm.py)
# instead of XLA's serial gather/scatter — the multi-chip version of the
# single-chip mxu path (ps/mxu_path.py).  Out-of-block ids land in the
# local sentinel tile, so ownership masking falls out of the kernel
# geometry for free (gathers read zeros, scatters write a discarded tile).
# ---------------------------------------------------------------------------

def local_plan(idx_local: jnp.ndarray, rows_loc: int, axis: str):
    """all_gather the ids and localize to this device's row block: ids
    outside [me*rows_loc, (me+1)*rows_loc) park at the sentinel tile, so
    ownership masking falls out of the kernel geometry.

    Pull and push need the IDENTICAL plan, so callers should build it once
    per step (or once per pass) and hand it to both — the sort is the only
    data-dependent cost in the exchange (≙ the reference building its
    shard index once in split_input_to_shard, heter_comm_inl.h:1117)."""
    from paddlebox_tpu.ops import sorted_spmm as sp
    me = lax.axis_index(axis)
    idx_all = lax.all_gather(idx_local, axis, axis=0, tiled=True)   # [P]
    dims = sp.spmm_dims(idx_all.shape[0], rows_loc)
    local = idx_all - me * rows_loc
    local = jnp.where((local >= 0) & (local < rows_loc), local,
                      dims.sentinel)
    return dims, sp.build_plan(local, dims)


def _plan_dims(plan, rows_loc: int):
    """Static geometry a local plan was built with (inv_perm carries the
    gathered occurrence count).  Sharded exchanges take UNTRIMMED plans
    only — a trimmed plan keeps inv_perm full-length while the worklists
    shrink, which would reconstruct an over-sized grid here."""
    from paddlebox_tpu.ops import sorted_spmm as sp
    dims = sp.spmm_dims(plan[2].shape[0], rows_loc)
    if plan[0].shape[0] != dims.n_chunks:
        raise ValueError(
            f"sharded exchange needs an untrimmed local_plan: rows2d has "
            f"{plan[0].shape[0]} chunks, geometry expects {dims.n_chunks}")
    return dims


def pull_rows_sharded_mxu(table_fm_local: jnp.ndarray,
                          idx_local: jnp.ndarray, axis: str,
                          interpret: bool = False,
                          plan=None) -> jnp.ndarray:
    """Inside shard_map.  table_fm_local: [W, rows_loc] feature-major block;
    idx_local: [P_loc] global row ids.  → [W, P_loc] pulled values.

    ≙ HeterComm pull_merge_sparse (heter_comm_inl.h:1296) with the shard
    walk replaced by all_gather(ids) + local SpMM + psum_scatter(values).
    plan: precomputed `local_plan` output for these ids (skips the in-step
    all_gather + sort; pull/push share one plan).
    """
    from paddlebox_tpu.ops import sorted_spmm as sp
    rows_loc = table_fm_local.shape[1]
    if plan is None:
        dims, plan = local_plan(idx_local, rows_loc, axis)
    else:
        dims = _plan_dims(plan, rows_loc)
    rows2d, perm, inv_perm, ch, tl, fg, fs, first_occ = plan
    # pad the local block to kernel geometry (sentinel tile = zeros)
    tab = jnp.zeros((table_fm_local.shape[0], dims.n_kernel),
                    table_fm_local.dtype)
    tab = lax.dynamic_update_slice(tab, table_fm_local, (0, 0))
    g = sp.gather_sorted(tab, rows2d, ch, tl, fg, dims,
                         interpret=interpret)                   # [W, p_pad]
    vals = jnp.take(g[:, :dims.p], inv_perm, axis=1)            # [W, P]
    # requester receives its slice; only the owner contributed nonzero.
    # Optional reduced-precision collective (EQuARX-style): every element
    # has exactly ONE nonzero contributor (the owning device), so the
    # bf16 "sum" incurs only the rounding of that single value — ids and
    # plans stay exact, ICI bytes halve.
    from paddlebox_tpu import flags as _flags
    if _flags.get_flags("sharded_exchange_bf16"):
        return lax.psum_scatter(vals.astype(jnp.bfloat16), axis,
                                scatter_dimension=1,
                                tiled=True).astype(jnp.float32)
    return lax.psum_scatter(vals, axis, scatter_dimension=1, tiled=True)


def push_rows_sharded_mxu(idx_local: jnp.ndarray,
                          payload_local: jnp.ndarray, rows_loc: int,
                          axis: str, interpret: bool = False,
                          first_only_col: int = -1,
                          plan=None) -> jnp.ndarray:
    """Inside shard_map.  payload_local: [W, P_loc] per-occurrence push
    values.  → merged per-row accumulators [W, rows_loc] for this device's
    block (feed to the local optimizer, ≙ gather_one_node_grad + local
    merge, heter_comm_inl.h:2027).

    first_only_col >= 0: that payload row keeps only each table row's FIRST
    occurrence before the merge (exact carry of e.g. the slot id instead of
    a sum — each row is owned by exactly one device, so its first gathered
    occurrence is the global first).
    plan: precomputed `local_plan` output (shared with the pull)."""
    from paddlebox_tpu.ops import sorted_spmm as sp
    if plan is None:
        dims, plan = local_plan(idx_local, rows_loc, axis)
    else:
        dims = _plan_dims(plan, rows_loc)
    rows2d, perm, inv_perm, ch, tl, fg, fs, first_occ = plan
    from paddlebox_tpu import flags as _flags
    if _flags.get_flags("sharded_exchange_bf16"):
        # halve the gathered payload bytes; the merge kernel's own hi/lo
        # split then operates on the rounded values.  The slot column must
        # stay EXACT (bf16 rounds integers > 256, and acc_from_delta
        # rint()s it back to an id) — gather it separately in f32.
        c = first_only_col
        if c >= 0:
            body = jnp.concatenate(
                [payload_local[:c], payload_local[c + 1:]])
            body_all = lax.all_gather(
                body.astype(jnp.bfloat16), axis, axis=1,
                tiled=True).astype(jnp.float32)
            slot_all = lax.all_gather(payload_local[c:c + 1], axis,
                                      axis=1, tiled=True)
            pay_all = jnp.concatenate(
                [body_all[:c], slot_all, body_all[c:]])
        else:
            pay_all = lax.all_gather(
                payload_local.astype(jnp.bfloat16), axis, axis=1,
                tiled=True).astype(jnp.float32)
    else:
        pay_all = lax.all_gather(payload_local, axis, axis=1, tiled=True)
    srt = jnp.take(pay_all, perm, axis=1)
    srt = jnp.concatenate(
        [srt, jnp.zeros((pay_all.shape[0], dims.p_pad - dims.p),
                        pay_all.dtype)], axis=1)
    if first_only_col >= 0:
        srt = srt.at[first_only_col, :].mul(first_occ)
    delta = sp.scatter_add_sorted(srt, rows2d, ch, tl, fs, dims,
                                  interpret=interpret)
    return delta[:, :rows_loc]


def push_rows_sharded_mxu_multinode(idx_local: jnp.ndarray,
                                    payload_local: jnp.ndarray,
                                    rows_loc: int, ici_axis, dcn_axis,
                                    interpret: bool = False,
                                    first_only_col: int = -1,
                                    plan=None) -> jnp.ndarray:
    """Two-tier push for the reference's multi-node layout: the table is
    sharded WITHIN a node (ici axis) and REPLICATED across nodes (dcn
    axis), nodes are data-parallel over the batch.

    ≙ gather_one_node_grad + gather_multi_node_grad
    (heter_comm_inl.h:2027,2131): stage 1 merges the node's own batch into
    this device's row block over ICI (all_gather ids/payload + local
    sorted-SpMM merge); stage 2 sums the node-merged [W, rows_loc] deltas
    across nodes over DCN — the per-node merge keeps the cross-node bytes
    at one dense block instead of every node's raw occurrence payload
    (the reference's reason for merging before the inter-node allgather).

    The first_only column (slot carry) is made node-consistent by pmax
    instead of the sum (each node's merge elects a first occurrence; the
    sum would add them)."""
    delta_node = push_rows_sharded_mxu(idx_local, payload_local, rows_loc,
                                       ici_axis, interpret=interpret,
                                       first_only_col=first_only_col,
                                       plan=plan)
    if first_only_col >= 0:
        slots = lax.pmax(delta_node[first_only_col], dcn_axis)
        delta = lax.psum(delta_node.at[first_only_col].set(0.0), dcn_axis)
        return delta.at[first_only_col].set(slots)
    return lax.psum(delta_node, dcn_axis)
