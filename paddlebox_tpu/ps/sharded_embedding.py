"""Explicit cross-chip embedding exchange — the HeterComm equivalent.

≙ HeterComm's sharded pull/push (heter_comm_inl.h): split_input_to_shard
(:1117, key % device_count), walk_to_dest/walk_to_src P2P hops (:303,316),
merged gradient push (:1730) and the inter-node allgather (:2027,2131).

TPU-first redesign inside shard_map over the table axis:
* the pass working set is row-sharded in CONTIGUOUS blocks (device d owns
  rows [d*rows_loc, (d+1)*rows_loc)) — owner = row // rows_loc, no hash;
* pull: all_gather the batch's row ids (ids are tiny vs values), each
  device gathers the rows it owns (masked), and one reduce_scatter returns
  exactly the requesting device's slice — two ICI collectives replacing the
  reference's per-pair cudaMemcpyPeer walks;
* push: the transpose — all_gather the grads' target ids + values?  No:
  grads all_gather is the reduce_scatter transpose, so we all_gather the
  (ids, grad) pairs and every device scatter-adds the rows it owns locally
  (≙ gather_one_node_grad's allgather + local merge, heter_comm_inl.h:2027).

Use when GSPMD's automatic layout of `table[idx]` is not wanted; the pjit
path (embedding.py + HybridTopology.table_spec) remains the default.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def pull_rows_sharded(table_local: jnp.ndarray, idx_local: jnp.ndarray,
                      axis: str) -> jnp.ndarray:
    """Inside shard_map.  table_local: [rows_loc, D] (this device's block of
    the [N, D] table); idx_local: [P_loc] global row ids needed by this
    device's batch shard.  → [P_loc, D]."""
    n_dev = lax.axis_size(axis)
    rows_loc = table_local.shape[0]
    me = lax.axis_index(axis)
    # 1. everyone learns everyone's requests (ids only — cheap)
    idx_all = lax.all_gather(idx_local, axis, axis=0, tiled=True)  # [P]
    # 2. gather the rows I own; zeros elsewhere
    local = idx_all - me * rows_loc
    mine = (local >= 0) & (local < rows_loc)
    vals = table_local[jnp.clip(local, 0, rows_loc - 1)] \
        * mine[:, None].astype(table_local.dtype)          # [P, D]
    # 3. sum over devices, returning each requester its slice
    return lax.psum_scatter(vals, axis, scatter_dimension=0, tiled=True)


def push_rows_sharded(table_local: jnp.ndarray, idx_local: jnp.ndarray,
                      grads_local: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Scatter-add grads into the row-sharded table (merge-by-key lands on
    the owner, ≙ push_sparse_multi_node).  grads_local: [P_loc, D]."""
    n_dev = lax.axis_size(axis)
    rows_loc = table_local.shape[0]
    me = lax.axis_index(axis)
    idx_all = lax.all_gather(idx_local, axis, axis=0, tiled=True)   # [P]
    g_all = lax.all_gather(grads_local, axis, axis=0, tiled=True)   # [P, D]
    local = idx_all - me * rows_loc
    mine = (local >= 0) & (local < rows_loc)
    safe = jnp.where(mine, local, 0)
    g_masked = g_all * mine[:, None].astype(g_all.dtype)
    # row 0 of device 0 is the global reserved row; non-owned writes go to
    # local row 0 with zero grads, so they are no-ops
    return table_local.at[safe].add(g_masked)
