"""Ragged CSR sparse step — per-step math in the [P_valid]/[U] domain.

The fast path (ps/fast_path.py) is padded-dense: every pull/push
materializes [S, L, B] occurrence tensors (≈1.27M cells at bench geometry)
behind a recomputed length mask, and its scalar-state update runs ~9
full-[N] elementwise passes over the whole working set per step even
though only U = |unique(idx)| rows are touched.  This module is the third
step lowering: the pass is lowered to CSR ONCE host-side
(data/pass_feed.py build_csr_plans — on the PR 7 prefetch worker the
build hides under pass N's training), and the jitted step then only ever
touches

* [P_valid] — the valid (non-padding) occurrences of one batch, and
* [U]       — the batch's sorted-unique working-set rows,

never the padded [S, L, B] domain and never a full-[N] sweep.  This is
the Ragged Paged Attention shape (PAPERS.md) applied to the embedding
step, and COGNATE's keep-sparse-compute-in-the-nonzero-domain argument;
the reference's fused kernels (pull_box_sparse_op / fused_seqpool_cvm_op)
do the same work from a pass-scope dedup index (DedupKeysAndFillIdx,
box_wrapper_impl.h:129).

Plan layout (one batch; see build_csr_plans for the full contract):
  seg    [P] int32 — pooled segment s*B + b of each valid occurrence
  inv    [P] int32 — occurrence → [U]-position; position 0 = row 0
  occ_w  [P] f32   — 1 valid / 0 pad (zeroes pad payloads on push)
  u_rows [U] int32 — sorted-unique working-set rows (u_rows[0] == 0)
  u_slot [U] int32 — merged per-row slot id (max over occurrences)

Forward = one [U]-row gather → ``jax.ops.segment_sum`` seqpool → CVM.
Backward = segment-sum of d_pooled into [U] accumulators → the EXISTING
optimizer rules (ps/optimizer.py apply_push) applied to the gathered
[U]-row sub-SoA → one ``.at[u_rows].set`` scatter back.  The optimizer
rules are shape-generic over their leading dim, and ``push_touched``'s
``arange(U) != 0`` exclusion lands exactly on [U]-position 0 = reserved
row 0, so the whole rule set is reused verbatim — no ragged-specific
update math to keep in sync.

Determinism: segment_sum lowers to a deterministic scatter-add whose
duplicate contributions apply in operand order; occurrences are
enumerated in the fast path's canonical (s, l, b) flat order, so per-row
summand order matches fast_path's own scatter-adds.  The write-back
scatter has duplicates only at row 0 ([U]-position 0 plus every pad
position), and all of them carry row 0's untouched pass-through values —
identical writes, deterministic result.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from paddlebox_tpu.config import SparseSGDConfig
from paddlebox_tpu.ps import optimizer as sparse_opt

# working-set fields the step must NOT route through the [U]-domain
# gather/update/scatter cycle (quantization sidecars etc. keyed off "mf"
# stay whatever shape embedding.py gave them; scalars have no row dim)
_ROW_FIELDS_SKIP = ("mf_scale",)


def _row_fields(ws: Dict[str, jnp.ndarray]):
    n = ws["show"].shape[0]
    return [f for f, v in ws.items()
            if f not in _ROW_FIELDS_SKIP
            and getattr(v, "ndim", 0) >= 1 and v.shape[0] == n]


def pull_pool_cvm(ws: Dict[str, jnp.ndarray], plan: Tuple[jnp.ndarray, ...],
                  shape_slb: Tuple[int, int, int],
                  use_cvm: bool = True) -> jnp.ndarray:
    """Fused pull + seqpool + CVM from a CSR plan.

    plan: (seg, inv, occ_w, u_rows, u_slot) — pass_feed.plan_tuple order.
    → pooled [B, S, E], E = 3 + D (cols: cvm'show, cvm'click, w, mf...) —
    bit-compatible with fast_path.pull_pool_cvm's output contract.

    Pad occurrences need no mask here: inv = 0 points at [U]-position 0 =
    working-set row 0, the reserved all-zero row, so their segment
    contribution is exactly 0.0.
    """
    seg, inv, occ_w, u_rows, u_slot = plan
    s, l, b = shape_slb
    from paddlebox_tpu.ps.embedding import mf_values
    head = jnp.stack([ws["show"][u_rows], ws["click"][u_rows],
                      ws["embed_w"][u_rows]], axis=-1)        # [U, 3]
    created = (ws["mf_size"][u_rows] > 0).astype(head.dtype)
    mf_u = mf_values(ws, ws["mf"][u_rows]) * created[:, None]  # [U, D]
    u_vals = jnp.concatenate([head, mf_u], axis=-1)            # [U, E]
    pooled = jax.ops.segment_sum(
        u_vals[inv], seg, num_segments=s * b).reshape(s, b, -1)
    show = pooled[:, :, 0]
    click = pooled[:, :, 1]
    if use_cvm:
        show_t = jnp.log(show + 1.0)
        click_t = jnp.log(click + 1.0) - show_t
    else:
        show_t, click_t = show, click
    pooled = jnp.concatenate(
        [jnp.stack([show_t, click_t], axis=-1), pooled[:, :, 2:]], axis=-1)
    return jnp.transpose(pooled, (1, 0, 2))                    # [B, S, E]


def push_and_update(ws: Dict[str, jnp.ndarray],
                    plan: Tuple[jnp.ndarray, ...], d_pooled: jnp.ndarray,
                    ins_cvm: jnp.ndarray, shape_slb: Tuple[int, int, int],
                    cfg: SparseSGDConfig) -> Dict[str, jnp.ndarray]:
    """Merged push + optimizer update, entirely in the [P]/[U] domain.

    d_pooled [B, S, E] (cols 0,1 ignored, replaced by ins_cvm per the
    reference push semantics); ins_cvm [B, 2].  Any OPTIMIZERS rule works:
    the [U]-row sub-SoA is gathered, apply_push runs verbatim on it, and
    the result scatters back with one ``.at[u_rows].set`` per field.
    """
    seg, inv, occ_w, u_rows, u_slot = plan
    s, l, b = shape_slb
    u = u_rows.shape[0]
    b_of = seg % b

    # -- per-occurrence payloads ([P]) -> merged [U] accumulators ---------
    # occ_w zeroes every pad position's payload, so pad occurrences add an
    # exact 0.0 into [U]-position 0 and push_touched never fires there.
    d_sb = jnp.transpose(d_pooled, (1, 0, 2)).reshape(s * b, -1)  # [S*B, E]
    occ_pay = jnp.take(d_sb, seg, axis=0)                         # [P, E]
    g_show = jax.ops.segment_sum(
        jnp.take(ins_cvm[:, 0], b_of) * occ_w, inv, num_segments=u)
    g_click = jax.ops.segment_sum(
        jnp.take(ins_cvm[:, 1], b_of) * occ_w, inv, num_segments=u)
    g_embed = jax.ops.segment_sum(occ_pay[:, 2] * occ_w, inv,
                                  num_segments=u)
    g_mf = jax.ops.segment_sum(occ_pay[:, 3:] * occ_w[:, None], inv,
                               num_segments=u)                    # [U, D]
    acc = {"g_show": g_show, "g_click": g_click, "g_embed": g_embed,
           "g_embedx": g_mf, "slot": u_slot}

    # -- optimizer on the [U]-row frontier only ---------------------------
    fields = _row_fields(ws)
    sub = {f: ws[f][u_rows] for f in fields}
    new = sparse_opt.apply_push(sub, acc, cfg)

    # -- one scatter back into the working-set SoA ------------------------
    # row 0 appears at [U]-position 0 and at every u_rows pad slot; all of
    # them were untouched (g_show == 0 there) so every duplicate write
    # carries row 0's original values — the .set is deterministic.
    out = dict(ws)
    for f in fields:
        if f in new:
            out[f] = ws[f].at[u_rows].set(new[f])
    return out
