"""Device-resident hot-row embedding cache — the HBM tier of the store.

≙ the HeterPS HBM-cached table (fleet/heter_ps: HeterComm keeps the pass
working set plus a hot-row pool resident in device memory; ps_gpu_wrapper
only faults cold rows in from the DRAM/SSD tiers).  We reproduce the same
three-tier layout on top of the existing pass lifecycle:

  HBM   DeviceRowCache (this file)      — hottest rows, survives passes
  DRAM  ShardedHostTable / remote PS    — full table, pass write-back
  SSD   ssd_table spill                 — cold rows

The cache is **write-back at pass granularity** and never a second source
of truth across a checkpoint commit:

* ``pass_manager._build_host`` intersects the pass's unique keys with an
  immutable index *snapshot* (published at ``begin_feed_pass``) and pulls
  only MISSES over the wire;
* at adoption (``begin_pass``, main thread) hits are re-resolved against
  the live index and gathered device-side into the working set
  (``embedding``-compatible dtypes, so ``pull_sparse``/``push_sparse_grads``
  are unchanged for the model);
* the ONLY row mutation is the ``end_pass`` fold-back
  (:meth:`update_after_pass`, after the table ``bulk_write`` succeeded)
  and :meth:`invalidate` at coherence points (``end_day`` decay,
  ``shrink``, checkpoint ``resume``/rollback, ``reset_feed_state``).
  pboxlint PB503 enforces exactly that call-site discipline.

Thread model (PassPrefetcher overlap): pass N+1's feed/build runs on
worker threads while pass N trains and folds back on the main thread.
Only the INDEX (sorted keys → slots) crosses threads, and it is
copy-on-write: mutations build new arrays and swap them under ``_lock``,
so a snapshot taken at ``begin_feed_pass`` is torn-read-free.  All VALUE
access (mirror reads, store gathers/scatters) happens on the main thread
at adoption/fold-back; a hit whose row was evicted between snapshot and
adoption simply re-resolves as a miss and falls back to a wire pull.

Bit-identity argument: a resident row's device values are exactly the
values ``build_working_set`` would produce from the host row we last
wrote back (same f32/int32 casts; the f64 ctr_double show/click are cast
host-side from the merged write-back values), and its host mirror equals
the written row — so a cache hit yields the same working-set bits, the
same f64 pulled-stats base, and the same delta-mode write-back base as a
wire pull of the row we just wrote.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from paddlebox_tpu.ps import embedding
from paddlebox_tpu.ps import heat
from paddlebox_tpu.utils import flight, lockdep
from paddlebox_tpu.utils.monitor import stat_add, stat_set


class CacheIndexSnapshot:
    """Frozen (version, sorted keys) view published at begin_feed_pass.

    The feed/build threads use it only to decide what NOT to pull; the
    authoritative key→slot resolution happens later on the main thread
    (:meth:`DeviceRowCache.resolve`)."""

    __slots__ = ("version", "keys")

    def __init__(self, version: int, keys: np.ndarray):
        self.version = version
        self.keys = keys            # sorted uint64, never mutated in place

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """Membership mask of `keys` (sorted unique) in the snapshot."""
        if len(self.keys) == 0 or len(keys) == 0:
            return np.zeros(len(keys), bool)
        pos = np.searchsorted(self.keys, keys)
        pos_c = np.minimum(pos, len(self.keys) - 1)
        return self.keys[pos_c] == keys


class CachePlan:
    """What a feed-thread build decided against a snapshot: which pass
    positions it expects to fill from the cache (so it did NOT pull them)
    and which keys it actually pulled.  Consumed at adoption on the main
    thread, where the hit set is re-validated against the live index."""

    __slots__ = ("keys", "pos", "snap", "n_miss", "pulled_keys")

    def __init__(self, keys: np.ndarray, pos: np.ndarray,
                 snap: CacheIndexSnapshot, n_miss: int,
                 pulled_keys: Optional[np.ndarray]):
        self.keys = keys            # snapshot-hit keys (sorted)
        self.pos = pos              # their positions in the pass key array
        self.snap = snap
        self.n_miss = n_miss
        self.pulled_keys = pulled_keys   # wire-pulled key set (None if none)


class DeviceRowCache:
    """Fixed-capacity device-resident row pages keyed by feasign.

    Rows live in two planes sharing one slot space:

    * ``_store``  — device arrays ``[capacity, ...]`` per working-set
      field (f32/int32, the exact dtypes ``build_working_set`` emits);
    * ``_mirror`` — host arrays per table field (native host dtypes,
      f64 show/click under ctr_double, plus ``unseen_days``) — the
      write-back base for delta-mode remotes and the f64 stats source.

    Admission/eviction ranks by the same day-scale score ``shrink`` uses
    (``nonclk_coeff*(show-click) + clk_coeff*click``) plus pass recency;
    rows touched by the current pass are never evicted by it.

    Step-path agnostic: the cache operates on whole working-set rows
    (gather at adoption, fold-back at end_pass), never on the step's
    intermediate layout — so fast ([S,L,B] padded), mxu (sorted-chunk),
    and ragged (CSR [U]-domain) steps compose with it unchanged, and the
    cache on/off bit-identity tests hold per path.
    """

    def __init__(self, capacity: int, nonclk_coeff: float = 0.1,
                 clk_coeff: float = 1.0):
        assert capacity > 0
        self.capacity = int(capacity)
        self.nonclk_coeff = float(nonclk_coeff)
        self.clk_coeff = float(clk_coeff)
        self._lock = lockdep.lock("ps.device_cache.DeviceRowCache._lock")
        self.version = 0
        # copy-on-write index: sorted resident keys + their slots
        self._keys = np.empty((0,), np.uint64)
        self._slots = np.empty((0,), np.int32)
        # per-slot metadata (value planes — main-thread only)
        self._slot_key = np.zeros((self.capacity,), np.uint64)  # 0 = free
        self._slot_score = np.zeros((self.capacity,), np.float64)
        self._slot_pass = np.full((self.capacity,), -1, np.int64)
        self._store: Optional[Dict[str, jnp.ndarray]] = None
        self._mirror: Optional[Dict[str, np.ndarray]] = None
        self.row_bytes = 0          # f32-basis host bytes per cached row
        # cluster topology (optional): the fleet's ServerMap plus this
        # device's rank/world.  With a map attached, admission is keyed
        # by the SAME splitmix64 placement the PS cluster uses — each
        # device caches a disjoint slice of the key space, so aggregate
        # cache capacity (and hit rate) scales with the device count
        # instead of every device burning HBM on the same head rows.
        self._server_map = None
        self._device_rank = 0
        self._device_world = 1
        # epoch stamp of the owned mask: the membership epoch the current
        # admission placement was computed under.  A live reshard bumps it
        # via update_server_map(), which also drops exactly the moved range.
        self._map_epoch = 0

    def attach_server_map(self, server_map, device_rank: int = 0,
                          device_world: int = 1) -> None:
        """Adopt the PS cluster's key placement for cache admission.

        ``shard_of_keys(key) % device_world == device_rank`` defines this
        device's owned slice.  Already-resident rows outside the slice are
        left to age out via normal eviction (attach happens before the
        first admission in practice, so the set is empty).  Main thread
        only, between passes.
        """
        with self._lock:
            self._server_map = server_map
            self._device_rank = int(device_rank)
            self._device_world = max(1, int(device_world))
            self._map_epoch = int(getattr(server_map, "epoch", 0))

    @property
    def map_epoch(self) -> int:
        """Membership epoch the resident set's owned mask was stamped
        under (0 when no ServerMap is attached)."""
        return self._map_epoch

    def update_server_map(self, new_map, reason: str = "") -> None:
        """Adopt a post-reshard ServerMap, invalidating ONLY the moved
        key range: rows whose owning shard is the same under the old and
        new placement keep their device/host planes hot; rows whose
        owner changed are dropped (their authoritative copy just moved
        between PS processes).  The owned admission mask is re-stamped
        with the new map's epoch.  Main thread only, between passes —
        same discipline as :meth:`invalidate` (PB503).
        """
        with self._lock:
            old_map = self._server_map
            if old_map is None or (
                    getattr(old_map, "n", 1) == getattr(new_map, "n", 1)
                    and getattr(old_map, "addrs", None)
                    == getattr(new_map, "addrs", None)):
                # first attach, or a no-op refresh (same membership):
                # nothing moved, just restamp
                self._server_map = new_map
                self._map_epoch = int(getattr(new_map, "epoch", 0))
                return
            keys = self._keys
            slots = self._slots
        if len(keys):
            moved = old_map.shard_of_keys(keys) != new_map.shard_of_keys(keys)
        else:
            moved = np.zeros((0,), bool)
        dropped = int(moved.sum())
        drop_slots = slots[moved]
        self._slot_key[drop_slots] = 0
        self._slot_score[drop_slots] = 0.0
        self._slot_pass[drop_slots] = -1
        keep = ~moved
        # version bump even when dropped == 0: in-flight snapshots may
        # predate the epoch flip and must resolve all-miss for safety
        with self._lock:
            self.version += 1
            self._keys = keys[keep]
            self._slots = slots[keep]
            self._server_map = new_map
            self._map_epoch = int(getattr(new_map, "epoch", 0))
            left = len(self._keys)
        stat_set("ps.cache.resident_rows", float(left))
        stat_add("ps.cache.invalidations")
        flight.record("cache_invalidate_moved", epoch=self._map_epoch,
                      reason=reason or "reshard", dropped=dropped,
                      kept=left)

    # -- index (cross-thread surface) ---------------------------------------
    def snapshot(self) -> CacheIndexSnapshot:
        """Publish the current index for a feed pass (prefetcher-safe:
        the returned arrays are never mutated in place)."""
        with self._lock:
            return CacheIndexSnapshot(self.version, self._keys)

    def resolve(self, keys: np.ndarray, snap: CacheIndexSnapshot
                ) -> Tuple[np.ndarray, np.ndarray]:
        """Authoritative hit resolution at adoption time (main thread):
        → (valid_mask, slots).  Keys evicted (or the whole cache
        invalidated) since the snapshot resolve as invalid and must be
        re-pulled over the wire by the caller."""
        with self._lock:
            if snap.version != self.version or len(self._keys) == 0 \
                    or len(keys) == 0:
                return np.zeros(len(keys), bool), \
                    np.zeros(len(keys), np.int32)
            pos = np.searchsorted(self._keys, keys)
            pos_c = np.minimum(pos, len(self._keys) - 1)
            found = self._keys[pos_c] == keys
            return found, np.where(found, self._slots[pos_c], 0)

    @property
    def resident_rows(self) -> int:
        with self._lock:
            return len(self._keys)

    # -- value planes (main-thread only) ------------------------------------
    def read_mirror(self, slots: np.ndarray,
                    fields: Optional[Tuple[str, ...]] = None
                    ) -> Dict[str, np.ndarray]:
        """Host-mirror rows for the given slots (write-back base /
        f64 stats source).  Main thread only."""
        assert self._mirror is not None
        names = fields if fields is not None else tuple(self._mirror)
        return {f: self._mirror[f][slots]
                for f in names if f in self._mirror}

    def host_templates(self, n: int) -> Dict[str, np.ndarray]:
        """Zero host-row arrays with the table's field dtypes/shapes —
        used when a pass has no misses at all (no wire pull to derive
        the SoA layout from)."""
        with self._lock:
            mirror = self._mirror
        assert mirror is not None
        return {f: np.zeros((n,) + v.shape[1:], v.dtype)
                for f, v in mirror.items()}

    def scatter_into(self, ws: Dict[str, jnp.ndarray], rows: np.ndarray,
                     slots: np.ndarray) -> Dict[str, jnp.ndarray]:
        """Cached-plane gather: copy resident rows into the pass working
        set device-side (no host staging, no wire bytes for hits).  Pure
        read of the store; returns the updated ws pytree."""
        assert self._store is not None
        slots_d = jnp.asarray(np.asarray(slots, np.int32))
        return embedding.scatter_device_rows(
            ws, np.asarray(rows, np.int32),
            {f: buf[slots_d] for f, buf in self._store.items()})

    def _ensure_planes(self, soa: Dict[str, np.ndarray],
                       ws: Dict[str, jnp.ndarray]) -> None:
        if self._store is not None:
            return
        store = {}
        for f in soa:
            if f == "unseen_days" or f not in ws:
                continue
            w = ws[f]
            store[f] = jnp.zeros((self.capacity,) + tuple(w.shape[1:]),
                                 w.dtype)
        self._mirror = {f: np.zeros((self.capacity,) + v.shape[1:], v.dtype)
                        for f, v in soa.items()}
        self._store = store
        self.row_bytes = int(sum(
            v.dtype.itemsize * int(np.prod(v.shape[1:], dtype=np.int64))
            for v in store.values()))

    def _score(self, soa: Dict[str, np.ndarray]) -> np.ndarray:
        show = np.asarray(soa["show"], np.float64)
        click = np.asarray(soa["click"], np.float64)
        return self.nonclk_coeff * (show - click) + self.clk_coeff * click

    # -- the single sanctioned mutation: end_pass fold-back ------------------
    def update_after_pass(self, keys: np.ndarray, soa: Dict[str, np.ndarray],
                          ws: Dict[str, jnp.ndarray], pass_id: int,
                          host_casts: Optional[Dict[str, np.ndarray]] = None
                          ) -> None:
        """Fold the pass's written rows back into the cache and run
        admission/eviction.  MUST be called only from the engine's
        ``end_pass``, after the table ``bulk_write`` succeeded (PB503) —
        on a write-back failure the cache stays untouched so the
        replayed end_pass folds back exactly once.

        ``keys`` are the pass's sorted unique keys (working-set rows
        1..n), ``soa`` the exact host rows just written, ``ws`` the
        trained device working set.  ``host_casts`` overrides the device
        source per field (ctr_double: the f64-merged show/click cast to
        f32 host-side, so hit rows replay the same f64→f32 cast a wire
        pull would).
        """
        n = len(keys)
        if n == 0:
            return
        self._ensure_planes(soa, ws)
        scores = self._score(soa)

        # resident rows of this pass: value refresh + recency/score
        if len(self._keys):
            pos = np.searchsorted(self._keys, keys)
            pos_c = np.minimum(pos, len(self._keys) - 1)
            res_mask = self._keys[pos_c] == keys
            res_idx = np.flatnonzero(res_mask)
            res_slots = self._slots[pos_c[res_mask]]
        else:
            res_idx = np.empty((0,), np.int64)
            res_slots = np.empty((0,), np.int32)

        # admission candidates: this pass's non-resident keys, hottest
        # first (stable key tie-break keeps the policy deterministic)
        cand_mask = np.ones((n,), bool)
        cand_mask[res_idx] = False
        cand = np.flatnonzero(cand_mask)
        # topology trio is co-mutated under _lock (attach_server_map /
        # update_server_map); a bare triple read could pair a new map
        # with the old rank/world mid-adopt — snapshot atomically (PB902)
        with self._lock:
            smap = self._server_map
            rank, world = self._device_rank, self._device_world
        if smap is not None and world > 1:
            # sharded topology: only admit this device's owned slice of
            # the key space (same ServerMap placement the wire uses)
            owned = (smap.shard_of_keys(keys[cand]) % world) == rank
            cand = cand[owned]
        order = np.lexsort((keys[cand], -scores[cand]))
        cand = cand[order]

        free = np.flatnonzero(self._slot_key == 0)
        take = cand[:len(free)]
        adm_idx: List[np.ndarray] = [take]
        adm_slots: List[np.ndarray] = [free[:len(take)]]
        rest = cand[len(free):]
        n_evict = 0
        if len(rest):
            # evict coldest residents NOT touched by this pass, but only
            # for strictly hotter candidates (ties keep the incumbent).
            # res_slots must be masked explicitly — their _slot_pass still
            # holds the PREVIOUS pass until the update block below
            evict_ok = (self._slot_key != 0) & (self._slot_pass < pass_id)
            evict_ok[res_slots] = False
            evictable = np.flatnonzero(evict_ok)
            if len(evictable):
                eorder = np.lexsort((self._slot_key[evictable],
                                     self._slot_pass[evictable],
                                     self._slot_score[evictable]))
                evictable = evictable[eorder]
                k = min(len(rest), len(evictable))
                wins = scores[rest[:k]] > self._slot_score[evictable[:k]]
                n_evict = int(np.argmin(wins)) if not wins.all() else k
                if n_evict:
                    ev = evictable[:n_evict]
                    if heat.ACTIVE is not None:
                        # churn tracking: which keys fall out of HBM
                        heat.ACTIVE.observe("cache_evict",
                                            self._slot_key[ev])
                    # pboxlint: disable-next=PB102 -- value planes are main-thread-only; _lock guards only the COW index
                    self._slot_key[ev] = 0
                    adm_idx.append(rest[:n_evict])
                    adm_slots.append(ev)
        adm_i = np.concatenate(adm_idx) if adm_idx else \
            np.empty((0,), np.int64)
        adm_s = np.concatenate(adm_slots) if adm_slots else \
            np.empty((0,), np.int32)

        upd_idx = np.concatenate([res_idx, adm_i]).astype(np.int64)
        upd_slots = np.concatenate([res_slots, adm_s]).astype(np.int32)
        if len(upd_idx):
            for f in self._mirror:
                if f in soa:
                    # pboxlint: disable-next=PB102 -- value planes are main-thread-only; _lock guards only the COW index
                    self._mirror[f][upd_slots] = soa[f][upd_idx]
            rows_d = jnp.asarray(upd_idx.astype(np.int32) + 1)  # ws rows 1..n
            slots_d = jnp.asarray(upd_slots)
            for f in self._store:
                if host_casts is not None and f in host_casts:
                    src = jnp.asarray(host_casts[f][upd_idx],
                                      self._store[f].dtype)
                else:
                    src = ws[f][rows_d]
                # pboxlint: disable-next=PB102 -- value planes are main-thread-only; _lock guards only the COW index
                self._store[f] = self._store[f].at[slots_d].set(src)
            self._slot_key[upd_slots] = keys[upd_idx]
            # pboxlint: disable-next=PB102 -- value planes are main-thread-only; _lock guards only the COW index
            self._slot_score[upd_slots] = scores[upd_idx]
            # pboxlint: disable-next=PB102 -- value planes are main-thread-only; _lock guards only the COW index
            self._slot_pass[upd_slots] = pass_id

        # copy-on-write index swap (feed threads may hold the old arrays)
        occ = np.flatnonzero(self._slot_key != 0).astype(np.int32)
        kocc = self._slot_key[occ]
        korder = np.argsort(kocc, kind="stable")
        with self._lock:
            lockdep.guards(self, "_keys")
            self._keys = kocc[korder]
            self._slots = occ[korder]
        stat_set("ps.cache.resident_rows", float(len(occ)))
        if heat.ACTIVE is not None and len(adm_i):
            heat.ACTIVE.observe("cache_admit", keys[adm_i])
        if n_evict:
            stat_add("ps.cache.evictions", float(n_evict))
            flight.record("cache_evict", pass_id=pass_id, count=n_evict,
                          resident=len(occ))

    # -- coherence points ----------------------------------------------------
    def invalidate(self, reason: str = "") -> None:
        """Version-bump + drop the whole index (end_day decay, shrink,
        checkpoint resume/rollback, reset_feed_state, server restart).
        In-flight snapshots resolve as all-miss afterwards; device/host
        planes stay allocated for reuse."""
        with self._lock:
            had = len(self._keys)
            self.version += 1
            self._keys = np.empty((0,), np.uint64)
            self._slots = np.empty((0,), np.int32)
        self._slot_key[:] = 0
        self._slot_score[:] = 0.0
        self._slot_pass[:] = -1
        stat_set("ps.cache.resident_rows", 0.0)
        stat_add("ps.cache.invalidations")
        flight.record("cache_invalidate", reason=reason or "unspecified",
                      dropped=had)

    def invalidate_shard(self, shard: int, reason: str = "") -> None:
        """Drop only one PS cluster shard's resident rows (single-shard
        supervisor restart behind a fan-out: the other N-1 shards never
        lost state, so their cached rows stay hot).  Falls back to a full
        invalidate when no ServerMap is attached.  Main thread only."""
        if self._server_map is None:
            self.invalidate(reason or f"shard-{shard}")
            return
        with self._lock:
            keys = self._keys
            slots = self._slots
        hit = self._server_map.shard_of_keys(keys) == int(shard)
        dropped = int(hit.sum())
        drop_slots = slots[hit]
        self._slot_key[drop_slots] = 0
        self._slot_score[drop_slots] = 0.0
        self._slot_pass[drop_slots] = -1
        keep = ~hit
        # version bump even when dropped == 0: in-flight snapshots may
        # predate the restart and must resolve all-miss for safety
        with self._lock:
            self.version += 1
            self._keys = keys[keep]
            self._slots = slots[keep]
            left = len(self._keys)
        stat_set("ps.cache.resident_rows", float(left))
        stat_add("ps.cache.invalidations")
        flight.record("cache_invalidate_shard", shard=int(shard),
                      reason=reason or "unspecified", dropped=dropped)
