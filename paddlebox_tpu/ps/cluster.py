"""Sharded PS cluster topology — key-space partitioning and 2-phase lifecycle.

≙ the reference's multi-server deployment (PAPER.md L5b: `brpc_ps_client`
routing `key % shard_num` across `brpc_ps_server` processes,
`boxps::MPICluster`): a :class:`ServerMap` assigns every feasign to exactly
one of N parameter servers by a deterministic splitmix64 hash, so placement
is stable across runs, restarts, and client instances — the property that
makes N=1 and N=4 training bit-identical (each key's row lives on exactly
one shard, fresh-row defaults are pure in (seed, key), and per-key RMW
order within a shard is unchanged by the partition).

The hash salt is DISTINCT from the host-table's internal shard salt so the
two partitions decorrelate: a server's local `ShardedHostTable` spreads its
subset of the key space evenly across its own lock shards regardless of
which cluster shard it is.

Cluster-wide lifecycle (`end_day`, and any future decaying verb) is
2-phase over the per-server dedup windows: ``lifecycle_prepare`` on every
shard under a pinned rid-group, then ``lifecycle_commit`` only after all N
prepared.  Every phase rid is deterministic (``<group>.p<k>`` /
``<group>.c<k>``), so a caller-level retry after a partial failure replays
through the dedup windows — shards that already prepared/committed return
their cached ack, shards that didn't execute once.  Exactly-once decay
survives any single-shard crash + supervisor restart because the dedup
window itself is part of the restart handoff (service.dedup_state /
DEDUP.bin).  The commit frame carries the full verb (not just the txn id):
a restarted server that lost its staged-txn dict can still execute the
commit directly.

Checkpoint fan-out: `cluster_save`/`cluster_load` write/read per-shard
``shard-<k:03d>/`` subdirectories under the caller's path.  Because all N
subdirs live inside one generation tmpdir, the PR 8 tmp+rename commit and
the single cluster MANIFEST atomically advance ALL shards together —
crash recovery rolls every shard back to the same generation.

Elastic membership (ps/reshard.py) adds a monotonic **epoch** to the map:
every fenced sparse verb carries its client's epoch, a server whose
membership disagrees answers a typed ``wrong_epoch`` / ``not_owner`` /
``migrating`` rejection (never silently applying to a range it no longer
owns), and the client refreshes its map from the fleet's ``health``
surface (shard 0 preferred, falling through dead entries) and re-drives
only the affected chunks through the dedup window.  Membership changes
MUST route through the reshard API — pboxlint PB803 flags hand-built
``ServerMap`` construction or ``addrs``/``epoch`` mutation anywhere else
(:func:`make_server_map` is the sanctioned constructor for client code).

``cluster_load`` reshards on load: when the on-disk dump width differs
from the fleet width (an N=4 dump restoring into an N=2 fleet), every
fleet shard walks ALL source subdirs server-side and keeps only the keys
that hash to itself — the offline fallback when a live handoff isn't
wanted.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddlebox_tpu.ps import wire
from paddlebox_tpu.ps.feature_value import _keyed_hash
from paddlebox_tpu.utils import lockdep
from paddlebox_tpu.utils.monitor import stat_add, stat_observe

# Cluster-placement salt — deliberately distinct from any host-table
# internal salt so cluster-shard and lock-shard partitions decorrelate.
CLUSTER_SALT = 0x9E2A5C7B3D41F68D

# env var exporting the PS fleet's addresses to spawned workers
# (cluster analogue of the single-server PBOX_PS_ADDR)
ADDRS_ENV = "PBOX_PS_ADDRS"

# lifecycle verbs legal inside a 2-phase cluster transaction
# (reshard_cutover = the membership flip: commit adopts the staged/carried
#  new map, drops moved rows on the sources, and unfreezes the moving range)
LIFECYCLE_VERBS = ("end_day", "reshard_cutover")


def shard_dir(path: str, shard: int) -> str:
    """Per-shard subdirectory of a cluster checkpoint/dump path."""
    return os.path.join(path, f"shard-{shard:03d}")


def format_addrs(addrs: Sequence[Tuple[str, int]]) -> str:
    return ",".join(f"{h}:{p}" for h, p in addrs)


def parse_addrs(spec: str) -> List[Tuple[str, int]]:
    """Parse "host:port,host:port,..." (the ADDRS_ENV format)."""
    out: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        out.append((host, int(port)))
    return out


def addrs_from_env() -> Optional[List[Tuple[str, int]]]:
    spec = os.environ.get(ADDRS_ENV, "")
    return parse_addrs(spec) if spec else None


class ServerMap:
    """Deterministic key-hash → shard assignment over N server addresses.

    splitmix64 on (key ^ CLUSTER_SALT) mod N: seed-stable, uniform, and
    independent of insertion order — the same key always routes to the
    same shard for every client of the same fleet size.
    """

    __slots__ = ("addrs", "n", "epoch")

    def __init__(self, addrs: Sequence[Tuple[str, int]], epoch: int = 0):
        if not addrs:
            raise ValueError("ServerMap needs at least one server address")
        self.addrs: List[Tuple[str, int]] = [tuple(a) for a in addrs]
        self.n = len(self.addrs)
        # monotonic membership epoch: bumped by exactly one on every
        # committed reshard; fenced sparse verbs carry it so a server
        # whose membership disagrees can answer a typed redirect instead
        # of silently applying to a range it doesn't own
        self.epoch = int(epoch)

    def describe(self) -> Dict:
        """Wire-shaped membership descriptor (health / redirect hint).
        Addresses ride as the compact ``format_addrs`` string — the wire
        codec carries scalars and flat dicts, not nested lists."""
        return {"epoch": self.epoch, "addrs": format_addrs(self.addrs)}

    def shard_of_keys(self, keys: np.ndarray) -> np.ndarray:
        """Vectorized shard id per key (int64; all zeros when n == 1)."""
        keys = np.asarray(keys, np.uint64)
        if self.n == 1:
            return np.zeros(keys.shape, np.int64)
        return (_keyed_hash(keys, CLUSTER_SALT) % np.uint64(self.n)) \
            .astype(np.int64)

    def shard_of_key(self, key: int) -> int:
        return int(self.shard_of_keys(np.asarray([key], np.uint64))[0])

    def partition(self, keys: np.ndarray) -> List[np.ndarray]:
        """Positions of each shard's keys in the original array.

        Returns ``pos`` with ``len(pos) == n``; ``pos[s]`` preserves the
        caller's relative order, which keeps per-shard chunk payloads —
        and therefore pinned-rid replay bytes — deterministic.
        """
        shards = self.shard_of_keys(keys)
        return [np.flatnonzero(shards == s) for s in range(self.n)]


def owned_mask(keys: np.ndarray, shard: int, n: int) -> np.ndarray:
    """Boolean mask of ``keys`` owned by ``shard`` in an ``n``-wide fleet
    — the pure placement predicate (no address list needed), used by the
    server-side reshard-on-load owner filter."""
    keys = np.asarray(keys, np.uint64)
    if n <= 1:
        return np.ones(keys.shape, bool)
    return (_keyed_hash(keys, CLUSTER_SALT) % np.uint64(n)).astype(
        np.int64) == int(shard)


def make_server_map(addrs: Sequence[Tuple[str, int]],
                    epoch: int = 0) -> ServerMap:
    """Sanctioned ServerMap constructor for client/server code.

    pboxlint PB803 flags direct ``ServerMap(...)`` construction outside
    ps/cluster.py + ps/reshard.py so every membership change routes
    through the reshard API; code that merely needs a map over a known
    address list (PSClient ctor, server membership adoption) builds it
    here.
    """
    return ServerMap(addrs, epoch=epoch)


def map_from_desc(desc: Dict) -> ServerMap:
    """Rebuild a ServerMap from a membership descriptor
    (:meth:`ServerMap.describe` — health responses, redirect hints).
    Accepts the wire string form and the in-process pair-list form."""
    a = desc["addrs"]
    addrs = parse_addrs(a) if isinstance(a, str) \
        else [(h, int(p)) for h, p in a]
    return ServerMap(addrs, epoch=int(desc.get("epoch", 0)))


class _InflightBudget:
    """Shared in-flight chunk cap across the per-shard pipeline runs.

    One sharded verb drives N concurrent :class:`_PipelineRun` s; this
    budget keeps the TOTAL frames in flight at the single-server window,
    so fan-out multiplies wire concurrency without multiplying client
    memory.  Lock order: a run's _cv is always taken BEFORE this lock
    (take() probes under its cv); release() never holds both — it drops
    the budget lock, then notifies each registered run cv with nothing
    held, so no cycle can form between same-named run cvs.
    """

    def __init__(self, cap: int):
        self.cap = max(1, int(cap))
        self._used = 0
        self._lock = lockdep.lock("ps.cluster._InflightBudget._lock")
        self._run_cvs: List = []

    def register(self, cv) -> None:
        with self._lock:
            self._run_cvs.append(cv)

    def try_acquire(self) -> bool:
        with self._lock:
            if self._used < self.cap:
                self._used += 1
                return True
            return False

    def release(self, n: int = 1) -> None:
        with self._lock:
            self._used = max(0, self._used - n)
            cvs = list(self._run_cvs)
        for cv in cvs:
            with cv:
                cv.notify_all()


def two_phase_lifecycle(client, verb: str, table: Optional[str] = None,
                        timeout: float = 60.0,
                        extra: Optional[Dict] = None,
                        group: Optional[str] = None):
    """Run a decaying lifecycle verb cluster-wide, exactly once per shard.

    n == 1 degrades to the plain single-server dedup'd send (byte- and
    rid-identical to the pre-cluster client).  n > 1 runs prepare on
    every shard, then commit only after ALL prepared; the rid group is
    pinned on the client until the commit completes, so a caller-level
    retry after any partial failure re-drives the SAME rids and the
    per-shard dedup windows collapse duplicates.

    ``extra`` is merged into every phase frame — the reshard cutover uses
    it to carry the new membership descriptor, so even a server that
    crashed and lost its staged migration state can execute the commit
    from the frame alone (the same self-containment the commit verb
    already has).

    ``group`` pins a CALLER-deterministic rid group instead of the
    client-private pin in ``_txn_groups``: a verb that must stay
    exactly-once across a caller PROCESS death (the trainer fleet's
    end_day, re-driven by whichever rank wins the leader lease) derives
    the group from durable coordinates (day id), so every driver —
    original leader, failover leader, the restarted original — replays
    the same rids through the dedup windows.  The n == 1 degenerate path
    pins ``<group>.c0`` for the same reason (the plain send otherwise
    mints a fresh rid per attempt).
    """
    if verb not in LIFECYCLE_VERBS:
        raise ValueError(f"not a cluster lifecycle verb: {verb!r}")
    extra = extra or {}
    n = getattr(client, "n_shards", 1)
    # every phase frame carries the client's membership epoch: a fleet
    # that resharded since this client last refreshed answers a typed
    # wrong_epoch instead of decaying only the shards the stale map
    # names (the client's verb layer refreshes and re-drives — the
    # pinned rid group makes the replay exactly-once per shard)
    stamp = getattr(client, "_stamp_ep", None) or (lambda r: r)
    if n <= 1:
        req = {"cmd": verb, "table": table, **extra}
        if group is not None:
            req[wire.RID_FIELD] = f"{group}.c0"
        return client._call(stamp(req), dedup=True, timeout=timeout)
    t0 = time.perf_counter()
    txn_key = (verb, table or "")
    pinned = group
    if group is None:
        group = client._txn_groups.get(txn_key)
        if group is None:
            group = client.new_rid_group()
            client._txn_groups[txn_key] = group
    prepared: List[int] = []
    try:
        for shard in range(n):
            client._call(stamp({"cmd": "lifecycle_prepare", "verb": verb,
                                "table": table, "txn": group, **extra,
                                wire.RID_FIELD: f"{group}.p{shard}"}),
                         shard=shard, timeout=timeout)
            prepared.append(shard)
    except Exception:
        # Best-effort abort of staged shards; the group stays pinned, so
        # a caller retry replays the same prepare rids (dedup'd) and can
        # still commit — abort only clears server-side staging bookkeeping.
        for shard in prepared:
            try:
                client._call(stamp({"cmd": "lifecycle_abort", "verb": verb,
                                    "table": table, "txn": group, **extra,
                                    wire.RID_FIELD: f"{group}.a{shard}"}),
                             shard=shard, timeout=5.0)
            except Exception:
                pass
        stat_add("ps.cluster.lifecycle_abort")
        raise
    out = None
    for shard in range(n):
        out = client._call(stamp({"cmd": "lifecycle_commit", "verb": verb,
                                  "table": table, "txn": group, **extra,
                                  wire.RID_FIELD: f"{group}.c{shard}"}),
                           shard=shard, timeout=timeout)
    if pinned is None:
        client._txn_groups.pop(txn_key, None)
    stat_add("ps.cluster.lifecycle_commit")
    stat_observe("ps.cluster.lifecycle_s", time.perf_counter() - t0)
    return out


def _fan_out(client, build_req, timeout: float) -> List[Dict]:
    """Send one request per shard concurrently; list of responses by shard.

    Control-plane fan-out (save/load/size/health — one frame per shard,
    no chunk streams), so plain threads over `_call` are enough; the row
    verbs use the budgeted per-shard pipeline instead.
    """
    n = client.n_shards
    out: List[Optional[Dict]] = [None] * n
    errs: List[Optional[BaseException]] = [None] * n

    def drive(shard: int) -> None:
        try:
            out[shard] = client._call(build_req(shard), shard=shard,
                                      timeout=timeout)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            errs[shard] = e

    threads = [threading.Thread(target=drive, args=(s,), daemon=True)
               for s in range(1, n)]
    for t in threads:
        t.start()
    drive(0)
    for t in threads:
        t.join()
    for shard, e in enumerate(errs):
        if e is not None:
            raise e
    return out  # type: ignore[return-value]


def cluster_save(client, path: str, mode: str = "all",
                 keys: Optional[np.ndarray] = None,
                 table: Optional[str] = None) -> int:
    """Fan `save` out per shard into ``shard-<k:03d>/`` subdirs.

    EVERY shard saves every generation — even one with zero delta keys —
    because the dump is also where that shard's DEDUP.bin lands; a
    restarting supervisor needs a current dedup window from its own
    subdir regardless of how the delta keys hashed.
    """
    n = getattr(client, "n_shards", 1)
    stamp = getattr(client, "_stamp_ep", None) or (lambda r: r)
    if n <= 1:
        req: Dict = stamp({"cmd": "save", "path": path, "mode": mode,
                           "table": table})
        if keys is not None:
            req["keys"] = np.asarray(keys, np.uint64)
        return int(client._call(req, timeout=120)["saved"])
    pos = None
    if keys is not None:
        keys = np.asarray(keys, np.uint64)
        pos = client.server_map.partition(keys)

    def build(shard: int) -> Dict:
        req = stamp({"cmd": "save", "path": shard_dir(path, shard),
                     "mode": mode, "table": table})
        if pos is not None:
            req["keys"] = keys[pos[shard]]
        return req

    out = _fan_out(client, build, timeout=120)
    return sum(int(r["saved"]) for r in out)


def dump_width(path: str) -> int:
    """Number of contiguous ``shard-<k:03d>/`` subdirs under a cluster
    dump path (0 = flat single-server dump)."""
    k = 0
    while os.path.isdir(shard_dir(path, k)):
        k += 1
    return k


def cluster_load(client, path: str, mode: str = "all",
                 table: Optional[str] = None) -> int:
    """Fan `load` out per shard from ``shard-<k:03d>/`` subdirs.

    **Reshard-on-load:** when the dump width on disk differs from the
    fleet width (an N=4 dump restoring into an N=2 fleet, or a flat
    single-server dump into any fleet), every fleet shard is asked to
    walk ALL source subdirs itself with an ``owner`` filter — it keeps
    only the keys that hash to it under the CURRENT map, so each row
    lands on exactly one shard and the restored key space is identical
    to a natively-sharded save.  The offline fallback to the live
    handoff in ps/reshard.py.
    """
    n = getattr(client, "n_shards", 1)
    stamp = getattr(client, "_stamp_ep", None) or (lambda r: r)
    src = dump_width(path)
    if n <= 1:
        if src in (0, 1):
            p = path if src == 0 else shard_dir(path, 0)
            return int(client._call(stamp({"cmd": "load", "path": p,
                                           "mode": mode, "table": table}),
                                    timeout=120)["loaded"])
        r = client._call(stamp({"cmd": "load", "path": path, "mode": mode,
                                "table": table,
                                "owner": np.asarray([0, 1], np.int64),
                                "src_shards": src}), timeout=120)
        stat_add("ps.cluster.reshard_on_load")
        return int(r["loaded"])
    if src == n:
        out = _fan_out(
            client,
            lambda shard: stamp({"cmd": "load",
                                 "path": shard_dir(path, shard),
                                 "mode": mode, "table": table}),
            timeout=120)
        return sum(int(r["loaded"]) for r in out)
    out = _fan_out(
        client,
        lambda shard: stamp({"cmd": "load", "path": path, "mode": mode,
                             "table": table,
                             "owner": np.asarray([shard, n], np.int64),
                             "src_shards": src}),
        timeout=120)
    stat_add("ps.cluster.reshard_on_load")
    return sum(int(r["loaded"]) for r in out)
