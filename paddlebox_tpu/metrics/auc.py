"""Streaming AUC / calibration metrics.

≙ BasicAucCalculator (fleet/metrics.h:46, metrics.cc:284-410) and the named
multi-metric registry with join/update phase filtering (box_wrapper.h:769-792,
MetricMsg hierarchy metrics.h:204+).

TPU-first split: bucket accumulation is a jit-able pure function
(scatter-add into pos/neg tables — runs on device inside the train step, the
equivalent of `mode_collect_in_gpu`, box_wrapper.h:787), while the final
compute() is a host-side reduction over the 1M-bucket tables.  Cross-host
aggregation is a jax psum over the data axis instead of the reference's
MPI/gloo allreduce (metrics.cc:288-307).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

TABLE_SIZE = 1_000_000  # ≙ box_wrapper.h:786
N_SCALARS = 6   # abserr, sqrerr, pred_sum, label_sum, total, nan_inf
K_RELATIVE_ERROR_BOUND = 0.05  # ≙ metrics.h:193
K_MAX_SPAN = 0.01              # ≙ metrics.h:194


def make_auc_state(table_size: int = TABLE_SIZE) -> Dict[str, jnp.ndarray]:
    """Device-side accumulator pytree: pos/neg bucket tables + scalar sums
    [abserr, sqrerr, pred_sum, label_sum, total, nan_inf]."""
    return {
        "pos": jnp.zeros((table_size,), jnp.float32),
        "neg": jnp.zeros((table_size,), jnp.float32),
        "scalars": jnp.zeros((N_SCALARS,), jnp.float32),
    }


def accumulate_auc(state: Dict[str, jnp.ndarray], pred: jnp.ndarray,
                   label: jnp.ndarray, mask: Optional[jnp.ndarray] = None
                   ) -> Dict[str, jnp.ndarray]:
    """Pure jit-able bucket accumulation (≙ add_unlock_data metrics.cc:84-105
    vectorized).  pred/label: [B]; mask False drops padded records
    (≙ add_mask_data metrics.cc:164)."""
    table_size = state["pos"].shape[0]
    pred = pred.astype(jnp.float32)
    # non-finite preds must not poison the buckets (NaN -> undefined int
    # cast): count them separately (≙ add_nan_inf_data metrics.cc:452)
    # and drop them from every other statistic
    finite = jnp.isfinite(pred)
    pred = jnp.clip(jnp.where(finite, pred, 0.0), 0.0, 1.0)
    label = label.astype(jnp.float32)
    if mask is None:
        w = jnp.ones_like(pred)
    else:
        w = mask.astype(jnp.float32)
    nan_inf = jnp.sum(w * (1.0 - finite.astype(jnp.float32)))
    w = w * finite.astype(jnp.float32)
    bucket = jnp.clip((pred * table_size).astype(jnp.int32), 0, table_size - 1)
    pos = state["pos"].at[bucket].add(w * label)
    neg = state["neg"].at[bucket].add(w * (1.0 - label))
    err = pred - label
    scalars = state["scalars"] + jnp.stack([
        jnp.sum(w * jnp.abs(err)),
        jnp.sum(w * err * err),
        jnp.sum(w * pred),
        jnp.sum(w * label),
        jnp.sum(w),
        nan_inf,
    ])
    return {"pos": pos, "neg": neg, "scalars": scalars}


class WuAucCalculator:
    """Per-user AUC family — uauc (mean of per-user AUCs) and wuauc
    (instance-weighted mean), ≙ WuAucMetricMsg + computeWuAuc /
    computeSingelUserAuc (metrics.h:287, metrics.cc:501-587).

    TPU-first shape: the reference sorts a record vector and walks each
    user's ROC with a tie-merging loop; here per-user AUC is the
    Mann-Whitney statistic with average ranks for pred ties (identical to
    the tie-merged trapezoid — tests diff against a transliteration of
    the reference loop), computed with vectorized lexsort + segment
    cumsums over ALL users at once.  Single-class users are skipped
    exactly like the reference's auc == -1 branch."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._uid: List[np.ndarray] = []
        self._pred: List[np.ndarray] = []
        self._label: List[np.ndarray] = []
        self._nan_inf = 0.0
        self._out_of_range = 0.0

    def add_data(self, pred, label, uid, mask=None) -> None:
        pred = np.asarray(pred, np.float64)
        label = np.asarray(label, np.int64)
        uid = np.asarray(uid, np.uint64)
        if mask is not None:
            keep = np.asarray(mask, bool)
            pred, label, uid = pred[keep], label[keep], uid[keep]
        # same invariant as AucCalculator: non-finite preds are counted,
        # never ranked (a NaN would lexsort to the top rank and inflate
        # the diverging model's per-user AUC)
        finite = np.isfinite(pred)
        if not finite.all():
            self._nan_inf += float((~finite).sum())
            pred, label, uid = pred[finite], label[finite], uid[finite]
        # keep preds UNCLIPPED for ranking: the Mann-Whitney statistic only
        # needs order, and clipping would collapse out-of-range preds into
        # artificial ties at 0/1 and shift per-user AUC.  NOTE the
        # reference does NOT rank raw out-of-range preds — its
        # add_uid_unlock_data PADDLE_ENFORCEs pred in [0,1] and rejects
        # the record outright; a non-sigmoid head violates that
        # precondition silently here, so count the violations (surfaced as
        # out_of_range_rate) the way _nan_inf tracks non-finite preds.
        self._out_of_range += float(((pred < 0.0) | (pred > 1.0)).sum())
        self._pred.append(pred)
        self._label.append(label)
        self._uid.append(uid)

    def compute(self) -> Dict[str, float]:
        if not self._pred or not sum(len(p) for p in self._pred):
            return {"uauc": 0.0, "wuauc": 0.0, "user_cnt": 0.0,
                    "size": 0.0, "nan_inf_rate": 1.0 if self._nan_inf
                    else 0.0, "out_of_range_rate": 1.0
                    if self._out_of_range else 0.0}
        pred = np.concatenate(self._pred)
        label = np.concatenate(self._label)
        uid = np.concatenate(self._uid)
        order = np.lexsort((pred, uid))
        u, p, l = uid[order], pred[order], label[order]
        n = len(u)
        new_user = np.empty(n, bool)
        new_user[0] = True
        np.not_equal(u[1:], u[:-1], out=new_user[1:])
        user_id = np.cumsum(new_user) - 1
        n_users = int(user_id[-1]) + 1
        first = np.nonzero(new_user)[0]
        pos_in_user = np.arange(n) - first[user_id] + 1    # 1-based rank
        # pred-tie groups within a user share the AVERAGE rank
        new_grp = new_user | np.concatenate([[True], p[1:] != p[:-1]])
        gid = np.cumsum(new_grp) - 1
        cnt_g = np.bincount(gid)
        avg_rank = np.bincount(gid, weights=pos_in_user) / cnt_g
        rank = avg_rank[gid]

        cnt_u = np.bincount(user_id, minlength=n_users).astype(np.float64)
        npos = np.bincount(user_id, weights=l, minlength=n_users)
        nneg = cnt_u - npos
        pos_rank_sum = np.bincount(user_id, weights=rank * l,
                                   minlength=n_users)
        ok = (npos > 0) & (nneg > 0)
        auc_u = np.zeros(n_users)
        auc_u[ok] = (pos_rank_sum[ok] - npos[ok] * (npos[ok] + 1) / 2.0) \
            / (npos[ok] * nneg[ok])
        user_cnt = float(ok.sum())
        size = float(cnt_u[ok].sum())
        return {
            "uauc": float(auc_u[ok].sum() / max(user_cnt, 1.0)),
            "wuauc": float((auc_u[ok] * cnt_u[ok]).sum() / max(size, 1.0)),
            "user_cnt": user_cnt, "size": size,
            "nan_inf_rate": float(
                self._nan_inf / (n + self._nan_inf)) if self._nan_inf
            else 0.0,
            # ranked records whose pred violates the reference's [0,1]
            # precondition (they ARE still ranked — see add_data)
            "out_of_range_rate": float(self._out_of_range / n)
            if self._out_of_range else 0.0,
        }


def allreduce_auc_state(state, client, world: int, key: str):
    """EXACT cross-process metrics: sum the pos/neg bucket tables + scalar
    sums over every worker through the PS service's keyed allreduce, so
    each worker finalizes the same GLOBAL AUC — ≙ fleet.metrics.auc's gloo
    all_reduce of stat_pos/stat_neg (fleet/metrics/metric.py:144), not an
    average of worker-local AUCs (which is biased whenever shards differ).

    client: ps.service.PSClient; key must be fresh per collective (e.g.
    f"auc-{pass_id}").  Returns a summed state finalizable by
    AucCalculator.merge_device_state/compute."""
    import jax
    host = jax.device_get(state)
    arrs = {k: np.asarray(v) for k, v in host.items()}
    return client.allreduce(arrs, world, key=key)


class AucCalculator:
    """Host wrapper with the reference's result surface
    (auc/bucket_error/mae/rmse/actual_ctr/predicted_ctr, metrics.h:108-121)."""

    def __init__(self, table_size: int = TABLE_SIZE):
        self.table_size = table_size
        self.reset()

    def reset(self) -> None:
        self._pos = np.zeros((self.table_size,), np.float64)
        self._neg = np.zeros((self.table_size,), np.float64)
        self._scalars = np.zeros((N_SCALARS,), np.float64)

    # -- host-side add (small batches / tests) ------------------------------
    def add_data(self, pred, label, mask=None) -> None:
        pred = np.asarray(pred, np.float64)
        label = np.asarray(label, np.float64)
        w = np.ones_like(pred) if mask is None else \
            np.asarray(mask, np.float64)
        # finite check BEFORE the clip (clip would turn +inf into 1.0)
        finite = np.isfinite(pred)
        pred = np.clip(np.where(finite, pred, 0.0), 0.0, 1.0)
        nan_inf = np.sum(w * (1.0 - finite))
        w = w * finite
        bucket = np.clip((pred * self.table_size).astype(np.int64), 0,
                         self.table_size - 1)
        np.add.at(self._pos, bucket, w * label)
        np.add.at(self._neg, bucket, w * (1.0 - label))
        err = pred - label
        self._scalars += [np.sum(w * np.abs(err)), np.sum(w * err * err),
                          np.sum(w * pred), np.sum(w * label), np.sum(w),
                          nan_inf]

    # -- merge device accumulator state -------------------------------------
    def merge_device_state(self, state) -> None:
        self._pos += np.asarray(state["pos"], np.float64)
        self._neg += np.asarray(state["neg"], np.float64)
        self._scalars += np.asarray(state["scalars"], np.float64)

    # -- final reduction (≙ compute() metrics.cc:284) -----------------------
    def compute(self) -> Dict[str, float]:
        pos, neg = self._pos, self._neg
        # trapezoid sweep from the top bucket down (metrics.cc:314-320)
        tp_cum = np.cumsum(pos[::-1])
        fp_cum = np.cumsum(neg[::-1])
        tp_prev = np.concatenate([[0.0], tp_cum[:-1]])
        fp_prev = np.concatenate([[0.0], fp_cum[:-1]])
        area = np.sum((fp_cum - fp_prev) * (tp_prev + tp_cum) / 2.0)
        fp, tp = fp_cum[-1], tp_cum[-1]
        if fp < 1e-3 or tp < 1e-3:
            auc = -0.5  # all-positive or all-negative (metrics.cc:321)
        else:
            auc = area / (fp * tp)
        size = fp + tp
        abserr, sqrerr, pred_sum, label_sum, total, nan_inf = self._scalars
        out = {
            "auc": float(auc),
            "size": float(size),
            "mae": float(abserr / size) if size else 0.0,
            "rmse": float(math.sqrt(sqrerr / size)) if size else 0.0,
            "actual_ctr": float(tp / size) if size else 0.0,
            "predicted_ctr": float(pred_sum / size) if size else 0.0,
            "bucket_error": self._bucket_error(),
            # ≙ nan_inf_rate (metrics.h:116): non-finite preds are counted
            # out of the other statistics, never bucketed
            "nan_inf_rate": float(nan_inf / (size + nan_inf))
            if (size + nan_inf) else 0.0,
        }
        return out

    def folded_buckets(self, bins: int = 50) -> "tuple[np.ndarray, np.ndarray]":
        """Fold the pos/neg bucket tables down to ``bins`` coarse buckets
        (exact counts, reduced resolution) — the compact per-pass export
        the windowed-AUC / drift monitors (metrics/quality.py) retain
        across passes without holding the 1M-bucket tables."""
        bins = max(1, int(bins))
        idx = (np.arange(self.table_size) * bins) // self.table_size
        pos = np.zeros((bins,), np.float64)
        neg = np.zeros((bins,), np.float64)
        np.add.at(pos, idx, self._pos)
        np.add.at(neg, idx, self._neg)
        return pos, neg

    def _bucket_error(self) -> float:
        """≙ calculate_bucket_error (metrics.cc:373-410): merge adjacent
        buckets until the adjusted-ctr estimate is statistically tight, then
        accumulate the relative error of actual vs adjusted ctr."""
        last_ctr = -1.0
        impression_sum = ctr_sum = click_sum = 0.0
        error_sum = error_count = 0.0
        nz = np.nonzero(self._pos + self._neg)[0]
        for i in nz:
            click = self._pos[i]
            show = self._pos[i] + self._neg[i]
            ctr = i / self.table_size
            if abs(ctr - last_ctr) > K_MAX_SPAN:
                last_ctr = ctr
                impression_sum = ctr_sum = click_sum = 0.0
            impression_sum += show
            ctr_sum += ctr * show
            click_sum += click
            adjust_ctr = ctr_sum / impression_sum
            if adjust_ctr <= 0 or adjust_ctr >= 1:
                continue
            relative_error = math.sqrt(
                (1 - adjust_ctr) / (adjust_ctr * impression_sum))
            if relative_error < K_RELATIVE_ERROR_BOUND:
                actual = click_sum / impression_sum
                error_sum += abs(actual / adjust_ctr - 1) * impression_sum
                error_count += impression_sum
                last_ctr = -1.0
        return error_sum / error_count if error_count > 0 else 0.0


class MetricGroup:
    """Named metric registry with phase filtering (≙ BoxWrapper metric maps,
    box_wrapper.h:769-792: InitMetric/UpdateMetric/GetMetricMsg; phases are
    the join/update pass flip, ≙ FlipPhase box_wrapper.h:805)."""

    def __init__(self):
        self._metrics: Dict[str, Dict] = {}
        self.phase = 1  # 1 = join, 0 = update (reference convention)

    def init_metric(self, name: str, label_var: str = "label",
                    pred_var: str = "prob", phase: int = -1,
                    cmatch_rank_group: str = "", ignore_rank: bool = False,
                    table_size: int = TABLE_SIZE,
                    metric_type: str = "auc",
                    uid_var: str = "",
                    multitask_group: str = "") -> None:
        """cmatch_rank_group: "222:1,223:2" keeps records whose
        (cmatch, rank) is listed; "222,223" (or ignore_rank) filters on
        cmatch only (≙ CmatchRankAucCalculator / MetricMsg variants,
        metrics.h:204+).  metric_type "wuauc" registers the per-user AUC
        family instead (≙ WuAucMetricMsg, metrics.h:287) — update() then
        requires uid.  metric_type "multi_task" (≙ MultiTaskMetricMsg,
        metrics.h:327): multitask_group maps (cmatch, rank) pairs
        ("222_0,223_0") to pred COLUMNS — each instance scores with the
        task column its cmatch selects, into one shared calculator."""
        if metric_type not in ("auc", "wuauc", "multi_task"):
            raise ValueError(f"unknown metric_type {metric_type!r}")
        task_pairs = []
        if metric_type == "multi_task":
            for tok in multitask_group.split(","):
                tok = tok.strip()
                if not tok:
                    continue
                parts = tok.split("_")
                if len(parts) != 2:
                    raise ValueError(
                        f"multitask_group token {tok!r}: expected "
                        "'cmatch_rank' (e.g. '222_0')")
                task_pairs.append((int(parts[0]), int(parts[1])))
            if not task_pairs:
                raise ValueError(
                    "metric_type='multi_task' needs multitask_group "
                    "(e.g. '222_0,223_0' — one cmatch_rank per pred "
                    "column)")
        elif multitask_group:
            raise ValueError(
                "multitask_group is only meaningful with "
                "metric_type='multi_task'")
        pairs = []
        for tok in cmatch_rank_group.split(","):
            tok = tok.strip()
            if not tok:
                continue
            if ":" in tok and not ignore_rank:
                c, r = tok.split(":")
                pairs.append((int(c), int(r)))
            else:
                pairs.append((int(tok.split(":")[0]), None))
        self._metrics[name] = {
            "calc": (WuAucCalculator() if metric_type == "wuauc"
                     else AucCalculator(table_size)),
            "type": metric_type, "uid_var": uid_var,
            "label_var": label_var, "pred_var": pred_var, "phase": phase,
            "cmatch_rank": pairs, "task_pairs": task_pairs,
        }

    def flip_phase(self) -> None:
        self.phase = 1 - self.phase

    def active(self) -> List[str]:
        return [n for n, m in self._metrics.items()
                if m["phase"] in (-1, self.phase)]

    def update(self, name: str, pred, label, mask=None,
               cmatch=None, rank=None, uid=None) -> None:
        """mask/cmatch/rank filtering (≙ add_mask_data metrics.cc:164 and
        the cmatch_rank MetricMsg update loop)."""
        m = self._metrics[name]
        pred = np.asarray(pred)
        keep = np.ones(len(pred), bool) if mask is None else \
            np.asarray(mask, bool).copy()
        if m["cmatch_rank"]:
            cm = np.asarray(cmatch) if cmatch is not None else \
                np.zeros(len(pred), np.int64)
            rk = np.asarray(rank) if rank is not None else \
                np.zeros(len(pred), np.int64)
            sel = np.zeros(len(pred), bool)
            for c, r in m["cmatch_rank"]:
                sel |= (cm == c) if r is None else ((cm == c) & (rk == r))
            keep &= sel
        if m.get("type") == "wuauc":
            if uid is None:
                raise ValueError(
                    f"metric {name!r} is wuauc — update() requires uid")
            m["calc"].add_data(pred, label, uid, keep)
        elif m.get("type") == "multi_task":
            # each instance scores with the pred COLUMN its (cmatch, rank)
            # selects (first match, ≙ the std::find loop metrics.h:394);
            # unmatched instances are skipped
            if pred.ndim != 2 or cmatch is None:
                raise ValueError(
                    f"metric {name!r} is multi_task — update() needs "
                    "pred [B, T] and cmatch")
            if len(m["task_pairs"]) > pred.shape[1]:
                raise ValueError(
                    f"metric {name!r}: {len(m['task_pairs'])} multitask "
                    f"pairs but pred has only {pred.shape[1]} columns")
            cm = np.asarray(cmatch)
            rk = (np.asarray(rank) if rank is not None
                  else np.zeros(len(cm), np.int64))
            sel = np.full(pred.shape[0], -1, np.int64)
            for t, (c, r) in enumerate(m["task_pairs"]):
                hit = (cm == c) & (rk == r) & (sel < 0)
                sel[hit] = t
            pick = (sel >= 0) & keep
            m["calc"].add_data(pred[np.nonzero(pick)[0], sel[pick]],
                               np.asarray(label)[pick])
        else:
            m["calc"].add_data(pred, label, keep)

    def merge_device_state(self, name: str, state) -> None:
        m = self._metrics[name]
        if m.get("type") == "wuauc":
            raise ValueError(
                f"metric {name!r} is wuauc — it accumulates host-side "
                "(uid, label, pred) records, not device bucket tables; "
                "feed it via update(..., uid=...).  Cross-worker "
                "aggregation needs the records gathered (variable "
                "length), which the fixed-shape PS allreduce does not "
                "carry — compute wuauc per worker or gather records "
                "upstream")
        m["calc"].merge_device_state(state)

    def calculator(self, name: str) -> "AucCalculator | WuAucCalculator":
        return self._metrics[name]["calc"]

    def get_metric_msg(self, name: str) -> Dict[str, float]:
        return self._metrics[name]["calc"].compute()

    def reset(self, name: Optional[str] = None) -> None:
        for n, m in self._metrics.items():
            if name is None or n == name:
                m["calc"].reset()
