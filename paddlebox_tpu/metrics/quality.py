"""Training-quality monitors — the model-health series feeding the
telemetry timeline (utils/timeline.py) and the per-pass report
(ps/pass_manager.pass_report).

The reference prints one AUC line per pass and forgets it; ROADMAP item
4's streaming mode needs AUC *over time* and concept-drift detection.
This module keeps a bounded window of per-pass results and derives:

* **windowed AUC** — an exact AUC over the union of the last W passes,
  recomputed from each pass's folded pos/neg bucket tables via
  :class:`~paddlebox_tpu.metrics.auc.AucCalculator` (not a mean of
  per-pass AUCs, which over-weights small passes);
* **calibration drift** — ``predicted_ctr / actual_ctr`` divergence
  (the COPC view of the reference's bucket_error);
* **PSI drift** — population-stability index of the prediction
  distribution between consecutive passes and between consecutive days
  (> 0.2 is the classic "distribution shifted" alarm level).

Everything lands as ``quality.*`` gauges in the StatRegistry, so the
timeline sampler picks the series up for free and the SLO watchdog's
``auc_drop`` rule reads ``quality.auc`` like any other metric.  Cost is
a few hundred floats per PASS — never per batch.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.metrics.auc import AucCalculator
from paddlebox_tpu.utils.monitor import StatRegistry, stat_add, stat_set

PSI_BINS = 10           # coarse decile bins, the classic PSI setup
_PSI_EPS = 1e-6         # zero-cell smoothing so ln() stays finite


def psi(expected: Sequence[float], actual: Sequence[float]) -> float:
    """Population-stability index between two distributions (counts or
    proportions; normalized internally).  0 = identical; > 0.2 is the
    conventional "significant shift" threshold."""
    e = np.asarray(expected, np.float64)
    a = np.asarray(actual, np.float64)
    if e.shape != a.shape or e.sum() <= 0 or a.sum() <= 0:
        return 0.0
    e = np.maximum(e / e.sum(), _PSI_EPS)
    a = np.maximum(a / a.sum(), _PSI_EPS)
    return float(np.sum((a - e) * np.log(a / e)))


def windowed_auc(window: Sequence[Dict[str, Sequence[float]]]) -> float:
    """Exact AUC over the union of several passes, from their folded
    pos/neg bucket exports (``AucCalculator.folded_buckets``).  Returns
    -0.5 (the reference's sentinel) when the union is single-class."""
    if not window:
        return -0.5
    bins = len(window[0]["pos"])
    calc = AucCalculator(table_size=bins)
    for b in window:
        calc._pos += np.asarray(b["pos"], np.float64)
        calc._neg += np.asarray(b["neg"], np.float64)
    return float(calc.compute()["auc"])


def calibration_drift(predicted_ctr: float, actual_ctr: float) -> float:
    """|COPC - 1|: how far predicted clicks diverge from observed ones
    (0 = perfectly calibrated).  0 when the pass saw no positives (the
    ratio is undefined, not infinitely wrong)."""
    if actual_ctr <= 0.0:
        return 0.0
    return abs(predicted_ctr / actual_ctr - 1.0)


def _pred_dist(buckets: Dict[str, Sequence[float]]) -> np.ndarray:
    """Prediction-score distribution (pos+neg mass per bucket) folded to
    PSI_BINS."""
    pos = np.asarray(buckets["pos"], np.float64)
    neg = np.asarray(buckets["neg"], np.float64)
    total = pos + neg
    n = len(total)
    idx = (np.arange(n) * PSI_BINS) // max(n, 1)
    out = np.zeros((PSI_BINS,), np.float64)
    np.add.at(out, idx, total)
    return out


class QualityMonitor:
    """Bounded-window per-pass quality tracker.  ``observe_pass``
    consumes one trainer metrics dict (``trainer.train_pass`` output:
    auc/predicted_ctr/actual_ctr/size plus the optional ``auc_buckets``
    export) and publishes the derived ``quality.*`` gauges."""

    def __init__(self, window: int = 8):
        self.window = max(2, int(window))
        self._lock = threading.Lock()
        self._aucs: "deque[float]" = deque(maxlen=self.window)
        self._buckets: "deque[Dict]" = deque(maxlen=self.window)
        self._prev_dist: Optional[np.ndarray] = None
        self._day_dist: Optional[np.ndarray] = None
        self._prev_day_dist: Optional[np.ndarray] = None

    def observe_pass(self, metrics: Optional[Dict],
                     pass_id: Optional[int] = None,
                     day: Optional[str] = None) -> Dict[str, float]:
        """Fold one pass result in; returns the derived quality gauges
        (also written to the StatRegistry).  ``None`` metrics (a pass
        skipped by the resume cursor) are ignored."""
        if not metrics or "auc" not in metrics:
            return {}
        # every gauge lands through a LITERAL stat_set site (not a k,v
        # loop): pboxlint PB207 statically cross-checks the watchdog's
        # rule metrics against these names, and one dynamic emission
        # site anywhere would disarm that check package-wide
        out: Dict[str, float] = {}
        with self._lock:
            auc = float(metrics["auc"])
            self._aucs.append(auc)
            out["quality.auc"] = auc
            stat_set("quality.auc", auc)
            drop = max(self._aucs) - auc
            out["quality.auc_drop"] = drop
            stat_set("quality.auc_drop", drop)
            buckets = metrics.get("auc_buckets")
            if buckets:
                self._buckets.append(buckets)
                wauc = windowed_auc(list(self._buckets))
                dist = _pred_dist(buckets)
                if self._prev_dist is not None:
                    p = psi(self._prev_dist, dist)
                    out["quality.psi.prediction"] = p
                    stat_set("quality.psi.prediction", p)
                self._prev_dist = dist
                self._day_dist = dist if self._day_dist is None \
                    else self._day_dist + dist
            else:
                # no bucket export (older trainer / hand-built metrics):
                # fall back to the plain windowed mean so the series
                # still exists
                wauc = float(sum(self._aucs) / len(self._aucs))
            out["quality.auc_window"] = wauc
            stat_set("quality.auc_window", wauc)
            cal = calibration_drift(
                float(metrics.get("predicted_ctr", 0.0)),
                float(metrics.get("actual_ctr", 0.0)))
            out["quality.calibration_drift"] = cal
            stat_set("quality.calibration_drift", cal)
        stat_add("quality.passes")
        return out

    def end_day(self, day: Optional[str] = None) -> Dict[str, float]:
        """Day rollover: PSI of the prediction distribution between the
        finished day and the previous one — the day-scale concept-drift
        series (ROADMAP item 4)."""
        out: Dict[str, float] = {}
        with self._lock:
            if self._day_dist is not None \
                    and self._prev_day_dist is not None:
                p = psi(self._prev_day_dist, self._day_dist)
                out["quality.psi.day"] = p
                stat_set("quality.psi.day", p)
            if self._day_dist is not None:
                self._prev_day_dist = self._day_dist
            self._day_dist = None
        return out

    def aucs(self) -> List[float]:
        with self._lock:
            return list(self._aucs)

    def reset(self) -> None:
        with self._lock:
            self._aucs.clear()
            self._buckets.clear()
            self._prev_dist = None
            self._day_dist = None
            self._prev_day_dist = None
        # a reset means "new model / new trajectory": the old model's
        # quality.* gauges must leave the registry too, or the timeline
        # sampler keeps feeding them to the SLO watchdog and the next
        # model's first pass reads as an AUC drop from the dead one
        StatRegistry.instance().remove_prefix("quality.")


# Process-wide monitor — always on (a few gauge writes per PASS); the
# flag-gated timeline sampler decides whether anything consumes the
# series continuously.
ACTIVE = QualityMonitor()


def observe_pass(metrics: Optional[Dict], pass_id: Optional[int] = None,
                 day: Optional[str] = None) -> Dict[str, float]:
    return ACTIVE.observe_pass(metrics, pass_id=pass_id, day=day)


def end_day(day: Optional[str] = None) -> Dict[str, float]:
    return ACTIVE.end_day(day)


def aucs() -> List[float]:
    """The retained per-pass AUC trajectory (bench.py's timeline
    summary)."""
    return ACTIVE.aucs()


def reset() -> None:
    ACTIVE.reset()
