from paddlebox_tpu.metrics.auc import AucCalculator, MetricGroup  # noqa: F401
