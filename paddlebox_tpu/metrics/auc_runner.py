"""AucRunner — in-training feature-importance evaluation.

≙ BoxWrapper AucRunner mode (box_wrapper.h:906-1000: InitializeAucRunner
:908, GetRandomReplace/PostUpdate/RecordReplace :948-989, flag
FLAGS_padbox_auc_runner_mode flags.cc:972): while training runs, keep a
random reservoir of instances; on evaluation passes, replace the feasigns of
one slot with spans sampled from the reservoir and measure the AUC drop —
the importance of that slot.  Phases flip join/update passes
(MetricGroup.flip_phase).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from paddlebox_tpu.data.slot_record import SlotRecordBlock
from paddlebox_tpu import flags


class AucRunner:
    def __init__(self, slots: Sequence[str], pool_size: int = 10000,
                 seed: int = 0):
        self.slots = list(slots)
        self.pool_size = pool_size
        self._rng = np.random.default_rng(seed)
        # per slot: list of feasign spans (np arrays)
        self._pool: Dict[str, List[np.ndarray]] = {s: [] for s in self.slots}
        self._seen = 0

    # -- ≙ RecordReplace: reservoir-sample spans during normal training -----
    def record(self, block: SlotRecordBlock) -> None:
        for name in self.slots:
            if name not in block.uint64_slots:
                continue
            values, offsets = block.uint64_slots[name]
            pool = self._pool[name]
            for i in range(block.n):
                span = values[offsets[i]:offsets[i + 1]]
                if len(pool) < self.pool_size:
                    pool.append(span.copy())
                else:
                    j = int(self._rng.integers(0, self._seen + i + 1))
                    if j < self.pool_size:
                        pool[j] = span.copy()
        self._seen += block.n

    # -- ≙ GetRandomReplace: build the ablated copy -------------------------
    def replace(self, block: SlotRecordBlock, slot: str) -> SlotRecordBlock:
        """Return a copy of `block` whose `slot` feasigns are random pool
        spans (other slots untouched)."""
        pool = self._pool.get(slot)
        if not pool:
            return block
        out = SlotRecordBlock(n=block.n, ins_ids=block.ins_ids,
                              search_ids=block.search_ids,
                              cmatch=block.cmatch, rank=block.rank)
        out.float_slots = dict(block.float_slots)
        out.uint64_slots = dict(block.uint64_slots)
        picks = self._rng.integers(0, len(pool), size=block.n)
        spans = [pool[p] for p in picks]
        lens = np.array([len(s) for s in spans], np.int64)
        offsets = np.zeros((block.n + 1,), np.int64)
        np.cumsum(lens, out=offsets[1:])
        values = (np.concatenate(spans) if spans else
                  np.empty((0,), np.uint64))
        out.uint64_slots[slot] = (values, offsets)
        return out

    def pool_sizes(self) -> Dict[str, int]:
        return {s: len(p) for s, p in self._pool.items()}
