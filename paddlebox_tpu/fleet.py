"""Fleet facade — the user-level API surface.

≙ paddle.distributed.fleet (fleet/base/fleet_base.py:144: init :211,
distributed_optimizer :912, minimize :1477), the BoxPSDataset python class
(python/paddle/fluid/dataset.py:1231: set_date/begin_pass/end_pass/
load_into_memory/preload_into_memory/wait_preload_done/slots_shuffle) and
Executor.train_from_dataset (executor.py:2412).

A reference user drives training as:
    fleet.init(strategy)
    dataset = fleet.DatasetFactory().create_dataset("BoxPSDataset")
    dataset.set_use_var(...); dataset.set_filelist(...)
    dataset.set_date(d); dataset.load_into_memory(); dataset.begin_pass()
    exe.train_from_dataset(program, dataset)
    dataset.end_pass(True)
This module offers the same verbs over the TPU engine/trainer.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from paddlebox_tpu.config import (DataFeedConfig, DistributedStrategy,
                                  EmbeddingTableConfig, MeshConfig,
                                  TrainerConfig)
from paddlebox_tpu.data.dataset import SlotDataset, ShuffleTransport
from paddlebox_tpu.metrics.auc import MetricGroup
from paddlebox_tpu.parallel.topology import HybridTopology
from paddlebox_tpu.ps.pass_manager import BoxPSEngine
from paddlebox_tpu.trainer.trainer import SparseTrainer

_GLOBAL: Dict = {"fleet": None}


class Fleet:
    """Process-wide runtime handle (≙ fleet_base.Fleet singleton)."""

    def __init__(self, strategy: Optional[DistributedStrategy] = None,
                 topology: Optional[HybridTopology] = None):
        self.strategy = strategy or DistributedStrategy()
        self.topology = topology
        self.engine: Optional[BoxPSEngine] = None
        self.metrics = MetricGroup()

    # ≙ fleet.init(is_collective/role_maker)
    def init_engine(self, table_config: Optional[EmbeddingTableConfig] = None,
                    seed: int = 0) -> BoxPSEngine:
        self.engine = BoxPSEngine(table_config or self.strategy.table,
                                  topology=self.topology, seed=seed)
        return self.engine

    @property
    def worker_num(self) -> int:
        return 1 if self.topology is None else self.topology.world_size

    def barrier_worker(self) -> None:
        pass  # single-host; multi-host via jax.distributed in launch.py


def init(strategy: Optional[DistributedStrategy] = None,
         topology: Optional[HybridTopology] = None) -> Fleet:
    f = Fleet(strategy, topology)
    _GLOBAL["fleet"] = f
    return f


def instance() -> Fleet:
    if _GLOBAL["fleet"] is None:
        init()
    return _GLOBAL["fleet"]


class BoxPSDataset:
    """≙ BoxPSDataset (dataset.py:1231) + the BoxHelper pass driver: one
    object owning the slot dataset AND driving the engine's feed-pass
    overlap, so user code reads like the reference's day/pass loop."""

    def __init__(self, feed_config: DataFeedConfig,
                 engine: Optional[BoxPSEngine] = None,
                 parse_ins_id: bool = False, parse_logkey: bool = False,
                 read_threads: int = 4,
                 transport: Optional[ShuffleTransport] = None):
        self.feed_config = feed_config
        self.engine = engine or instance().engine
        assert self.engine is not None, "fleet.init_engine() first"
        self.dataset = SlotDataset(feed_config, parse_ins_id, parse_logkey,
                                   read_threads, transport)
        self.engine.attach_dataset(self.dataset)

    # -- file/date plumbing (dataset.py:1252-1285) --------------------------
    def set_filelist(self, filelist: Sequence[str]) -> None:
        self.dataset.set_filelist(filelist)

    def set_date(self, date: str) -> None:
        self.engine.set_date(date)

    # -- pass lifecycle ------------------------------------------------------
    def load_into_memory(self) -> None:
        self.engine.begin_feed_pass()
        self.dataset.load_into_memory()

    def preload_into_memory(self) -> None:
        self.engine.begin_feed_pass()
        self.dataset.preload_into_memory()

    def wait_preload_done(self) -> None:
        self.dataset.wait_preload_done()
        # readers are done feeding keys: kick the background working-set
        # build so it overlaps any still-running training pass
        self.engine.end_feed_pass(async_build=True)

    def begin_pass(self) -> None:
        if self.engine._feeding:
            self.engine.end_feed_pass()
        self.engine.begin_pass()

    def end_pass(self, need_save_delta: bool = False,
                 delta_path: str = "") -> None:
        self.engine.end_pass(need_save_delta, delta_path)
        self.dataset.release_memory()

    # -- shuffles ------------------------------------------------------------
    def local_shuffle(self) -> None:
        self.dataset.local_shuffle()

    def global_shuffle(self, by_ins_id: bool = False) -> None:
        self.dataset.global_shuffle(by_ins_id)

    def slots_shuffle(self, slots: Sequence[str]) -> None:
        """≙ BoxPSDataset.slots_shuffle (dataset.py:1302 →
        SlotsShuffle box_wrapper.h:1186): permute the chosen slots' feasign
        spans across instances, keeping everything else fixed (feature
        importance ablation)."""
        import numpy as _np
        rng = _np.random.default_rng(0)
        for block in self.dataset.get_blocks():
            for name in slots:
                if name not in block.uint64_slots:
                    continue
                values, offsets = block.uint64_slots[name]
                lens = _np.diff(offsets)
                order = rng.permutation(block.n)
                # records keep their own length; only spans with equal length
                # swap cleanly — group by length and permute within groups
                for length in _np.unique(lens):
                    rows = _np.nonzero(lens == length)[0]
                    if len(rows) < 2 or length == 0:
                        continue
                    perm = rows[rng.permutation(len(rows))]
                    spans = _np.stack([
                        values[offsets[r]:offsets[r] + length]
                        for r in perm])
                    for i, r in enumerate(rows):
                        values[offsets[r]:offsets[r] + length] = spans[i]

    # -- stats ---------------------------------------------------------------
    def get_memory_data_size(self) -> int:
        return self.dataset.instance_num()

    def get_shuffle_data_size(self) -> int:
        return self.dataset.instance_num()


class DatasetFactory:
    """≙ fluid.DatasetFactory (dataset.py:31)."""

    def create_dataset(self, name: str = "BoxPSDataset", **kw) -> BoxPSDataset:
        if name in ("BoxPSDataset", "InMemoryDataset", "SlotRecordDataset"):
            return BoxPSDataset(**kw)
        raise ValueError(f"unknown dataset type {name}")


def train_from_dataset(trainer: SparseTrainer, dataset: BoxPSDataset,
                       ) -> Dict[str, float]:
    """≙ Executor.train_from_dataset (executor.py:2412 →
    BoxPSTrainer::Run)."""
    return trainer.train_pass(dataset.dataset)


def train_passes(trainer: SparseTrainer, dataset: BoxPSDataset,
                 passes: Sequence[Sequence[str]], date: Optional[str] = None,
                 before_pass=None, prefetch: Optional[bool] = None,
                 ) -> list:
    """Day loop over per-pass filelists — the reference's
    set_date/load_into_memory/begin_pass/train/end_pass sequence
    (dataset.py:1231 usage), pipelined when ``FLAGS_pass_prefetch`` is on:
    while pass N trains, pass N+1's read + key dedup + table pull + pack
    run on the prefetcher's background threads (data/prefetch.py), so the
    device never waits on the host between passes.  Results are
    bit-identical either way (tests/test_pass_pipeline.py).

    passes: one filelist per pass.  before_pass(dataset) runs after the
    load, inside the pass's feed window — e.g.
    ``lambda ds: ds.preprocess_instance()`` for pv-grouped training.
    prefetch: override the flag (None = read FLAGS_pass_prefetch).
    Returns the per-pass train metrics."""
    from paddlebox_tpu import flags as _flags
    from paddlebox_tpu.data.prefetch import PassPrefetcher
    engine, ds = dataset.engine, dataset.dataset
    if date is not None:
        dataset.set_date(date)
    if prefetch is None:
        prefetch = bool(_flags.get_flags("pass_prefetch"))
    metrics = []
    if not prefetch:
        for filelist in passes:
            dataset.set_filelist(filelist)
            dataset.load_into_memory()
            if before_pass is not None:
                before_pass(ds)
            dataset.begin_pass()
            feed = trainer.build_pass_feed(ds)
            metrics.append(trainer.train_pass(feed))
            dataset.end_pass()
        return metrics

    def load(filelist):
        # runs on the prefetch worker INSIDE the feed window the
        # prefetcher opened (begin_feed_pass is its job, not ours)
        ds.set_filelist(filelist)
        ds.load_into_memory()       # reader threads feed keys to engine
        if before_pass is not None:
            before_pass(ds)
        return ds

    pf = PassPrefetcher(engine, trainer)
    try:
        for filelist in passes:
            pf.submit(lambda fl=filelist: load(fl))
        for _ in passes:
            feed = pf.next_pass()
            metrics.append(trainer.train_pass(feed))
            # NOT dataset.end_pass(): its release_memory would drop the
            # blocks the worker already loaded for the NEXT pass
            pf.end_pass()
    finally:
        pf.close()
    return metrics
