"""Fleet facade — the user-level API surface.

≙ paddle.distributed.fleet (fleet/base/fleet_base.py:144: init :211,
distributed_optimizer :912, minimize :1477), the BoxPSDataset python class
(python/paddle/fluid/dataset.py:1231: set_date/begin_pass/end_pass/
load_into_memory/preload_into_memory/wait_preload_done/slots_shuffle) and
Executor.train_from_dataset (executor.py:2412).

A reference user drives training as:
    fleet.init(strategy)
    dataset = fleet.DatasetFactory().create_dataset("BoxPSDataset")
    dataset.set_use_var(...); dataset.set_filelist(...)
    dataset.set_date(d); dataset.load_into_memory(); dataset.begin_pass()
    exe.train_from_dataset(program, dataset)
    dataset.end_pass(True)
This module offers the same verbs over the TPU engine/trainer.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from paddlebox_tpu.config import (DataFeedConfig, DistributedStrategy,
                                  EmbeddingTableConfig, MeshConfig,
                                  TrainerConfig)
from paddlebox_tpu.data.dataset import SlotDataset, ShuffleTransport
from paddlebox_tpu.metrics.auc import MetricGroup
from paddlebox_tpu.parallel.topology import HybridTopology
from paddlebox_tpu.ps.pass_manager import BoxPSEngine
from paddlebox_tpu.trainer.trainer import SparseTrainer

_GLOBAL: Dict = {"fleet": None}


class Fleet:
    """Process-wide runtime handle (≙ fleet_base.Fleet singleton)."""

    def __init__(self, strategy: Optional[DistributedStrategy] = None,
                 topology: Optional[HybridTopology] = None):
        self.strategy = strategy or DistributedStrategy()
        self.topology = topology
        self.engine: Optional[BoxPSEngine] = None
        self.metrics = MetricGroup()

    # ≙ fleet.init(is_collective/role_maker)
    def init_engine(self, table_config: Optional[EmbeddingTableConfig] = None,
                    seed: int = 0) -> BoxPSEngine:
        self.engine = BoxPSEngine(table_config or self.strategy.table,
                                  topology=self.topology, seed=seed)
        return self.engine

    @property
    def worker_num(self) -> int:
        return 1 if self.topology is None else self.topology.world_size

    def barrier_worker(self) -> None:
        pass  # single-host; multi-host via jax.distributed in launch.py


def init(strategy: Optional[DistributedStrategy] = None,
         topology: Optional[HybridTopology] = None) -> Fleet:
    f = Fleet(strategy, topology)
    _GLOBAL["fleet"] = f
    return f


def instance() -> Fleet:
    if _GLOBAL["fleet"] is None:
        init()
    return _GLOBAL["fleet"]


class BoxPSDataset:
    """≙ BoxPSDataset (dataset.py:1231) + the BoxHelper pass driver: one
    object owning the slot dataset AND driving the engine's feed-pass
    overlap, so user code reads like the reference's day/pass loop."""

    def __init__(self, feed_config: DataFeedConfig,
                 engine: Optional[BoxPSEngine] = None,
                 parse_ins_id: bool = False, parse_logkey: bool = False,
                 read_threads: int = 4,
                 transport: Optional[ShuffleTransport] = None):
        self.feed_config = feed_config
        self.engine = engine or instance().engine
        assert self.engine is not None, "fleet.init_engine() first"
        self.dataset = SlotDataset(feed_config, parse_ins_id, parse_logkey,
                                   read_threads, transport)
        self.engine.attach_dataset(self.dataset)

    # -- file/date plumbing (dataset.py:1252-1285) --------------------------
    def set_filelist(self, filelist: Sequence[str]) -> None:
        self.dataset.set_filelist(filelist)

    def set_date(self, date: str) -> None:
        self.engine.set_date(date)

    # -- pass lifecycle ------------------------------------------------------
    def load_into_memory(self) -> None:
        self.engine.begin_feed_pass()
        self.dataset.load_into_memory()

    def preload_into_memory(self) -> None:
        self.engine.begin_feed_pass()
        self.dataset.preload_into_memory()

    def wait_preload_done(self) -> None:
        self.dataset.wait_preload_done()
        # readers are done feeding keys: kick the background working-set
        # build so it overlaps any still-running training pass
        self.engine.end_feed_pass(async_build=True)

    def begin_pass(self) -> None:
        if self.engine._feeding:
            self.engine.end_feed_pass()
        self.engine.begin_pass()

    def end_pass(self, need_save_delta: bool = False,
                 delta_path: str = "") -> None:
        self.engine.end_pass(need_save_delta, delta_path)
        self.dataset.release_memory()

    # -- shuffles ------------------------------------------------------------
    def local_shuffle(self) -> None:
        self.dataset.local_shuffle()

    def global_shuffle(self, by_ins_id: bool = False) -> None:
        self.dataset.global_shuffle(by_ins_id)

    def slots_shuffle(self, slots: Sequence[str]) -> None:
        """≙ BoxPSDataset.slots_shuffle (dataset.py:1302 →
        SlotsShuffle box_wrapper.h:1186): permute the chosen slots' feasign
        spans across instances, keeping everything else fixed (feature
        importance ablation)."""
        import numpy as _np
        rng = _np.random.default_rng(0)
        for block in self.dataset.get_blocks():
            for name in slots:
                if name not in block.uint64_slots:
                    continue
                values, offsets = block.uint64_slots[name]
                lens = _np.diff(offsets)
                order = rng.permutation(block.n)
                # records keep their own length; only spans with equal length
                # swap cleanly — group by length and permute within groups
                for length in _np.unique(lens):
                    rows = _np.nonzero(lens == length)[0]
                    if len(rows) < 2 or length == 0:
                        continue
                    perm = rows[rng.permutation(len(rows))]
                    spans = _np.stack([
                        values[offsets[r]:offsets[r] + length]
                        for r in perm])
                    for i, r in enumerate(rows):
                        values[offsets[r]:offsets[r] + length] = spans[i]

    # -- stats ---------------------------------------------------------------
    def get_memory_data_size(self) -> int:
        return self.dataset.instance_num()

    def get_shuffle_data_size(self) -> int:
        return self.dataset.instance_num()


class DatasetFactory:
    """≙ fluid.DatasetFactory (dataset.py:31)."""

    def create_dataset(self, name: str = "BoxPSDataset", **kw) -> BoxPSDataset:
        if name in ("BoxPSDataset", "InMemoryDataset", "SlotRecordDataset"):
            return BoxPSDataset(**kw)
        raise ValueError(f"unknown dataset type {name}")


def train_from_dataset(trainer: SparseTrainer, dataset: BoxPSDataset,
                       ) -> Dict[str, float]:
    """≙ Executor.train_from_dataset (executor.py:2412 →
    BoxPSTrainer::Run)."""
    return trainer.train_pass(dataset.dataset)


def train_passes(trainer: SparseTrainer, dataset: BoxPSDataset,
                 passes: Sequence[Sequence[str]], date: Optional[str] = None,
                 before_pass=None, prefetch: Optional[bool] = None,
                 checkpoint=None, resume=None) -> list:
    """Day loop over per-pass filelists — the reference's
    set_date/load_into_memory/begin_pass/train/end_pass sequence
    (dataset.py:1231 usage), pipelined when ``FLAGS_pass_prefetch`` is on:
    while pass N trains, pass N+1's read + key dedup + table pull + pack
    run on the prefetcher's background threads (data/prefetch.py), so the
    device never waits on the host between passes.  Results are
    bit-identical either way (tests/test_pass_pipeline.py).

    passes: one filelist per pass.  before_pass(dataset) runs after the
    load, inside the pass's feed window — e.g.
    ``lambda ds: ds.preprocess_instance()`` for pv-grouped training.
    prefetch: override the flag (None = read FLAGS_pass_prefetch).

    Crash recovery (the production re-drive-by-date contract): pass a
    ``TrainCheckpoint`` (or set ``FLAGS_ckpt_dir``) and an auto-resume
    budget (``resume=N`` / True / ``FLAGS_auto_resume``) and the loop
    (1) resumes from the last committed generation — completed passes of
    the same ``date`` are SKIPPED via the checkpointed pass cursor,
    (2) saves an incremental generation after every completed pass, and
    (3) survives a mid-run failure with a two-tier retry: a write-back
    ``ConnectionError`` re-drives ``end_pass`` in place (the pinned-rid
    replay — chunks that landed dedup server-side), while a simulated
    process death (faults.InjectedFault from a lifecycle kill site) or an
    exhausted in-place retry tears the prefetcher down, reloads the last
    generation (rolling back any partial pass) and re-drives the
    remaining passes.  Bit-identity vs a fault-free run is asserted by
    tests/test_crash_recovery.py.

    Device row cache (``FLAGS_ps_device_cache``): no interaction needed
    here — both recovery tiers already pass through its coherence points.
    The prefetcher teardown calls ``engine.reset_feed_state`` and the
    checkpoint rollback calls ``TrainCheckpoint.resume``, each of which
    invalidates the cache, so a re-driven pass always rebuilds it cold
    from the rolled-back table and stays bit-identical to a cache-off
    run (tests/test_device_cache.py).

    Returns the per-pass train metrics; passes skipped by the resume
    cursor (completed by a PREVIOUS incarnation) yield ``None`` entries
    so indices still line up with ``passes``."""
    from paddlebox_tpu import flags as _flags
    from paddlebox_tpu.data.prefetch import PassPrefetcher
    from paddlebox_tpu.io import checkpoint as _ckpt  # noqa: F401 -- the
    # auto_resume/ckpt_dir/ckpt_every_passes flags read below are
    # registered by this module's import; without it a caller that never
    # touched io.checkpoint gets KeyError("undefined flag")
    from paddlebox_tpu.metrics import quality as _quality
    from paddlebox_tpu.ps import faults as _faults
    from paddlebox_tpu.utils.backoff import Backoff as _Backoff
    from paddlebox_tpu.utils.monitor import stat_add as _stat_add

    engine, ds = dataset.engine, dataset.dataset
    if prefetch is None:
        prefetch = bool(_flags.get_flags("pass_prefetch"))
    if resume is None:
        budget = int(_flags.get_flags("auto_resume"))
    elif resume is True:
        budget = int(_flags.get_flags("auto_resume")) or 8
    else:
        budget = int(resume)
    if checkpoint is None:
        root = _flags.get_flags("ckpt_dir")
        if root:
            from paddlebox_tpu.io.checkpoint import TrainCheckpoint
            checkpoint = TrainCheckpoint(root)

    # resume BEFORE set_date: the restored day cursor decides whether
    # set_date triggers an end_day rollover (resuming into a new day) or
    # is a same-day re-drive (skip completed passes)
    state = None
    if checkpoint is not None and budget > 0:
        state = checkpoint.resume(engine, trainer)
    start = 0
    if state is not None and date is not None \
            and state.get("day_id") == date:
        start = min(int(state.get("pass_index", 0) or 0), len(passes))
    if date is not None:
        dataset.set_date(date)
    if checkpoint is not None and budget > 0 and state is None:
        # durable floor before the first pass: a crash after pass 0's
        # write-back but before its generation commits must roll back TO
        # something, or the re-driven pass double-applies
        checkpoint.save(engine, trainer,
                        extra={"day_id": engine.day_id, "pass_index": start})

    metrics: list = [None] * start

    def end_with_replay(end_fn) -> None:
        # in-place tier: the server died (or dropped us) mid write-back
        # while THIS trainer survived — engine/adapter state is intact, so
        # re-driving end_pass resends byte-identical chunks under pinned
        # rids (already-landed chunks dedup server-side).  The backoff
        # window rides out a supervisor restart (launch.PSServerSupervisor)
        bo = _Backoff(base=0.05, cap=2.0, deadline=30.0)
        attempt = 0
        while True:
            try:
                end_fn()
                return
            except _faults.InjectedFault:
                raise       # simulated process death → outer resume tier
            except ConnectionError:
                attempt += 1
                _stat_add("ps.fleet.end_pass_replay")
                if not bo.sleep(attempt):
                    raise

    def save_cursor(i: int) -> None:
        if checkpoint is not None:
            checkpoint.save_pass(engine, trainer,
                                 extra={"day_id": engine.day_id,
                                        "pass_index": i + 1})

    def run_serial(todo) -> None:
        for i in todo:
            dataset.set_filelist(passes[i])
            dataset.load_into_memory()
            if before_pass is not None:
                before_pass(ds)
            dataset.begin_pass()
            feed = trainer.build_pass_feed(ds)
            m = trainer.train_pass(feed)
            end_with_replay(dataset.end_pass)
            metrics.append(m)
            _quality.observe_pass(m, pass_id=engine.pass_id,
                                  day=engine.day_id)
            save_cursor(i)

    def run_prefetch(todo) -> None:
        def load(filelist):
            # runs on the prefetch worker INSIDE the feed window the
            # prefetcher opened (begin_feed_pass is its job, not ours)
            ds.set_filelist(filelist)
            ds.load_into_memory()   # reader threads feed keys to engine
            if before_pass is not None:
                before_pass(ds)
            return ds

        pf = PassPrefetcher(engine, trainer)
        try:
            for i in todo:
                pf.submit(lambda fl=passes[i]: load(fl))
            for i in todo:
                feed = pf.next_pass()
                m = trainer.train_pass(feed)
                # NOT dataset.end_pass(): its release_memory would drop
                # the blocks the worker already loaded for the NEXT pass
                end_with_replay(pf.end_pass)
                metrics.append(m)
                _quality.observe_pass(m, pass_id=engine.pass_id,
                                      day=engine.day_id)
                save_cursor(i)
        except BaseException:
            # failure path only: drop the pipeline AND the engine's
            # in-flight feed state so the resume tier re-drives against a
            # clean pass boundary (the happy path keeps feed state — the
            # caller may chain more days onto this engine)
            pf.abort()
            raise
        finally:
            pf.close()

    todo = list(range(start, len(passes)))
    while True:
        try:
            if prefetch:
                run_prefetch(todo)
            else:
                run_serial(todo)
            return metrics
        except (ConnectionError, RuntimeError):
            if checkpoint is None or budget <= 0:
                raise
            budget -= 1
            _stat_add("ps.fleet.auto_resume")
            # roll the world back to the last committed generation: the
            # partial pass's table writes (if any) are discarded with the
            # reload, and the re-drive below replays it deterministically
            if not prefetch:
                if hasattr(engine, "reset_feed_state"):
                    engine.reset_feed_state()
            ds.release_memory()
            state = checkpoint.resume(engine, trainer)
            # the cursor only stands when the restored generation belongs
            # to THE DAY THIS CALL DRIVES — a crash before the new day's
            # first durable pass rolls the world back into the previous
            # day, whose completed cursor must not skip the new passes
            new_start = 0
            if state is not None and date is not None \
                    and state.get("day_id") == date:
                new_start = min(int(state.get("pass_index", 0) or 0),
                                len(passes))
            if date is not None and engine.day_id != date:
                # rolled back across the day boundary: re-drive set_date
                # (end_day decay) exactly as the first attempt did —
                # deterministic, since the table was rolled back with it
                dataset.set_date(date)
            del metrics[new_start:]
            metrics.extend([None] * (new_start - len(metrics)))
            todo = list(range(new_start, len(passes)))


def run_trainer_fleet(world, ps_addrs, workdir, table_config, model_fn,
                      feed_config, days, *, batch_size: int = 128,
                      virtual_shards: Optional[int] = None,
                      table_seed: int = 0, trainer_seed: int = 0,
                      prefetch: bool = False,
                      trainer_addrs: Optional[Sequence] = None,
                      fault_plans: Optional[Dict[int, object]] = None,
                      max_restarts: int = 3,
                      client_deadline: float = 60.0,
                      auc_table_size: int = 100_000) -> list:
    """Drive ``world`` supervised fleet trainers over one PS cluster —
    the N x M data-parallel entry (trainer/fleet_runner.py protocol,
    launch.TrainerSupervisor restarts).

    Every rank's supervisor builds a FULL fresh incarnation per attempt
    (PSClient + shuffle transport + FleetRunner); ``fault_plans`` (rank →
    ps.faults.FaultPlan) arm only the FIRST incarnation, so an injected
    kill exercises the same recovery path a real crash would.  Returns
    the per-rank run() results in rank order; any rank that spent its
    restart budget re-raises its terminal error from ``join()``.

    ``trainer_addrs``: one (host, port) per rank for the shuffle
    transport — required when world > 1.  Use fixed, non-ephemeral
    ports: a restarted rank re-binds its OWN address, which must not be
    squattable by concurrent outbound dials."""
    from paddlebox_tpu.launch import TrainerSupervisor
    from paddlebox_tpu.ps.service import PSClient
    from paddlebox_tpu.data.shuffle_transport import TcpShuffleTransport
    from paddlebox_tpu.trainer.fleet_runner import FleetRunner

    if world is None:
        world = int(_flags.get_flags("trainers"))   # --trainers knob
    if world > 1 and not trainer_addrs:
        raise ValueError("world > 1 requires trainer_addrs for the "
                         "shuffle transport")
    plans = dict(fault_plans or {})

    def factory(rank: int):
        plan = plans.pop(rank, None)     # first incarnation only
        client = PSClient(ps_addrs, deadline=client_deadline)
        transport = (TcpShuffleTransport(rank, list(trainer_addrs))
                     if world > 1 else None)
        return FleetRunner(
            rank=rank, world=world, client=client, workdir=workdir,
            table_config=table_config, model_fn=model_fn,
            feed_config=feed_config, batch_size=batch_size,
            virtual_shards=virtual_shards, table_seed=table_seed,
            trainer_seed=trainer_seed, prefetch=prefetch,
            transport=transport, fault_plan=plan,
            auc_table_size=auc_table_size)

    sups = [TrainerSupervisor(factory, r, days, max_restarts=max_restarts)
            for r in range(world)]
    results, errors = [], []
    for s in sups:
        try:
            results.append(s.join())
        except BaseException as e:  # noqa: BLE001 — surface after joining all
            errors.append(e)
            results.append(None)
    for s in sups:
        s.stop()
    if errors:
        raise errors[0]
    return results
