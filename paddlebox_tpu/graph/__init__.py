from paddlebox_tpu.graph.graph_table import GraphTable  # noqa: F401
