"""Graph engine for GNN training (graph-learning mode of the PS).

≙ heter_ps/graph_gpu_ps_table.h GpuPsGraphTable + graph_gpu_wrapper +
graph_sampler (SURVEY §2.2: device graph table with neighbor sampling and
random walks feeding the sparse-PS embedding path).

TPU-first shape: the adjacency is CSR in device arrays (indptr/indices —
built host-side with the same key→dense-id discipline as the embedding pass
working set), and sampling/walks are jit-able static-shape programs:
per-draw uniform offsets into each node's neighbor span, `lax.scan` for
walks (≙ graph_sampler walk kernels), and weighted draws by inverse-CDF
binary search over per-span normalized CDFs (f64-built on host so float32
resolution is span-local, never global).  Degree-0 nodes yield -1 (masked
downstream).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp


class GraphTable:
    """Host-built CSR graph, device-sampled."""

    def __init__(self, edges: np.ndarray,
                 weights: Optional[np.ndarray] = None,
                 num_nodes: Optional[int] = None):
        """edges: [M, 2] (src, dst) dense node ids."""
        edges = np.asarray(edges, np.int64)
        n = int(num_nodes if num_nodes is not None else edges.max() + 1)
        order = np.argsort(edges[:, 0], kind="stable")
        src = edges[order, 0]
        dst = edges[order, 1]
        counts = np.bincount(src, minlength=n)
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        self.num_nodes = n
        self.num_edges = len(edges)
        self.indptr = jnp.asarray(indptr, jnp.int32)
        self.indices = jnp.asarray(dst, jnp.int32)
        if weights is not None and len(edges) > 0:
            # Per-span normalized CDF, built in f64: float32 only ever
            # stores values in [0, 1] *within* a span, so resolution never
            # degrades with graph size (a single global f32 cumsum loses
            # per-edge increments past ~2^24 total weight).  Zero-weight
            # spans get a uniform CDF instead of a degenerate table.
            w = np.asarray(weights, np.float64)[order]
            if np.any(w < 0):
                raise ValueError("negative edge weight")
            m = len(w)
            cums = np.cumsum(w)
            span_id = np.repeat(np.arange(n), counts)
            span_start = indptr[span_id]
            span_end = indptr[span_id + 1]
            base = np.where(span_start > 0, cums[span_start - 1], 0.0)
            tot = cums[span_end - 1] - base
            uniform = ((np.arange(m) - span_start + 1)
                       / np.maximum(span_end - span_start, 1))
            lc = np.where(tot > 0, (cums - base) / np.where(tot > 0, tot, 1.0),
                          uniform)
            self.local_cdf = jnp.asarray(lc, jnp.float32)
        else:
            self.local_cdf = None

    # ------------------------------------------------------------------
    def degrees(self, nodes: jnp.ndarray) -> jnp.ndarray:
        return self.indptr[nodes + 1] - self.indptr[nodes]

    @partial(jax.jit, static_argnums=(0, 2))
    def sample_neighbors(self, nodes: jnp.ndarray, k: int,
                         key: jax.Array) -> jnp.ndarray:
        """Uniform (or alias-weighted) sample of k neighbors per node
        (≙ graph_neighbor_sample, graph_gpu_ps_table_inl.cu).
        nodes [B] → [B, k]; -1 where degree == 0."""
        start = self.indptr[nodes]                     # [B]
        deg = self.indptr[nodes + 1] - start
        B = nodes.shape[0]
        k1, k2 = jax.random.split(key)
        off = jax.random.randint(k1, (B, k), 0, jnp.maximum(deg, 1)[:, None])
        pos = start[:, None] + off
        if self.local_cdf is not None:
            end = start + deg
            u = jax.random.uniform(k2, (B, k))
            lc = self.local_cdf
            m = lc.shape[0]
            # first edge e in the span with local_cdf[e] >= u — branchless
            # binary search (32 steps covers any span)
            lo = jnp.broadcast_to(start[:, None], (B, k))
            hi = jnp.broadcast_to(end[:, None], (B, k))

            def bs(_, lh):
                lo, hi = lh
                mid = (lo + hi) // 2
                go = lc[jnp.clip(mid, 0, m - 1)] < u
                return (jnp.where(go, mid + 1, lo), jnp.where(go, hi, mid))

            lo, _ = jax.lax.fori_loop(0, 32, bs, (lo, hi))
            wpos = jnp.clip(lo, start[:, None],
                            jnp.maximum(end - 1, 0)[:, None])
            pos = jnp.where(deg[:, None] > 0, wpos, pos)
        nb = self.indices[pos]
        return jnp.where(deg[:, None] > 0, nb, -1)

    @partial(jax.jit, static_argnums=(0, 2))
    def random_walk(self, starts: jnp.ndarray, length: int,
                    key: jax.Array) -> jnp.ndarray:
        """Deepwalk-style walks (≙ graph_sampler walk path).
        starts [B] → [B, length+1]; stuck walks repeat their node."""
        def step(carry, k):
            cur = carry
            nxt = self.sample_neighbors(jnp.maximum(cur, 0), 1, k)[:, 0]
            nxt = jnp.where((cur >= 0) & (nxt >= 0), nxt, cur)
            return nxt, nxt

        keys = jax.random.split(key, length)
        _, path = jax.lax.scan(step, starts, keys)
        return jnp.concatenate([starts[:, None], path.T], axis=1)

    def sample_nodes(self, key: jax.Array, count: int) -> jnp.ndarray:
        """Uniform node draws (negative sampling, ≙ graph_node_sample)."""
        return jax.random.randint(key, (count,), 0, self.num_nodes)


def sage_aggregate(emb: jnp.ndarray, neigh_idx: jnp.ndarray,
                   reduce: str = "mean") -> jnp.ndarray:
    """GraphSage neighbor aggregation (≙ the feature aggregation the
    reference's GNN mode feeds from graph_neighbor_sample outputs).

    emb [N, D] node-indexed features/embeddings; neigh_idx [B, K] sampled
    neighbor ids, -1 where a node had no neighbor (sample_neighbors'
    convention) → [B, D] mean/max over VALID neighbors (all-invalid rows
    aggregate to zeros).  Pure jit-able gather + masked reduce.
    """
    if reduce not in ("mean", "max"):
        raise ValueError(f"reduce must be mean|max, got {reduce!r}")
    valid = neigh_idx >= 0                                  # [B, K]
    rows = emb[jnp.maximum(neigh_idx, 0)]                   # [B, K, D]
    m = valid[..., None].astype(emb.dtype)
    if reduce == "mean":
        cnt = jnp.maximum(valid.sum(axis=1, keepdims=True), 1)
        return (rows * m).sum(axis=1) / cnt.astype(emb.dtype)
    neg = jnp.where(valid[..., None], rows, -jnp.inf)
    out = jnp.max(neg, axis=1)
    return jnp.where(valid.any(axis=1, keepdims=True), out, 0.0)


def metapath_walk(tables, starts: jnp.ndarray, length: int,
                  key: jax.Array) -> jnp.ndarray:
    """Meta-path walks over typed edge tables (≙ GraphConfig.meta_path +
    first_node_type, data_feed.proto:29-40: e.g. "user2item-item2user"
    walks alternate edge types so each hop lands on the path's next node
    type).  tables: one GraphTable per meta-path edge type, applied
    cyclically; starts [B] nodes of the first type → [B, length+1] walk.

    A walk that dead-ends STAYS stuck (repeating its node) — id spaces of
    different node types may overlap across tables, so re-sampling a
    stuck node in a later edge type could silently resume through an
    unrelated entity of the wrong type.  One lax.scan program (like
    random_walk), with lax.switch selecting the hop's edge table."""
    if not tables:
        raise ValueError("metapath_walk needs at least one edge table")
    cur = jnp.asarray(starts, jnp.int32)
    k = len(tables)
    keys = jax.random.split(key, length)

    def step(carry, inp):
        node, stuck = carry
        t_idx, subkey = inp
        branches = [
            (lambda sk, nd, t=t: t.sample_neighbors(
                jnp.maximum(nd, 0), 1, sk)[:, 0]) for t in tables]
        nxt_raw = jax.lax.switch(t_idx, branches, subkey, node)
        ok = (nxt_raw >= 0) & ~stuck
        nxt = jnp.where(ok, nxt_raw, node)
        stuck = stuck | (nxt_raw < 0)
        return (nxt, stuck), nxt

    t_ids = jnp.arange(length, dtype=jnp.int32) % k
    (_, _), path = jax.lax.scan(
        step, (cur, jnp.zeros_like(cur, bool)), (t_ids, keys))
    return jnp.concatenate([cur[:, None], path.T], axis=1)
