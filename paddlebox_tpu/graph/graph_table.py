"""Graph engine for GNN training (graph-learning mode of the PS).

≙ heter_ps/graph_gpu_ps_table.h GpuPsGraphTable + graph_gpu_wrapper +
graph_sampler (SURVEY §2.2: device graph table with neighbor sampling and
random walks feeding the sparse-PS embedding path).

TPU-first shape: the adjacency is CSR in device arrays (indptr/indices —
built host-side with the same key→dense-id discipline as the embedding pass
working set), and sampling/walks are jit-able static-shape programs:
per-draw uniform offsets into each node's neighbor span, `lax.scan` for
walks (≙ graph_sampler walk kernels), alias tables for weighted graphs
(ops/alias_method.py).  Degree-0 nodes yield -1 (masked downstream).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp


class GraphTable:
    """Host-built CSR graph, device-sampled."""

    def __init__(self, edges: np.ndarray,
                 weights: Optional[np.ndarray] = None,
                 num_nodes: Optional[int] = None):
        """edges: [M, 2] (src, dst) dense node ids."""
        edges = np.asarray(edges, np.int64)
        n = int(num_nodes if num_nodes is not None else edges.max() + 1)
        order = np.argsort(edges[:, 0], kind="stable")
        src = edges[order, 0]
        dst = edges[order, 1]
        counts = np.bincount(src, minlength=n)
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        self.num_nodes = n
        self.num_edges = len(edges)
        self.indptr = jnp.asarray(indptr, jnp.int32)
        self.indices = jnp.asarray(dst, jnp.int32)
        if weights is not None:
            # Weighted draws by inverse-CDF over a global per-edge cumsum:
            # the cumsum is nondecreasing, so a span draw is one batched
            # searchsorted — O(m) vectorized build (vs per-node alias
            # construction) and zero-weight spans degrade to the uniform
            # fallback instead of a degenerate table.
            w = np.asarray(weights, np.float64)[order]
            if np.any(w < 0):
                raise ValueError("negative edge weight")
            self.cum_w = jnp.asarray(np.cumsum(w), jnp.float32)
        else:
            self.cum_w = None

    # ------------------------------------------------------------------
    def degrees(self, nodes: jnp.ndarray) -> jnp.ndarray:
        return self.indptr[nodes + 1] - self.indptr[nodes]

    @partial(jax.jit, static_argnums=(0, 2))
    def sample_neighbors(self, nodes: jnp.ndarray, k: int,
                         key: jax.Array) -> jnp.ndarray:
        """Uniform (or alias-weighted) sample of k neighbors per node
        (≙ graph_neighbor_sample, graph_gpu_ps_table_inl.cu).
        nodes [B] → [B, k]; -1 where degree == 0."""
        start = self.indptr[nodes]                     # [B]
        deg = self.indptr[nodes + 1] - start
        B = nodes.shape[0]
        k1, k2 = jax.random.split(key)
        off = jax.random.randint(k1, (B, k), 0, jnp.maximum(deg, 1)[:, None])
        pos = start[:, None] + off
        if self.cum_w is not None:
            end = start + deg
            base = jnp.where(start > 0, self.cum_w[start - 1], 0.0)  # [B]
            total = self.cum_w[jnp.maximum(end - 1, 0)] - base
            u = jax.random.uniform(k2, (B, k))
            v = base[:, None] + u * total[:, None]
            wpos = jnp.searchsorted(self.cum_w, v, side="left")
            # zero-total spans (all weights 0) keep the uniform draw
            pos = jnp.where((total > 0)[:, None],
                            jnp.clip(wpos, start[:, None],
                                     jnp.maximum(end - 1, 0)[:, None]), pos)
        nb = self.indices[pos]
        return jnp.where(deg[:, None] > 0, nb, -1)

    @partial(jax.jit, static_argnums=(0, 2))
    def random_walk(self, starts: jnp.ndarray, length: int,
                    key: jax.Array) -> jnp.ndarray:
        """Deepwalk-style walks (≙ graph_sampler walk path).
        starts [B] → [B, length+1]; stuck walks repeat their node."""
        def step(carry, k):
            cur = carry
            nxt = self.sample_neighbors(jnp.maximum(cur, 0), 1, k)[:, 0]
            nxt = jnp.where((cur >= 0) & (nxt >= 0), nxt, cur)
            return nxt, nxt

        keys = jax.random.split(key, length)
        _, path = jax.lax.scan(step, starts, keys)
        return jnp.concatenate([starts[:, None], path.T], axis=1)

    def sample_nodes(self, key: jax.Array, count: int) -> jnp.ndarray:
        """Uniform node draws (negative sampling, ≙ graph_node_sample)."""
        return jax.random.randint(key, (count,), 0, self.num_nodes)
