"""PB1xx — lock discipline.

For every class that creates a ``threading.Lock/RLock/Condition``
attribute:

  PB101  an instance attribute is mutated both inside and outside
         ``with self.<lock>:`` blocks — the guard is advisory only.
  PB102  a lock-adjacent method (one that acquires a class lock directly,
         or transitively through other methods of the class) reads an
         instance attribute and later mutates it, with BOTH accesses
         outside any lock block — the check-then-act / read-modify-write
         race class (the pre-fix ps/service.py pull_sparse estimate bug).
  PB103  a lock acquired via ``.acquire()`` whose release is not
         protected by ``try/finally`` — an exception leaks the lock.
  PB104  blocking socket/file I/O performed while holding a
         ``threading.Lock``/``RLock``/``Condition`` (``with self.<lock>:``
         or a module-level lock): every other holder of that lock stalls
         behind the network/disk — the exact pattern the pipelined PS
         client removed from ``PSClient._call`` (ps/service.py).  Flags
         calls whose terminal name is a socket primitive (sendall, recv,
         create_connection, ...), the package's frame helpers
         (``_send``/``_recv``/``_send_msg``/``_read_exact``) or builtin
         ``open``.  Deliberate designs where the file IS the locked
         resource (SSD log store) suppress with a reason.

Scope notes (deliberate):
  * ``__init__``/``__new__`` bodies — and private helpers called only
    from them — run before the instance is shared; their accesses count
    as neither inside nor outside.
  * Nested function bodies (thread targets, callbacks) execute on their
    own schedule, typically sequenced by start/join, so they are skipped
    for PB102; their writes still count for PB101.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_MUTATOR_METHODS = {"append", "extend", "insert", "add", "update",
                    "setdefault", "pop", "popleft", "popitem", "remove",
                    "discard", "clear", "sort", "reverse"}
# terminal call names treated as blocking I/O for PB104: socket
# primitives, the package's own length-prefixed frame helpers, and
# builtin open()
_BLOCKING_IO = {"sendall", "sendto", "recv", "recv_into", "recvfrom",
                "accept", "connect", "connect_ex", "makefile",
                "create_connection", "create_server",
                "_send", "_recv", "_send_msg", "_recv_msg", "_read_exact",
                "open"}


_LOCKDEP_FACTORIES = {"lock", "rlock", "condition"}


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    tail = name.rsplit(".", 1)[-1]
    if tail in _LOCK_FACTORIES and (
            "." not in name or name.startswith("threading.")):
        return True
    # utils/lockdep factories create (optionally instrumented) locks —
    # they must count as lock ctors or converting a creation site would
    # silently disable PB101/PB102/PB104 for that class
    return tail in _LOCKDEP_FACTORIES and name.startswith("lockdep.")


def _contains_lock_ctor(node: ast.AST) -> bool:
    return any(_is_lock_ctor(n) for n in ast.walk(node))


def _self_attr(node: ast.AST, self_name: str) -> Optional[str]:
    """`self.X`, `self.X[...]` (any subscript depth) → "X"."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == self_name):
        return node.attr
    return None


class _MethodScan(ast.NodeVisitor):
    """Per-method access log: (attr, line, inside-lock?) reads/writes,
    direct lock acquisition, and intra-class call edges."""

    def __init__(self, self_name: str, lock_attrs: Set[str]):
        self.self_name = self_name
        self.lock_attrs = lock_attrs
        # entries: (attr, line, inside_lock, in_nested_def)
        self.reads: List[Tuple[str, int, bool, bool]] = []
        self.writes: List[Tuple[str, int, bool, bool]] = []
        self.acquires = False
        self.calls: Set[str] = set()
        self._depth = 0          # >0 → inside a lock-guarded with block
        self._fn_depth = 0       # >0 → inside a nested def/lambda

    # -- lock context --------------------------------------------------------
    def _is_lock_expr(self, node: ast.AST) -> bool:
        attr = _self_attr(node, self.self_name)
        return attr is not None and attr in self.lock_attrs

    def visit_With(self, node: ast.With) -> None:
        guarded = any(self._is_lock_expr(item.context_expr)
                      for item in node.items)
        for item in node.items:
            self.visit(item.context_expr)
        if guarded:
            self._depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if guarded:
            self._depth -= 1

    # -- nested scopes -------------------------------------------------------
    def visit_FunctionDef(self, node) -> None:
        self._fn_depth += 1
        self.generic_visit(node)
        self._fn_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    # -- accesses ------------------------------------------------------------
    def _record_write(self, target: ast.AST) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_write(elt)
            return
        if isinstance(target, ast.Starred):
            self._record_write(target.value)
            return
        attr = _self_attr(target, self.self_name)
        if attr is not None:
            self.writes.append((attr, target.lineno, self._depth > 0,
                                self._fn_depth > 0))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_write(t)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_write(node.target)
            self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = _self_attr(node.target, self.self_name)
        if attr is not None:
            inside = self._depth > 0
            nested = self._fn_depth > 0
            self.reads.append((attr, node.lineno, inside, nested))
            self.writes.append((attr, node.lineno, inside, nested))
        self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._record_write(t)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            recv = _self_attr(node.func.value, self.self_name)
            if recv is not None:
                if node.func.attr in ("acquire",) and recv in self.lock_attrs:
                    self.acquires = True
                elif node.func.attr in _MUTATOR_METHODS:
                    # container mutation through a method call is a write
                    self.writes.append((recv, node.lineno,
                                        self._depth > 0,
                                        self._fn_depth > 0))
            if isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == self.self_name:
                self.calls.add(node.func.attr)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            attr = _self_attr(node, self.self_name)
            if attr is not None:
                self.reads.append((attr, node.lineno, self._depth > 0,
                                   self._fn_depth > 0))
        self.generic_visit(node)


class _ClassInfo:
    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.methods: Dict[str, ast.FunctionDef] = {
            m.name: m for m in node.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.lock_attrs = self._find_lock_attrs()
        self.scans: Dict[str, _MethodScan] = {}
        for name, m in self.methods.items():
            scan = _MethodScan(self._self_name(m), self.lock_attrs)
            for stmt in m.body:
                scan.visit(stmt)
            self.scans[name] = scan

    @staticmethod
    def _self_name(m: ast.FunctionDef) -> str:
        return m.args.args[0].arg if m.args.args else "self"

    def _find_lock_attrs(self) -> Set[str]:
        locks: Set[str] = set()
        # class-level: `_instance_lock = threading.Lock()`
        for stmt in self.node.body:
            if isinstance(stmt, ast.Assign) \
                    and _contains_lock_ctor(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        locks.add(t.id)
        # instance-level: `self.X = threading.Lock()` (incl. containers
        # of locks, e.g. `{name: threading.Lock() for ...}`)
        for m in self.methods.values():
            self_name = self._self_name(m)
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) \
                        and _contains_lock_ctor(node.value):
                    for t in node.targets:
                        attr = _self_attr(t, self_name)
                        if attr is not None:
                            locks.add(attr)
        return locks

    def init_only_methods(self) -> Set[str]:
        """__init__/__new__ plus private helpers reachable only from them
        (index builders etc. that run before the instance is shared)."""
        base = {"__init__", "__new__"}
        callers: Dict[str, Set[str]] = {name: set() for name in self.methods}
        for name, scan in self.scans.items():
            for callee in scan.calls:
                if callee in callers:
                    callers[callee].add(name)
        out = set(base)
        changed = True
        while changed:
            changed = False
            for name, who in callers.items():
                if (name not in out and name.startswith("_")
                        and not name.startswith("__") and who
                        and who <= out):
                    out.add(name)
                    changed = True
        return out

    def lock_adjacent_methods(self) -> Set[str]:
        """Methods that acquire a class lock directly or via intra-class
        calls (transitive closure over `self.m()` edges)."""
        adjacent = {name for name, scan in self.scans.items()
                    if self._has_lock_with(name)}
        changed = True
        while changed:
            changed = False
            for name, scan in self.scans.items():
                if name not in adjacent and scan.calls & adjacent:
                    adjacent.add(name)
                    changed = True
        return adjacent

    def _has_lock_with(self, name: str) -> bool:
        scan = self.scans[name]
        m = self.methods[name]
        if scan.acquires:
            return True
        self_name = self._self_name(m)
        for node in ast.walk(m):
            if isinstance(node, ast.With):
                for item in node.items:
                    attr = _self_attr(item.context_expr, self_name)
                    if attr in self.lock_attrs:
                        return True
        return False


def _check_class(mod: Module, cls: ast.ClassDef) -> List[Finding]:
    info = _ClassInfo(cls)
    if not info.lock_attrs:
        return []
    findings: List[Finding] = []
    init_only = info.init_only_methods()
    adjacent = info.lock_adjacent_methods() - init_only

    # PB101: per-attribute inside+outside mutation
    writes_in: Dict[str, List[Tuple[str, int]]] = {}
    writes_out: Dict[str, List[Tuple[str, int]]] = {}
    for name, scan in info.scans.items():
        if name in init_only:
            continue
        for attr, line, inside, _nested in scan.writes:
            if attr in info.lock_attrs:
                continue
            (writes_in if inside else writes_out).setdefault(
                attr, []).append((name, line))
    for attr in sorted(set(writes_in) & set(writes_out)):
        for name, line in sorted(writes_out[attr], key=lambda t: t[1]):
            findings.append(Finding(
                mod.path, line, "PB101",
                f"{cls.name}.{attr} is mutated here outside the lock but "
                f"under it elsewhere (e.g. line "
                f"{min(l for _, l in writes_in[attr])}) — move this "
                f"mutation under the lock"))

    # PB102: unlocked read-modify-write in lock-adjacent methods
    for name in sorted(adjacent):
        scan = info.scans[name]
        out_reads: Dict[str, int] = {}
        for attr, line, inside, nested in scan.reads:
            if not inside and not nested \
                    and attr not in info.lock_attrs:
                out_reads.setdefault(attr, line)
        flagged: Set[str] = set()
        for attr, line, inside, nested in scan.writes:
            if (inside or nested or attr in info.lock_attrs
                    or attr in flagged or attr not in out_reads
                    or line < out_reads[attr]):
                continue
            flagged.add(attr)
            findings.append(Finding(
                mod.path, line, "PB102",
                f"{cls.name}.{name} reads {attr} (line {out_reads[attr]}) "
                f"and mutates it here without holding the class lock — a "
                f"concurrent caller interleaves between check and act"))
    return findings


class _IOUnderLock(ast.NodeVisitor):
    """PB104 walker for one function/method body: tracks the stack of
    held locks (``with``-acquired self attrs or module-level lock names)
    and flags blocking-I/O calls made while any is held.  Nested function
    bodies run on their own schedule, not at def time — they reset the
    held stack."""

    def __init__(self, path: str, self_name: Optional[str],
                 self_locks: Set[str], global_locks: Set[str]):
        self.path = path
        self.self_name = self_name
        self.self_locks = self_locks
        self.global_locks = global_locks
        self.findings: List[Finding] = []
        self._held: List[str] = []

    def _lock_desc(self, expr: ast.AST) -> Optional[str]:
        if self.self_name is not None:
            attr = _self_attr(expr, self.self_name)
            if attr is not None and attr in self.self_locks:
                return f"{self.self_name}.{attr}"
        if isinstance(expr, ast.Name) and expr.id in self.global_locks:
            return expr.id
        return None

    def visit_With(self, node: ast.With) -> None:
        n_acquired = 0
        for item in node.items:
            desc = self._lock_desc(item.context_expr)
            if desc is None:
                # a non-lock with-item (e.g. `open(...)`) is evaluated
                # AFTER any lock item listed before it — already-held
                # locks apply to it
                self.visit(item.context_expr)
            else:
                self._held.append(desc)
                n_acquired += 1
        for stmt in node.body:
            self.visit(stmt)
        if n_acquired:
            del self._held[len(self._held) - n_acquired:]

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node) -> None:
        held, self._held = self._held, []
        self.generic_visit(node)
        self._held = held

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        if self._held:
            if isinstance(node.func, ast.Attribute):
                name = node.func.attr
            elif isinstance(node.func, ast.Name):
                name = node.func.id
            else:
                name = ""
            if name in _BLOCKING_IO:
                self.findings.append(Finding(
                    self.path, node.lineno, "PB104",
                    f"blocking I/O {name}() while holding lock "
                    f"{self._held[-1]} — every other holder stalls behind "
                    f"the network/disk; move the I/O outside the guarded "
                    f"region (the pre-pipelining PSClient._call pattern)"))
        self.generic_visit(node)


def _module_lock_names(mod: Module) -> Set[str]:
    names: Set[str] = set()
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and _contains_lock_ctor(stmt.value):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _check_io_under_lock(mod: Module) -> List[Finding]:
    global_locks = _module_lock_names(mod)
    findings: List[Finding] = []

    def scan_fn(fn, self_name: Optional[str], self_locks: Set[str]):
        walker = _IOUnderLock(mod.path, self_name, self_locks, global_locks)
        for stmt in fn.body:
            walker.visit(stmt)
        findings.extend(walker.findings)

    for node in mod.walk():
        if isinstance(node, ast.ClassDef):
            info = _ClassInfo(node)
            for name, m in info.methods.items():
                scan_fn(m, info._self_name(m), info.lock_attrs)
    for stmt in mod.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_fn(stmt, None, set())
    return findings


def _check_bare_acquire(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    for node in mod.walk():
        body_lists = [getattr(node, f, None)
                      for f in ("body", "orelse", "finalbody")]
        for body in body_lists:
            if not isinstance(body, list):
                continue
            for i, stmt in enumerate(body):
                if not (isinstance(stmt, ast.Expr)
                        and isinstance(stmt.value, ast.Call)
                        and isinstance(stmt.value.func, ast.Attribute)
                        and stmt.value.func.attr == "acquire"):
                    continue
                recv = ast.dump(stmt.value.func.value)
                nxt = body[i + 1] if i + 1 < len(body) else None
                ok = False
                if isinstance(nxt, ast.Try) and nxt.finalbody:
                    for n in ast.walk(ast.Module(body=nxt.finalbody,
                                                 type_ignores=[])):
                        if (isinstance(n, ast.Call)
                                and isinstance(n.func, ast.Attribute)
                                and n.func.attr == "release"
                                and ast.dump(n.func.value) == recv):
                            ok = True
                if not ok:
                    findings.append(Finding(
                        mod.path, stmt.lineno, "PB103",
                        "lock.acquire() without an immediately following "
                        "try/finally release — an exception leaks the "
                        "lock; prefer `with lock:`"))
    return findings


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in mod.walk():
        if isinstance(node, ast.ClassDef):
            findings.extend(_check_class(mod, node))
    findings.extend(_check_bare_acquire(mod))
    findings.extend(_check_io_under_lock(mod))
    return findings
