"""pboxlint — AST-based static analysis for this codebase's invariants.

The reference PaddleBox system hardens its C++ hot paths with
compiler-enforced invariants (PADDLE_ENFORCE, the gflags registry, guarded
BoxPS lifecycle).  The Python/JAX rebuild gets none of that from the
interpreter, so this package supplies the equivalent as lint passes over
`ast`, one small visitor per rule family:

  PB1xx  lock discipline        (tools/pboxlint/locks.py)
  PB2xx  flag hygiene           (tools/pboxlint/flags_hygiene.py)
         + metric/span name hygiene, PB204
           (tools/pboxlint/metric_names.py)
         + SLO rule coverage, PB207 (tools/pboxlint/slo_rules.py)
  PB3xx  JAX purity             (tools/pboxlint/purity.py)
  PB4xx  threading lifecycle    (tools/pboxlint/lifecycle.py)
  PB5xx  retry/backoff          (tools/pboxlint/retries.py)
         + durable-write atomicity, PB502
           (tools/pboxlint/atomic_io.py)
         + device-cache mutation scope, PB503
           (tools/pboxlint/device_cache.py)
  PB6xx  lock-order graph       (tools/pboxlint/lockgraph.py)
  PB7xx  serving read path + frozen-plane immutability, PB702
                                (tools/pboxlint/serving_path.py)
  PB8xx  cluster commit safety  (tools/pboxlint/cluster_commit.py)
  PB9xx  guarded-by inference / data races
                                (tools/pboxlint/raceguard.py)

CLI::

    python -m paddlebox_tpu.tools.pboxlint paddlebox_tpu/
    python -m paddlebox_tpu.tools.pboxlint --select=PB9xx --stats paddlebox_tpu/

emits ``file:line: PBnnn message`` per finding and exits nonzero when any
survive suppression.  Suppress a deliberate exception precisely::

    risky_line()            # pboxlint: disable=PB102 -- justification
    # pboxlint: disable-next=PB102 -- justification
    risky_line()

Tier-1 runs the whole-package gate (tests/test_pboxlint.py) and asserts
zero findings, so the analyzer and the tree stay clean together.
"""

from paddlebox_tpu.tools.pboxlint.core import (  # noqa: F401
    Finding, Module, PackageContext, lint_modules, lint_paths, lint_source,
    ALL_CHECKERS)
