"""PB6xx — interprocedural lock-order analysis (lockgraph).

Propagates held-lock sets along the whole-package call graph
(``callgraph.PackageGraph``).  Locks are named by *class-level*
fingerprints — ``ps.service.PSClient._lock``, ``ps.host_table._Shard.lock``,
``utils.workpool._POOL_LOCK`` — so every instance of a class shares one
node, exactly like Linux lockdep's lock classes.  When a lock is created
through the ``utils.lockdep`` factories the literal name argument *is*
the fingerprint, which keeps the static graph and the runtime witness
(``lockdep.edges()``) in the same namespace; the tier-1 cross-validation
soak asserts runtime ⊆ static.

  PB601  lock-order inversion: two lock classes acquirable in both
         orders on different paths (potential ABBA deadlock).  Ordering
         edges come from nested ``with`` blocks *and* from call chains —
         holding A while calling a function that (transitively) takes B
         adds A→B.  ``WorkPool.submit``/``map`` hand-offs ALSO order:
         the pool runs tasks inline on the submitting thread (one
         worker, one item, re-entrant fan-out), so a pool task's locks
         can really nest inside the submitter's.  ``Thread(target=)``
         never runs inline — the caller's held-set does not flow into
         it (it is analyzed as a root of its own).
  PB602  blocking call reachable *transitively* while a lock is held —
         the interprocedural generalization of PB104 (which only sees
         the same function).  Blocking primitives: socket/file I/O and
         the package frame helpers (PB104's set), ``Condition.wait``,
         ``Future.result`` and ``WorkPool.map`` submit-and-wait.  A
         blocking site carrying a PB104/PB602 suppression in its own
         module is a vetted design — it does not propagate.
  PB603  a task submitted to a bounded ``WorkPool`` that can re-enter a
         pool of the same kind (submit-and-wait from inside the pool
         starves the fixed worker set; the inline re-entrant path in
         ``WorkPool.map`` exists precisely because of this).
  PB604  untimed ``Condition.wait()`` outside a ``while`` predicate
         loop — wakeups are advisory (spurious wakeup / missed
         predicate).  ``wait(timeout)`` outside a loop is an
         interruptible sleep and is fine.
  PB605  (PB604 family, fleet collectives) an unbounded retry of a
         fleet collective/barrier wait: a ``while True`` loop in the
         collective-wait modules (parallel/collective.py,
         trainer/fleet_runner.py, data/shuffle_transport.py) that
         swallows ``ConnectionError``/``OSError``/``RuntimeError`` yet
         carries no deadline evidence — no ``time.monotonic()``
         comparison and no ``Backoff(deadline=...)``.  The fleet
         robustness contract (PB604 discipline applied to peers) is
         that EVERY wait on another trainer is deadline-bounded and
         expiry raises the typed PeerDead/ShufflePeerDead — an
         unbounded retry turns one dead peer into a hung fleet.

Unknown call targets *widen* the analysis (CHA fallback to every
same-named package method) — the caller's held-set is never dropped.
To keep the widening from flooding PB601/PB602 with phantom paths,
widened edges only propagate when the callee name is unique enough
(< _WIDEN_FANOUT_CAP candidates).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from paddlebox_tpu.tools.pboxlint import callgraph
from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)
from paddlebox_tpu.tools.pboxlint.locks import _BLOCKING_IO

_LOCK_FACTORIES = {"Lock": False, "RLock": False, "Condition": True}
_LOCKDEP_FACTORIES = {"lock": False, "rlock": False, "condition": True}
_WIDEN_FANOUT_CAP = 4     # CHA fallback fans out to at most this many


@dataclasses.dataclass(frozen=True)
class LockDef:
    fp: str               # class-level fingerprint ("ps.service.PSClient._lock")
    is_condition: bool


@dataclasses.dataclass
class _Summary:
    """Per-function facts (own body only, nested defs excluded)."""
    fn: "callgraph.FuncInfo"
    acquires: List[Tuple[str, int, Tuple[str, ...]]]          # (fp, line, held)
    call_held: Dict[int, Tuple[str, ...]]                     # id(ast.Call) → held
    blocking: List[Tuple[str, int]]                           # (desc, line)
    waits: List[Tuple[str, int, bool]]                        # (fp, line, in_while)
    pool_uses: List[Tuple[str, int]]                          # (pool kind, line)


class LockAnalysis:
    """Whole-package result: ordering edges, summaries, findings."""

    def __init__(self, graph: callgraph.PackageGraph):
        self.graph = graph
        self.class_locks: Dict[str, Dict[str, LockDef]] = {}
        self.module_locks: Dict[str, Dict[str, LockDef]] = {}
        self.local_locks: Dict[str, Dict[str, LockDef]] = {}
        self._discover_locks()
        self.summaries: Dict[str, _Summary] = {
            q: self._summarize(fn) for q, fn in graph.functions.items()}
        self.acq: Dict[str, Set[str]] = {}
        self.blk: Dict[str, Dict[str, Tuple[str, int]]] = {}
        self._fixpoint()
        # ordering edges: (from_fp, to_fp) → first witness (path, line, note)
        self.edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        self._build_edges()
        self.findings: List[Finding] = []
        self._check_pb601()
        self._check_pb602()
        self._check_pb603()
        self._check_pb604()

    # ---------------------------------------------------- lock discovery
    def _lock_def_from_ctor(self, call: ast.AST,
                            default_fp: str) -> Optional[LockDef]:
        """threading.Lock/RLock/Condition or lockdep.lock/rlock/condition
        (literal first arg wins the fingerprint) → LockDef."""
        if not isinstance(call, ast.Call):
            return None
        name = dotted_name(call.func)
        tail = name.rsplit(".", 1)[-1]
        if tail in _LOCK_FACTORIES and (
                "." not in name or name.startswith("threading.")):
            return LockDef(default_fp, _LOCK_FACTORIES[tail])
        if tail in _LOCKDEP_FACTORIES and name.startswith("lockdep."):
            fp = default_fp
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, str):
                fp = call.args[0].value
            return LockDef(fp, _LOCKDEP_FACTORIES[tail])
        return None

    def _find_ctor(self, value: ast.AST,
                   default_fp: str) -> Optional[LockDef]:
        """The value may *be* a lock ctor or *contain* one (dict/list of
        locks share the container's fingerprint)."""
        for node in ast.walk(value):
            ld = self._lock_def_from_ctor(node, default_fp)
            if ld is not None:
                return ld
        return None

    def _condition_alias(self, call: ast.AST, fn_cls, self_name,
                         locks: Dict[str, LockDef]) -> Optional[str]:
        """`Condition(self.X)` shares X's underlying lock → alias fp."""
        if not (isinstance(call, ast.Call) and call.args):
            return None
        tail = dotted_name(call.func).rsplit(".", 1)[-1]
        if tail not in ("Condition", "condition"):
            return None
        arg = call.args[0]
        if (isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id == self_name and arg.attr in locks):
            return locks[arg.attr].fp
        return None

    def _discover_locks(self) -> None:
        g = self.graph
        for cq, cls in g.classes.items():
            locks: Dict[str, LockDef] = {}
            # class-level assigns
            for stmt in cls.node.body:
                if isinstance(stmt, ast.Assign):
                    ld = None
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            ld = self._find_ctor(stmt.value,
                                                 f"{cq}.{t.id}")
                            if ld:
                                locks[t.id] = ld
            # instance assigns — two passes so Condition(self.X) aliases
            for _pass in (0, 1):
                for fi in cls.methods.values():
                    self_name = fi.self_name or "self"
                    for node in ast.walk(fi.node):
                        if not (isinstance(node, ast.Assign)
                                and len(node.targets) >= 1):
                            continue
                        for t in node.targets:
                            if not (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == self_name):
                                continue
                            alias = self._condition_alias(
                                node.value, cls, self_name, locks)
                            if alias:
                                locks[t.attr] = LockDef(alias, True)
                                continue
                            ld = self._find_ctor(node.value,
                                                 f"{cq}.{t.attr}")
                            if ld:
                                locks.setdefault(t.attr, ld)
            self.class_locks[cq] = locks
        for mod in g.modules:
            modname = callgraph.module_name(mod.path)
            mlocks: Dict[str, LockDef] = {}
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            ld = self._find_ctor(stmt.value,
                                                 f"{modname}.{t.id}")
                            if ld:
                                mlocks[t.id] = ld
            self.module_locks[modname] = mlocks
        for q, fn in g.functions.items():
            flocks: Dict[str, LockDef] = {}
            for node in self._own_body_walk(fn.node):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            ld = self._find_ctor(node.value,
                                                 f"{q}.{t.id}")
                            if ld:
                                flocks[t.id] = ld
            if flocks:
                self.local_locks[q] = flocks

    @staticmethod
    def _own_body_walk(fn_node) -> Iterable[ast.AST]:
        stack = list(fn_node.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # ------------------------------------------------ fingerprint lookup
    def _class_lock(self, cq: str, attr: str) -> Optional[LockDef]:
        """Lock attr on class `cq`, searching package bases too."""
        seen: Set[str] = set()
        stack = [cq]
        while stack:
            q = stack.pop(0)
            if q in seen:
                continue
            seen.add(q)
            ld = self.class_locks.get(q, {}).get(attr)
            if ld is not None:
                return ld
            stack.extend(self.graph.classes[q].bases
                         if q in self.graph.classes else [])
        return None

    def _lock_expr(self, fn: "callgraph.FuncInfo", expr: ast.AST,
                   local_types: Dict[str, str]) -> Optional[LockDef]:
        node = expr
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name):
            base = node.value.id
            if fn.cls is not None and base == fn.self_name:
                return self._class_lock(fn.cls.qname, node.attr)
            t = local_types.get(base)
            if t:
                return self._class_lock(t, node.attr)
            return None
        if isinstance(node, ast.Name):
            # local lock in this function or an enclosing closure scope
            q = fn.qname
            while q:
                ld = self.local_locks.get(q, {}).get(node.id)
                if ld is not None:
                    return ld
                q = q.rsplit(".", 1)[0] if "." in q else ""
            modname = callgraph.module_name(fn.mod.path)
            return self.module_locks.get(modname, {}).get(node.id)
        return None

    # --------------------------------------------------- per-fn summary
    def _summarize(self, fn: "callgraph.FuncInfo") -> _Summary:
        local_types = self.graph._local_types(fn)
        analysis = self
        suppressions = fn.mod.suppressions
        summary = _Summary(fn, [], {}, [], [], [])
        call_by_id = {id(cs.node): cs for cs in fn.calls
                      if cs.node is not None}

        def suppressed_here(line: int, codes: Tuple[str, ...]) -> bool:
            s = suppressions.get(line, set())
            return "ALL" in s or any(c in s for c in codes)

        class W(ast.NodeVisitor):
            def __init__(self):
                self.held: List[str] = []
                self.while_depth = 0

            def _ld(self, expr) -> Optional[LockDef]:
                return analysis._lock_expr(fn, expr, local_types)

            def visit_With(self, node: ast.With) -> None:
                n = 0
                for item in node.items:
                    ld = self._ld(item.context_expr)
                    if ld is None:
                        self.visit(item.context_expr)
                    else:
                        summary.acquires.append(
                            (ld.fp, item.context_expr.lineno,
                             tuple(self.held)))
                        self.held.append(ld.fp)
                        n += 1
                for stmt in node.body:
                    self.visit(stmt)
                if n:
                    del self.held[len(self.held) - n:]

            visit_AsyncWith = visit_With

            def visit_While(self, node: ast.While) -> None:
                self.while_depth += 1
                self.generic_visit(node)
                self.while_depth -= 1

            def visit_FunctionDef(self, node) -> None:
                pass               # nested defs are their own summaries

            visit_AsyncFunctionDef = visit_FunctionDef
            visit_Lambda = visit_FunctionDef

            def visit_Call(self, node: ast.Call) -> None:
                held = tuple(self.held)
                summary.call_held[id(node)] = held
                cs = call_by_id.get(id(node))
                if cs is not None and cs.kind == "spawn" \
                        and cs.pool is not None:
                    summary.pool_uses.append((cs.pool, node.lineno))
                    if cs.name == "map" and not suppressed_here(
                            node.lineno, ("PB104", "PB602")):
                        summary.blocking.append(
                            ("WorkPool.map submit-and-wait", node.lineno))
                if isinstance(node.func, ast.Attribute):
                    meth = node.func.attr
                    if meth in ("wait",):
                        ld = self._ld(node.func.value)
                        if ld is not None and ld.is_condition:
                            # a timed wait outside a loop is an
                            # interruptible sleep, tolerant of spurious
                            # wakeup — only untimed waits need the
                            # predicate loop
                            timed = bool(node.args) or any(
                                kw.arg == "timeout" for kw in node.keywords)
                            summary.waits.append(
                                (ld.fp, node.lineno,
                                 self.while_depth > 0 or timed))
                            if not suppressed_here(node.lineno,
                                                   ("PB104", "PB602")):
                                summary.blocking.append(
                                    (f"{ld.fp}.wait()", node.lineno))
                    elif meth == "acquire":
                        ld = self._ld(node.func.value)
                        if ld is not None:
                            summary.acquires.append(
                                (ld.fp, node.lineno, held))
                    elif meth == "result" or meth in _BLOCKING_IO:
                        desc = ("Future.result()" if meth == "result"
                                else f"{meth}()")
                        if not suppressed_here(node.lineno,
                                               ("PB104", "PB602")):
                            summary.blocking.append((desc, node.lineno))
                elif isinstance(node.func, ast.Name) \
                        and node.func.id in _BLOCKING_IO:
                    if not suppressed_here(node.lineno, ("PB104", "PB602")):
                        summary.blocking.append(
                            (f"{node.func.id}()", node.lineno))
                self.generic_visit(node)

        w = W()
        for stmt in fn.node.body:
            w.visit(stmt)
        return summary

    # ---------------------------------------------------------- fixpoint
    def _call_targets(self, cs: "callgraph.CallSite") -> Tuple[str, ...]:
        """Sync-propagatable targets of a call site (widening capped)."""
        if cs.kind != "call":
            return ()
        if cs.widened and len(cs.targets) > _WIDEN_FANOUT_CAP:
            return ()
        return cs.targets

    def _order_targets(self, cs: "callgraph.CallSite") -> Tuple[str, ...]:
        """Targets whose ACQUIRES order after the caller's held locks.
        Sync calls, plus POOL spawns: ``WorkPool.map``/``submit`` run the
        task inline on the caller's thread when the pool has one worker,
        one item, or is re-entered from a worker — so a pool task's locks
        really can nest inside the submitter's (the runtime witness sees
        those edges; the static graph must over-approximate them).
        ``Thread(target=)`` never runs inline and stays excluded."""
        if cs.kind == "spawn":
            if cs.pool is None:
                return ()
            # the submitter also runs WorkPool.map/submit's own body
            # (bookkeeping under WorkPool._lock) on its thread
            meth = f"utils.workpool.WorkPool.{cs.name}"
            extra = (meth,) if meth in self.summaries else ()
            return cs.targets + extra
        return self._call_targets(cs)

    def _fixpoint(self) -> None:
        acq = {q: {fp for fp, _l, _h in s.acquires}
               for q, s in self.summaries.items()}
        blk: Dict[str, Dict[str, Tuple[str, int]]] = {}
        for q, s in self.summaries.items():
            blk[q] = {desc: (s.fn.mod.path, line)
                      for desc, line in s.blocking}
        changed = True
        while changed:
            changed = False
            for q, s in self.summaries.items():
                for cs in s.fn.calls:
                    # ordering (acq) flows through pool spawns too; the
                    # blocking relation (blk → PB602) stays sync-only —
                    # a task blocking on a pool thread does not stall
                    # the submitter's lock holders
                    for t in self._order_targets(cs):
                        if t in acq and not acq[t] <= acq[q]:
                            acq[q] |= acq[t]
                            changed = True
                    for t in self._call_targets(cs):
                        for desc, wit in blk.get(t, {}).items():
                            if desc not in blk[q]:
                                blk[q][desc] = wit
                                changed = True
        self.acq = acq
        self.blk = blk

    # ------------------------------------------------------------- edges
    def _add_edge(self, a: str, b: str, path: str, line: int,
                  note: str) -> None:
        if a != b:
            self.edges.setdefault((a, b), (path, line, note))

    def _build_edges(self) -> None:
        for q, s in self.summaries.items():
            path = s.fn.mod.path
            for fp, line, held in s.acquires:
                for h in held:
                    self._add_edge(h, fp, path, line,
                                   f"nested acquire in {q}")
            for cs in s.fn.calls:
                held = s.call_held.get(id(cs.node), ())
                if not held:
                    continue
                for t in self._order_targets(cs):
                    for fp in self.acq.get(t, ()):
                        for h in held:
                            self._add_edge(
                                h, fp, path, cs.line,
                                f"{q} → {t}")

    # ---------------------------------------------------------- checkers
    def _check_pb601(self) -> None:
        seen: Set[Tuple[str, str]] = set()
        for (a, b), (path, line, note) in sorted(self.edges.items()):
            if (b, a) not in self.edges or (b, a) in seen:
                continue
            seen.add((a, b))
            rpath, rline, rnote = self.edges[(b, a)]
            self.findings.append(Finding(
                path, line, "PB601",
                f"lock-order inversion: {a} → {b} here ({note}) but "
                f"{b} → {a} at {rpath}:{rline} ({rnote}) — potential "
                f"ABBA deadlock; pick one global order"))

    def _check_pb602(self) -> None:
        for q, s in sorted(self.summaries.items()):
            path = s.fn.mod.path
            reported: Set[int] = set()
            for cs in s.fn.calls:
                held = s.call_held.get(id(cs.node), ())
                if not held or cs.line in reported:
                    continue
                for t in self._call_targets(cs):
                    hits = self.blk.get(t, {})
                    if not hits:
                        continue
                    desc, (bpath, bline) = sorted(hits.items())[0]
                    reported.add(cs.line)
                    self.findings.append(Finding(
                        path, cs.line, "PB602",
                        f"{cs.name}() called while holding {held[-1]} "
                        f"reaches blocking {desc} ({bpath}:{bline}) — "
                        f"every other holder stalls behind it; move the "
                        f"call outside the guarded region"))
                    break

    def _reachable(self, roots: Iterable[str]) -> Set[str]:
        out: Set[str] = set()
        stack = list(roots)
        while stack:
            q = stack.pop()
            if q in out or q not in self.summaries:
                continue
            out.add(q)
            for cs in self.summaries[q].fn.calls:
                stack.extend(self._call_targets(cs))
        return out

    def _check_pb603(self) -> None:
        pool_lock_fp = "utils.workpool.WorkPool._lock"
        for q, s in sorted(self.summaries.items()):
            for cs in s.fn.calls:
                if cs.kind != "spawn" or cs.pool is None:
                    continue
                for t in cs.targets:
                    for r in sorted(self._reachable([t])):
                        rs = self.summaries.get(r)
                        if rs is None or r == q:
                            continue
                        inner = [(k, l) for k, l in rs.pool_uses
                                 if k == cs.pool or "?" in (k, cs.pool)]
                        if inner:
                            self.findings.append(Finding(
                                s.fn.mod.path, cs.line, "PB603",
                                f"task {t} submitted to the bounded "
                                f"'{cs.pool}' pool re-enters a "
                                f"'{inner[0][0]}' pool via {r} "
                                f"({rs.fn.mod.path}:{inner[0][1]}) — "
                                f"submit-and-wait from inside the pool "
                                f"can starve the fixed worker set"))
                            break
                        if pool_lock_fp in {fp for fp, _l, _h
                                            in rs.acquires}:
                            self.findings.append(Finding(
                                s.fn.mod.path, cs.line, "PB603",
                                f"task {t} submitted to the bounded "
                                f"'{cs.pool}' pool takes the pool's own "
                                f"lock via {r} — deadlocks if the pool "
                                f"holds it while dispatching"))
                            break

    def _check_pb604(self) -> None:
        for q, s in sorted(self.summaries.items()):
            for fp, line, in_while in s.waits:
                if not in_while:
                    self.findings.append(Finding(
                        s.fn.mod.path, line, "PB604",
                        f"{fp}.wait() outside a while-predicate loop — "
                        f"wakeups are advisory; spurious wakeup or a "
                        f"stolen predicate proceeds on stale state"))


def analyze(modules: Sequence[Module]) -> LockAnalysis:
    return LockAnalysis(callgraph.PackageGraph(modules))


def analyze_paths(paths: Sequence[str]) -> LockAnalysis:
    """Convenience for tests & the runtime cross-validation soak."""
    from paddlebox_tpu.tools.pboxlint.core import iter_py_files
    mods = []
    for p in iter_py_files(paths):
        with open(p, encoding="utf-8") as f:
            mods.append(Module(p, f.read()))
    return analyze(mods)


# -- PB605: unbounded fleet-collective retry (module-local scan) -----------

_COLLECTIVE_WAIT_PATHS = ("/parallel/collective.py",
                          "/trainer/fleet_runner.py",
                          "/data/shuffle_transport.py")
_RETRY_EXC_NAMES = {"ConnectionError", "OSError", "RuntimeError",
                    "socket.error"}


def _handler_catches_retryable(handler: ast.ExceptHandler) -> bool:
    types = []
    t = handler.type
    if isinstance(t, ast.Tuple):
        types = list(t.elts)
    elif t is not None:
        types = [t]
    for ty in types:
        name = dotted_name(ty) or (ty.id if isinstance(ty, ast.Name)
                                   else "")
        if name.rpartition(".")[2] in {n.rpartition(".")[2]
                                       for n in _RETRY_EXC_NAMES}:
            return True
    return False


def _handler_exits_loop(handler: ast.ExceptHandler) -> bool:
    """A handler whose body unconditionally leaves the loop (return /
    raise / break as its last statement) is an exit path, not a retry —
    an accept-loop's ``except OSError: return`` shutdown is fine."""
    if not handler.body:
        return False
    return isinstance(handler.body[-1], (ast.Return, ast.Raise, ast.Break))


_TEARDOWN_VERBS = {"close", "shutdown"}


def _try_is_teardown(t: ast.Try) -> bool:
    """``try: sock.close() except OSError: pass`` is a cleanup swallow,
    not a retry of a peer wait — every statement in the try body is a
    bare call to a teardown verb."""
    for stmt in t.body:
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr in _TEARDOWN_VERBS):
            return False
    return bool(t.body)


def _loop_has_deadline_evidence(loop: ast.While) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, ast.Call):
            fn = node.func
            attr = fn.attr if isinstance(fn, ast.Attribute) else \
                (fn.id if isinstance(fn, ast.Name) else "")
            if attr == "monotonic":
                return True
            if attr == "Backoff" and any(kw.arg == "deadline"
                                         for kw in node.keywords):
                return True
            # a Backoff built just outside the loop: its .sleep() result
            # gating a raise/return IS the deadline check
            if attr == "sleep" and isinstance(fn, ast.Attribute):
                return True
    return False


def _check_pb605(mod: Module) -> List[Finding]:
    path = mod.path.replace("\\", "/")
    if not any(path.endswith(p) for p in _COLLECTIVE_WAIT_PATHS):
        return []
    findings: List[Finding] = []
    for node in mod.walk():
        if not (isinstance(node, ast.While)
                and isinstance(node.test, ast.Constant)
                and node.test.value is True):
            continue
        catches = [h for t in ast.walk(node) if isinstance(t, ast.Try)
                   and not _try_is_teardown(t)
                   for h in t.handlers if _handler_catches_retryable(h)
                   and not _handler_exits_loop(h)]
        if not catches:
            continue
        if _loop_has_deadline_evidence(node):
            continue
        findings.append(Finding(
            mod.path, node.lineno, "PB605",
            "unbounded fleet-collective retry: this while-True loop "
            "swallows connection errors with no deadline evidence "
            "(time.monotonic() comparison or Backoff(deadline=...)/"
            ".sleep() budget) — every wait on a peer must be bounded "
            "and raise the typed PeerDead/ShufflePeerDead on expiry, "
            "or one dead trainer hangs the whole fleet"))
    return findings


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    cache = getattr(ctx, "_lockgraph", None)
    if cache is None:
        cache = analyze(ctx.modules)
        ctx._lockgraph = cache
    return [f for f in cache.findings if f.path == mod.path] \
        + _check_pb605(mod)
