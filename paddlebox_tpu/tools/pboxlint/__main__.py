"""`python -m paddlebox_tpu.tools.pboxlint <file-or-dir> [...]`."""

import sys

from paddlebox_tpu.tools.pboxlint.core import main

if __name__ == "__main__":
    sys.exit(main())
