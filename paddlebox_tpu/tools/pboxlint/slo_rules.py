"""PB2xx (cont.) — SLO-rule metric cross-check (utils/timeline.py).

  PB207  an ``SloRule(...)`` construction names a metric that no
         ``stat_add``/``stat_set``/``stat_max``/``stat_observe`` call
         site anywhere in the linted set actually emits — a dead rule:
         its series stays empty, it can never breach, and the SLO it was
         meant to guard is silently unwatched.  The watchdog face of
         PB205's dead-knob detection: a flag nobody reads changes
         nothing; a rule watching a metric nobody emits alarms on
         nothing.

Emitted names are collected as literals plus f-string patterns (each
interpolation matched as a bounded ``[a-z0-9_.]+`` segment, the PB204
name alphabet); ``stat_observe`` names also contribute their derived
histogram keys (``.count/.sum/.p50/.p95/.p99/.max``), since rules read
the flattened snapshot the timeline samples.  Rule sites are resolved
through each module's imports of ``paddlebox_tpu.utils.timeline`` (the
PB206 sink-resolution approach), so unrelated ``SloRule`` classes are
out of scope.  Disarmed entirely when any emission site uses a fully
dynamic name (the emitted set is then out of static reach), and per
rule when the metric argument is non-literal.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set, Tuple

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)

_EMIT_SINKS = {"stat_add", "stat_set", "stat_max", "stat_observe"}
_HIST_SUFFIXES = (".count", ".sum", ".p50", ".p95", ".p99", ".max")
_DYN_SEGMENT = r"[a-z0-9_.]+"       # PB204's metric-name alphabet
_TIMELINE_MOD = "paddlebox_tpu.utils.timeline"

# emitted by dict write inside StatRegistry.observe (monitor.py), not
# through a stat_* wrapper — the one name the call-site sweep can't see
_BUILTIN_EMITS = {"obs.non_finite_dropped"}


def _collect_emitted(ctx: PackageContext
                     ) -> Tuple[Set[str], List[str], bool]:
    """→ (literal names, f-string regex patterns, any-dynamic-emit).
    Memoized on the context — one sweep per lint run."""
    cached = getattr(ctx, "_pb207_emitted", None)
    if cached is not None:
        return cached
    literals: Set[str] = set(_BUILTIN_EMITS)
    patterns: List[str] = []
    dynamic = False
    for mod in ctx.modules:
        for node in mod.walk():
            if not (isinstance(node, ast.Call) and node.args):
                continue
            tail = dotted_name(node.func).rsplit(".", 1)[-1]
            if tail not in _EMIT_SINKS:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                literals.add(arg.value)
                if tail == "stat_observe":
                    literals.update(arg.value + s for s in _HIST_SUFFIXES)
            elif isinstance(arg, ast.JoinedStr):
                parts = []
                for part in arg.values:
                    if isinstance(part, ast.Constant):
                        parts.append(re.escape(str(part.value)))
                    else:
                        parts.append(_DYN_SEGMENT)
                pat = "".join(parts)
                patterns.append(pat + r"\Z")
                if tail == "stat_observe":
                    patterns.extend(pat + re.escape(s) + r"\Z"
                                    for s in _HIST_SUFFIXES)
            else:
                dynamic = True      # emitted set out of static reach
    out = (literals, patterns, dynamic)
    ctx._pb207_emitted = out
    return out


def _rule_sinks(mod: Module) -> Set[str]:
    """Dotted call names in this module that resolve to
    timeline.SloRule — plus the bare name inside timeline.py itself
    (where default_rules constructs them)."""
    sinks: Set[str] = set()
    for node in mod.walk():
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == _TIMELINE_MOD:
                    sinks.add(f"{alias.asname or alias.name}.SloRule")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "paddlebox_tpu.utils":
                for alias in node.names:
                    if alias.name == "timeline":
                        sinks.add(f"{alias.asname or 'timeline'}.SloRule")
            elif node.module == _TIMELINE_MOD:
                for alias in node.names:
                    if alias.name == "SloRule":
                        sinks.add(alias.asname or "SloRule")
        elif isinstance(node, ast.ClassDef) and node.name == "SloRule":
            sinks.add("SloRule")
    return sinks


def _metric_arg(call: ast.Call) -> "ast.AST | None":
    """SloRule(name, metric, ...): positional #2 or metric= kwarg."""
    if len(call.args) >= 2:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "metric":
            return kw.value
    return None


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    sinks = _rule_sinks(mod)
    if not sinks:
        return []
    literals, patterns, dynamic = _collect_emitted(ctx)
    if dynamic:
        return []
    findings: List[Finding] = []
    for node in mod.walk():
        if not isinstance(node, ast.Call):
            continue
        if dotted_name(node.func) not in sinks:
            continue
        arg = _metric_arg(node)
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue            # dynamic metric name: out of static reach
        metric = arg.value
        if metric in literals:
            continue
        if any(re.match(p, metric) for p in patterns):
            continue
        findings.append(Finding(
            mod.path, node.lineno, "PB207",
            f"SLO rule watches metric {metric!r} but no stat_add/"
            f"stat_set/stat_max/stat_observe call site anywhere in the "
            f"linted set emits that name — the rule's series stays "
            f"empty and it can never breach (dead rule)"))
    return findings
