"""PB2xx (cont.) — flight-recorder event-kind hygiene (utils/flight.py).

  PB206  an event kind passed to ``flight.record`` is either

         * built dynamically (f-string / ``+`` concatenation) from a
           part that is not a KNOWN BOUNDED FIELD — ``counts()``,
           ``events(kind=...)`` and every postmortem group by kind, so
           an unbounded kind (a rid, a path, a key) shreds the taxonomy
           into one-off buckets and defeats ring triage, or
         * a literal that is not a lowercase identifier
           (``[a-z0-9_]``) — mixed-case/dotted kinds fracture the
           closed event vocabulary that /flightz filters key on, or
         * a lowercase literal that is not in :data:`KNOWN_KINDS` — the
           taxonomy is CLOSED: a new event kind is a deliberate
           vocabulary change (postmortem tooling, /flightz dashboards
           and the ``?kind=`` filters all key on it), so it lands by
           adding the name here in the same change, not by ad-hoc
           minting at a call site.

Same bounded-field vocabulary as PB204 (``cmd / verb / site / kind /
role / phase / stage / table``); unbounded values belong in the event's
**fields**, never in its kind.  Sinks are resolved through the module's
imports — only calls that actually reach ``paddlebox_tpu.utils.flight
.record`` are checked, so unrelated ``record`` methods (bench partials,
IntervalRecorder.record) are out of scope.
"""

from __future__ import annotations

import ast
import re
from typing import List, Set

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)
from paddlebox_tpu.tools.pboxlint.metric_names import (_BOUNDED_FIELDS,
                                                       _binop_leaves,
                                                       _terminal_field)

_KIND_OK = re.compile(r"[a-z0-9_]*\Z")
_FLIGHT_MOD = "paddlebox_tpu.utils.flight"

# The closed event-kind taxonomy.  Every whole-literal kind passed to
# flight.record must be one of these; adding an event kind means adding
# it HERE in the same change (the /flightz ?kind= filters, postmortem
# groupers and dashboard queries all key on this vocabulary).
KNOWN_KINDS = frozenset({
    # pass / day lifecycle
    "pass_begin", "pass_end", "pass_feed_begin", "pass_feed_end",
    "day_end", "prefetch_pass_ready", "prefetch_pass_failed",
    # checkpoint / commit
    "checkpoint_save", "checkpoint_load", "ckpt_commit", "ckpt_gc",
    "membership_commit",
    # device row cache
    "cache_evict", "cache_invalidate", "cache_invalidate_moved",
    "cache_invalidate_shard",
    # wire / verbs / dedup
    "verb_retry", "verb_give_up", "fence_redirect", "stream_reconnect",
    "dedup_hit", "dedup_evict", "dedup_restore", "map_refresh",
    "backoff_sleep", "backoff_exhausted",
    # reshard / elastic fleet
    "reshard_begin", "reshard_drive", "reshard_cutover", "reshard_abort",
    "reshard_done", "ps_fleet_resize", "elastic_grow", "elastic_scale_in",
    "elastic_rerendezvous", "leader_elect", "fleet_cursor",
    # trainer / supervisor lifecycle
    "trainer_resume", "trainer_restart", "worker_restart",
    "resume_begin", "resume_ok", "supervisor_give_up",
    # serving tier
    "serving_load", "serving_swap", "serving_resurrect",
    "serving_failover", "serving_delta_flip", "manifest_retry",
    "manifest_giveup",
    # diagnostics
    "fault_injected", "lock_cycle", "race_suspect", "pool_saturated",
    "postmortem_written", "slo_breach", "slo_clear",
    # key-space heat telemetry (ps/heat.py)
    "heat_snapshot", "heat_imbalance",
    # out-of-package emitters sharing the ring (bench.py)
    "bench_phase",
})


def _record_sinks(mod: Module) -> Set[str]:
    """Dotted call names in this module that resolve to flight.record."""
    sinks: Set[str] = set()
    for node in mod.walk():
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == _FLIGHT_MOD:
                    sinks.add(f"{alias.asname or alias.name}.record")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "paddlebox_tpu.utils":
                for alias in node.names:
                    if alias.name == "flight":
                        sinks.add(f"{alias.asname or 'flight'}.record")
            elif node.module == _FLIGHT_MOD:
                for alias in node.names:
                    if alias.name == "record":
                        sinks.add(alias.asname or "record")
    return sinks


def _findings_for_kind(mod: Module, call: ast.Call,
                       arg: ast.AST) -> List[Finding]:
    out: List[Finding] = []

    def flag(reason: str) -> None:
        out.append(Finding(
            mod.path, call.lineno, "PB206",
            f"{dotted_name(call.func) or '<call>'}(...) flight event kind "
            f"{reason} — kinds are the closed taxonomy /flightz filters "
            f"and postmortems group by; unbounded values go in event "
            f"fields, bounded dynamic parts are {sorted(_BOUNDED_FIELDS)}, "
            f"or suppress with a reason"))

    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        if not _KIND_OK.match(arg.value):
            flag(f"literal {arg.value!r} is not a lowercase identifier")
        elif arg.value not in KNOWN_KINDS:
            flag(f"literal {arg.value!r} is not in the closed KNOWN_KINDS "
                 f"taxonomy (tools/pboxlint/flight_events.py) — new event "
                 f"kinds are added there in the same change")
        return out
    if isinstance(arg, ast.JoinedStr):
        for part in arg.values:
            if isinstance(part, ast.Constant):
                if isinstance(part.value, str) \
                        and not _KIND_OK.match(part.value):
                    flag(f"literal segment {part.value!r} is not a "
                         f"lowercase identifier")
            elif isinstance(part, ast.FormattedValue):
                if _terminal_field(part.value) not in _BOUNDED_FIELDS:
                    flag("has an f-string part that is not a known "
                         "bounded field")
        return out
    leaves = _binop_leaves(arg)
    if isinstance(arg, ast.BinOp) and leaves is not None:
        for leaf in leaves:
            if isinstance(leaf, ast.Constant):
                if isinstance(leaf.value, str) \
                        and not _KIND_OK.match(leaf.value):
                    flag(f"literal segment {leaf.value!r} is not a "
                         f"lowercase identifier")
            elif _terminal_field(leaf) not in _BOUNDED_FIELDS:
                flag("is concatenated (+) from a part that is not a "
                     "known bounded field")
        return out
    # bare names/calls as the whole kind are out of static reach — the
    # f-string/+ forms are where unbounded kinds actually get minted
    return out


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    sinks = _record_sinks(mod)
    if not sinks:
        return []
    findings: List[Finding] = []
    for node in mod.walk():
        if not (isinstance(node, ast.Call) and node.args):
            continue
        if dotted_name(node.func) not in sinks:
            continue
        findings.extend(_findings_for_kind(mod, node, node.args[0]))
    return findings
