"""PB5xx — device-cache coherence discipline (the fold-back rule).

  PB503  a device-cache mutation outside its sanctioned call sites.  The
         HBM row cache (ps/device_cache.py) is write-back at pass
         granularity: the ONLY row mutation is the ``end_pass`` fold-back
         (``cache.update_after_pass``, after the table write succeeded),
         and the only other state change is ``cache.invalidate`` at a
         coherence point (end_day decay, shrink, checkpoint resume /
         rollback, feed-state reset, serving freeze, load).  A fold-back
         from anywhere else can commit rows the table never accepted
         (breaking exactly-once replay), and an ad-hoc invalidation —
         or a MISSING one at a rollback — silently forks the cache from
         the table.  Keeping both behind greppable, named lifecycle
         functions is what makes the bit-identity argument auditable.

         Scope: any call ``<something>cache<...>.update_after_pass(...)``
         outside a function whose name mentions ``end_pass``, and any
         ``<something>cache<...>.invalidate(...)`` outside a function
         whose name mentions a recognized coherence point (invalidate /
         reset / resume / rollback / restore / set_date / end_day /
         shrink / load / close / abort / freeze / restart / teardown /
         swap — the serving tier's generation hot-swap).
         ``ps/device_cache.py`` itself (the implementation) and test
         files are exempt.
"""

from __future__ import annotations

import ast
from typing import List

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)

_FOLD_HINTS = ("end_pass",)
_INVALIDATE_HINTS = ("invalidate", "reset", "resume", "rollback", "restore",
                     "set_date", "end_day", "shrink", "load", "close",
                     "abort", "freeze", "restart", "teardown", "swap")
_EXEMPT_BASENAMES = ("device_cache.py",)


def _is_cache_receiver(node: ast.Call) -> bool:
    """The call's receiver chain names a cache (`self.cache.…`,
    `engine.cache.…`, `row_cache.…`)."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return False
    recv = dotted_name(func.value)
    return "cache" in recv.lower()


def _allowed(stack: List[str], hints) -> bool:
    return any(any(h in fn.lower() for h in hints) for fn in stack)


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    path = mod.path.replace("\\", "/")
    if mod.basename in _EXEMPT_BASENAMES or "/tests/" in path \
            or mod.basename.startswith("test_"):
        return []
    findings: List[Finding] = []

    def visit(node: ast.AST, stack: List[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack = stack + [node.name]
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and _is_cache_receiver(node):
            attr = node.func.attr
            if attr == "update_after_pass" \
                    and not _allowed(stack, _FOLD_HINTS):
                findings.append(Finding(
                    mod.path, node.lineno, "PB503",
                    "device-cache fold-back outside end_pass: "
                    "update_after_pass may only run from the engine's "
                    "end_pass, after the table write succeeded — a "
                    "fold-back elsewhere can commit rows the table "
                    "never accepted and breaks exactly-once replay"))
            elif attr == "invalidate" \
                    and not _allowed(stack, _INVALIDATE_HINTS):
                findings.append(Finding(
                    mod.path, node.lineno, "PB503",
                    "device-cache invalidation outside a named coherence "
                    "point (end_day/shrink/resume/rollback/reset/...): "
                    "keep it behind a lifecycle function whose name says "
                    "WHY the cache went cold, so the coherence audit "
                    "stays greppable"))
        for child in ast.iter_child_nodes(node):
            visit(child, stack)

    visit(mod.tree, [])
    return findings
