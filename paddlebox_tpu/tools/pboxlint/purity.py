"""PB3xx — JAX purity inside traced functions.

A function is *traced* when it is decorated with ``jax.jit`` (directly or
through ``partial(jax.jit, ...)``), wrapped by a ``jax.jit(fn)`` call, or
passed by name into a tracing combinator (``lax.scan`` / ``while_loop`` /
``cond`` / ``fori_loop`` / ``switch`` / ``map`` / ``jax.pmap``).  Inside a
traced function:

  PB301  host-synchronizing / side-effecting calls: ``float()``,
         ``int()``, ``bool()``, ``.item()``, ``np.asarray``/``np.array``,
         ``print``, ``get_flags``, ``jax.device_get`` — they either force
         a device→host sync mid-trace, bake a trace-time value into the
         compiled program (silently stale after retrace), or spam once
         per trace instead of per step.
  PB302  attribute mutation on ``self`` (or any argument) — the write
         happens once at trace time, not per step; the compiled program
         never sees it again.

Nested functions defined inside a traced function execute during the
trace, so they are walked too.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)

_TRACING_CALLS = {"jit", "pmap", "scan", "while_loop", "cond", "fori_loop",
                  "switch", "map", "associative_scan"}
_TRACING_ROOTS = ("jax", "lax", "jax.lax")
_HOST_BUILTINS = {"float", "int", "bool", "print"}
_NP_DENY = {"asarray", "array", "frombuffer", "fromiter", "copyto",
            "ascontiguousarray", "save", "savez", "load"}


def _is_tracing_callable(name: str) -> bool:
    if not name:
        return False
    head, _, tail = name.rpartition(".")
    return tail in _TRACING_CALLS and (head in _TRACING_ROOTS or not head
                                       and tail == "jit")


def _is_jit_reference(node: ast.AST) -> bool:
    """`jax.jit`, `jit`, or `partial(jax.jit, ...)` as an expression."""
    name = dotted_name(node)
    if name in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname.rsplit(".", 1)[-1] == "partial":
            return any(_is_jit_reference(a) for a in node.args)
        # decorator form `jax.jit(...)` / `lax-free jit(...)`
        return _is_jit_reference(node.func)
    return False


def _collect_traced(mod: Module) -> List[ast.AST]:
    """Function nodes whose bodies execute under tracing."""
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in mod.walk():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)

    traced: List[ast.AST] = []
    seen: Set[int] = set()

    def mark(node: ast.AST) -> None:
        if id(node) not in seen:
            seen.add(id(node))
            traced.append(node)

    for node in mod.walk():
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_reference(d) for d in node.decorator_list):
                mark(node)
        elif isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if not (_is_tracing_callable(fname)
                    or _is_jit_reference(node.func)):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    for d in defs_by_name.get(arg.id, ()):
                        mark(d)
                elif isinstance(arg, ast.Lambda):
                    mark(arg)
    return traced


def _first_param(fn: ast.AST) -> str:
    args = getattr(fn, "args", None)
    if args and args.args:
        return args.args[0].arg
    return ""


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    findings: List[Finding] = []
    for fn in _collect_traced(mod):
        fn_name = getattr(fn, "name", "<lambda>")
        self_name = _first_param(fn)
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        # a param rebound to a fresh local (`ws = dict(ws)`) is a copy —
        # mutating the copy is the idiomatic functional-update pattern,
        # not trace-time state mutation
        rebind_line = None
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == self_name
                        for t in node.targets):
                    rebind_line = (node.lineno if rebind_line is None
                                   else min(rebind_line, node.lineno))
        for node in [n for stmt in body for n in ast.walk(stmt)]:
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                tail = name.rsplit(".", 1)[-1]
                root = name.split(".", 1)[0]
                msg = None
                if name in _HOST_BUILTINS:
                    msg = (f"{name}() on a traced value forces a "
                           f"device→host sync (or bakes a trace-time "
                           f"constant into the compiled program)")
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "item"):
                    msg = ".item() forces a device→host sync inside the " \
                          "traced function"
                elif root in ("np", "numpy") and tail in _NP_DENY:
                    msg = (f"{name}() materializes a host array mid-trace "
                           f"— use jnp, or hoist the host work out of the "
                           f"traced function")
                elif tail == "get_flags":
                    msg = ("get_flags() inside a traced function bakes the "
                           "flag's trace-time value into the compiled "
                           "program — read it at build time and close over "
                           "it")
                elif name in ("jax.device_get",):
                    msg = "jax.device_get() mid-trace forces a host sync"
                if msg is not None:
                    findings.append(Finding(
                        mod.path, node.lineno, "PB301",
                        f"in traced function {fn_name!r}: {msg}"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)) \
                    and self_name:
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    base = t
                    while isinstance(base, (ast.Subscript, ast.Attribute)):
                        base = base.value
                    is_attr_or_item = isinstance(
                        t, (ast.Attribute, ast.Subscript))
                    if (is_attr_or_item and isinstance(base, ast.Name)
                            and base.id == self_name
                            and not (rebind_line is not None
                                     and rebind_line <= t.lineno)):
                        findings.append(Finding(
                            mod.path, t.lineno, "PB302",
                            f"in traced function {fn_name!r}: mutation of "
                            f"{self_name!r} state happens once at trace "
                            f"time, not per step — return the new value "
                            f"instead"))
    return findings
