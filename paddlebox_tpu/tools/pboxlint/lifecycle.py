"""PB4xx — threading lifecycle.

  PB401  ``threading.Thread(...)`` created without an explicit ``daemon=``
         and never ``.join()``-ed in its owning scope (the enclosing class
         for ``self.X`` threads, the enclosing function for locals) — on
         interpreter shutdown a forgotten non-daemon thread hangs the
         process; a daemon-less *joined* thread is a deliberate lifecycle.
  PB402  a blocking ``Queue.get()`` / ``Channel.get()`` (no timeout) in a
         ``while`` loop whose body has neither a sentinel escape
         (``break``/``return``) nor an exception handler — the consumer
         hang class seen in channel/pass-feed code: the producer dies, the
         loop blocks forever.
  PB403  a ``ThreadPoolExecutor(...)`` created without a
         ``thread_name_prefix=`` (anonymous pool threads make stack dumps
         and the workpool re-entrancy guard unreadable/unworkable), OR
         one that is never ``shutdown()``-ed in its owning scope and not
         managed by a ``with`` statement — its non-daemon workers hang
         interpreter shutdown exactly like a forgotten PB401 thread.
  PB405  a raw ``threading.Thread`` whose ``target=`` resolves in-module
         to a function containing a loop (recurring work) and that is
         never ``.join()``-ed in its owning scope — recurring work
         belongs on a managed surface (``utils/workpool.WorkPool``, a
         named executor, or a thread with an explicit join lifecycle);
         an unjoined pump thread outlives errors silently and cannot be
         drained at shutdown.  One-shot handoff threads (no loop in the
         target) and unresolvable targets (dynamic callables, foreign
         receivers like ``srv.serve_forever``) are not flagged;
         legitimate long-lived pumps/dispatchers suppress with a reason.

Queue-typed receivers are recognized syntactically: any name (local or
``self.X``) assigned from a ``queue.Queue``-family constructor or from a
``Channel(...)`` call anywhere in the module.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)

_QUEUE_CTORS = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                "Channel"}


def _is_thread_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    return name in ("threading.Thread", "Thread")


def _has_daemon_kw(call: ast.Call) -> bool:
    return any(kw.arg == "daemon" for kw in call.keywords)


def _target_name(target: ast.AST) -> Tuple[Optional[str], bool]:
    """→ (name, is_self_attr); (None, False) when not a simple target."""
    if isinstance(target, ast.Name):
        return target.id, False
    if (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id in ("self", "cls")):
        return target.attr, True
    return None, False


def _method_calls_on(scope: ast.AST, method: str) -> Set[Tuple[str, bool]]:
    """Receivers of `<recv>.<method>(...)` in scope → {(name, is_self)}."""
    out: Set[Tuple[str, bool]] = set()
    for node in ast.walk(scope):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == method):
            name, is_self = _target_name(node.func.value)
            if name is not None:
                out.add((name, is_self))
    return out


def _daemon_assigns(scope: ast.AST) -> Set[Tuple[str, bool]]:
    """Receivers of `<recv>.daemon = ...` in scope."""
    out: Set[Tuple[str, bool]] = set()
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon":
                    name, is_self = _target_name(t.value)
                    if name is not None:
                        out.add((name, is_self))
    return out


def _check_threads(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    # owning scope for a `self.X` thread is its innermost class; for a
    # local, the innermost function (module body otherwise).
    parent = {}
    for node in mod.walk():
        for child in ast.iter_child_nodes(node):
            parent[child] = node

    def owning_scope(node: ast.AST, want_class: bool) -> ast.AST:
        cur = parent.get(node)
        while cur is not None:
            if want_class and isinstance(cur, ast.ClassDef):
                return cur
            if not want_class and isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parent.get(cur)
        return mod.tree

    for node in mod.walk():
        if isinstance(node, ast.Assign) and _is_thread_ctor(node.value):
            call = node.value
            for name, is_self in map(_target_name, node.targets):
                if name is None:
                    continue
                scope = owning_scope(node, want_class=is_self)
                if (_has_daemon_kw(call)
                        or (name, is_self) in _daemon_assigns(scope)
                        or (name, is_self) in _method_calls_on(scope,
                                                               "join")):
                    continue
                findings.append(Finding(
                    mod.path, call.lineno, "PB401",
                    f"thread {name!r} is started without an explicit "
                    f"daemon= and never joined in its owning scope — a "
                    f"forgotten non-daemon thread hangs interpreter "
                    f"shutdown"))
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            # bare `threading.Thread(...).start()` — nothing to join
            inner = node.value
            if (isinstance(inner.func, ast.Attribute)
                    and inner.func.attr == "start"
                    and _is_thread_ctor(inner.func.value)
                    and not _has_daemon_kw(inner.func.value)):
                findings.append(Finding(
                    mod.path, inner.lineno, "PB401",
                    "anonymous thread started without an explicit "
                    "daemon= — it can never be joined and a non-daemon "
                    "default hangs interpreter shutdown"))
    return findings


def _thread_target_def(mod: Module, call: ast.Call) -> Optional[ast.AST]:
    """The in-module def a Thread ctor's ``target=`` resolves to: a
    module/local function for ``target=name``, a method def for
    ``target=self.name``.  None for dynamic / foreign targets (lambdas
    cannot hold loops; ``obj.method`` on a non-self receiver is another
    object's lifecycle)."""
    for kw in call.keywords:
        if kw.arg != "target":
            continue
        v = kw.value
        if isinstance(v, ast.Name):
            name = v.id
        elif (isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id in ("self", "cls")):
            name = v.attr
        else:
            return None
        for node in mod.walk():
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == name):
                return node
    return None


def _has_loop(fn: ast.AST) -> bool:
    return any(isinstance(n, (ast.While, ast.For)) for n in ast.walk(fn))


def _check_recurring_threads(mod: Module) -> List[Finding]:
    """PB405 — recurring work on a raw unjoined thread."""
    findings: List[Finding] = []
    parent = {}
    for node in mod.walk():
        for child in ast.iter_child_nodes(node):
            parent[child] = node

    def owning_scope(node: ast.AST, want_class: bool) -> ast.AST:
        cur = parent.get(node)
        while cur is not None:
            if want_class and isinstance(cur, ast.ClassDef):
                return cur
            if not want_class and isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parent.get(cur)
        return mod.tree

    def flag(call: ast.Call, label: str) -> None:
        findings.append(Finding(
            mod.path, call.lineno, "PB405",
            f"{label} runs a looping target on a raw thread with no "
            f"join in its owning scope — recurring work belongs on "
            f"WorkPool/a named executor, or join the thread (managed "
            f"lifecycle); suppress with a reason for deliberate "
            f"long-lived pumps"))

    for node in mod.walk():
        if isinstance(node, ast.Assign) and _is_thread_ctor(node.value):
            call = node.value
            fn = _thread_target_def(mod, call)
            if fn is None or not _has_loop(fn):
                continue
            for name, is_self in map(_target_name, node.targets):
                if name is None:
                    continue
                scope = owning_scope(node, want_class=is_self)
                if (name, is_self) in _method_calls_on(scope, "join"):
                    continue
                flag(call, f"thread {name!r}")
        elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            inner = node.value                # Thread(...).start(): unjoinable
            if (isinstance(inner.func, ast.Attribute)
                    and inner.func.attr == "start"
                    and _is_thread_ctor(inner.func.value)):
                fn = _thread_target_def(mod, inner.func.value)
                if fn is not None and _has_loop(fn):
                    flag(inner.func.value, "anonymous thread")
    return findings


def _queue_names(mod: Module) -> Set[str]:
    """Names (attr or local, unqualified) assigned from a queue ctor
    anywhere in the module."""
    out: Set[str] = set()
    for node in mod.walk():
        if not (isinstance(node, (ast.Assign, ast.AnnAssign))
                and node.value is not None
                and isinstance(node.value, ast.Call)):
            continue
        ctor = dotted_name(node.value.func).rsplit(".", 1)[-1]
        if ctor not in _QUEUE_CTORS:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            name, _ = _target_name(t)
            if name is not None:
                out.add(name)
    return out


def _loop_has_escape(loop: ast.While) -> bool:
    """break / return / raise anywhere in the loop body (not counting
    nested loops' own breaks — close enough for a lint heuristic)."""
    for node in ast.walk(loop):
        if isinstance(node, (ast.Break, ast.Return, ast.Raise)):
            return True
    return False


def _in_try_with_handler(loop: ast.While, get_call: ast.Call) -> bool:
    for node in ast.walk(loop):
        if isinstance(node, ast.Try) and node.handlers:
            if any(n is get_call for n in ast.walk(node)):
                return True
    return False


def _check_queue_gets(mod: Module) -> List[Finding]:
    queues = _queue_names(mod)
    if not queues:
        return []
    findings: List[Finding] = []
    for loop in mod.walk():
        if not isinstance(loop, ast.While):
            continue
        for node in ast.walk(loop):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and not node.args and not node.keywords):
                continue
            recv, _ = _target_name(node.func.value)
            if recv not in queues:
                continue
            if _loop_has_escape(loop) or _in_try_with_handler(loop, node):
                continue
            # a loop gated on the queue's own state (`while q.size():`)
            # only calls get() when an item is present — not the hang class
            if any(_target_name(n)[0] == recv
                   for n in ast.walk(loop.test)
                   if isinstance(n, (ast.Attribute, ast.Name))):
                continue
            findings.append(Finding(
                mod.path, node.lineno, "PB402",
                f"blocking {recv}.get() with no timeout in a loop with no "
                f"break/return and no exception handler — if the producer "
                f"dies this consumer hangs forever; add a timeout or a "
                f"sentinel escape"))
    return findings


def _is_executor_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    return dotted_name(node.func).rsplit(".", 1)[-1] == "ThreadPoolExecutor"


def _check_executors(mod: Module) -> List[Finding]:
    findings: List[Finding] = []
    parent = {}
    for node in mod.walk():
        for child in ast.iter_child_nodes(node):
            parent[child] = node

    def owning_scope(node: ast.AST, want_class: bool) -> ast.AST:
        cur = parent.get(node)
        while cur is not None:
            if want_class and isinstance(cur, ast.ClassDef):
                return cur
            if not want_class and isinstance(
                    cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = parent.get(cur)
        return mod.tree

    # ctors managed by a `with` statement: shutdown is implicit
    with_managed = set()
    for node in mod.walk():
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if _is_executor_ctor(item.context_expr):
                    with_managed.add(id(item.context_expr))

    for node in mod.walk():
        if not _is_executor_ctor(node):
            continue
        call = node
        if not any(kw.arg == "thread_name_prefix" for kw in call.keywords):
            findings.append(Finding(
                mod.path, call.lineno, "PB403",
                "ThreadPoolExecutor created without thread_name_prefix= — "
                "anonymous pool threads make stack dumps unattributable "
                "and defeat name-based re-entrancy guards"))
        if id(call) in with_managed:
            continue                     # `with` handles shutdown
        assigned = parent.get(call)
        ok = False
        if isinstance(assigned, ast.Assign):
            for name, is_self in map(_target_name, assigned.targets):
                if name is None:
                    continue
                scope = owning_scope(call, want_class=is_self)
                if (name, is_self) in _method_calls_on(scope, "shutdown"):
                    ok = True
        if not ok:
            findings.append(Finding(
                mod.path, call.lineno, "PB403",
                "ThreadPoolExecutor is never shutdown() in its owning "
                "scope (and not managed by a `with` statement) — its "
                "non-daemon workers hang interpreter shutdown"))
    return findings


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    return (_check_threads(mod) + _check_queue_gets(mod)
            + _check_executors(mod) + _check_recurring_threads(mod))
