"""PB5xx — durable-write discipline (the atomic-rename rule).

  PB502  a bare write sink (``open(path, "wb")``, ``np.savez(path)``,
         ``fs.open_write(path)``) targeting a FINAL path inside
         checkpoint/dump code.  A crash mid-write leaves a torn file at
         the committed name — the exact corruption the generation-chain
         protocol (io/checkpoint.py) exists to rule out.  Durable
         artifacts must be written to a scratch path and published with
         ``os.replace`` (write-tmp + fsync + rename), so the committed
         name only ever points at a complete file.

         Scope: calls inside a function whose name mentions
         save/dump/checkpoint/persist/write_… or anywhere in an ``io/``
         module — ad-hoc writes elsewhere (test fixtures, debug dumps)
         are not durability-critical.  A sink whose path expression
         mentions ``tmp`` (``path + ".tmp"``, ``tmp_path``, a
         ``mkstemp``/``TemporaryDirectory`` product) IS the scratch leg
         of the protocol and is never flagged.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)

_WRITE_MODES = set("wax")
_FUNC_HINTS = ("save", "dump", "checkpoint", "persist", "write")


def _path_arg(node: ast.Call) -> Optional[ast.AST]:
    if node.args:
        return node.args[0]
    for kw in node.keywords:
        if kw.arg in ("file", "path", "filename"):
            return kw.value
    return None


def _is_tmp_path(arg: Optional[ast.AST]) -> bool:
    """The sink already targets a scratch name: its path expression
    mentions tmp (``path + ".tmp"``, ``tmp_dir``, tempfile products)."""
    if arg is None:
        return False
    try:
        return "tmp" in ast.unparse(arg).lower()
    except Exception:
        return False


def _sink(node: ast.Call) -> Optional[str]:
    """Classify a call as a final-path write sink; None when it isn't."""
    name = dotted_name(node.func)
    if name == "open":
        for i, arg in enumerate(node.args[:2]):
            if i == 1 and isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str) \
                    and _WRITE_MODES & set(arg.value):
                return f'open(..., "{arg.value}")'
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str) \
                    and _WRITE_MODES & set(kw.value.value):
                return f'open(..., mode="{kw.value.value}")'
        return None
    tail = name.rsplit(".", 1)[-1] if name else ""
    if tail in ("savez", "savez_compressed") or name in ("np.save",
                                                         "numpy.save"):
        return name
    if tail == "open_write":
        return name
    return None


def _durable_context(mod: Module, func_stack: List[str]) -> bool:
    if "/io/" in mod.path.replace("\\", "/"):
        return True
    return any(any(h in fn.lower() for h in _FUNC_HINTS)
               for fn in func_stack)


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    findings: List[Finding] = []

    def visit(node: ast.AST, stack: List[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack = stack + [node.name]
        if isinstance(node, ast.Call):
            sink = _sink(node)
            if sink is not None and _durable_context(mod, stack) \
                    and not _is_tmp_path(_path_arg(node)):
                findings.append(Finding(
                    mod.path, node.lineno, "PB502",
                    f"bare write sink {sink} at a final path in "
                    "checkpoint/dump code: a crash mid-write leaves a "
                    "torn file at the committed name — write to a "
                    "*.tmp scratch path and publish with os.replace"))
        for child in ast.iter_child_nodes(node):
            visit(child, stack)

    visit(mod.tree, [])
    return findings
