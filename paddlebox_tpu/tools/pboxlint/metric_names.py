"""PB2xx (cont.) — metric/span name hygiene (the StatRegistry +
SpanTracer cardinality discipline, utils/monitor.py / utils/trace.py).

  PB204  a metric or span name passed to ``stat_add`` / ``stat_observe``
         / ``stat_max`` / ``stat_set`` / ``stat_get`` or a span starter
         (``span`` / ``start_span``) is either

         * built dynamically (f-string / ``+`` concatenation) from a
           part that is not a KNOWN BOUNDED FIELD — every distinct name
           becomes a permanent StatRegistry entry, so an unbounded
           dynamic part (a key, a rid, a path) silently grows the
           process-wide registry forever, or
         * a literal that is not a lowercase dotted identifier
           (``[a-z0-9_.]``) — mixed-case/spaced names fracture the
           dotted-prefix namespace that ``snapshot(prefix)``, the
           per-pass report and the Prometheus exporter all key on.

Bounded fields are the closed vocabularies of the wire protocol: a verb
name, a fault site/kind, a role, a configured serving tenant —
recognized syntactically as a name, attribute or const-subscript whose
TERMINAL component is one of
``cmd / verb / site / kind / role / phase / stage / table / tenant /
shard`` (a cluster shard rank is bounded by the fleet size) (e.g.
``verb``, ``msg['cmd']``, ``hit.kind``).  Anything else — ``f"k.{key}"``,
``"k." + rid`` — is flagged.  A deliberately dynamic name suppresses
with a reason, like every other rule.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)

_NAME_SINKS = {"stat_add", "stat_observe", "stat_max", "stat_set",
               "stat_get", "span", "start_span"}
_BOUNDED_FIELDS = {"cmd", "verb", "site", "kind", "role", "phase",
                   "stage", "table", "tenant", "shard"}
_LITERAL_OK = re.compile(r"[a-z0-9_.]*\Z")


def _terminal_field(node: ast.AST) -> Optional[str]:
    """The terminal component of a simple value expression: ``verb`` →
    "verb", ``hit.kind`` → "kind", ``msg['cmd']`` → "cmd"; None for
    anything more dynamic (calls, arithmetic, nested subscripts...)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if (isinstance(node, ast.Subscript)
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)):
        return node.slice.value
    return None


def _check_literal(text: str) -> bool:
    return bool(_LITERAL_OK.match(text))


def _binop_leaves(node: ast.AST) -> Optional[List[ast.AST]]:
    """Flatten a ``+`` concatenation tree into leaves; None when the
    tree contains a non-Add operator (out of scope)."""
    if isinstance(node, ast.BinOp):
        if not isinstance(node.op, ast.Add):
            return None
        left = _binop_leaves(node.left)
        right = _binop_leaves(node.right)
        if left is None or right is None:
            return None
        return left + right
    return [node]


def _findings_for_name(mod: Module, call: ast.Call,
                       arg: ast.AST) -> List[Finding]:
    out: List[Finding] = []

    def flag(reason: str) -> None:
        out.append(Finding(
            mod.path, call.lineno, "PB204",
            f"{dotted_name(call.func) or '<call>'}(...) metric/span name "
            f"{reason} — unbounded name cardinality grows the "
            f"process-wide StatRegistry forever; bounded dynamic parts "
            f"are {sorted(_BOUNDED_FIELDS)}, or suppress with a reason"))

    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        if not _check_literal(arg.value):
            flag(f"literal {arg.value!r} is not a lowercase dotted "
                 f"identifier")
        return out
    if isinstance(arg, ast.JoinedStr):
        for part in arg.values:
            if isinstance(part, ast.Constant):
                if isinstance(part.value, str) \
                        and not _check_literal(part.value):
                    flag(f"literal segment {part.value!r} is not "
                         f"lowercase dotted")
            elif isinstance(part, ast.FormattedValue):
                field = _terminal_field(part.value)
                if field not in _BOUNDED_FIELDS:
                    flag("has an f-string part that is not a known "
                         "bounded field")
        return out
    leaves = _binop_leaves(arg)
    if isinstance(arg, ast.BinOp) and leaves is not None:
        for leaf in leaves:
            if isinstance(leaf, ast.Constant):
                if isinstance(leaf.value, str) \
                        and not _check_literal(leaf.value):
                    flag(f"literal segment {leaf.value!r} is not "
                         f"lowercase dotted")
            elif _terminal_field(leaf) not in _BOUNDED_FIELDS:
                flag("is concatenated (+) from a part that is not a "
                     "known bounded field")
    # bare names / calls as the whole argument are out of static reach:
    # the value may be a constant threaded through a helper — the
    # f-string/+ forms are where unbounded keys actually get minted
    return out


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in mod.walk():
        if not (isinstance(node, ast.Call) and node.args):
            continue
        tail = dotted_name(node.func).rsplit(".", 1)[-1]
        if tail not in _NAME_SINKS:
            continue
        findings.extend(_findings_for_name(mod, node, node.args[0]))
    return findings
