"""PB5xx — retry/backoff discipline.

  PB501  a constant-argument ``time.sleep`` inside a retry loop (a
         ``for``/``while`` whose body contains a ``try`` with an
         exception handler) — a fixed sleep bypasses the shared backoff
         helper (utils/backoff.Backoff): no exponential growth, no
         jitter (a fleet of clients retries in lockstep), and no overall
         deadline budget.  A sleep of a *computed* value (the helper's
         own ``bo.sleep(attempt)``, a variable, an attribute) is not
         flagged.
"""

from __future__ import annotations

import ast
from typing import List, Set

from paddlebox_tpu.tools.pboxlint.core import (Finding, Module,
                                               PackageContext, dotted_name)


def _is_const_sleep(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    if name not in ("time.sleep", "sleep"):
        return False
    if not node.args or node.keywords:
        return False
    arg = node.args[0]
    return (isinstance(arg, ast.Constant)
            and isinstance(arg.value, (int, float))
            and not isinstance(arg.value, bool))


def check(mod: Module, ctx: PackageContext) -> List[Finding]:
    findings: List[Finding] = []
    flagged: Set[int] = set()       # nested loops: report each sleep once
    for loop in mod.walk():
        if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
            continue
        if not any(isinstance(n, ast.Try) and n.handlers
                   for n in ast.walk(loop)):
            continue                # not a retry loop — plain polling
        for node in ast.walk(loop):
            if _is_const_sleep(node) and node.lineno not in flagged:
                flagged.add(node.lineno)
                findings.append(Finding(
                    mod.path, node.lineno, "PB501",
                    "fixed-sleep retry loop: constant time.sleep() "
                    "inside a loop with an exception handler bypasses "
                    "the shared backoff helper — use utils/backoff."
                    "Backoff (exponential + jitter under a deadline "
                    "budget)"))
    return findings
